"""Declarative agent metrics.

Mirrors the reference's registry (`pkg/metrics/metrics.go:66-162`): eviction
counters/sizes, dropped flows, ringbuf events, kernel global counters, buffer
gauges, interface events, eviction-latency histogram, sampling gauge, errors by
severity — all behind a configurable prefix and verbosity level.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from prometheus_client import (
    CollectorRegistry, Counter, Gauge, Histogram,
)

from netobserv_tpu.model.flow import GlobalCounter

log = logging.getLogger("netobserv_tpu.metrics")

LEVELS = ("info", "debug", "trace")


@dataclass
class MetricsSettings:
    prefix: str = "ebpf_agent_"
    level: str = "info"


class Metrics:
    """Facade handed to every pipeline stage (reference: `metrics.Metrics`)."""

    def __init__(self, settings: MetricsSettings = MetricsSettings(),
                 registry: CollectorRegistry | None = None):
        self.settings = settings
        self.registry = registry if registry is not None else CollectorRegistry()
        p = settings.prefix

        self.evictions_total = Counter(
            p + "evictions_total", "Eviction cycles", ["source"],
            registry=self.registry)
        self.evicted_flows_total = Counter(
            p + "evicted_flows_total", "Flows evicted", ["source"],
            registry=self.registry)
        self.dropped_flows_total = Counter(
            p + "dropped_flows_total", "Flows dropped by the pipeline",
            ["source"], registry=self.registry)
        self.ringbuf_events_total = Counter(
            p + "ringbuf_events_total",
            "Flow events received via the map-full fallback ring buffer",
            registry=self.registry)
        self.kernel_counters_total = Counter(
            p + "kernel_counters_total",
            "Datapath global counters (scraped each eviction)", ["name"],
            registry=self.registry)
        self.exported_batches_total = Counter(
            p + "exported_batches_total", "Batches exported", ["exporter"],
            registry=self.registry)
        self.exported_flows_total = Counter(
            p + "exported_flows_total", "Flows exported", ["exporter"],
            registry=self.registry)
        self.export_errors_total = Counter(
            p + "export_errors_total", "Export errors", ["exporter", "error"],
            registry=self.registry)
        self.errors_total = Counter(
            p + "errors_total", "Agent errors by component and severity",
            ["component", "severity"], registry=self.registry)
        self.buffer_size = Gauge(
            p + "buffer_size", "Pipeline buffer occupancy", ["name"],
            registry=self.registry)
        self.interface_events_total = Counter(
            p + "interface_events_total", "Interface attach/detach events",
            ["type"], registry=self.registry)
        self.sampling_rate = Gauge(
            p + "sampling_rate", "Configured sampling (1/N; 0=all)",
            registry=self.registry)
        self.eviction_seconds = Histogram(
            p + "lookup_and_delete_map_duration_seconds",
            "Map eviction (lookup+delete) latency",
            buckets=(.001, .005, .01, .025, .05, .1, .25, .5, 1, 2.5, 5),
            registry=self.registry)
        # tpu-sketch backend metrics (new)
        self.sketch_batches_total = Counter(
            p + "sketch_batches_total", "Columnar batches folded on device",
            registry=self.registry)
        self.sketch_records_total = Counter(
            p + "sketch_records_total", "Flow records folded on device",
            registry=self.registry)
        self.sketch_window_reports_total = Counter(
            p + "sketch_window_reports_total", "Window reports emitted",
            registry=self.registry)
        self.sketch_ingest_seconds = Histogram(
            p + "sketch_ingest_seconds", "Device ingest step latency",
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5),
            registry=self.registry)

    # --- convenience methods used by pipeline stages ---
    def observe_eviction(self, source: str, n_flows: int, seconds: float) -> None:
        self.evictions_total.labels(source).inc()
        if n_flows:
            self.evicted_flows_total.labels(source).inc(n_flows)
        if seconds > 0:
            self.eviction_seconds.observe(seconds)

    def count_dropped(self, n: int, source: str) -> None:
        self.dropped_flows_total.labels(source).inc(n)

    def count_ringbuf_event(self) -> None:
        self.ringbuf_events_total.inc()

    def add_global_counter(self, key: GlobalCounter, val: int) -> None:
        if val:
            self.kernel_counters_total.labels(key.name.lower()).inc(val)

    def count_exported(self, exporter: str, n_flows: int) -> None:
        self.exported_batches_total.labels(exporter).inc()
        if n_flows:
            self.exported_flows_total.labels(exporter).inc(n_flows)

    def count_export_error(self, exporter: str, error: str) -> None:
        self.export_errors_total.labels(exporter, error).inc()

    def count_error(self, component: str, severity: str = "error") -> None:
        self.errors_total.labels(component, severity).inc()

    def count_interface_event(self, kind: str) -> None:
        self.interface_events_total.labels(kind).inc()
