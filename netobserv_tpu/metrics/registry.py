"""Declarative agent metrics.

Mirrors the reference's registry (`pkg/metrics/metrics.go:66-162`): eviction
counters/sizes, dropped flows, ringbuf events, kernel global counters, buffer
gauges, interface events, eviction-latency histogram, sampling gauge, errors by
severity — all behind a configurable prefix and verbosity level.

METRICS_LEVEL controls interface-event cardinality exactly like the
reference's `newInterfaceEventsCounter` (`pkg/metrics/metrics.go:337-368`):

- ``info``  — only the event ``type`` label is populated
- ``debug`` — ``type`` + attach ``retries``
- ``trace`` (spelled ``trace!`` in the reference, accepted here too: the
  bang warns the cardinality is unbounded) — full per-interface series
  (``ifname``/``ifindex``/``netns``/``mac``) that SELF-EXPIRE after
  ``trace_ttl_s`` via a janitor thread, bounding steady-state cardinality.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from prometheus_client import (
    CollectorRegistry, Counter, Gauge, Histogram,
)

from netobserv_tpu.model.flow import GlobalCounter

log = logging.getLogger("netobserv_tpu.metrics")

LEVELS = ("info", "debug", "trace")


@dataclass
class MetricsSettings:
    prefix: str = "ebpf_agent_"
    level: str = "info"
    trace_ttl_s: float = 300.0  # trace-level series lifetime (reference: 5min)

    def normalized_level(self) -> str:
        lvl = self.level.rstrip("!").lower()  # reference spells trace "trace!"
        if lvl not in LEVELS:
            raise ValueError(
                f"invalid METRICS_LEVEL {self.level!r} (one of {LEVELS})")
        return lvl


class Metrics:
    """Facade handed to every pipeline stage (reference: `metrics.Metrics`)."""

    def __init__(self, settings: MetricsSettings | None = None,
                 registry: CollectorRegistry | None = None):
        # construct per call: a dataclass default instance would be silently
        # SHARED by every Metrics() built without args (one caller mutating
        # trace_ttl_s would retime every other facade's janitor)
        if settings is None:
            settings = MetricsSettings()
        self.settings = settings
        self.level = settings.normalized_level()
        self.registry = registry if registry is not None else CollectorRegistry()
        # per-series LATEST deadline — an increment refreshes the TTL
        self._trace_expiry: dict[tuple[str, ...], float] = {}
        self._trace_lock = threading.Lock()
        self._trace_janitor: threading.Thread | None = None
        p = settings.prefix

        self.evictions_total = Counter(
            p + "evictions_total", "Eviction cycles", ["source"],
            registry=self.registry)
        self.evicted_flows_total = Counter(
            p + "evicted_flows_total", "Flows evicted", ["source"],
            registry=self.registry)
        self.dropped_flows_total = Counter(
            p + "dropped_flows_total", "Flows dropped by the pipeline",
            ["source"], registry=self.registry)
        self.ringbuf_events_total = Counter(
            p + "ringbuf_events_total",
            "Flow events received via the map-full fallback ring buffer",
            registry=self.registry)
        self.kernel_counters_total = Counter(
            p + "kernel_counters_total",
            "Datapath global counters (scraped each eviction)", ["name"],
            registry=self.registry)
        self.exported_batches_total = Counter(
            p + "exported_batches_total", "Batches exported", ["exporter"],
            registry=self.registry)
        self.exported_flows_total = Counter(
            p + "exported_flows_total", "Flows exported", ["exporter"],
            registry=self.registry)
        self.export_errors_total = Counter(
            p + "export_errors_total", "Export errors", ["exporter", "error"],
            registry=self.registry)
        self.errors_total = Counter(
            p + "errors_total", "Agent errors by component and severity",
            ["component", "severity"], registry=self.registry)
        self.buffer_size = Gauge(
            p + "buffer_size", "Pipeline buffer occupancy", ["name"],
            registry=self.registry)
        self.interface_events_total = Counter(
            p + "interface_events_total", "Interface attach/detach events",
            ["type", "ifname", "ifindex", "netns", "mac", "retries"],
            registry=self.registry)
        self.sampling_rate = Gauge(
            p + "sampling_rate", "Configured sampling (1/N; 0=all)",
            registry=self.registry)
        self.eviction_seconds = Histogram(
            p + "lookup_and_delete_map_duration_seconds",
            "Map eviction (lookup+delete) latency",
            buckets=(.001, .005, .01, .025, .05, .1, .25, .5, 1, 2.5, 5),
            registry=self.registry)
        self.eviction_decode_seconds = Histogram(
            p + "eviction_decode_seconds",
            "Columnar eviction-plane latency per drain (decode + per-CPU "
            "merge + key alignment, the userspace half of an eviction)",
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5, 1),
            registry=self.registry)
        self.evicted_flows_per_drain = Histogram(
            p + "evicted_flows_per_drain",
            "Flows returned by one map drain (eviction batch size)",
            buckets=(0, 10, 100, 1000, 10000, 100000, 1000000),
            registry=self.registry)
        self.flowpack_abi_fallback_total = Counter(
            p + "flowpack_abi_fallback_total",
            "Native flowpack library loads that failed (missing .so or "
            "stale ABI) — the pure-python twins carried the host path; "
            "rebuild with `make native`", registry=self.registry)
        self.flowpack_native_calls_total = Counter(
            p + "flowpack_native_calls_total",
            "Eviction drains by host path while EVICT_NATIVE_PIPELINE is "
            "enabled (fused = one fp_drain_to_resident native call; chain "
            "= the python island chain, incl. the batch-support probe "
            "drain)", ["path"], registry=self.registry)
        self.host_native_pipeline_seconds = Histogram(
            p + "host_native_pipeline_seconds",
            "Per-stage seconds inside the fused native drain pipeline "
            "(drain = batched bpf(2) syscalls, merge = per-CPU columnar "
            "merge, join = key join + feature alignment, pack = resident "
            "region pack)", ["stage"],
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5),
            registry=self.registry)
        # tpu-sketch backend metrics (new)
        self.sketch_batches_total = Counter(
            p + "sketch_batches_total", "Columnar batches folded on device",
            registry=self.registry)
        self.sketch_records_total = Counter(
            p + "sketch_records_total", "Flow records folded on device",
            registry=self.registry)
        self.sketch_window_reports_total = Counter(
            p + "sketch_window_reports_total", "Window reports emitted",
            registry=self.registry)
        self.sketch_ingest_seconds = Histogram(
            p + "sketch_ingest_seconds", "Device ingest step latency",
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5),
            registry=self.registry)
        self.sketch_staging_stalls_total = Counter(
            p + "sketch_staging_stalls_total",
            "Staging-ring folds that had to WAIT for a slot's previous "
            "ingest (device slower than the eviction feed)",
            registry=self.registry)
        self.sketch_resident_continuations_total = Counter(
            p + "sketch_resident_continuations_total",
            "Extra resident-feed chunks shipped because a side lane filled "
            "(sustained high rates mean the caps are undersized for this "
            "traffic mix)", registry=self.registry)
        self.sketch_resident_dict_epochs_total = Counter(
            p + "sketch_resident_dict_epochs_total",
            "Resident key-dictionary epoch rolls (dictionary reached "
            "SKETCH_RESIDENT_SLOTS; size it above the flow working set)",
            registry=self.registry)
        self.sketch_dense_fallback_total = Counter(
            p + "sketch_dense_fallback_total",
            "Compact-feed batches whose non-v4/drop rows overflowed the "
            "spill lane and shipped full-width instead (synchronous, "
            "dense-path speed — sustained increments mean v6-heavy or "
            "drop-storm traffic outgrew the compact feed)",
            registry=self.registry)
        self.sketch_resident_spill_rows_total = Counter(
            p + "sketch_resident_spill_rows_total",
            "Rows that rode the full-width spill lane instead of a hot row",
            registry=self.registry)
        self.sketch_direct_fold_rows_total = Counter(
            p + "sketch_direct_fold_rows_total",
            "Rows ROUTED through the direct-to-lane fast path "
            "(batch-aligned prefixes handed to the fold as zero-copy "
            "eviction-decode views, bypassing the pending-buffer copy; "
            "the sub-batch tail still copies in). Routing, not device "
            "success — a swallowed ingest error downstream still counts "
            "here but not in sketch_records_total",
            registry=self.registry)
        self.sketch_superbatch_folds_total = Counter(
            p + "sketch_superbatch_folds_total",
            "Superbatch fold dispatches by ladder size k (k queued batches "
            "coalesced into one fixed-shape device dispatch; a healthy "
            "overloaded host shows mass at the largest k, an idle one at "
            "k=1)", ["k"], registry=self.registry)
        # overload control plane (sketch/overload.py + flow/map_tracer.py)
        self.sketch_shed_factor = Gauge(
            p + "sketch_shed_factor",
            "Current 1-in-N load-shedding factor at the exporter seam "
            "(1 = no shedding). Driven by the AIMD overload controller "
            "when SKETCH_SHED_WATERMARK is set; surviving rows carry the "
            "factor in their sampling field so estimates stay unbiased",
            registry=self.registry)
        self.sketch_shed_rows_total = Counter(
            p + "sketch_shed_rows_total",
            "Rows dropped by overload shedding (unbiased 1-in-N row "
            "sampling; the surviving rows stand in for these, scaled)",
            registry=self.registry)
        self.sketch_shed_batches_total = Counter(
            p + "sketch_shed_batches_total",
            "Eviction batches thinned by overload shedding",
            registry=self.registry)
        self.sketch_slot_wait_seconds = Histogram(
            p + "sketch_slot_wait_seconds",
            "Staging-ring slot wait per fold (time the feed spent blocked "
            "on the device consuming a previous batch; the overload "
            "controller's backpressure signal)",
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5, 1, 5),
            registry=self.registry)
        self.sketch_heavy_evictions_total = Counter(
            p + "sketch_heavy_evictions_total",
            "Valid heavy-hitter slot-table occupants evicted by heavier "
            "challengers (persistent-slot top-K plane; incremented at "
            "each window publish by that window's eviction count — "
            "sustained high rates mean the table is churning under "
            "capacity pressure: raise SKETCH_TOPK)",
            registry=self.registry)
        self.sketch_tier_promotions_total = Counter(
            p + "sketch_tier_promotions_total",
            "Counters promoted out of the narrow u8 base plane "
            "(SKETCH_TIERED; incremented at each closed-window publish by "
            "that window's count of base-saturated counters, per CM "
            "table — sustained growth means the tier geometry is too "
            "narrow for the traffic: raise SKETCH_TIER_BYTES_UNIT or "
            "widen the sketch)", ["table"],
            registry=self.registry)
        self.sketch_tiered_interior_folds_total = Counter(
            p + "sketch_tiered_interior_folds_total",
            "Ingest folds served by the tier-interior Pallas walk "
            "(SKETCH_TIERED + use_pallas: the fold ran directly on the "
            "packed u8/u16/u32 tiles, no wide decode temporary — compare "
            "against sketch_batches_total to confirm the interior form is "
            "the one actually engaged)",
            registry=self.registry)
        # multi-tenant sketch planes (sketch/tenancy.py)
        self.sketch_tenant_folds_total = Counter(
            p + "sketch_tenant_folds_total",
            "Stacked tenant-fold dispatches (SKETCH_TENANTS): each folds "
            "EVERY tenant's pending rows as one vmapped executable — the "
            "dispatch-amortization the tenant stack exists for (compare "
            "against sketch_records_total for rows-per-dispatch)",
            registry=self.registry)
        self.sketch_tenants_active = Gauge(
            p + "sketch_tenants_active",
            "Tenant states stacked in the live tenant plane (0 = "
            "single-tenant path; set at exporter construction, zeroed at "
            "close when the per-tenant labelled series are evicted)",
            registry=self.registry)
        self.sketch_tenant_window_records = Gauge(
            p + "sketch_tenant_window_records",
            "Per-tenant records in the last closed window (cardinality = "
            "LIVE tenants: series ride Metrics.remove_labeled when a "
            "tenant plane is drained/closed — the federation "
            "agent-eviction hygiene pattern)",
            ["tenant"], registry=self.registry)
        self.sketch_resident_hbm_bytes = Gauge(
            p + "sketch_resident_hbm_bytes",
            "Resident sketch-state bytes on device (sum over all state "
            "arrays; shape math, set once at exporter construction). "
            "SKETCH_TIERED shrinks this ~4x over the counter tables — "
            "the windows/tenants-per-HBM capacity signal",
            registry=self.registry)
        self.sketch_reports_shed_total = Counter(
            p + "sketch_reports_shed_total",
            "Unpublished window reports shed because the report queue "
            "overflowed behind a wedged sink (that window's report is "
            "lost; the sketch state already rolled)",
            registry=self.registry)
        self.map_occupancy_ratio = Histogram(
            p + "map_occupancy_ratio",
            "Kernel aggregation-map occupancy at each drain, as a "
            "fraction of the map capacity (the probed max_entries in "
            "bpfman mode, else CACHE_MAX_FLOWS; mass near 1.0 means the "
            "map fills between evictions — the ringbuf fallback engages)",
            buckets=(.1, .25, .5, .75, .9, .95, 1.0),
            registry=self.registry)
        self.map_pressure_evictions_total = Counter(
            p + "map_pressure_evictions_total",
            "Early (half-period) evictions triggered by the map-occupancy "
            "watermark (MAP_PRESSURE_WATERMARK)", registry=self.registry)
        self.evict_ringbuf_fallback_total = Counter(
            p + "evict_ringbuf_fallback_total",
            "Feature rows whose flow was missing from the aggregation "
            "drain and became standalone appended events (ringbuf-fallback "
            "singles or a racing eviction — the one bounded double-count "
            "overload path, shared with the reference)",
            registry=self.registry)
        # query plane (netobserv_tpu/query + the /query/* routes on the
        # metrics server)
        self.query_requests_total = Counter(
            p + "query_requests_total",
            "Agent query-surface requests by route (topk / frequency / "
            "cardinality / victims / status) and result (ok / no_window / "
            "bad_request / not_found / error)", ["route", "result"],
            registry=self.registry)
        self.query_snapshot_age_seconds = Gauge(
            p + "query_snapshot_age_seconds",
            "Seconds since the agent's query snapshot was last published "
            "(resets at every window roll; with SKETCH_QUERY_REFRESH set "
            "it also resets at each mid-window refresh — growth past the "
            "window period means the publish path is failing)",
            registry=self.registry)
        # continuous detection & alerting plane (netobserv_tpu/alerts +
        # /query/alerts; the aggregator's engine shares these series)
        self.alerts_active = Gauge(
            p + "alerts_active",
            "Alerts currently RAISED by the continuous detection plane "
            "(hysteresis state machine over every snapshot publish; 0 with "
            "ALERT_RULES unset — no engine exists)",
            registry=self.registry)
        self.alerts_transitions_total = Counter(
            p + "alerts_transitions_total",
            "Alert state transitions by rule and action (raise / clear), "
            "exactly one per hysteresis crossing (incremented by the "
            "metrics sink)", ["rule", "action"], registry=self.registry)
        self.alert_sink_errors_total = Counter(
            p + "alert_sink_errors_total",
            "Alert transitions a sink failed to deliver after its bounded "
            "retries (swallowed + counted; the engine state machine and "
            "the other sinks were unaffected)", ["sink"],
            registry=self.registry)
        self.alert_eval_seconds = Histogram(
            p + "alert_eval_seconds",
            "Alert-engine evaluation latency per snapshot publish (host-"
            "only rule walk on the timer thread; sink I/O excluded)",
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5),
            registry=self.registry)
        self.sketch_window_records = Gauge(
            p + "sketch_window_records", "Flow records in the last window",
            registry=self.registry)
        self.sketch_window_drop_bytes = Gauge(
            p + "sketch_window_drop_bytes",
            "Kernel-dropped bytes in the last window",
            registry=self.registry)
        self.sketch_window_suspects = Gauge(
            p + "sketch_window_suspects",
            "Anomaly suspects reported in the last window, by signal",
            ["signal"], registry=self.registry)
        # supervision layer (agent/supervisor.py)
        self.stage_failures_total = Counter(
            p + "stage_failures_total",
            "Supervised-stage failures detected (crash = dead thread, "
            "hang = heartbeat deadline exceeded)", ["stage", "kind"],
            registry=self.registry)
        self.stage_restarts_total = Counter(
            p + "stage_restarts_total",
            "Supervised-stage restarts performed", ["stage"],
            registry=self.registry)
        self.stage_degraded = Gauge(
            p + "stage_degraded",
            "1 when a stage exhausted its restart budget and was marked "
            "DEGRADED", ["stage"], registry=self.registry)
        self.sketch_ingest_errors_total = Counter(
            p + "sketch_ingest_errors_total",
            "Device ingest failures absorbed by dropping the batch "
            "(graceful degradation; the window timer stays alive)",
            registry=self.registry)
        # flight recorder (utils/tracing.py) + retrace watchdog
        # (utils/retrace.py)
        self.stage_seconds = Histogram(
            p + "stage_seconds",
            "Per-stage latency of sampled batch/window traces (flight "
            "recorder spans; populated only when TRACE_SAMPLE > 0)",
            ["stage"],
            buckets=(.0001, .0005, .001, .005, .01, .05, .1, .5, 1, 5),
            registry=self.registry)
        self.sketch_retraces_total = Counter(
            p + "sketch_retraces_total",
            "Post-warmup XLA recompilations of a watched jitted entry "
            "point — the fixed-shape ingest invariant is broken (each one "
            "is a multi-second stall; see the retrace watchdog log line "
            "for the offending abstract shapes)", ["fn"],
            registry=self.registry)
        self.executable_dispatch_seconds_total = Counter(
            p + "executable_dispatch_seconds_total",
            "Cumulative wall seconds spent dispatching each watched jitted "
            "entry point (the per-executable attribution split behind "
            "/debug/executables; one monotonic-clock pair per batch "
            "dispatch, never per record)", ["fn"],
            registry=self.registry)
        self.trace_context_propagated_total = Counter(
            p + "trace_context_propagated_total",
            "Cross-process trace contexts carried over the delta wire, by "
            "result (stamped = an agent encoded a sampled window trace "
            "into a frame; continued = the aggregator adopted a frame's "
            "context and recorded child spans under the same trace id)",
            ["result"], registry=self.registry)
        # federation plane (federation/aggregator.py + the agent-side delta
        # sink, exporter/federation.py)
        self.federation_deltas_total = Counter(
            p + "federation_deltas_total",
            "Delta frames received by the aggregator, by outcome (ok / "
            "duplicate / stale / legacy / version_mismatch / "
            "shape_mismatch / decode_error / merge_error). duplicate and "
            "stale are acked-and-discarded by the idempotency ledger; "
            "legacy is a merged v1 frame with no delivery header",
            ["result"], registry=self.registry)
        self.federation_delta_bytes_total = Counter(
            p + "federation_delta_bytes_total",
            "Wire bytes of received delta frames (the federation plane's "
            "ingress volume)", registry=self.registry)
        self.federation_deltas_sent_total = Counter(
            p + "federation_deltas_sent_total",
            "Delta frames pushed by this agent, by outcome (ok / "
            "duplicate / stale / rejected / terminal / error). duplicate "
            "= an ambiguous-deadline retry the aggregator's ledger safely "
            "deduplicated; stale = the aggregator acked-and-DISCARDED the "
            "window as out-of-order (that window's data is lost); "
            "terminal = a non-retryable gRPC status "
            "(INVALID_ARGUMENT class) failed fast; error = the retry "
            "ladder was exhausted and the window's frame was dropped",
            ["result"], registry=self.registry)
        self.federation_merge_seconds = Histogram(
            p + "federation_merge_seconds",
            "On-device hierarchical merge latency per accepted delta frame",
            buckets=(.0005, .001, .005, .01, .05, .1, .5, 1, 5),
            registry=self.registry)
        self.federation_agent_staleness_seconds = Gauge(
            p + "federation_agent_staleness_seconds",
            "Seconds since each known agent's last accepted delta "
            "(cardinality = LIVE fleet size: series are deleted when the "
            "agent is evicted past FEDERATION_AGENT_TTL; an agent past "
            "~2 windows is dark)",
            ["agent"], registry=self.registry)
        self.federation_active_agents = Gauge(
            p + "federation_active_agents",
            "Agents that contributed a delta to the last aggregator window",
            registry=self.registry)
        self.federation_fleet_requests_total = Counter(
            p + "federation_fleet_requests_total",
            "Fleet-table requests (/federation/fleet), by result (ok / "
            "error). Served from the aggregator's published host-side "
            "fleet snapshot only — no device op, no merge lock",
            ["result"], registry=self.registry)
        self.federation_agent_evictions_total = Counter(
            p + "federation_agent_evictions_total",
            "Agents evicted from the aggregator's ownership view after "
            "FEDERATION_AGENT_TTL seconds without a delta (their "
            "staleness gauge series is deleted at the same time)",
            registry=self.registry)
        # sketch warehouse (netobserv_tpu/archive): on-disk window
        # archive + device-merged range queries
        self.archive_segments_total = Counter(
            p + "archive_segments_total",
            "Archive segments written (raw closed-window segments AND "
            "compacted super-windows)", registry=self.registry)
        self.archive_bytes_total = Counter(
            p + "archive_bytes_total",
            "Bytes written into the archive directory (the warehouse's "
            "write amplification numerator; compaction rewrites count)",
            registry=self.registry)
        self.archive_compactions_total = Counter(
            p + "archive_compactions_total",
            "Retention compactions: ARCHIVE_COMPACT_GROUP segments merged "
            "into one coarser super-window one level up",
            registry=self.registry)
        self.archive_range_requests_total = Counter(
            p + "archive_range_requests_total",
            "Range-query requests against the archive (/query/range and "
            "/federation/range), by result (ok / bad_request / "
            "not_found / error)", ["result"], registry=self.registry)
        self.federation_checkpoints_total = Counter(
            p + "federation_checkpoints_total",
            "Aggregator state+ledger checkpoints at window roll, by "
            "outcome (ok / error — error means the window rolled without "
            "durability; a restart then loses back to the previous "
            "checkpoint)", ["result"], registry=self.registry)

    # --- convenience methods used by pipeline stages ---
    def observe_eviction(self, source: str, n_flows: int, seconds: float) -> None:
        self.evictions_total.labels(source).inc()
        if n_flows:
            self.evicted_flows_total.labels(source).inc(n_flows)
        if seconds > 0:
            self.eviction_seconds.observe(seconds)

    def count_dropped(self, n: int, source: str) -> None:
        self.dropped_flows_total.labels(source).inc(n)

    def count_ringbuf_event(self) -> None:
        self.ringbuf_events_total.inc()

    def add_global_counter(self, key: GlobalCounter, val: int) -> None:
        if val:
            self.kernel_counters_total.labels(key.name.lower()).inc(val)

    def count_exported(self, exporter: str, n_flows: int) -> None:
        self.exported_batches_total.labels(exporter).inc()
        if n_flows:
            self.exported_flows_total.labels(exporter).inc(n_flows)

    def count_export_error(self, exporter: str, error: str) -> None:
        self.export_errors_total.labels(exporter, error).inc()

    def count_error(self, component: str, severity: str = "error") -> None:
        self.errors_total.labels(component, severity).inc()

    def remove_labeled(self, metric, *labelvalues: str) -> None:
        """Delete one labeled series from a metric family — the
        cardinality-lifecycle seam (departed federation agents, expired
        trace-level series). Removing a series that never existed (or was
        already removed) is a no-op, so callers can evict blindly."""
        try:
            metric.remove(*labelvalues)
        except KeyError:
            pass

    def observe_stage(self, stage: str, seconds: float) -> None:
        self.stage_seconds.labels(stage).observe(seconds)

    def count_retrace(self, fn: str) -> None:
        self.sketch_retraces_total.labels(fn).inc()

    def observe_dispatch(self, fn: str, seconds: float) -> None:
        self.executable_dispatch_seconds_total.labels(fn).inc(seconds)

    def count_stage_failure(self, stage: str, kind: str) -> None:
        self.stage_failures_total.labels(stage, kind).inc()

    def count_stage_restart(self, stage: str) -> None:
        self.stage_restarts_total.labels(stage).inc()

    def set_stage_degraded(self, stage: str, degraded: bool) -> None:
        self.stage_degraded.labels(stage).set(1 if degraded else 0)

    def count_interface_event(self, kind: str, ifname: str = "",
                              ifindex: int = 0, netns: str = "",
                              mac: str = "", retries: int = 0) -> None:
        """Level-gated cardinality, mirroring the reference's
        `newInterfaceEventsCounter` (`pkg/metrics/metrics.go:337-368`):
        info = type only; debug = + retries; trace = full per-interface
        series that self-expire after `trace_ttl_s`."""
        if self.level == "info":
            self.interface_events_total.labels(kind, "", "", "", "", "").inc()
        elif self.level == "debug":
            self.interface_events_total.labels(
                kind, "", "", "", "", str(retries)).inc()
        else:
            labels = (kind, ifname, str(ifindex), netns, mac, str(retries))
            # refresh the deadline BEFORE incrementing: the janitor re-checks
            # deadlines under the lock at removal time, so an increment can
            # never be swallowed by a concurrent expiry
            self._schedule_trace_expiry(labels)
            self.interface_events_total.labels(*labels).inc()

    def _schedule_trace_expiry(self, labels: tuple[str, ...]) -> None:
        """Trace-level series have unbounded cardinality (one per interface
        identity); a single janitor thread removes each series trace_ttl_s
        after its LAST increment — re-incrementing refreshes the deadline
        (reference: per-series 5-minute goroutine)."""
        deadline = time.monotonic() + self.settings.trace_ttl_s
        with self._trace_lock:
            self._trace_expiry[labels] = deadline
            if self._trace_janitor is None:
                self._trace_janitor = threading.Thread(
                    target=self._trace_janitor_loop, name="metrics-trace-ttl",
                    daemon=True)
                self._trace_janitor.start()

    def _trace_janitor_loop(self) -> None:
        while True:
            with self._trace_lock:
                now = time.monotonic()
                due = [l for l, d in self._trace_expiry.items() if d <= now]
                for labels in due:
                    del self._trace_expiry[labels]
            for labels in due:
                with self._trace_lock:
                    if labels in self._trace_expiry:
                        continue  # refreshed since collection — keep it
                    try:
                        self.interface_events_total.remove(*labels)
                    except KeyError:
                        pass  # raced with registry-level removal
            with self._trace_lock:
                if not self._trace_expiry:
                    # nothing left to expire: exit so an idle Metrics (and
                    # its registry) can be GC'd; the next trace increment
                    # restarts the janitor
                    self._trace_janitor = None
                    return
            time.sleep(min(self.settings.trace_ttl_s / 4, 5.0))
