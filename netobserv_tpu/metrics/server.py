"""Async /metrics HTTP server with optional TLS, plus /healthz, /readyz and
the agent query surface (/query/*).

Reference analog: `pkg/prometheus/prom_server.go:27-70` (TLS1.3 minimum when
certs are configured) and the hardened defaults in `pkg/server/common.go`.

Health surface (supervision layer, agent/supervisor.py): when a
``health_source`` callable is supplied, the server also answers

- ``/healthz`` — liveness + per-stage detail. 200 while the agent runs
  (including Degraded: the process is alive and partially serving — a
  kubelet restart would lose the healthy stages too); 503 once Stopped.
- ``/readyz``  — readiness. 200 only while status is Started and no stage
  is Degraded; 503 otherwise (orchestrators pull a degraded pod out of
  rotation without killing it).

Both return the same machine-readable JSON body:
``{"status": ..., "degraded": ..., "overloaded": ..., "conditions": ...,
"stages": {name: {state, restarts, consecutive_failures, last_failure,
heartbeat_age_s, ...}}}``.

``overloaded`` (the overload controller shedding load,
docs/architecture.md "Overload & backpressure") is deliberately NOT a
readiness failure: an overloaded agent is alive and serving, trading
resolution for stability — pulling it out of rotation would shift the
same load onto its peers and cascade. Orchestrators that want to act on
it read the JSON body (or the ``sketch_shed_factor`` gauge), which also
carries the controller's live state under ``conditions.overloaded``.

Query surface: when a ``query_routes`` handler is supplied
(`netobserv_tpu/query/routes.py`, wired by the tpu-sketch exporter), the
server additionally answers ``/query/topk|frequency|cardinality|victims|
alerts|status`` against the agent's published window snapshot — host-side
only, same off-hot-path rules as /debug/traces (docs/architecture.md
"Query plane"). ``/query/alerts`` is the continuous detection plane's
view (active alerts + recent transitions; 404 with ``ALERT_RULES``
unset). Like OVERLOADED, a RAISED alert surfaces as the ``alerting``
condition in the health bodies without failing readiness — detection is
the agent working, not a broken stage.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from prometheus_client import CollectorRegistry, generate_latest
from prometheus_client.exposition import CONTENT_TYPE_LATEST

log = logging.getLogger("netobserv_tpu.metrics.server")

#: health_source contract: () -> {"status": str, "degraded": bool,
#: "stages": {...}} (FlowsAgent.health_snapshot)
HealthSource = Callable[[], dict]

_READY_STATUSES = ("Started",)
# "Stopping" stays live: the graceful shutdown performs a final eviction
# and checkpoint — a liveness 503 there would invite a force-kill that
# loses exactly the flows the source-first stop ordering preserves
_LIVE_STATUSES = ("NotStarted", "Starting", "Started", "Degraded",
                  "Stopping")


class _Handler(BaseHTTPRequestHandler):
    registry: CollectorRegistry = None  # set per-server subclass
    health_source: Optional[HealthSource] = None
    query_routes = None  # netobserv_tpu.query.routes.QueryRoutes

    def do_GET(self):  # noqa: N802 - http.server API
        path = self.path.split("?")[0]
        if path in ("/healthz", "/readyz"):
            self._serve_health(path)
            return
        if path == "/query" or path.startswith("/query/"):
            self._serve_query()
            return
        if path not in ("/metrics", "/"):
            self.send_error(404)
            return
        payload = generate_latest(self.registry)
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE_LATEST)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_query(self) -> None:
        """/query/* — the agent query plane (netobserv_tpu/query). All the
        route/param logic lives in QueryRoutes so the federation surface
        and tests share it; this method only speaks HTTP."""
        if self.query_routes is None:
            self.send_error(404, explain="no query source configured "
                            "(EXPORT=tpu-sketch serves one)")
            return
        url = urlparse(self.path)
        params = {k: v[0] for k, v in parse_qs(url.query).items()}
        code, obj = self.query_routes.handle(url.path, params)
        payload = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def _serve_health(self, path: str) -> None:
        if self.health_source is None:
            self.send_error(404, explain="no health source configured")
            return
        try:
            health = self.health_source()
        except Exception as exc:  # a broken probe must still answer
            health = {"status": "Unknown", "degraded": True,
                      "error": str(exc), "stages": {}}
        status = health.get("status", "Unknown")
        degraded = bool(health.get("degraded"))
        if path == "/readyz":
            ok = status in _READY_STATUSES and not degraded
        else:
            ok = status in _LIVE_STATUSES
        payload = json.dumps(health, separators=(",", ":")).encode()
        self.send_response(200 if ok else 503)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):  # quiet access logs
        log.debug("metrics http: " + fmt, *args)


def start_metrics_server(registry: CollectorRegistry, address: str = "",
                         port: int = 9090, tls_cert_path: str = "",
                         tls_key_path: str = "",
                         health_source: Optional[HealthSource] = None,
                         query_routes=None,
                         ) -> ThreadingHTTPServer:
    """Start the exposition server on a daemon thread; returns the server
    (call .shutdown() to stop)."""
    # staticmethod keeps a plain-function health_source from being rebound
    # as an instance method of the handler (which would call it with `self`
    # and turn every probe into a swallowed TypeError -> "Unknown" 503);
    # bound methods like FlowsAgent.health_snapshot pass through unchanged
    handler = type("Handler", (_Handler,),
                   {"registry": registry,
                    "query_routes": query_routes,
                    "health_source": (staticmethod(health_source)
                                      if health_source is not None
                                      else None)})
    srv = ThreadingHTTPServer((address or "0.0.0.0", port), handler)
    srv.timeout = 10  # hardened-ish defaults (reference: pkg/server/common.go)
    if tls_cert_path and tls_key_path:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(tls_cert_path, tls_key_path)
        srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    t = threading.Thread(target=srv.serve_forever, name="metrics-http",
                         daemon=True)
    t.start()
    log.info("metrics server listening on %s:%d (tls=%s, health=%s)",
             address or "0.0.0.0", srv.server_address[1],
             bool(tls_cert_path), health_source is not None)
    return srv
