"""Async /metrics HTTP server with optional TLS.

Reference analog: `pkg/prometheus/prom_server.go:27-70` (TLS1.3 minimum when
certs are configured) and the hardened defaults in `pkg/server/common.go`.
"""

from __future__ import annotations

import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from prometheus_client import CollectorRegistry, generate_latest
from prometheus_client.exposition import CONTENT_TYPE_LATEST

log = logging.getLogger("netobserv_tpu.metrics.server")


class _Handler(BaseHTTPRequestHandler):
    registry: CollectorRegistry = None  # set per-server subclass

    def do_GET(self):  # noqa: N802 - http.server API
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        payload = generate_latest(self.registry)
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE_LATEST)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):  # quiet access logs
        log.debug("metrics http: " + fmt, *args)


def start_metrics_server(registry: CollectorRegistry, address: str = "",
                         port: int = 9090, tls_cert_path: str = "",
                         tls_key_path: str = "") -> ThreadingHTTPServer:
    """Start the exposition server on a daemon thread; returns the server
    (call .shutdown() to stop)."""
    handler = type("Handler", (_Handler,), {"registry": registry})
    srv = ThreadingHTTPServer((address or "0.0.0.0", port), handler)
    srv.timeout = 10  # hardened-ish defaults (reference: pkg/server/common.go)
    if tls_cert_path and tls_key_path:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_3
        ctx.load_cert_chain(tls_cert_path, tls_key_path)
        srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    t = threading.Thread(target=srv.serve_forever, name="metrics-http",
                         daemon=True)
    t.start()
    log.info("metrics server listening on %s:%d (tls=%s)",
             address or "0.0.0.0", srv.server_address[1],
             bool(tls_cert_path))
    return srv
