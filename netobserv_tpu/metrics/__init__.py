"""Observability: declarative Prometheus metrics + HTTP exposition.

Reference analog: `pkg/metrics/metrics.go` (declarative metric defs, prefix,
verbosity levels) and `pkg/prometheus/prom_server.go` (async /metrics server
with TLS option).
"""

from netobserv_tpu.metrics.registry import Metrics, MetricsSettings  # noqa: F401
from netobserv_tpu.metrics.server import start_metrics_server  # noqa: F401
