"""RingBufTracer: drains the map-full fallback ring buffer.

Reference analog: `pkg/flow/tracer_ringbuf.go:394-471` — blocking reads of raw
flow events pushed by the kernel when the aggregation map insert failed; each
received event also signals the MapTracer to flush early (pressure relief,
`docs/ebpf_implementation.md` rationale). Off by default, like the reference
(ENABLE_FLOWS_RINGBUF_FALLBACK).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.datapath.fetcher import FlowFetcher
from netobserv_tpu.model import binfmt
from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.flow.ringbuf_tracer")

_LOG_EVERY_S = 5.0


class RingBufTracer:
    def __init__(self, fetcher: FlowFetcher, out: "queue.Queue[np.void]",
                 flusher: Optional[Callable[[], None]] = None,
                 metrics=None, poll_timeout_s: float = 0.2):
        self._fetcher = fetcher
        self._out = out
        self._flusher = flusher
        self._metrics = metrics
        self._poll = poll_timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_log = 0.0
        #: supervision hook: beats once per poll (agent/supervisor.py)
        self.heartbeat = lambda: None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="ringbuf-tracer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self._poll * 4)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            raw = faultinject.fire("ringbuf_tracer.read",
                                   self._fetcher.read_ringbuf(self._poll))
            if raw is None:
                continue
            if len(raw) != binfmt.FLOW_EVENT_DTYPE.itemsize:
                self._rate_limited_log(
                    "bad ringbuf event size %d (want %d)", len(raw),
                    binfmt.FLOW_EVENT_DTYPE.itemsize)
                continue
            event = np.frombuffer(raw, dtype=binfmt.FLOW_EVENT_DTYPE)[0]
            if self._metrics is not None:
                self._metrics.count_ringbuf_event()
            if self._flusher is not None:
                self._flusher()  # relieve map pressure with an early eviction
            try:
                self._out.put_nowait(event)
            except queue.Full:
                self._rate_limited_log("ringbuf event dropped: buffer full")

    def _rate_limited_log(self, msg: str, *args) -> None:
        now = time.monotonic()
        if now - self._last_log > _LOG_EVERY_S:
            log.warning(msg, *args)
            self._last_log = now
