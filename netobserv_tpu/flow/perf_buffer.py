"""PCA packet pipeline stages.

Reference analogs: `pkg/flow/tracer_perf.go` (PerfTracer: blocking packet
ringbuf reads -> PacketRecord) and `pkg/flow/perfbuffer.go` (PerfBuffer:
batch by size/timeout before export).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

import numpy as np

from netobserv_tpu.model import binfmt
from netobserv_tpu.model.packet_record import PacketRecord
from netobserv_tpu.model.record import MonotonicClock

log = logging.getLogger("netobserv_tpu.flow.perf")


class PerfTracer:
    """Reads raw packet events from the datapath's packet ring buffer."""

    def __init__(self, fetcher, out: "queue.Queue[PacketRecord]",
                 poll_timeout_s: float = 0.2):
        self._fetcher = fetcher
        self._out = out
        self._poll = poll_timeout_s
        self._clock = MonotonicClock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="perf-tracer",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self._poll * 4)

    def _loop(self) -> None:
        while not self._stop.is_set():
            raw = self._fetcher.read_packet(self._poll)
            if raw is None:
                continue
            if len(raw) != binfmt.PACKET_EVENT_DTYPE.itemsize:
                log.debug("bad packet event size %d", len(raw))
                continue
            ev = np.frombuffer(raw, dtype=binfmt.PACKET_EVENT_DTYPE)[0]
            cur_mono, cur_wall = self._clock.now_pair()
            rec = PacketRecord(
                if_index=int(ev["if_index"]),
                timestamp_ns=int(ev["timestamp_ns"]) + (cur_wall - cur_mono),
                payload=ev["payload"][:min(
                    int(ev["pkt_len"]), binfmt.MAX_PAYLOAD_SIZE)].tobytes())
            try:
                # brief blocking put: the ring buffer already absorbed the
                # burst, so give the batcher a moment before shedding
                self._out.put(rec, timeout=0.5)
            except queue.Full:
                log.debug("packet dropped: buffer full")


class PerfBuffer:
    """Batches packets by max size or timeout before the exporter."""

    def __init__(self, inp: "queue.Queue[PacketRecord]",
                 out: "queue.Queue[list[PacketRecord]]",
                 max_batch: int = 100, timeout_s: float = 0.5):
        self._in = inp
        self._out = out
        self._max = max_batch
        self._timeout = timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="perf-buffer",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self._timeout + 1)

    def _flush(self, batch: list[PacketRecord]) -> None:
        if not batch:
            return
        try:
            self._out.put_nowait(batch)
        except queue.Full:
            log.warning("packet batch dropped: exporter not keeping up")

    def _loop(self) -> None:
        batch: list[PacketRecord] = []
        deadline = time.monotonic() + self._timeout
        while not self._stop.is_set():
            try:
                batch.append(self._in.get(timeout=0.1))
            except queue.Empty:
                pass
            if len(batch) >= self._max or time.monotonic() >= deadline:
                self._flush(batch)
                batch = []
                deadline = time.monotonic() + self._timeout
        self._flush(batch)
