"""Flow processing pipeline stages (layer L3 in SURVEY.md §1).

Stages are threads connected by bounded queues (the gopipes-node analog,
`pkg/agent/agent.go:387-442`): MapTracer -> CapacityLimiter -> exporter, with
the optional ringbuffer fallback path RingBufTracer -> Accounter feeding the
same limiter. Backpressure is explicit and lossy at exactly one point
(CapacityLimiter), like the reference.
"""

from netobserv_tpu.flow.map_tracer import MapTracer  # noqa: F401
from netobserv_tpu.flow.ringbuf_tracer import RingBufTracer  # noqa: F401
from netobserv_tpu.flow.accounter import Accounter  # noqa: F401
from netobserv_tpu.flow.limiter import CapacityLimiter  # noqa: F401
