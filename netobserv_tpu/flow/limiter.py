"""CapacityLimiter: the pipeline's single designated lossy point.

Reference analog: `pkg/flow/limiter.go` — forwards batches downstream, drops
when the exporter can't keep up, and logs drop warnings with exponential
backoff so a saturated exporter doesn't also saturate the log.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from netobserv_tpu.model.record import Record
from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.flow.limiter")

_INITIAL_LOG_PERIOD_S = 1.0
_MAX_LOG_PERIOD_S = 300.0


class CapacityLimiter:
    def __init__(self, inp: "queue.Queue[list[Record]]",
                 out: "queue.Queue[list[Record]]", metrics=None):
        self._in = inp
        self._out = out
        self._metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dropped_since_log = 0
        self._log_period = _INITIAL_LOG_PERIOD_S
        self._next_log = 0.0
        #: supervision hook: beats once per poll (agent/supervisor.py)
        self.heartbeat = lambda: None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="capacity-limiter", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1.0)
        # drain whatever arrived during/after the last get() so a final
        # eviction produced at shutdown is not lost
        while True:
            try:
                batch = self._in.get_nowait()
            except queue.Empty:
                break
            try:
                self._out.put_nowait(batch)
            except queue.Full:
                if self._metrics is not None:
                    self._metrics.count_dropped(len(batch), "limiter")
                break

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            faultinject.fire("limiter.forward")
            try:
                batch = self._in.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._out.put_nowait(batch)
                if self._metrics is not None:
                    self._metrics.buffer_size.labels("export").set(
                        self._out.qsize())
                self._log_period = _INITIAL_LOG_PERIOD_S  # recovered
            except queue.Full:
                self._dropped_since_log += len(batch)
                if self._metrics is not None:
                    self._metrics.count_dropped(len(batch), "limiter")
                now = time.monotonic()
                if now >= self._next_log:
                    log.warning(
                        "exporter is not keeping up: dropped %d flows "
                        "(next warning in %.0fs)",
                        self._dropped_since_log, self._log_period)
                    self._dropped_since_log = 0
                    self._next_log = now + self._log_period
                    self._log_period = min(
                        self._log_period * 2, _MAX_LOG_PERIOD_S)
