"""Accounter: userspace re-aggregation of ringbuffer singles.

Reference analog: `pkg/flow/account.go:180-270` — a bounded map keyed by flow
identity merges single-packet fallback events; evicts on timeout or when full,
using the same accumulate semantics as the kernel merge.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

import numpy as np

from netobserv_tpu.model import accumulate, binfmt
from netobserv_tpu.model.record import (
    MonotonicClock, Record, interface_namer, records_from_events,
)
from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.flow.accounter")


class Accounter:
    def __init__(self, inp: "queue.Queue[np.void]",
                 out: "queue.Queue[list[Record]]",
                 max_entries: int = 5000, evict_timeout_s: float = 5.0,
                 agent_ip: str = "", metrics=None, ssl_correlator=None):
        self._ssl_correlator = ssl_correlator
        self._in = inp
        self._out = out
        self._max = max_entries
        self._timeout = evict_timeout_s
        self._agent_ip = agent_ip
        self._metrics = metrics
        self._clock = MonotonicClock()
        self._entries: dict[bytes, np.void] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: supervision hook: beats once per poll (agent/supervisor.py)
        self.heartbeat = lambda: None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="accounter", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self._timeout + 1)
        self._evict()  # drain remaining entries on shutdown

    def _loop(self) -> None:
        deadline = time.monotonic() + self._timeout
        while not self._stop.is_set():
            self.heartbeat()
            faultinject.fire("accounter.loop")
            timeout = max(deadline - time.monotonic(), 0.01)
            try:
                event = self._in.get(timeout=min(timeout, 0.2))
            except queue.Empty:
                event = None
            if event is not None:
                self._account(event)
            if time.monotonic() >= deadline or len(self._entries) >= self._max:
                self._evict()
                deadline = time.monotonic() + self._timeout

    def _account(self, event: np.void) -> None:
        key = bytes(event["key"].tobytes())
        existing = self._entries.get(key)
        if existing is None:
            self._entries[key] = event.copy()
        else:
            accumulate.accumulate_base(existing["stats"], event["stats"])

    def _evict(self) -> None:
        if not self._entries:
            return
        events = np.zeros(len(self._entries), dtype=binfmt.FLOW_EVENT_DTYPE)
        for i, ev in enumerate(self._entries.values()):
            events[i] = ev
        self._entries.clear()
        records = records_from_events(
            events, clock=self._clock, agent_ip=self._agent_ip,
            namer=interface_namer())
        if self._ssl_correlator is not None:
            # ringbuf-fallback flows must not lose their plaintext credits
            for rec in records:
                n_ev, n_bytes = self._ssl_correlator.take(rec.key)
                rec.features.ssl_plaintext_events = n_ev
                rec.features.ssl_plaintext_bytes = n_bytes
        if self._metrics is not None:
            self._metrics.observe_eviction("accounter", len(records), 0.0)
        try:
            self._out.put_nowait(records)
        except queue.Full:
            if self._metrics is not None:
                self._metrics.count_dropped(len(records), "accounter")
            log.warning("accounter eviction dropped: buffer full")
