"""MapTracer: the timer-driven eviction loop.

Reference analog: `pkg/flow/tracer_map.go:42-146` — a ticker drains the kernel
aggregation map every CACHE_ACTIVE_TIMEOUT; a Flush() signal (raised by the
ringbuffer path under map pressure) forces an early eviction; only one eviction
runs at a time.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from netobserv_tpu.datapath.fetcher import FlowFetcher
from netobserv_tpu.utils import faultinject, tracing
from netobserv_tpu.utils.dnsnames import decode_qname
from netobserv_tpu.model.record import (
    InterfaceNamer, MonotonicClock, Record, interface_namer,
    records_from_events,
)

log = logging.getLogger("netobserv_tpu.flow.map_tracer")


class MapTracer:
    def __init__(self, fetcher: FlowFetcher, out: "queue.Queue[list[Record]]",
                 active_timeout_s: float = 5.0, agent_ip: str = "",
                 namer: Optional[InterfaceNamer] = None,
                 metrics=None, stale_purge_s: float = 5.0,
                 columnar: bool = False, udn_mapper=None,
                 force_gc: bool = False, ssl_correlator=None,
                 map_capacity: int = 0,
                 pressure_watermark: float = 0.0,
                 occupancy_sink=None):
        self._fetcher = fetcher
        self._out = out
        self._timeout = active_timeout_s
        # map-pressure relief (MAP_PRESSURE_WATERMARK): when a drain finds
        # the kernel aggregation map at or above watermark * capacity, the
        # next eviction comes EARLY — at half the configured period, so the
        # cadence is bounded at 2x — shrinking the window in which a full
        # map spills into the ringbuf fallback (whose singles can
        # double-count across interfaces). Both values 0 = disabled.
        self._map_capacity = map_capacity
        self._pressure_watermark = pressure_watermark
        self._pressure_relief = False
        # optional per-DRAIN occupancy observer (the sketch exporter's
        # fleet-telemetry block rides it): one callable-or-None check per
        # drain, never per record; errors are the observer's problem, not
        # the eviction loop's
        self._occupancy_sink = occupancy_sink
        self._agent_ip = agent_ip
        self._namer = namer
        self._clock = MonotonicClock()
        self._metrics = metrics
        self._stale_purge_s = stale_purge_s
        # columnar mode: forward EvictedFlows untouched (no per-record Python
        # objects) for exporters that consume columns directly (tpu-sketch)
        self._columnar = columnar
        self._udn_mapper = udn_mapper  # ifaces.udn.UdnMapper when enabled
        # flow/ssl_correlator.SSLCorrelator when OpenSSL tracking is on:
        # enrichment consumes its per-flow plaintext counters
        self._ssl_correlator = ssl_correlator
        if columnar and udn_mapper is not None:
            log.warning("UDN mapping is a no-op on the columnar fast path "
                        "(records are never materialized)")
        # FORCE_GARBAGE_COLLECTION parity: collect after each eviction so
        # the burst of short-lived record objects returns to the allocator
        # (record path only — the columnar path births no per-record objects)
        self._force_gc = force_gc
        self._flush = threading.Event()
        self._stop = threading.Event()
        # one eviction at a time — ALSO load-bearing for the parallel
        # drain lanes (loader.BpfmanFetcher): a lane's zero-copy views
        # alias its map's cached batch buffers until decode copies them
        # out, so two concurrent lookup_and_delete calls would rewrite
        # buffers under a live decode; this lock is what serializes them
        self._evict_lock = threading.Lock()
        self._drain_lanes_logged = False
        if metrics is not None:
            # one-time sync: library-load failures happened at import,
            # before any registry existed (the counted-fallback contract —
            # flowpack._find_lib warns AND counts instead of raising)
            from netobserv_tpu.datapath import flowpack
            if flowpack.abi_fallbacks:
                metrics.flowpack_abi_fallback_total.inc(
                    flowpack.abi_fallbacks)
        self._thread: Optional[threading.Thread] = None
        #: supervision hook (agent/supervisor.py): the loop beats once per
        #: wakeup; the supervisor replaces this no-op at registration
        self.heartbeat = lambda: None

    def flush(self) -> None:
        """Force an early eviction (map-pressure relief)."""
        self._flush.set()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="map-tracer", daemon=True)
        self._thread.start()

    def stop(self, final_evict: bool = True) -> None:
        self._stop.set()
        self._flush.set()
        if self._thread:
            self._thread.join(timeout=self._timeout + 2)
        if final_evict:
            self._evict_once()

    def _loop(self) -> None:
        while not self._stop.is_set():
            # wait for either the ticker period or an explicit flush; under
            # map pressure the period halves (bounded 2x cadence)
            self._flush.wait(timeout=(self._timeout / 2
                                      if self._pressure_relief
                                      else self._timeout))
            self._flush.clear()
            self.heartbeat()
            if self._stop.is_set():
                return
            faultinject.fire("map_tracer.evict")
            self._evict_once()

    def _evict_once(self) -> None:
        with self._evict_lock:
            self._evict_locked()

    def _check_map_pressure(self, drained: int) -> None:
        """Drive the pressure-relief latch from this drain's occupancy (a
        drain empties the map, so its size IS the occupancy the drain
        interval accumulated). At or above the watermark the next eviction
        comes at half period. A LATCHED relief sustains down to HALF the
        watermark: halved drains accumulate roughly half the flows, so
        without hysteresis any watermark > 0.5 would oscillate latched/
        clear on alternating drains (and re-log every other cycle) instead
        of holding until load genuinely drops."""
        if not self._map_capacity:
            return
        occupancy = drained / self._map_capacity
        # the histogram populates whenever capacity is known — it is the
        # evidence for whether to set the watermark at all; only the
        # relief latch below is gated on the knob
        if self._metrics is not None:
            self._metrics.map_occupancy_ratio.observe(occupancy)
        if self._occupancy_sink is not None:
            try:
                self._occupancy_sink(occupancy)
            except Exception:
                log.debug("occupancy sink failed", exc_info=True)
        if not self._pressure_watermark:
            return
        pressured = occupancy >= self._pressure_watermark
        sustained = (self._pressure_relief
                     and occupancy >= self._pressure_watermark / 2)
        relief = pressured or sustained
        if relief:
            # stage-boundary chaos seam: per drain, never per record
            faultinject.fire("map_tracer.pressure_evict")
            if not self._pressure_relief:
                log.warning(
                    "kernel map at %.0f%% of capacity (>= watermark %.0f%%);"
                    " halving the eviction period until pressure clears",
                    occupancy * 100, self._pressure_watermark * 100)
            if self._metrics is not None:
                self._metrics.map_pressure_evictions_total.inc()
        self._pressure_relief = relief

    def _evict_locked(self) -> None:
        # flight recorder: a batch trace is born here and rides the evicted
        # batch to the exporter fold (columnar path); un-sampled evictions
        # get the shared NULL trace — no timestamps, no locks
        trace = tracing.start_trace("batch")
        t0 = time.perf_counter()
        with trace.stage("evict"):
            # bind the sampled trace for the drain's child spans
            # (decode/merge_percpu/align in the columnar eviction plane);
            # unsampled drains pay one bool check
            if trace.sampled:
                tracing.set_active(trace)
            try:
                evicted = self._fetcher.lookup_and_delete()
            finally:
                if trace.sampled:
                    tracing.clear_active()
            # purge orphaned auxiliary entries (e.g. DNS never answered)
            purge = getattr(self._fetcher, "purge_stale", None)
            if purge is not None:
                purge(self._stale_purge_s)
        if self._metrics is not None:
            self._metrics.observe_eviction(
                "map", len(evicted), time.perf_counter() - t0)
            self._metrics.evicted_flows_per_drain.observe(len(evicted))
            ds = getattr(evicted, "decode_stats", None)
            if ds is not None:
                self._metrics.eviction_decode_seconds.observe(
                    ds.get("seconds", 0.0))
                if not self._drain_lanes_logged and ds.get("drain_lanes"):
                    # once per process: which drain topology this agent
                    # actually resolved (EVICT_DRAIN_LANES auto rule)
                    self._drain_lanes_logged = True
                    log.info("eviction drain running with %d lane(s)",
                             ds["drain_lanes"])
                # ringbuf-fallback singles (feature rows whose flow missed
                # the aggregation drain) — the one known double-count
                # overload path, now observable per drain
                fallback = ds.get("fallback_rows", 0)
                if fallback:
                    self._metrics.evict_ringbuf_fallback_total.inc(fallback)
                # fused native pipeline (EVICT_NATIVE_PIPELINE): which host
                # path carried this drain + the fused call's per-stage split
                path = ds.get("native_path")
                if path:
                    self._metrics.flowpack_native_calls_total.labels(
                        path).inc()
                native = ds.get("native")
                if native is not None:
                    for stage in ("drain", "merge", "join", "pack"):
                        (self._metrics.host_native_pipeline_seconds
                         .labels(stage).observe(native.get(f"{stage}_s",
                                                           0.0)))
            self._metrics.buffer_size.labels("evicted").set(
                self._out.qsize())
            for key, val in self._fetcher.read_global_counters().items():
                self._metrics.add_global_counter(key, val)
        self._check_map_pressure(len(evicted))
        if self._force_gc and not self._columnar:
            # FORCE_GARBAGE_COLLECTION parity is for the record path's burst
            # of short-lived objects; the columnar fast path materializes no
            # per-record Python objects, so a collect there is pure stall
            import gc
            gc.collect()
        if len(evicted) == 0:
            return  # idle eviction: drop the trace unrecorded (no flows)
        if self._columnar:
            if trace.sampled:
                evicted.trace = trace  # the exporter fold finishes it
            try:
                self._out.put_nowait(evicted)
            except queue.Full:
                if self._metrics is not None:
                    self._metrics.count_dropped(len(evicted), "map_tracer")
                log.warning("eviction dropped: downstream buffer full "
                            "(%d flows)", len(evicted))
                trace.finish()  # never reaches the fold — seal what we have
            return
        with trace.stage("enrich"):
            namer = self._namer or interface_namer()
            records = records_from_events(
                evicted.events, clock=self._clock, agent_ip=self._agent_ip,
                namer=namer)
            _attach_features(records, evicted,
                             ssl_correlator=self._ssl_correlator)
            if self._udn_mapper is not None:
                for rec in records:
                    rec.udn = self._udn_mapper.udn_for(rec.interface)
                    rec.dup_list = [
                        (name, d, self._udn_mapper.udn_for(name))
                        for name, d, _u in rec.dup_list]
        # record batches are plain lists and cannot carry a trace context;
        # the record path's trace ends at enqueue (evict + enrich spans)
        trace.finish()
        try:
            self._out.put_nowait(records)
        except queue.Full:
            # downstream full: the limiter's role; count and drop
            if self._metrics is not None:
                self._metrics.count_dropped(len(records), "map_tracer")
            log.warning("eviction dropped: downstream buffer full (%d records)",
                        len(records))


def _attach_features(records: list[Record], evicted,
                     ssl_correlator=None) -> None:
    """Copy per-feature arrays onto the enriched records (already merged)."""
    for i, rec in enumerate(records):
        f = rec.features
        if ssl_correlator is not None:
            n_ev, n_bytes = ssl_correlator.take(rec.key)
            f.ssl_plaintext_events = n_ev
            f.ssl_plaintext_bytes = n_bytes
        if evicted.dns is not None and i < len(evicted.dns):
            d = evicted.dns[i]
            f.dns_id = int(d["dns_id"])
            f.dns_flags = int(d["dns_flags"])
            f.dns_latency_ns = int(d["latency_ns"])
            f.dns_errno = int(d["errno"])
            f.dns_name = decode_qname(bytes(d["name"]))
        if evicted.drops is not None and i < len(evicted.drops):
            d = evicted.drops[i]
            f.drop_bytes = int(d["bytes"])
            f.drop_packets = int(d["packets"])
            f.drop_latest_flags = int(d["latest_flags"])
            f.drop_latest_state = int(d["latest_state"])
            f.drop_latest_cause = int(d["latest_cause"])
        if evicted.extra is not None and i < len(evicted.extra):
            e = evicted.extra[i]
            f.rtt_ns = int(e["rtt_ns"])
            f.ipsec_encrypted = bool(e["ipsec_encrypted"])
            f.ipsec_encrypted_ret = int(e["ipsec_ret"])
        if evicted.xlat is not None and i < len(evicted.xlat):
            x = evicted.xlat[i]
            if x["src_ip"].any() or x["dst_ip"].any():
                f.xlat_src_ip = x["src_ip"].tobytes()
                f.xlat_dst_ip = x["dst_ip"].tobytes()
                f.xlat_src_port = int(x["src_port"])
                f.xlat_dst_port = int(x["dst_port"])
                f.xlat_zone_id = int(x["zone_id"])
        if evicted.nevents is not None and i < len(evicted.nevents):
            n = evicted.nevents[i]
            # n_events is a wrapping ring cursor (accumulate_network_events),
            # not a count: render every occupied slot instead, keyed on
            # packets[j] != 0 like the reference (pkg/model/record.go:129-131)
            for j in range(n["events"].shape[0]):
                if int(n["packets"][j]) != 0 or n["events"][j].any():
                    f.network_events.append(n["events"][j].tobytes())
        if evicted.quic is not None and i < len(evicted.quic):
            q = evicted.quic[i]
            f.quic_version = int(q["version"])
            f.quic_seen_long_hdr = bool(q["seen_long_hdr"])
            f.quic_seen_short_hdr = bool(q["seen_short_hdr"])
