"""SSL event tracer: drains the OpenSSL-uprobe plaintext ring buffer.

Reference analog: the SSL ringbuf variant of `pkg/flow/tracer_ringbuf.go`
(NewSSLRingBufTracer, `:403,473-527`): events carry (timestamp, pid_tgid,
direction, plaintext) from the SSL_write uprobe; a handler receives decoded
events (the reference forwards them to a correlation cache that flags flows
whose ciphertext/plaintext accounting mismatches).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.model import binfmt
from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.flow.ssl_tracer")


@dataclass
class SSLEvent:
    timestamp_ns: int
    pid: int
    tid: int
    direction: int  # 1 = write
    data: bytes


SSLHandler = Callable[[SSLEvent], None]


def decode_ssl_event(raw: bytes) -> Optional[SSLEvent]:
    if len(raw) != binfmt.SSL_EVENT_DTYPE.itemsize:
        return None
    ev = np.frombuffer(raw, dtype=binfmt.SSL_EVENT_DTYPE)[0]
    n = max(0, min(int(ev["data_len"]), binfmt.MAX_SSL_DATA))
    pid_tgid = int(ev["pid_tgid"])
    return SSLEvent(
        timestamp_ns=int(ev["timestamp_ns"]),
        pid=pid_tgid >> 32, tid=pid_tgid & 0xFFFFFFFF,
        direction=int(ev["ssl_type"]),
        data=ev["data"][:n].tobytes())


class SSLTracer:
    """Blocking reader over the datapath's ssl_events ring buffer."""

    def __init__(self, fetcher, handler: SSLHandler,
                 poll_timeout_s: float = 0.2):
        self._fetcher = fetcher
        self._handler = handler
        self._poll = poll_timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: supervision hook: beats once per poll (agent/supervisor.py)
        self.heartbeat = lambda: None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, name="ssl-tracer",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self._poll * 4)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            raw = faultinject.fire("ssl_tracer.read",
                                   self._fetcher.read_ssl(self._poll))
            if raw is None:
                continue
            event = decode_ssl_event(raw)
            if event is None:
                log.debug("bad ssl event size %d", len(raw))
                continue
            try:
                self._handler(event)
            except Exception as exc:
                log.error("ssl handler failed: %s", exc)
