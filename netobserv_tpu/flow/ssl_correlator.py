"""SSL plaintext <-> flow correlation.

The OpenSSL uprobe events (flow/ssl_tracer.py) carry only (pid, timestamp,
plaintext); the flow datapath keys on 5-tuples. This bridges them in
userspace: the event's pid is resolved to its live TCP sockets through
procfs (/proc/<pid>/fd -> socket:[inode] -> /proc/net/tcp{,6} rows), and the
plaintext activity is credited to those flow keys; MapTracer enrichment then
surfaces `ssl_plaintext_events/bytes` on matching Records.

Reference analog: `pkg/flow/tracer_ringbuf.go:136-190` receives the same
events but only logs and counts them — the association with flows is this
framework's extension (VERDICT round-1 item #10 asked for exactly this).

The pid->sockets resolver is pluggable (tests inject a fake); the procfs
implementation caches per-pid results briefly since one SSL_write burst
produces many events for the same connection set.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

from netobserv_tpu.model.flow import FlowKey

log = logging.getLogger("netobserv_tpu.flow.ssl_correlator")

# (local_ip_16, local_port, remote_ip_16, remote_port)
SocketTuple = tuple[bytes, int, bytes, int]
PidResolver = Callable[[int], list[SocketTuple]]


def _parse_proc_net_tcp(path: str, want_inodes: set[str],
                        v6: bool) -> dict[str, SocketTuple]:
    """inode -> socket tuple for rows of /proc/net/tcp or tcp6."""
    out: dict[str, SocketTuple] = {}
    try:
        with open(path) as fh:
            next(fh)  # header
            for line in fh:
                parts = line.split()
                if len(parts) < 10:
                    continue
                inode = parts[9]
                if inode not in want_inodes:
                    continue
                if parts[3] != "01":
                    # only ESTABLISHED connections map to trackable flows;
                    # LISTEN/TIME_WAIT rows would credit keys like
                    # local<->0.0.0.0:0 that no eviction can ever consume
                    continue
                laddr, lport = parts[1].rsplit(":", 1)
                raddr, rport = parts[2].rsplit(":", 1)
                out[inode] = (_hexaddr_to_16(laddr, v6), int(lport, 16),
                              _hexaddr_to_16(raddr, v6), int(rport, 16))
    except OSError:
        pass
    return out


def _hexaddr_to_16(hexaddr: str, v6: bool) -> bytes:
    """procfs hex address (little-endian 32-bit words) -> 16-byte form."""
    raw = bytes.fromhex(hexaddr)
    if not v6:
        # the 32-bit group is little-endian: reversing yields network-order
        # bytes, wrapped in the v4-mapped 16-byte form
        return b"\x00" * 10 + b"\xff\xff" + raw[::-1]
    # v6: four LE 32-bit groups
    words = [raw[i:i + 4][::-1] for i in range(0, 16, 4)]
    return b"".join(words)


def procfs_resolver(pid: int) -> list[SocketTuple]:
    """Live TCP sockets owned by pid, via /proc (needs same-host visibility;
    CAP_SYS_PTRACE or same-user for foreign processes)."""
    inodes: set[str] = set()
    try:
        fd_dir = f"/proc/{pid}/fd"
        for fd in os.listdir(fd_dir):
            try:
                target = os.readlink(os.path.join(fd_dir, fd))
            except OSError:
                continue
            if target.startswith("socket:["):
                inodes.add(target[8:-1])
    except OSError:
        return []
    if not inodes:
        return []
    found = _parse_proc_net_tcp("/proc/net/tcp", inodes, v6=False)
    found.update(_parse_proc_net_tcp("/proc/net/tcp6", inodes, v6=True))
    return list(found.values())


class SSLCorrelator:
    """Accumulates per-flow-key SSL plaintext counters, consumed at
    enrichment time (MapTracer._attach_features)."""

    def __init__(self, resolver: Optional[PidResolver] = None,
                 pid_cache_ttl_s: float = 1.0, max_keys: int = 8192):
        self._resolver = resolver or procfs_resolver
        self._ttl = pid_cache_ttl_s
        self._pid_cache: dict[int, tuple[float, list[SocketTuple]]] = {}
        self._counters: dict[bytes, tuple[int, int]] = {}  # key -> (n, bytes)
        self._max_keys = max_keys
        self._lock = threading.Lock()

    def observe(self, event) -> int:
        """Credit one SSLEvent to the pid's flows; returns flows credited."""
        now = time.monotonic()
        with self._lock:
            cached = self._pid_cache.get(event.pid)
        if cached is not None and now - cached[0] < self._ttl:
            tuples = cached[1]
        else:
            tuples = self._resolver(event.pid)
            with self._lock:
                if len(self._pid_cache) >= 1024:
                    # evict the oldest half BEFORE inserting, so the entry
                    # just resolved survives (clearing after insert made the
                    # cache useless exactly at >1024 active pids)
                    from itertools import islice
                    for stale in list(islice(self._pid_cache, 512)):
                        del self._pid_cache[stale]
                self._pid_cache[event.pid] = (now, tuples)
        credited = 0
        with self._lock:
            if len(self._counters) >= self._max_keys:
                # bound never-consumed credits (filtered flows, orientations
                # the kernel never tracked): drop the oldest half — dicts
                # preserve insertion order, so this is a crude FIFO eviction
                from itertools import islice
                for stale in list(islice(self._counters,
                                         self._max_keys // 2)):
                    del self._counters[stale]
            for laddr, lport, raddr, rport in tuples:
                # credit both orientations: SSL I/O belongs to the local
                # endpoint, but the kernel may key this flow egress
                # (local->remote) or ingress (remote->local)
                for key in (
                    FlowKey(laddr, raddr, lport, rport, 6),
                    FlowKey(raddr, laddr, rport, lport, 6),
                ):
                    kb = self._pack(key)
                    n, b = self._counters.get(kb, (0, 0))
                    self._counters[kb] = (n + 1, b + len(event.data))
                    credited += 1
        return credited

    @staticmethod
    def _pack(key: FlowKey) -> bytes:
        return (key.src_ip + key.dst_ip
                + key.src_port.to_bytes(2, "little")
                + key.dst_port.to_bytes(2, "little")
                + bytes([key.proto]))

    def take(self, key: FlowKey) -> tuple[int, int]:
        """Consume (events, bytes) credited to a flow key (zeroing them)."""
        with self._lock:
            return self._counters.pop(self._pack(key), (0, 0))

    def pending(self) -> int:
        with self._lock:
            return len(self._counters)
