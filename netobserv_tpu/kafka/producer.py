"""Produce-only Kafka client: metadata discovery + record-batch v2 produce.

Supports TLS, SASL PLAIN and SCRAM-SHA-256/512, acks control, batching by
message count/bytes. Compression codecs are accepted but sent uncompressed
(codec "none"); gzip is implemented since it's stdlib.
"""

from __future__ import annotations

import base64
import gzip
import hashlib
import hmac as hmac_mod
import logging
import os
import socket
import ssl
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from netobserv_tpu.kafka import wire
from netobserv_tpu.kafka.wire import Reader, crc32c, karray, kbytes, kstr, varint

log = logging.getLogger("netobserv_tpu.kafka")

API_PRODUCE = 0
API_METADATA = 3
API_SASL_HANDSHAKE = 17
API_SASL_AUTHENTICATE = 36

_CLIENT_ID = "netobserv-tpu"


@dataclass
class TLSSettings:
    enable: bool = False
    insecure_skip_verify: bool = False
    ca_path: str = ""
    cert_path: str = ""
    key_path: str = ""


@dataclass
class SASLSettings:
    enable: bool = False
    mechanism: str = "plain"  # plain | scram-sha256 | scram-sha512
    username: str = ""
    password: str = ""


class _Conn:
    """One broker connection with request/response framing."""

    def __init__(self, host: str, port: int, tls: TLSSettings,
                 sasl: SASLSettings, timeout_s: float = 10.0):
        sock = socket.create_connection((host, port), timeout=timeout_s)
        if tls.enable:
            ctx = ssl.create_default_context(
                cafile=tls.ca_path or None)
            if tls.insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if tls.cert_path:
                ctx.load_cert_chain(tls.cert_path, tls.key_path or None)
            sock = ctx.wrap_socket(sock, server_hostname=host)
        self._sock = sock
        self._corr = 0
        self._lock = threading.Lock()
        if sasl.enable:
            self._authenticate(sasl)

    def request(self, api_key: int, api_version: int, body: bytes,
                expect_response: bool = True) -> Optional[Reader]:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = struct.pack(">hhi", api_key, api_version, corr) + \
                kstr(_CLIENT_ID)
            frame = header + body
            self._sock.sendall(struct.pack(">i", len(frame)) + frame)
            if not expect_response:
                # brokers send nothing back for acks=0 produce requests
                return None
            resp = self._read_frame()
        r = Reader(resp)
        got_corr = r.i32()
        if got_corr != corr:
            raise IOError(f"kafka correlation mismatch {got_corr} != {corr}")
        return r

    def _read_frame(self) -> bytes:
        hdr = self._recv_exact(4)
        (n,) = struct.unpack(">i", hdr)
        return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("kafka broker closed connection")
            buf += chunk
        return buf

    def _authenticate(self, sasl: SASLSettings) -> None:
        mech = {"plain": "PLAIN", "scram-sha256": "SCRAM-SHA-256",
                "scram-sha512": "SCRAM-SHA-512"}[sasl.mechanism.lower()]
        r = self.request(API_SASL_HANDSHAKE, 1, kstr(mech))
        err = r.i16()
        if err:
            raise IOError(f"SASL handshake rejected (error {err})")
        if mech == "PLAIN":
            token = b"\x00" + sasl.username.encode() + b"\x00" + \
                sasl.password.encode()
            self._sasl_auth(token)
        else:
            self._scram(sasl, mech)

    def _sasl_auth(self, token: bytes) -> bytes:
        r = self.request(API_SASL_AUTHENTICATE, 0, kbytes(token))
        err = r.i16()
        msg = r.string()
        if err:
            raise IOError(f"SASL auth failed (error {err}): {msg}")
        return r.bytes_() or b""

    def _scram(self, sasl: SASLSettings, mech: str) -> None:
        algo = hashlib.sha256 if mech.endswith("256") else hashlib.sha512
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f"n={sasl.username},r={nonce}"
        server_first = self._sasl_auth(f"n,,{first_bare}".encode()).decode()
        parts = dict(p.split("=", 1) for p in server_first.split(","))
        it = int(parts["i"])
        salt = base64.b64decode(parts["s"])
        rnonce = parts["r"]
        salted = hashlib.pbkdf2_hmac(
            algo().name, sasl.password.encode(), salt, it)
        client_key = hmac_mod.new(salted, b"Client Key", algo).digest()
        stored = algo(client_key).digest()
        without_proof = f"c=biws,r={rnonce}"
        auth_msg = f"{first_bare},{server_first},{without_proof}".encode()
        sig = hmac_mod.new(stored, auth_msg, algo).digest()
        proof = base64.b64encode(
            bytes(a ^ b for a, b in zip(client_key, sig))).decode()
        self._sasl_auth(f"{without_proof},p={proof}".encode())

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _record_batch(records: list[tuple[bytes | None, bytes]],
                  compression: str = "none") -> bytes:
    """Encode one record batch (message format v2)."""
    now_ms = int(time.time() * 1000)
    body = b""
    for i, (key, value) in enumerate(records):
        rec = b"\x00"  # attributes
        rec += varint(0)  # timestamp delta
        rec += varint(i)  # offset delta
        rec += varint(len(key)) + key if key is not None else varint(-1)
        rec += varint(len(value)) + value
        rec += varint(0)  # headers
        body += varint(len(rec)) + rec
    attrs = 0
    if compression == "gzip":
        body = gzip.compress(body)
        attrs = 1
    # crc32c covers everything AFTER the crc field:
    crc_payload = struct.pack(">hi", attrs, len(records) - 1)
    crc_payload += struct.pack(">qq", now_ms, now_ms)  # first/max timestamp
    crc_payload += struct.pack(">qhi", -1, -1, -1)  # producerId/epoch/baseSeq
    crc_payload += struct.pack(">i", len(records))
    crc_payload += body
    # batchLength counts partitionLeaderEpoch(4) + magic(1) + crc(4) + payload
    batch_len = 4 + 1 + 4 + len(crc_payload)
    return (struct.pack(">qi", 0, batch_len)      # baseOffset, batchLength
            + struct.pack(">i", 0)                 # partitionLeaderEpoch
            + struct.pack(">b", 2)                 # magic
            + struct.pack(">I", crc32c(crc_payload))
            + crc_payload)


class KafkaProducer:
    def __init__(self, brokers: list[str], topic: str, acks: int = 1,
                 tls: TLSSettings = TLSSettings(),
                 sasl: SASLSettings = SASLSettings(),
                 compression: str = "none", timeout_s: float = 10.0):
        self._brokers = [self._parse(b) for b in brokers]
        self._topic = topic
        self._acks = acks
        self._tls = tls
        self._sasl = sasl
        self._compression = "gzip" if compression == "gzip" else "none"
        if compression not in ("none", "gzip"):
            log.warning("compression %r unsupported; sending uncompressed",
                        compression)
        self._timeout = timeout_s
        self._meta_conn: Optional[_Conn] = None
        self._leader_conns: dict[int, _Conn] = {}
        self._partitions: list[tuple[int, int]] = []  # (partition, leader id)
        self._broker_addrs: dict[int, tuple[str, int]] = {}
        self._refresh_metadata()

    @staticmethod
    def _parse(b: str) -> tuple[str, int]:
        host, _, port = b.rpartition(":")
        return host or b, int(port) if port.isdigit() else 9092

    def _connect_any(self) -> _Conn:
        last: Exception = RuntimeError("no brokers")
        for host, port in self._brokers:
            try:
                return _Conn(host, port, self._tls, self._sasl, self._timeout)
            except OSError as exc:
                last = exc
        raise last

    def _refresh_metadata(self) -> None:
        if self._meta_conn is None:
            self._meta_conn = self._connect_any()
        body = karray([kstr(self._topic)])
        r = self._meta_conn.request(API_METADATA, 1, body)
        n_brokers = r.i32()
        self._broker_addrs = {}
        for _ in range(n_brokers):
            node = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            self._broker_addrs[node] = (host, port)
        r.i32()  # controller id
        n_topics = r.i32()
        self._partitions = []
        for _ in range(n_topics):
            err = r.i16()
            name = r.string()
            r.i8()  # is_internal
            n_parts = r.i32()
            for _ in range(n_parts):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                if name == self._topic and not perr:
                    self._partitions.append((pid, leader))
            if err:
                raise IOError(f"kafka topic metadata error {err} for {name}")
        if not self._partitions:
            raise IOError(f"no partitions for topic {self._topic}")

    def _leader_conn(self, leader: int) -> _Conn:
        conn = self._leader_conns.get(leader)
        if conn is None:
            host, port = self._broker_addrs[leader]
            conn = _Conn(host, port, self._tls, self._sasl, self._timeout)
            self._leader_conns[leader] = conn
        return conn

    def partition_for(self, key: bytes | None) -> tuple[int, int]:
        if key is None:
            idx = int(time.monotonic_ns() // 1000) % len(self._partitions)
        else:
            # partition assignment needs no cross-client compatibility; use
            # C-speed zlib.crc32 instead of the pure-python crc32c
            import zlib
            idx = zlib.crc32(key) % len(self._partitions)
        return self._partitions[idx]

    def send_batch(self, messages: list[tuple[bytes | None, bytes]]) -> None:
        """Send (key, value) messages, grouped by partition, one produce call
        per leader."""
        by_partition: dict[int, list] = {}
        leaders: dict[int, int] = {}
        for key, value in messages:
            pid, leader = self.partition_for(key)
            by_partition.setdefault(pid, []).append((key, value))
            leaders[pid] = leader
        by_leader: dict[int, dict[int, list]] = {}
        for pid, msgs in by_partition.items():
            by_leader.setdefault(leaders[pid], {})[pid] = msgs
        for leader, parts in by_leader.items():
            self._produce(leader, parts)

    def _produce(self, leader: int, parts: dict[int, list]) -> None:
        try:
            self._produce_once(leader, parts)
        except (OSError, ConnectionError):
            # the usual reason a send fails is that partition leadership
            # moved: refresh metadata, then re-group the partitions by their
            # *current* leaders before retrying (not the stale leader id)
            self._leader_conns.pop(leader, None)
            self._refresh_metadata()
            current = dict(self._partitions)
            regrouped: dict[int, dict[int, list]] = {}
            for pid, msgs in parts.items():
                new_leader = current.get(pid)
                if new_leader is None:
                    raise IOError(
                        f"partition {pid} missing after metadata refresh")
                regrouped.setdefault(new_leader, {})[pid] = msgs
            for new_leader, new_parts in regrouped.items():
                self._produce_once(new_leader, new_parts)

    def _produce_once(self, leader: int, parts: dict[int, list]) -> None:
        partition_data = []
        for pid, msgs in parts.items():
            batch = _record_batch(msgs, self._compression)
            partition_data.append(struct.pack(">i", pid) + kbytes(batch))
        topic_data = karray([kstr(self._topic) + karray(partition_data)])
        body = kstr(None) + struct.pack(">hi", self._acks,
                                        int(self._timeout * 1000)) + topic_data
        conn = self._leader_conn(leader)
        expect = self._acks != 0
        r = conn.request(API_PRODUCE, 3, body, expect_response=expect)
        if self._acks:
            n_topics = r.i32()
            for _ in range(n_topics):
                r.string()
                for _ in range(r.i32()):
                    r.i32()  # partition
                    err = r.i16()
                    r.i64()  # base offset
                    r.i64()  # log append time
                    if err:
                        raise IOError(f"kafka produce error {err}")

    def close(self) -> None:
        for conn in self._leader_conns.values():
            conn.close()
        if self._meta_conn is not None:
            self._meta_conn.close()
