"""Kafka protocol primitives: framing, primitive encoders, crc32c, varints."""

from __future__ import annotations

import struct

# ---------------------------------------------------------------------------
# crc32c (Castagnoli) — required by record-batch v2; slice-by-8 tables keep the
# pure-python loop to one iteration per 8 bytes (native crc comes with the C++
# packer later)
# ---------------------------------------------------------------------------
_CRC32C_POLY = 0x82F63B78
_T = [[0] * 256 for _ in range(8)]
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _CRC32C_POLY if _c & 1 else _c >> 1
    _T[0][_i] = _c
for _i in range(256):
    _c = _T[0][_i]
    for _k in range(1, 8):
        _c = _T[0][_c & 0xFF] ^ (_c >> 8)
        _T[_k][_i] = _c


def crc32c(data: bytes) -> int:
    # prefer the native implementation (~100x) when flowpack is built
    native = _native_crc32c()
    if native is not None:
        result = native(data)
        if result is not None:
            return result
    return _crc32c_py(data)


_native_cached = None


def _native_crc32c():
    global _native_cached
    if _native_cached is None:
        try:
            from netobserv_tpu.datapath.flowpack import crc32c as fp_crc
            _native_cached = fp_crc
        except Exception:  # flowpack unavailable: stick with pure python
            _native_cached = False
    return _native_cached if _native_cached is not False else None


def _crc32c_py(data: bytes) -> int:
    crc = 0xFFFFFFFF
    n = len(data)
    i = 0
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    while n - i >= 8:
        crc ^= (data[i] | data[i + 1] << 8 | data[i + 2] << 16
                | data[i + 3] << 24)
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
               ^ t5[(crc >> 16) & 0xFF] ^ t4[(crc >> 24) & 0xFF]
               ^ t3[data[i + 4]] ^ t2[data[i + 5]]
               ^ t1[data[i + 6]] ^ t0[data[i + 7]])
        i += 8
    for b in data[i:]:
        crc = _T[0][(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# ---------------------------------------------------------------------------
# zigzag varints (record encoding)
# ---------------------------------------------------------------------------

def zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def varint(n: int) -> bytes:
    u = zigzag(n) & 0xFFFFFFFFFFFFFFFF
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


# ---------------------------------------------------------------------------
# primitive encoders (classic, non-flexible protocol versions)
# ---------------------------------------------------------------------------

def kstr(s: str | None) -> bytes:
    if s is None:
        return struct.pack(">h", -1)
    raw = s.encode()
    return struct.pack(">h", len(raw)) + raw


def kbytes(b: bytes | None) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def karray(items: list[bytes]) -> bytes:
    return struct.pack(">i", len(items)) + b"".join(items)


class Reader:
    """Cursor over a response body."""

    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def _take(self, n: int) -> bytes:
        out = self.data[self.off:self.off + n]
        if len(out) != n:
            raise EOFError("short kafka response")
        self.off += n
        return out

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def string(self) -> str | None:
        n = self.i16()
        if n < 0:
            return None
        return self._take(n).decode()

    def bytes_(self) -> bytes | None:
        n = self.i32()
        if n < 0:
            return None
        return self._take(n)


def unzigzag(u: int) -> int:
    return (u >> 1) ^ -(u & 1)


def read_varint(data: bytes, off: int) -> tuple[int, int]:
    """Decode one zigzag varint at `off`; returns (value, next_off)."""
    shift = 0
    u = 0
    while True:
        b = data[off]
        off += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            return unzigzag(u), off
        shift += 7
