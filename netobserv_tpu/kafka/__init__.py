"""Minimal pure-Python Kafka produce-only client.

No Kafka client library exists in this image, so the wire protocol is spoken
directly: Metadata (v1) for leader discovery, Produce (v3, record-batch v2 with
crc32c), SaslHandshake/SaslAuthenticate (PLAIN/SCRAM) and TLS sockets. Only
what a flow exporter needs — no consumer, no idempotence, no transactions.

Reference analog: the segmentio/kafka-go writer usage in
`pkg/exporter/kafka_proto.go` + `pkg/agent/agent.go:283-331`.
"""

from netobserv_tpu.kafka.producer import KafkaProducer  # noqa: F401
