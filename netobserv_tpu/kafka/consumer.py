"""Fetch-side Kafka client: metadata + ListOffsets v1 + Fetch v4 with
record-batch v2 decode — the consumer leg the e2e suites (and any
FLP-transformer-style downstream) need to read the agent's topic back.

Mirrors the producer's wire layer (`kafka/producer.py` `_Conn`,
`kafka/wire.py`); same TLS/SASL settings apply. Reference analog: the
flowlogs-pipeline Kafka ingest the reference pairs its Kafka export with
(`/root/reference/e2e/kafka/manifests/20-flp-transformer.yml`).
"""

from __future__ import annotations

import gzip
import logging
import struct
from typing import Optional

from netobserv_tpu.kafka.producer import (
    API_METADATA, SASLSettings, TLSSettings, _Conn,
)
from netobserv_tpu.kafka.wire import karray, kstr, read_varint

log = logging.getLogger("netobserv_tpu.kafka")

API_FETCH = 1
API_LIST_OFFSETS = 2

EARLIEST = -2
LATEST = -1


def decode_record_batches(blob: bytes,
                          ) -> tuple[list[tuple[Optional[bytes], bytes]],
                                     Optional[int]]:
    """Decode a concatenation of record batches (message format v2) into
    (key, value) pairs, plus the offset AFTER the last complete batch
    (None if no complete batch decoded). Tolerates a trailing partial
    batch — brokers may truncate at the fetch size boundary."""
    out: list[tuple[Optional[bytes], bytes]] = []
    next_offset: Optional[int] = None
    off = 0
    while off + 17 <= len(blob):
        base_offset = struct.unpack(">q", blob[off:off + 8])[0]
        batch_len = struct.unpack(">i", blob[off + 8:off + 12])[0]
        end = off + 12 + batch_len
        if batch_len <= 0 or end > len(blob):
            break  # partial trailing batch
        if batch_len < 5:
            # the magic byte sits 5 bytes into the batch body: a corrupt
            # batch_len in 1..4 would make the read below peek past the
            # batch end and misroute the decoder — treat as partial
            break
        magic = blob[off + 16]
        if magic != 2:
            # legacy (v0/v1) message set (can legitimately be < 49 bytes):
            # not decoded, but the offset MUST still advance or poll()
            # would re-fetch this blob forever
            log.warning("skipping record batch with magic %d", magic)
            next_offset = max(next_offset or 0, base_offset + 1)
            off = end
            continue
        if batch_len < 49:
            # a v2 batch body is at least 49 bytes (through the record
            # count at +57..61); a corrupt batch_len in 1..48 passes the
            # end-bounds check yet would crash the header unpacks below
            # with struct.error — treat it like a partial trailing batch
            break
        attrs = struct.unpack(">h", blob[off + 21:off + 23])[0]
        last_delta = struct.unpack(">i", blob[off + 23:off + 27])[0]
        n_records = struct.unpack(">i", blob[off + 57:off + 61])[0]
        next_offset = base_offset + last_delta + 1
        body = blob[off + 61:end]
        if attrs & 0x07 == 1:
            body = gzip.decompress(body)
        elif attrs & 0x07:
            raise ValueError(f"unsupported compression codec {attrs & 0x07}")
        p = 0
        for _ in range(n_records):
            rec_len, p = read_varint(body, p)
            rec_end = p + rec_len
            p += 1  # attributes
            _, p = read_varint(body, p)  # timestamp delta
            _, p = read_varint(body, p)  # offset delta
            klen, p = read_varint(body, p)
            key = None if klen < 0 else body[p:p + max(klen, 0)]
            p += max(klen, 0)
            vlen, p = read_varint(body, p)
            value = body[p:p + max(vlen, 0)]
            p = rec_end  # headers skipped wholesale
            out.append((key, value))
        off = end
    return out, next_offset


class KafkaConsumer:
    """Minimal fetch loop over every partition of one topic.

    `pin_bootstrap=True` fetches through the bootstrap connection instead
    of the advertised leader address — the single-broker case where the
    advertised name isn't resolvable from here (e.g. a port-forwarded
    in-cluster broker)."""

    def __init__(self, brokers: list[str], topic: str,
                 tls: TLSSettings = TLSSettings(),
                 sasl: SASLSettings = SASLSettings(),
                 timeout_s: float = 10.0,
                 start_at: int = EARLIEST,
                 pin_bootstrap: bool = False):
        self._topic = topic
        self._tls, self._sasl, self._timeout = tls, sasl, timeout_s
        host, _, port = brokers[0].rpartition(":")
        self._conn = _Conn(host or brokers[0],
                           int(port) if port.isdigit() else 9092,
                           tls, sasl, timeout_s)
        self._pin = pin_bootstrap
        self._leader_conns: dict[int, _Conn] = {}
        self._partitions: list[int] = []
        self._leaders: dict[int, int] = {}
        self._brokers_meta: dict[int, tuple[str, int]] = {}
        self._refresh_metadata()
        self._offsets: dict[int, int] = {
            pid: self._list_offset(pid, start_at) for pid in self._partitions}

    def _refresh_metadata(self) -> None:
        r = self._conn.request(API_METADATA, 1, karray([kstr(self._topic)]))
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            self._brokers_meta[node] = (host, port)
        r.i32()  # controller
        self._partitions = []
        for _ in range(r.i32()):
            err = r.i16()
            name = r.string()
            r.i8()  # is_internal
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                if name == self._topic and not perr:
                    self._partitions.append(pid)
                    self._leaders[pid] = leader
            if err:
                raise IOError(f"metadata error {err} for topic {name}")
        if not self._partitions:
            raise IOError(f"topic {self._topic} has no partitions")

    def _conn_for(self, pid: int) -> _Conn:
        if self._pin:
            return self._conn
        leader = self._leaders[pid]
        conn = self._leader_conns.get(leader)
        if conn is None:
            host, port = self._brokers_meta[leader]
            conn = _Conn(host, port, self._tls, self._sasl, self._timeout)
            self._leader_conns[leader] = conn
        return conn

    def _list_offset(self, pid: int, at: int) -> int:
        body = struct.pack(">i", -1)  # replica_id
        body += karray([kstr(self._topic) + karray(
            [struct.pack(">iq", pid, at)])])
        r = self._conn_for(pid).request(API_LIST_OFFSETS, 1, body)
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                rpid = r.i32()
                err = r.i16()
                r.i64()  # timestamp
                offset = r.i64()
                if rpid == pid:
                    if err:
                        raise IOError(f"list_offsets error {err} p{pid}")
                    return offset
        raise IOError(f"partition {pid} missing from ListOffsets response")

    def poll(self, max_wait_ms: int = 500, max_bytes: int = 4 << 20
             ) -> list[tuple[Optional[bytes], bytes]]:
        """One fetch round over all partitions; advances offsets."""
        out: list[tuple[Optional[bytes], bytes]] = []
        for pid in self._partitions:
            body = struct.pack(">iiii", -1, max_wait_ms, 1, max_bytes)
            body += b"\x00"  # isolation_level: read_uncommitted
            body += karray([kstr(self._topic) + karray(
                [struct.pack(">iqi", pid, self._offsets[pid], max_bytes)])])
            r = self._conn_for(pid).request(API_FETCH, 4, body)
            r.i32()  # throttle_time_ms
            for _ in range(r.i32()):
                r.string()  # topic
                for _ in range(r.i32()):
                    rpid = r.i32()
                    err = r.i16()
                    r.i64()  # high watermark
                    r.i64()  # last stable offset
                    n_aborted = r.i32()
                    for _ in range(max(n_aborted, 0)):
                        r.i64()
                        r.i64()
                    blob = r.bytes_() or b""
                    if err:
                        raise IOError(f"fetch error {err} p{rpid}")
                    if rpid != pid or not blob:
                        continue
                    records, next_off = decode_record_batches(blob)
                    out.extend(records)
                    if next_off is not None:
                        self._offsets[pid] = max(self._offsets[pid], next_off)
        return out

    def close(self) -> None:
        self._conn.close()
        for c in self._leader_conns.values():
            c.close()
