"""Debug/profiling HTTP server (the pprof analog).

Reference analog: the Go pprof endpoint enabled by PPROF_ADDR
(`cmd/netobserv-ebpf-agent.go:49-56`). Python equivalents exposed:
- /debug/threads      — live stack dump of every thread (faulthandler style)
- /debug/tracemalloc  — top allocation sites (starts tracemalloc on first hit)
- /debug/gc           — GC stats + object counts by type (top 40)
"""

from netobserv_tpu.server.debug import start_debug_server  # noqa: F401
