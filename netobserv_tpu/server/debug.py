"""Debug HTTP endpoints (see package docstring)."""

from __future__ import annotations

import gc
import io
import logging
import sys
import threading
import traceback
import tracemalloc
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger("netobserv_tpu.server.debug")


def _threads_dump() -> str:
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(f"--- thread {t.name} (daemon={t.daemon})\n")
        frame = frames.get(t.ident)
        if frame is not None:
            traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


def _tracemalloc_dump(top: int = 25) -> str:
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "tracemalloc started; hit this endpoint again for a snapshot\n"
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    return "".join(f"{s.size / 1024:.1f} KiB  {s.count} blocks  "
                   f"{s.traceback}\n" for s in stats)


def _gc_dump() -> str:
    counts = Counter(type(o).__name__ for o in gc.get_objects())
    lines = [f"gc counts: {gc.get_count()} thresholds: {gc.get_threshold()}\n"]
    lines += [f"{n:>10}  {name}\n" for name, n in counts.most_common(40)]
    return "".join(lines)


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        routes = {
            "/debug/threads": _threads_dump,
            "/debug/tracemalloc": _tracemalloc_dump,
            "/debug/gc": _gc_dump,
        }
        path = self.path.split("?")[0]
        if path in ("/", "/debug", "/debug/"):
            body = "\n".join(routes) + "\n"
        elif path in routes:
            body = routes[path]()
        else:
            self.send_error(404)
            return
        payload = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):
        log.debug("debug http: " + fmt, *args)


def start_debug_server(addr: str) -> ThreadingHTTPServer:
    """addr is "host:port" or ":port" (reference PPROF_ADDR shape)."""
    host, _, port = addr.rpartition(":")
    srv = ThreadingHTTPServer((host or "0.0.0.0", int(port)), _Handler)
    t = threading.Thread(target=srv.serve_forever, name="debug-http",
                         daemon=True)
    t.start()
    log.info("debug server on %s", addr)
    return srv
