"""Debug HTTP endpoints (see package docstring)."""

from __future__ import annotations

import gc
import io
import json
import logging
import sys
import threading
import traceback
import tracemalloc
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

log = logging.getLogger("netobserv_tpu.server.debug")

_JSON = "application/json"
_TEXT = "text/plain; charset=utf-8"


def _threads_dump(q=None) -> str:
    out = io.StringIO()
    frames = sys._current_frames()
    for t in threading.enumerate():
        out.write(f"--- thread {t.name} (daemon={t.daemon})\n")
        frame = frames.get(t.ident)
        if frame is not None:
            traceback.print_stack(frame, file=out)
        out.write("\n")
    return out.getvalue()


def _tracemalloc_dump(q=None, top: int = 25) -> str:
    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return "tracemalloc started; hit this endpoint again for a snapshot\n"
    snap = tracemalloc.take_snapshot()
    stats = snap.statistics("lineno")[:top]
    return "".join(f"{s.size / 1024:.1f} KiB  {s.count} blocks  "
                   f"{s.traceback}\n" for s in stats)


def _gc_dump(q=None) -> str:
    counts = Counter(type(o).__name__ for o in gc.get_objects())
    lines = [f"gc counts: {gc.get_count()} thresholds: {gc.get_threshold()}\n"]
    lines += [f"{n:>10}  {name}\n" for name, n in counts.most_common(40)]
    return "".join(lines)


def _traces_dump(q=None) -> str:
    """Flight recorder: last N completed batch/window traces, newest first,
    each with per-stage durations and inter-stage queue-wait gaps
    (utils/tracing.py; empty unless TRACE_SAMPLE > 0). ?limit= caps the
    list; ?trace= returns only the spans of one trace id (cross-process
    lookup: an agent-stamped id continued by the aggregator answers on
    both tiers' mounts)."""
    from netobserv_tpu.utils import tracing

    q = q or {}
    limit = None
    if q.get("limit"):
        try:
            limit = max(0, int(q["limit"]))
        except ValueError:
            limit = None
    return json.dumps({
        "sampling_enabled": tracing.enabled(),
        "traces": tracing.snapshot(limit=limit, trace_id=q.get("trace")),
    }, separators=(",", ":"))


def _executables_dump(q=None) -> str:
    """Per-executable device accounting from the retrace watchdog registry
    (utils/retrace.py): every watched jit's dispatch count, cumulative
    dispatch wall seconds, compile seconds, retraces, last abstract-shape
    signature, and donated-bytes estimate. Host-side counters only — the
    route never dispatches a device op."""
    from netobserv_tpu.utils import retrace

    return json.dumps({
        "executables": retrace.snapshot(),
        "retraces_total": retrace.total_retraces(),
    }, separators=(",", ":"))


def _jax_dump(q=None) -> str:
    """JAX runtime state: backend/platform, devices, live-array count,
    compilation-cache stats, and the retrace watchdog's per-entry-point
    compile accounting (utils/retrace.py). Touching this route initializes
    the JAX backend if nothing else has."""
    from netobserv_tpu.utils import retrace

    out: dict = {}
    try:
        import jax

        out["backend"] = jax.default_backend()
        out["process_index"] = jax.process_index()
        out["device_count"] = jax.device_count()
        out["devices"] = [str(d) for d in jax.devices()]
        out["live_arrays"] = len(jax.live_arrays())
        try:
            from jax._src import compilation_cache as cc

            cache = cc._cache  # persistent cache; None when never enabled
            out["compilation_cache"] = {
                "enabled": cache is not None,
                "dir": (jax.config.jax_compilation_cache_dir or ""),
            }
        except Exception:
            out["compilation_cache"] = {"enabled": False}
    except Exception as exc:  # debug surface must answer on broken backends
        out["error"] = str(exc)
    out["retrace_watchdog"] = retrace.snapshot()
    out["retraces_total"] = retrace.total_retraces()
    return json.dumps(out, separators=(",", ":"))


#: route -> (handler, content type, one-line description for the index)
_ROUTES = {
    "/debug/threads": (
        _threads_dump, _TEXT,
        "stack dump of every live thread"),
    "/debug/tracemalloc": (
        _tracemalloc_dump, _TEXT,
        "top host allocation sites (first hit arms tracemalloc)"),
    "/debug/gc": (
        _gc_dump, _TEXT,
        "gc counters and the most common live object types"),
    "/debug/traces": (
        _traces_dump, _JSON,
        "flight recorder: last completed batch/window traces, newest "
        "first, with per-stage durations and queue-wait gaps "
        "(TRACE_SAMPLE; ?limit= caps, ?trace= single-trace lookup)"),
    "/debug/executables": (
        _executables_dump, _JSON,
        "per-executable device accounting: dispatch count + wall seconds, "
        "compile seconds, retraces, last shape signature, donated-bytes "
        "estimate for every watched jit"),
    "/debug/jax": (
        _jax_dump, _JSON,
        "jax backend/devices, live arrays, compilation cache, and the "
        "retrace watchdog's per-entry-point compile counts"),
}


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        url = urlparse(self.path)
        path = url.path
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        if path in ("/", "/debug", "/debug/"):
            body = "".join(f"{route:<22} {desc}\n"
                           for route, (_fn, _ct, desc)
                           in sorted(_ROUTES.items()))
            ctype = _TEXT
        elif path in _ROUTES:
            fn, ctype, _desc = _ROUTES[path]
            body = fn(q)
        else:
            self.send_error(404)
            return
        payload = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):
        log.debug("debug http: " + fmt, *args)


def start_debug_server(addr: str) -> ThreadingHTTPServer:
    """addr is "host:port" or ":port" (reference PPROF_ADDR shape)."""
    host, _, port = addr.rpartition(":")
    srv = ThreadingHTTPServer((host or "0.0.0.0", int(port)), _Handler)
    t = threading.Thread(target=srv.serve_forever, name="debug-http",
                         daemon=True)
    t.start()
    log.info("debug server on %s", addr)
    return srv
