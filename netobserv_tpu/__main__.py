"""CLI entry: env-configured agent binary (reference analog:
`cmd/netobserv-ebpf-agent.go` — zero flags, SIGTERM-driven shutdown)."""

from __future__ import annotations

import logging
import signal
import sys
import threading

from netobserv_tpu import __version__
from netobserv_tpu.agent import FlowsAgent
from netobserv_tpu.config import load_config
from netobserv_tpu.metrics.server import start_metrics_server

log = logging.getLogger("netobserv_tpu")


def main() -> int:
    from netobserv_tpu.utils.platform import maybe_force_cpu
    maybe_force_cpu()  # honor an explicit JAX_PLATFORMS=cpu request
    cfg = load_config()
    logging.basicConfig(
        level=getattr(logging, cfg.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
        stream=sys.stderr)
    log.info("starting netobserv_tpu agent %s (export=%s)",
             __version__, cfg.export)

    dbg = None
    if cfg.pprof_addr:
        from netobserv_tpu.server import start_debug_server
        dbg = start_debug_server(cfg.pprof_addr)

    try:
        if cfg.federation_mode == "aggregator":
            # central aggregator tier: delta ingest + device merge + the
            # cluster-wide query surface, no datapath/flow pipeline at all
            from netobserv_tpu.federation.service import (
                FederationAggregatorService,
            )
            agent = FederationAggregatorService(cfg)
        elif cfg.enable_pca:
            import os as _os

            if not cfg.target_host or not cfg.target_port:
                raise ValueError(
                    "ENABLE_PCA: TARGET_HOST and TARGET_PORT (or "
                    "PCA_SERVER_PORT) are required")
            from netobserv_tpu.agent.packets_agent import PacketsAgent
            mode = _os.environ.get("DATAPATH", "auto")
            if mode.startswith("pcap:"):
                from netobserv_tpu.datapath.replay import PcapPacketFetcher
                pkt_fetcher = PcapPacketFetcher(mode[5:])
            else:
                # self-managed kernel capture: hand-assembled PCA program,
                # verifier-loaded, no compiler required
                from netobserv_tpu.datapath.loader import \
                    load_packet_fetcher
                pkt_fetcher = load_packet_fetcher(cfg)
            agent = PacketsAgent(cfg, pkt_fetcher)
        else:
            agent = FlowsAgent.from_config(cfg)
    except (ValueError, RuntimeError) as exc:
        log.error("invalid configuration: %s", exc)
        return 2

    srv = None
    metrics = getattr(agent, "metrics", None)
    if cfg.metrics_enable and metrics is not None:
        # /healthz + /readyz ride on the metrics server when the agent
        # exposes a supervised health snapshot (FlowsAgent does)
        srv = start_metrics_server(
            metrics.registry, cfg.metrics_server_address,
            cfg.metrics_server_port, cfg.metrics_tls_cert_path,
            cfg.metrics_tls_key_path,
            health_source=getattr(agent, "health_snapshot", None),
            query_routes=getattr(agent, "query_routes", None))

    stop = threading.Event()

    def _terminate(signum, _frame):
        log.info("received %s, stopping agent", signal.Signals(signum).name)
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)

    agent.run(stop)
    if srv is not None:
        srv.shutdown()
    if dbg is not None:
        dbg.shutdown()
    log.info("agent stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
