"""PacketsAgent: the PCA (packet capture) pipeline.

Reference analog: `pkg/agent/packets_agent.go` — mutually exclusive with the
flow agent; packet ringbuf -> PerfTracer -> PerfBuffer -> gRPC pcap stream.
"""

from __future__ import annotations

import enum
import logging
import queue
import threading
from typing import Optional, Protocol

from netobserv_tpu.config import AgentConfig
from netobserv_tpu.exporter.grpc_packets import GRPCPacketExporter
from netobserv_tpu.flow.perf_buffer import PerfBuffer, PerfTracer
from netobserv_tpu.model.packet_record import PacketRecord

log = logging.getLogger("netobserv_tpu.agent.packets")


class PacketFetcher(Protocol):
    def read_packet(self, timeout_s: float) -> Optional[bytes]: ...

    def close(self) -> None: ...


class FakePacketFetcher:
    def __init__(self):
        self._q: "queue.Queue[bytes]" = queue.Queue()

    def inject(self, raw: bytes) -> None:
        self._q.put(raw)

    def read_packet(self, timeout_s: float) -> Optional[bytes]:
        try:
            return self._q.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def close(self) -> None:
        pass


class PacketsAgent:
    def __init__(self, cfg: AgentConfig, fetcher: PacketFetcher,
                 exporter=None):
        self.cfg = cfg
        self.fetcher = fetcher
        self.exporter = exporter or GRPCPacketExporter(
            cfg.target_host, cfg.target_port,
            tls_ca=cfg.target_tls_ca_cert_path,
            tls_cert=cfg.target_tls_user_cert_path,
            tls_key=cfg.target_tls_user_key_path)
        buf = cfg.buffers_length
        self._pkt_q: "queue.Queue[PacketRecord]" = queue.Queue(maxsize=buf * 10)
        self._batch_q: "queue.Queue[list[PacketRecord]]" = queue.Queue(maxsize=buf)
        self.tracer = PerfTracer(fetcher, self._pkt_q)
        self.buffer = PerfBuffer(self._pkt_q, self._batch_q,
                                 timeout_s=min(cfg.cache_active_timeout, 0.5))
        self._stop = threading.Event()
        self._export_thread: Optional[threading.Thread] = None
        if cfg.flow_filter_rules and hasattr(fetcher, "program_filters"):
            fetcher.program_filters(cfg.parsed_filter_rules())
        # kernel-backed packet fetchers attach per-interface like the flow
        # datapath; replay/fake fetchers skip discovery
        self.iface_listener = None
        if getattr(fetcher, "needs_iface_discovery", False):
            from netobserv_tpu.agent.interfaces_listener import (
                InterfaceListener,
            )
            self.iface_listener = InterfaceListener(cfg, fetcher)

    def run(self, stop: Optional[threading.Event] = None) -> None:
        self._export_thread = threading.Thread(
            target=self._export_loop, name="packet-export", daemon=True)
        self._export_thread.start()
        if self.iface_listener is not None:
            self.iface_listener.start()
        self.buffer.start()
        self.tracer.start()
        self._active_stop = stop = stop or self._stop
        stop.wait()
        self.shutdown()

    def stop(self) -> None:
        self._stop.set()
        active = getattr(self, "_active_stop", None)
        if active is not None:
            active.set()

    def shutdown(self) -> None:
        if self.iface_listener is not None:
            self.iface_listener.stop()
        self.tracer.stop()
        self.buffer.stop()
        self._stop.set()
        if self._export_thread:
            self._export_thread.join(timeout=2.0)
        # drain remaining batches
        while True:
            try:
                self.exporter.export_packets(self._batch_q.get_nowait())
            except queue.Empty:
                break
            except Exception as exc:
                log.error("final packet export failed: %s", exc)
                break
        self.exporter.close()
        self.fetcher.close()

    def _export_loop(self) -> None:
        while not self._stop.is_set():
            try:
                batch = self._batch_q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.exporter.export_packets(batch)
            except Exception as exc:
                log.error("packet export failed: %s", exc)
