"""Pipeline supervision: stage registry, heartbeats, bounded restarts.

Every pipeline thread (map tracer, ringbuf tracer, accounter, limiter, queue
exporter, SSL tracer, interface listener, sketch window timer) registers with
the supervisor: a *thread getter* (so crashes — dead threads — are detected),
a *restart callable* (the stage's own ``start()``), and a *heartbeat
deadline* (so hangs — a live thread that stopped beating — are detected too).

The monitor loop restarts failed stages with bounded exponential backoff and
counts restarts/failures in the metrics registry. A stage that keeps dying
past its restart budget is declared DEGRADED: the supervisor stops burning
restarts on it, trips the degraded gauge, and notifies the agent (which
transitions its own status machine to Degraded) — a stalled stage is an
explicit, machine-readable condition (/healthz), never a silent stall.

The budget is *consecutive*: a stage that stays healthy for
``healthy_reset_s`` after a restart earns its budget back (crash storms
degrade; a once-a-day hiccup never does).
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

log = logging.getLogger("netobserv_tpu.agent.supervisor")


class StageState(enum.Enum):
    RUNNING = "Running"
    RESTARTING = "Restarting"
    DEGRADED = "Degraded"
    STOPPED = "Stopped"


@dataclass
class _Stage:
    name: str
    restart: Callable[[], None]
    thread_getter: Callable[[], Optional[threading.Thread]]
    heartbeat_timeout_s: Optional[float]
    max_restarts: int
    backoff_initial_s: float
    backoff_max_s: float
    healthy_reset_s: float
    state: StageState = StageState.RUNNING
    last_beat: float = field(default_factory=time.monotonic)
    restarts: int = 0            # lifetime, for /healthz + metrics
    consecutive_failures: int = 0
    last_failure: str = ""       # "crash" | "hang" | ""
    next_restart_at: float = 0.0
    last_restart_at: float = 0.0


class Supervisor:
    """Monitors registered stages; restarts crashed/hung ones within budget.

    `on_degraded(stage_name)` fires (once per stage) when a restart budget
    is exhausted; the agent uses it to enter its Degraded status.
    """

    def __init__(self, metrics=None, check_period_s: float = 0.25,
                 on_degraded: Optional[Callable[[str], None]] = None):
        self._metrics = metrics
        self._period = check_period_s
        self._on_degraded = on_degraded
        self._stages: dict[str, _Stage] = {}
        #: named health CONDITIONS, probed at snapshot time — states a
        #: stage reports about itself that are not crash/hang/degraded
        #: (e.g. the exporter's OVERLOADED while the overload controller
        #: sheds load). A probe returns a dict with at least
        #: {"active": bool}; /healthz + /readyz surface them distinct
        #: from DEGRADED.
        self._conditions: dict[str, Callable[[], dict]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- registry ---
    def register(self, name: str, restart: Callable[[], None],
                 thread_getter: Callable[[], Optional[threading.Thread]],
                 heartbeat_timeout_s: Optional[float] = None,
                 max_restarts: int = 5, backoff_initial_s: float = 0.2,
                 backoff_max_s: float = 30.0,
                 healthy_reset_s: float = 30.0) -> Callable[[], None]:
        """Register a stage; returns its heartbeat callable (cheap, lock-free
        on the beat path — stages call it once per loop iteration)."""
        stage = _Stage(name=name, restart=restart,
                       thread_getter=thread_getter,
                       heartbeat_timeout_s=heartbeat_timeout_s,
                       max_restarts=max_restarts,
                       backoff_initial_s=backoff_initial_s,
                       backoff_max_s=backoff_max_s,
                       healthy_reset_s=healthy_reset_s)
        with self._lock:
            self._stages[name] = stage

        def beat(_s=stage) -> None:
            # a hang restart replaces the stage thread while the hung one is
            # still alive; if that zombie ever unblocks, it must NOT resume
            # draining shared queues next to its replacement. Its first beat
            # notices it was superseded and exits silently (threading
            # swallows SystemExit) — overlap is bounded to the one iteration
            # that was already in flight when it unblocked.
            current = _s.thread_getter()
            if current is not None and current is not threading.current_thread():
                raise SystemExit(f"superseded {_s.name} thread exiting")
            _s.last_beat = time.monotonic()

        return beat

    def register_stage(self, name: str, stage_obj,
                       **kwargs) -> Callable[[], None]:
        """Convenience for the repo's stage shape: ``start()`` (re)creates
        ``_thread``. Installs the heartbeat on ``stage_obj.heartbeat`` when
        the stage exposes that attribute."""
        beat = self.register(
            name, restart=stage_obj.start,
            thread_getter=lambda: getattr(stage_obj, "_thread", None),
            **kwargs)
        if hasattr(stage_obj, "heartbeat"):
            stage_obj.heartbeat = beat
        return beat

    # --- lifecycle ---
    def start(self) -> None:
        now = time.monotonic()
        with self._lock:
            for st in self._stages.values():
                st.last_beat = now
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="supervisor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._period * 8 + 1)
        with self._lock:
            for st in self._stages.values():
                if st.state != StageState.DEGRADED:
                    st.state = StageState.STOPPED

    def register_condition(self, name: str,
                           probe: Callable[[], dict]) -> None:
        """Register a named health condition (see `_conditions`). The
        latest registration under a name wins (a restarted stage
        re-registers its condition)."""
        with self._lock:
            self._conditions[name] = probe

    def conditions(self) -> dict:
        """Evaluate every registered condition probe. A raising probe
        reports {"active": False, "error": ...} — the health surface must
        answer even when a stage's introspection is broken."""
        with self._lock:
            probes = dict(self._conditions)
        out = {}
        for name, probe in probes.items():
            try:
                out[name] = probe()
            except Exception as exc:
                out[name] = {"active": False, "error": str(exc)}
        return out

    def condition_active(self, name: str) -> bool:
        with self._lock:
            probe = self._conditions.get(name)
        if probe is None:
            return False
        try:
            return bool(probe().get("active"))
        except Exception:
            return False

    # --- introspection (health surface) ---
    @property
    def degraded(self) -> bool:
        with self._lock:
            return any(s.state == StageState.DEGRADED
                       for s in self._stages.values())

    def snapshot(self) -> dict:
        """Machine-readable per-stage state for /healthz."""
        now = time.monotonic()
        out = {}
        with self._lock:
            for name, s in self._stages.items():
                out[name] = {
                    "state": s.state.value,
                    "restarts": s.restarts,
                    "consecutive_failures": s.consecutive_failures,
                    "last_failure": s.last_failure,
                    "heartbeat_age_s": round(now - s.last_beat, 3),
                    "heartbeat_timeout_s": s.heartbeat_timeout_s,
                }
        return out

    # --- monitor loop ---
    def _loop(self) -> None:
        while not self._stop.wait(timeout=self._period):
            self._check_once()

    def _check_once(self) -> None:
        now = time.monotonic()
        with self._lock:
            stages = list(self._stages.values())
        for st in stages:
            if self._stop.is_set():
                return
            if st.state == StageState.DEGRADED:
                continue
            if st.state == StageState.RESTARTING:
                if now >= st.next_restart_at:
                    self._restart(st)
                continue
            thread = st.thread_getter()
            if thread is None or not thread.is_alive():
                self._fail(st, "crash")
            elif (st.heartbeat_timeout_s is not None
                    and now - st.last_beat > st.heartbeat_timeout_s):
                self._fail(st, "hang")
            elif (st.consecutive_failures
                    and now - st.last_restart_at >= st.healthy_reset_s):
                st.consecutive_failures = 0  # earned the budget back

    def _fail(self, st: _Stage, kind: str) -> None:
        st.last_failure = kind
        st.consecutive_failures += 1
        if self._metrics is not None:
            self._metrics.count_stage_failure(st.name, kind)
        if st.consecutive_failures > st.max_restarts:
            st.state = StageState.DEGRADED
            log.error("stage %s exhausted its restart budget (%d); "
                      "marking DEGRADED", st.name, st.max_restarts)
            if self._metrics is not None:
                self._metrics.set_stage_degraded(st.name, True)
            if self._on_degraded is not None:
                try:
                    self._on_degraded(st.name)
                except Exception:
                    log.exception("on_degraded callback failed")
            return
        backoff = min(
            st.backoff_initial_s * (2 ** (st.consecutive_failures - 1)),
            st.backoff_max_s)
        st.state = StageState.RESTARTING
        st.next_restart_at = time.monotonic() + backoff
        log.warning("stage %s %s detected (failure %d/%d); restarting in "
                    "%.2fs", st.name, kind, st.consecutive_failures,
                    st.max_restarts, backoff)

    def _restart(self, st: _Stage) -> None:
        try:
            st.restart()
        except Exception as exc:
            # a restart that itself blows up consumes budget like a crash
            log.error("stage %s restart failed: %s", st.name, exc)
            self._fail(st, "crash")
            return
        st.restarts += 1
        st.last_restart_at = st.last_beat = time.monotonic()
        st.state = StageState.RUNNING
        if self._metrics is not None:
            self._metrics.count_stage_restart(st.name)
        log.info("stage %s restarted (lifetime restarts: %d)",
                 st.name, st.restarts)
