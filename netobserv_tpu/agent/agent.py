"""FlowsAgent: builds and runs the flow pipeline.

Reference analog: `pkg/agent/agent.go:71-230,347-442` — constructs metrics,
exporter, fetcher; wires the stage graph with bounded queues; exposes a status
state machine; injectable constructor for fake-driven tests.
"""

from __future__ import annotations

import enum
import logging
import queue
import threading
from typing import Optional

from netobserv_tpu.agent.supervisor import Supervisor
from netobserv_tpu.config import AgentConfig
from netobserv_tpu.datapath.fetcher import FlowFetcher
from netobserv_tpu.exporter import build_exporter
from netobserv_tpu.exporter.base import Exporter, QueueExporter
from netobserv_tpu.flow import Accounter, CapacityLimiter, MapTracer, RingBufTracer
from netobserv_tpu.metrics.registry import Metrics, MetricsSettings
from netobserv_tpu.utils import retrace, tracing

log = logging.getLogger("netobserv_tpu.agent")


class Status(enum.Enum):
    NOT_STARTED = "NotStarted"
    STARTING = "Starting"
    STARTED = "Started"
    #: a supervised stage exhausted its restart budget: the agent keeps
    #: serving with the surviving stages, but /readyz reports 503 and the
    #: condition is explicit (never a silent stall)
    DEGRADED = "Degraded"
    STOPPING = "Stopping"
    STOPPED = "Stopped"


class FlowsAgent:
    """Build with `FlowsAgent.from_config(cfg)` for the real wiring, or inject
    fetcher/exporter directly for tests (reference: the private `flowsAgent`
    ctor, `agent.go:180`)."""

    def __init__(self, cfg: AgentConfig, fetcher: FlowFetcher,
                 exporter: Exporter, metrics: Optional[Metrics] = None,
                 agent_ip: str = "", iface_informer=None):
        self.cfg = cfg
        self.fetcher = fetcher
        self.exporter = exporter
        self.metrics = metrics or Metrics(MetricsSettings(
            prefix=cfg.metrics_prefix, level=cfg.metrics_level))
        # observability plumbing (utils/tracing.py, utils/retrace.py):
        # sampled flight-recorder spans feed stage_seconds{stage=...}, and
        # post-warmup jit retraces alarm via sketch_retraces_total{fn=...}
        tracing.set_metrics(self.metrics)
        retrace.set_metrics(self.metrics)
        self._status = Status.NOT_STARTED
        self._status_lock = threading.Lock()
        self._stop = threading.Event()

        buf = cfg.buffers_length
        export_buf = cfg.exporter_buffer_length or buf
        self._evicted_q: queue.Queue = queue.Queue(maxsize=buf)
        self._export_q: queue.Queue = queue.Queue(maxsize=export_buf)

        udn_mapper = None
        if cfg.enable_udn_mapping:
            from netobserv_tpu.ifaces.udn import UdnMapper
            udn_mapper = UdnMapper()
        self._ovn_decoder = None
        if cfg.enable_network_events_monitoring:
            # install the OVN sample decoder (ovsdb-backed when the OVN
            # socket exists, static otherwise; reference agent.go:136-147)
            from netobserv_tpu.utils import ovn_decoder
            self._ovn_decoder = ovn_decoder.make_decoder(cfg)
            ovn_decoder.set_decoder(self._ovn_decoder)
        columnar = getattr(exporter, "supports_columnar", False)
        ssl_tracking = (cfg.enable_openssl_tracking
                        and hasattr(fetcher, "read_ssl"))
        self.ssl_correlator = None
        if ssl_tracking:
            if columnar:
                # _attach_features never runs on the columnar fast path, so
                # credits would accumulate forever and never export
                log.warning("SSL plaintext correlation is a no-op on the "
                            "columnar fast path (records are never "
                            "materialized)")
            else:
                from netobserv_tpu.flow.ssl_correlator import SSLCorrelator
                self.ssl_correlator = SSLCorrelator()
        # map capacity for the occupancy histogram + pressure relief:
        # bpfman-mode fetchers report the REAL kernel map capacity (an
        # external manager sized it); self-managed datapaths sized theirs
        # from CACHE_MAX_FLOWS, so that is the honest denominator when no
        # probe answers. Probed UNCONDITIONALLY: map_occupancy_ratio is the
        # evidence operators read to decide whether to set
        # MAP_PRESSURE_WATERMARK, so it must populate before the knob is on
        probe = getattr(fetcher, "map_capacity", None)
        map_capacity = probe() if probe is not None else 0
        if not map_capacity:
            map_capacity = cfg.cache_max_flows
        self.map_tracer = MapTracer(
            fetcher, self._evicted_q,
            active_timeout_s=cfg.cache_active_timeout, agent_ip=agent_ip,
            metrics=self.metrics,
            stale_purge_s=cfg.stale_entries_evict_timeout,
            # columnar fast path: exporters that consume raw evictions skip
            # per-record Python object materialization entirely
            columnar=columnar,
            udn_mapper=udn_mapper,
            force_gc=cfg.force_garbage_collection,
            ssl_correlator=self.ssl_correlator,
            map_capacity=map_capacity,
            pressure_watermark=cfg.map_pressure_watermark,
            # fleet telemetry: a sketch exporter records the last drain's
            # occupancy so its delta frames carry it (one float store per
            # drain; exporters without the hook opt out via None)
            occupancy_sink=getattr(exporter, "note_map_occupancy", None))
        # fused native pipeline (EVICT_NATIVE_PIPELINE): when both ends
        # speak it — a bpfman fetcher with the gate on and a sketch
        # exporter whose resident ring can accept pre-packed regions —
        # bind the exporter's pack surface so fused drains also run the
        # resident pack natively. Either side missing leaves the fetcher
        # on its drain+merge+join fusion (still one native call).
        bind = getattr(fetcher, "bind_pack_surface", None)
        surface_of = getattr(exporter, "resident_pack_surface", None)
        if bind is not None and surface_of is not None:
            surface = surface_of()
            if surface is not None:
                bind(surface)
        self.limiter = CapacityLimiter(
            self._evicted_q, self._export_q, metrics=self.metrics)
        self.terminal = QueueExporter(
            exporter, self._export_q, metrics=self.metrics)

        self.ssl_tracer = None
        if ssl_tracking:
            from netobserv_tpu.flow.ssl_tracer import SSLTracer

            def _ssl_handle(event):
                if self.ssl_correlator is not None:
                    credited = self.ssl_correlator.observe(event)
                else:
                    credited = 0
                log.debug("ssl %s pid=%d %dB -> %d flow keys credited",
                          "write" if event.direction else "read", event.pid,
                          len(event.data), credited)

            self.ssl_tracer = SSLTracer(fetcher, _ssl_handle)

        self.rb_tracer: Optional[RingBufTracer] = None
        self.accounter: Optional[Accounter] = None
        if cfg.enable_flows_ringbuf_fallback:
            self._rb_q: queue.Queue = queue.Queue(maxsize=buf * 10)
            self.rb_tracer = RingBufTracer(
                fetcher, self._rb_q, flusher=self.map_tracer.flush,
                metrics=self.metrics)
            self.accounter = Accounter(
                self._rb_q, self._evicted_q,
                max_entries=cfg.cache_max_flows,
                evict_timeout_s=cfg.cache_active_timeout,
                agent_ip=agent_ip, metrics=self.metrics,
                ssl_correlator=self.ssl_correlator)

        if cfg.sampling:
            self.metrics.sampling_rate.set(cfg.sampling)

        # program kernel flow filters when the datapath supports it
        if cfg.flow_filter_rules and hasattr(fetcher, "program_filters"):
            fetcher.program_filters(cfg.parsed_filter_rules())

        # discovery is only useful when the datapath actually attaches to
        # interfaces (kernel loader); replay/fake fetchers skip it unless
        # a custom informer is injected
        self.iface_listener = None
        if iface_informer is not None or getattr(
                fetcher, "needs_iface_discovery", False):
            from netobserv_tpu.agent.interfaces_listener import InterfaceListener
            self.iface_listener = InterfaceListener(
                cfg, fetcher, metrics=self.metrics, informer=iface_informer)

        # query plane: exporters that publish a window snapshot (tpu-sketch)
        # expose a QueryRoutes handler; the metrics server serves it at
        # /query/* (docs/architecture.md "Query plane")
        self.query_routes = getattr(exporter, "query_routes", None)

        # supervision: every stage thread registers a heartbeat + restart;
        # crashed/hung stages restart with bounded backoff, exhausted
        # budgets degrade the agent explicitly (agent/supervisor.py)
        self.supervisor = Supervisor(
            metrics=self.metrics,
            check_period_s=cfg.supervisor_check_period,
            on_degraded=self._on_stage_degraded)
        self._register_stages()

    def _register_stages(self) -> None:
        cfg = self.cfg
        budget = dict(max_restarts=cfg.supervisor_max_restarts,
                      backoff_initial_s=cfg.supervisor_backoff_initial,
                      backoff_max_s=cfg.supervisor_backoff_max,
                      healthy_reset_s=cfg.supervisor_healthy_reset)
        hb = cfg.supervisor_heartbeat_timeout
        sup = self.supervisor
        # the map tracer beats once per eviction wakeup, so its hang
        # deadline rides on top of the eviction period
        sup.register_stage("map-tracer", self.map_tracer,
                           heartbeat_timeout_s=cfg.cache_active_timeout + hb,
                           **budget)
        sup.register_stage("capacity-limiter", self.limiter,
                           heartbeat_timeout_s=hb, **budget)
        sup.register_stage("exporter", self.terminal,
                           heartbeat_timeout_s=hb, **budget)
        if self.accounter is not None:
            sup.register_stage("accounter", self.accounter,
                               heartbeat_timeout_s=hb, **budget)
        if self.rb_tracer is not None:
            sup.register_stage("ringbuf-tracer", self.rb_tracer,
                               heartbeat_timeout_s=hb, **budget)
        if self.ssl_tracer is not None:
            sup.register_stage("ssl-tracer", self.ssl_tracer,
                               heartbeat_timeout_s=hb, **budget)
        if self.iface_listener is not None:
            sup.register_stage("iface-listener", self.iface_listener,
                               heartbeat_timeout_s=hb, **budget)
        # the tpu-sketch exporter supervises its own window timer (and any
        # future exporter with background threads can opt in the same way)
        register = getattr(self.exporter, "register_supervised", None)
        if register is not None:
            register(sup, heartbeat_timeout_s=hb, **budget)

    def _on_stage_degraded(self, stage: str) -> None:
        with self._status_lock:
            if self._status == Status.STARTED:
                self._status = Status.DEGRADED
        log.error("agent DEGRADED: stage %s is down for good "
                  "(restart budget exhausted)", stage)

    def health_snapshot(self) -> dict:
        """Machine-readable agent health for /healthz + /readyz
        (metrics/server.py). `conditions` carries supervisor-registered
        stage conditions (e.g. the overload controller's OVERLOADED);
        `overloaded` hoists that one to the top level — it is DISTINCT
        from `degraded`: an overloaded agent is healthy and serving,
        deliberately trading resolution for stability, so it stays
        ready (pulling it from rotation would just shift the load)."""
        conditions = self.supervisor.conditions()
        return {
            "status": self.status.value,
            "degraded": self.supervisor.degraded,
            "overloaded": bool(
                conditions.get("overloaded", {}).get("active")),
            "conditions": conditions,
            "stages": self.supervisor.snapshot(),
        }

    @classmethod
    def from_config(cls, cfg: AgentConfig) -> "FlowsAgent":
        cfg.validate()
        agent_ip = resolve_agent_ip(cfg)
        metrics = Metrics(MetricsSettings(
            prefix=cfg.metrics_prefix, level=cfg.metrics_level))
        exporter = build_exporter(cfg, metrics=metrics)
        fetcher = build_fetcher(cfg)
        return cls(cfg, fetcher, exporter, metrics=metrics, agent_ip=agent_ip)

    @property
    def status(self) -> Status:
        with self._status_lock:
            return self._status

    def _set_status(self, s: Status) -> None:
        with self._status_lock:
            self._status = s
        log.debug("agent status: %s", s.value)

    def run(self, stop: Optional[threading.Event] = None) -> None:
        """Start the pipeline and block until `stop` is set (or .stop())."""
        self._set_status(Status.STARTING)
        if self.iface_listener is not None:
            self.iface_listener.start()
        self.terminal.start()
        self.limiter.start()
        if self.accounter is not None:
            self.accounter.start()
        if self.rb_tracer is not None:
            self.rb_tracer.start()
        if self.ssl_tracer is not None:
            self.ssl_tracer.start()
        self.map_tracer.start()
        if self.cfg.supervisor_enable:
            self.supervisor.start()
        self._set_status(Status.STARTED)
        self._active_stop = stop = stop or self._stop
        stop.wait()
        self.shutdown()

    def stop(self) -> None:
        self._stop.set()
        active = getattr(self, "_active_stop", None)
        if active is not None:
            active.set()

    def shutdown(self) -> None:
        if self.status in (Status.STOPPING, Status.STOPPED):
            return
        self._set_status(Status.STOPPING)
        # the supervisor goes first: a stopping stage's dead thread must not
        # be mistaken for a crash and restarted mid-shutdown
        self.supervisor.stop()
        # stop stages source-first, with a final eviction so nothing is lost
        if self.iface_listener is not None:
            self.iface_listener.stop()
        self.map_tracer.stop(final_evict=True)
        if self.ssl_tracer is not None:
            self.ssl_tracer.stop()
        if self.rb_tracer is not None:
            self.rb_tracer.stop()
        if self.accounter is not None:
            self.accounter.stop()
        self.limiter.stop()
        self.terminal.stop()
        self.fetcher.close()
        if self._ovn_decoder is not None:
            from netobserv_tpu.utils import ovn_decoder
            self._ovn_decoder.close()
            ovn_decoder.set_decoder(None)  # drop this agent's global install
            self._ovn_decoder = None
        self._set_status(Status.STOPPED)


def build_fetcher(cfg: AgentConfig) -> FlowFetcher:
    """Datapath selection: kernel loader when available, replay otherwise.

    DATAPATH env ("kernel" | "synthetic" | "pcap:<path>" | "grpc:<port>")
    overrides; default tries the kernel loader (bpfman mode when
    EBPF_PROGRAM_MANAGER_MODE is set) and falls back to synthetic with a
    warning. "grpc:<port>" turns this process into a collector-tier worker
    consuming other agents' pbflow streams.
    """
    import os

    mode = os.environ.get("DATAPATH", "auto")
    # an explicit DATAPATH replay request overrides everything (debug/replay)
    if mode.startswith("pcap:"):
        from netobserv_tpu.datapath.replay import PcapReplayFetcher
        return PcapReplayFetcher(mode[5:], window_s=cfg.cache_active_timeout)
    if mode == "synthetic":
        from netobserv_tpu.datapath.replay import SyntheticFetcher
        return SyntheticFetcher()
    if mode.startswith("grpc:"):
        from netobserv_tpu.datapath.grpc_ingest import GrpcIngestFetcher
        return GrpcIngestFetcher(int(mode[5:]))
    if cfg.ebpf_program_manager_mode:
        from netobserv_tpu.datapath.loader import BpfmanFetcher
        return BpfmanFetcher.load(cfg)
    try:
        from netobserv_tpu.datapath.loader import KernelFetcher
        return KernelFetcher.load(cfg)
    except Exception as exc:
        log.debug("full kernel datapath unavailable: %s", exc)
    try:
        # hand-assembled minimal datapath: real IPv4 TCP/UDP flow capture
        # without a compiled BPF object (datapath/asm_flowpath.py)
        from netobserv_tpu.datapath.loader import MinimalKernelFetcher
        fetcher = MinimalKernelFetcher.load(cfg)
        log.info("using the minimal hand-assembled kernel datapath "
                 "(IPv4 TCP/UDP base flows; build the clang object for "
                 "full features)")
        return fetcher
    except Exception as exc:
        if mode == "kernel":
            raise
        log.warning("kernel datapath unavailable (%s); using synthetic replay",
                    exc)
        from netobserv_tpu.datapath.replay import SyntheticFetcher
        return SyntheticFetcher()


def resolve_agent_ip(cfg: AgentConfig) -> str:
    """Agent IP resolution (reference analog: `pkg/agent/ip.go:27-126`).

    AGENT_IP takes precedence; otherwise derive from the routing table
    (external) or hostname (local), honoring AGENT_IP_TYPE (any/ipv4/ipv6).
    """
    import socket

    if cfg.agent_ip:
        return cfg.agent_ip
    want = cfg.agent_ip_type
    if cfg.agent_ip_iface == "local":
        host = socket.gethostname()
        try:
            infos = socket.getaddrinfo(host, None)
        except OSError:
            return "127.0.0.1"
        for family in ((socket.AF_INET,) if want in ("any", "ipv4")
                       else ()) + ((socket.AF_INET6,)
                                   if want in ("any", "ipv6") else ()):
            for info in infos:
                if info[0] == family:
                    return info[4][0]
        return "127.0.0.1"
    # "external": learn the egress address by opening a dummy UDP socket
    probes = []
    if want in ("any", "ipv4"):
        probes.append((socket.AF_INET, "8.8.8.8"))
    if want in ("any", "ipv6"):
        probes.append((socket.AF_INET6, "2001:4860:4860::8888"))
    for family, target in probes:
        try:
            s = socket.socket(family, socket.SOCK_DGRAM)
            s.connect((target, 80))
            ip = s.getsockname()[0]
            s.close()
            return ip
        except OSError:
            continue
    return "127.0.0.1"
