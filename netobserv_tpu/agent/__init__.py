"""Agent orchestration (L5 in SURVEY.md §1)."""

from netobserv_tpu.agent.agent import FlowsAgent, Status  # noqa: F401
