"""Interface listener: discovery events -> filtered attach/detach with retry.

Reference analog: `pkg/agent/interfaces_listener.go` — allow/deny filtering,
per-event retry with linear backoff (TC_ATTACH_RETRIES, 300ms*attempt),
tcx/tc/any attach-mode fallback, and registration of the interface namer.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from netobserv_tpu.config import AgentConfig
from netobserv_tpu.datapath.fetcher import FlowFetcher
from netobserv_tpu.ifaces import (
    Event, EventType, InterfaceFilter, Poller, Registerer, Watcher,
)
from netobserv_tpu.model.record import interface_namer, set_interface_namer
from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.agent.ifaces")

_RETRY_BACKOFF_S = 0.3


class DoNotRetryError(Exception):
    """Attach failure that retrying cannot fix (reference: tracer.Error with
    DoNotRetry, `pkg/tracer/errors.go`)."""


class InterfaceListener:
    def __init__(self, cfg: AgentConfig, fetcher: FlowFetcher,
                 metrics=None, informer=None):
        self._cfg = cfg
        self._fetcher = fetcher
        self._metrics = metrics
        if informer is not None:
            self._informer = informer
        elif cfg.listen_interfaces == "poll":
            self._informer = Poller(period_s=cfg.listen_poll_period)
        else:
            self._informer = Watcher()
        self._filter = InterfaceFilter(
            allowed=cfg.interfaces, excluded=cfg.exclude_interfaces,
            ip_cidrs=cfg.interface_ips)
        self._registerer = Registerer(cfg.preferred_interface_for_mac_prefix)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.attached: set[tuple[str, int]] = set()
        #: supervision hook: beats once per poll (agent/supervisor.py)
        self.heartbeat = lambda: None
        self._events: Optional["queue.Queue[Event]"] = None

    def start(self) -> None:
        set_interface_namer(self._registerer.name_for)
        # a supervisor restart reuses the live subscription — resubscribing
        # would replay/miss discovery events depending on the informer
        if self._events is None:
            self._events = self._informer.subscribe()
        self._thread = threading.Thread(
            target=self._loop, args=(self._events,), name="iface-listener",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._informer.stop()
        if self._thread:
            self._thread.join(timeout=2.0)
        # drop the global namer hook: it closes over this listener's
        # registerer, which stops updating now (and would leak stale names
        # into any later agent instance in the same process)
        from netobserv_tpu.model.record import default_namer
        if interface_namer() == self._registerer.name_for:
            set_interface_namer(default_namer)

    def _loop(self, events: "queue.Queue[Event]") -> None:
        while not self._stop.is_set():
            self.heartbeat()
            faultinject.fire("iface_listener.loop")
            try:
                event = events.get(timeout=0.2)
            except queue.Empty:
                continue
            self._registerer.observe(event)
            self._count_attach(event.type.value, event.interface, 0)
            iface = event.interface
            if event.type == EventType.ADDED:
                if not self._filter.allowed(iface):
                    log.debug("interface %s excluded by filter", iface.name)
                    continue
                self._attach_with_retry(iface)
            else:
                try:
                    self._fetcher.detach(iface.index, iface.name,
                                         netns=iface.netns)
                    self.attached.discard((iface.netns, iface.index))
                except Exception as exc:
                    log.debug("detach %s failed: %s", iface.name, exc)

    def _count_attach(self, kind: str, iface, attempt: int) -> None:
        # reference counts attach_tc/attach_tcx/attach_fail with the attempt
        # number (interfaces_listener.go:192-247); level gates cardinality,
        # so the mac string is only built when trace level will expose it
        if self._metrics is not None:
            mac = (":".join(f"{b:02x}" for b in iface.mac)
                   if self._metrics.level == "trace" else "")
            self._metrics.count_interface_event(
                kind, ifname=iface.name, ifindex=iface.index,
                netns=iface.netns, mac=mac, retries=attempt)

    def _attach_with_retry(self, iface) -> None:
        retries = max(self._cfg.tc_attach_retries, 1)
        for attempt in range(1, retries + 1):
            if self._stop.is_set():
                return
            try:
                self._fetcher.attach(iface.index, iface.name,
                                     self._cfg.direction, netns=iface.netns)
                self.attached.add((iface.netns, iface.index))
                self._count_attach("attach", iface, attempt)
                log.info("attached to %s (index %d, netns %r)", iface.name,
                         iface.index, iface.netns)
                return
            except DoNotRetryError as exc:
                self._count_attach("attach_fail", iface, attempt)
                log.warning("attach %s failed permanently: %s",
                            iface.name, exc)
                return
            except Exception as exc:
                self._count_attach("attach_fail", iface, attempt)
                log.warning("attach %s failed (attempt %d/%d): %s",
                            iface.name, attempt, retries, exc)
                time.sleep(_RETRY_BACKOFF_S * attempt)
        if self._metrics is not None:
            self._metrics.count_error("iface-listener")
