"""Sketch federation plane: delta export/ingest + the central aggregator.

Per-host agents snapshot their mergeable sketch tables at every window roll
into a versioned protobuf frame (`delta.py`, jax-free — it must run on the
big-endian qemu CI tier), stream it over gRPC (`netobserv_tpu.grpc.
federation`), and a central TPU aggregator (`aggregator.py`) hierarchically
merges frames on-device and serves cluster-wide top-K / frequency /
cardinality / victim buckets from a non-blocking HTTP query surface
(`query.py`). docs/architecture.md "Sketch federation plane" is the map.
"""
