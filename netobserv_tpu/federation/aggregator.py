"""Central TPU aggregator: the fleet's one sketch-merge plane.

Hundreds of per-host agents each stream one delta frame per closed window
(`federation.delta`); this tier decodes, validates, and hierarchically
merges them ON DEVICE:

- single device: one jitted `statemerge.merge_tables` entry (donated
  aggregate, fixed frame shapes — compiled once, watched for retraces);
- in-pod mesh (`FEDERATION_MESH_SHAPE`): agents are hash-assigned to data
  shards and folded into per-shard partials with NO collectives
  (`parallel.merge.make_fold_delta_fn`); the two-axis ICI gather at window
  roll (`parallel.merge.make_merge_fn`) reconciles — the same steady-state/
  roll split as the flow ingest, one level up;
- cross-pod: `parallel.distributed.maybe_initialize_distributed` wires the
  spanning mesh (FEDERATION_* or SKETCH_* coordinator envs), and the same
  shard_map programs run across hosts over DCN.

The aggregate IS a `SketchState` fed by deltas instead of records, so the
existing window roll and report renderer serve the cluster-wide report
unchanged. Everything query-facing is published as a HOST-side snapshot at
window roll on the timer thread — the HTTP query surface (`federation.
query`) never dispatches a device op (same off-hot-path rules as
/debug/traces).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import zlib
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.federation import delta as fdelta
from netobserv_tpu.pb import sketch_delta_pb2
from netobserv_tpu.utils import faultinject, retrace, tracing

log = logging.getLogger("netobserv_tpu.federation.aggregator")


def agent_owner_shard(agent_id: str, n_shards: int) -> int:
    """Stable agent -> data-shard assignment (mesh mode): one agent's
    deltas always fold into the same shard's partial."""
    return zlib.crc32(agent_id.encode()) % max(1, n_shards)


class FederationAggregator:
    """Delta ingest + on-device merge + windowed cluster reports.

    Exporter-grade failure semantics: a bad frame is acked `accepted=0`
    and counted, a merge failure loses that frame (counted), a roll
    failure retries next window — nothing here ever tears down the gRPC
    stream every other agent is pushing on.
    """

    def __init__(self, sketch_cfg=None, window_s: float = 60.0,
                 mesh_shape: str = "", metrics=None,
                 sink: Optional[Callable[[dict], None]] = None,
                 stale_after_s: float = 120.0,
                 report_kwargs: Optional[dict] = None,
                 checkpoint_dir: str = "", checkpoint_every: int = 1,
                 agent_ttl_s: float = 0.0, alerts=None, archive=None):
        from netobserv_tpu.parallel.distributed import (
            maybe_initialize_distributed,
        )
        # the aggregator tier's spanning mesh wires under its own env
        # prefix (FEDERATION_*), falling back to the shared SKETCH_* one
        maybe_initialize_distributed(prefixes=("FEDERATION_", "SKETCH_"))
        import jax

        from netobserv_tpu.sketch import state as sk

        self._sk = sk
        self._cfg = sketch_cfg or sk.SketchConfig()
        self._window_s = window_s
        self._metrics = metrics
        self._sink = sink
        self._stale_after_s = stale_after_s
        self._report_kwargs = report_kwargs or {}
        #: previous merged-window heavy identity index (EvictedKeys diff)
        self._prev_heavy_index: Optional[dict] = None
        if metrics is not None:
            retrace.set_metrics(metrics)
            tracing.set_metrics(metrics)
        # frame contract: expected tensor shapes + geometry, derived from
        # THIS aggregator's config (a foreign shape must never reach the
        # fixed-shape jitted merge)
        template = sk.state_tables(sk.init_state(self._cfg))
        self._expected_shapes = fdelta.expected_shapes(template)
        self._dims = {"cm_depth": self._cfg.cm_depth,
                      "cm_width": self._cfg.cm_width,
                      "hll_precision": self._cfg.hll_precision,
                      "topk": self._cfg.topk,
                      "ewma_buckets": self._cfg.ewma_buckets}

        self._distributed = bool(mesh_shape)
        if self._distributed:
            from netobserv_tpu.parallel import (
                MeshSpec, make_mesh, merge as pmerge)
            spec = MeshSpec.parse(mesh_shape, len(jax.devices()))
            self._mesh = make_mesh(spec)
            self._ndata = spec.data
            self._pm = pmerge
            self._state = pmerge.init_dist_state(self._cfg, self._mesh)
            self._fold = pmerge.make_fold_delta_fn(self._mesh, self._cfg)
            self._roll = pmerge.make_merge_fn(self._mesh, self._cfg,
                                              with_tables=True)
        else:
            from netobserv_tpu.federation import statemerge
            self._ndata = 1
            self._state = sk.init_state(self._cfg)
            self._fold = retrace.watch(
                jax.jit(statemerge.merge_tables, donate_argnums=(0,)),
                "federation_merge")
            self._roll = retrace.watch(
                sk.make_roll_fn(self._cfg, with_tables=True),
                "federation_roll")

        self._lock = threading.Lock()          # aggregate state + counters
        self._publish_lock = threading.Lock()
        self._reports: collections.deque = collections.deque()
        self._max_queued_reports = 4
        self._window_deadline = time.monotonic() + window_s
        #: agent id -> {"last_ms", "window", "frames"} (monotonic last too)
        self._agents: dict[str, dict] = {}
        #: idempotent-delivery ledger: agent id -> {"epoch", "window_seq",
        #: "frame_uuid"} of the LAST APPLIED v2 frame. Checkpointed next to
        #: the aggregate state (same step) so redelivery across an
        #: aggregator restart still dedups; bounded by agent-TTL eviction.
        self._ledger: dict[str, dict] = {}
        self._window_agents: set[str] = set()
        self._frames_total = 0
        #: staleness-based agent eviction (FEDERATION_AGENT_TTL; 0 = off):
        #: past the TTL an agent leaves the ownership view AND its
        #: staleness gauge series is deleted (label cardinality must not
        #: grow forever with departed agents)
        self._agent_ttl_s = agent_ttl_s
        #: host mirror of the aggregate's window counter (updated at roll/
        #: restore): delta churn tensors re-base into the CLUSTER window
        #: domain before merging (fdelta.localize_churn) and reading the
        #: device scalar per frame would be a sync on the ingest path
        self._window_host = 0
        self._snapshot: Optional[dict] = None
        self._snap_lock = threading.Lock()
        self._snap_seq = 0
        #: continued agent traces parked for the current window (sampled
        #: frames only); adopted by the window trace at roll so the
        #: roll/publish spans complete each agent's cross-process journey
        self._window_traces: list = []
        self._max_window_traces = 32
        #: published fleet snapshot (/federation/fleet): whole-dict
        #: seq-stamped swaps, rebuilt on the timer thread — the route only
        #: ever reads the published reference (torn reads impossible by
        #: construction, merge lock never taken on the request path)
        self._fleet: Optional[dict] = None
        self._fleet_lock = threading.Lock()
        self._fleet_seq = 0
        self._closed = threading.Event()
        # cluster-wide continuous detection (netobserv_tpu/alerts): the
        # SAME engine core the agents mount, driven here by the merged-
        # window snapshot each roll publishes (thin-adapter pattern, like
        # federation/query.py over query/core). None = disabled, one
        # is-None check on the publish path.
        self.alerts = alerts
        # cluster-wide sketch warehouse (netobserv_tpu/archive): the SAME
        # archive plane the agents mount, fed here by each MERGED window's
        # tables at publish — /federation/range is a thin adapter over its
        # route_payload (the federation/query.py never-fork rule). None =
        # disabled, one is-None check on the publish path.
        self.archive = archive

        # checkpoint/restore: aggregate SketchState + delivery ledger saved
        # at window roll (post-roll state, so a restore can never re-publish
        # a closed window); restart loses at most the uncheckpointed
        # partial window
        self._ckpt = None
        self._ckpt_dir = checkpoint_dir
        self._ckpt_every = max(1, int(checkpoint_every))
        self._n_rolls = 0
        self._pending_ckpt: Optional[tuple] = None
        if checkpoint_dir:
            from netobserv_tpu.sketch.checkpoint import SketchCheckpointer
            self._ckpt = SketchCheckpointer(checkpoint_dir)
            self._maybe_restore()

        self.heartbeat = lambda: None
        self._timer: Optional[threading.Thread] = None
        self.start_window_timer()

    # --- checkpoint/restore ---------------------------------------------
    def _maybe_restore(self) -> None:
        """Restore the aggregate state + delivery ledger from the latest
        checkpoint. A restore failure starts a fresh window (logged) — the
        aggregator tier must come up in any case. The restored pytree has
        the SAME shapes/dtypes as the init template, so the jitted
        fold/roll entries never retrace across a restart."""
        try:
            step = self._ckpt.latest_step()
            if step is not None:
                self._state = self._ckpt.restore(self._state)
                self._apply_restored_meta(
                    self._ckpt.read_metadata(step) or {})
            # publish-commit marker: with checkpoint_every > 1 (or before
            # the first tensor save) windows PUBLISHED after the newest
            # tensor checkpoint must neither re-use their window id nor
            # re-merge their redelivered frames — fast-forward the window
            # counter past the last published id and overlay the ledger
            # those publishes committed (the skipped windows' tensor
            # contribution is the documented every-N durability loss)
            pub = self._ckpt.read_publish_marker()
            restored_w = int(np.asarray(self._state.window))
            if pub is not None and pub["window"] >= restored_w:
                self._apply_restored_meta(pub["meta"])
                self._state = self._state._replace(
                    window=self._state.window
                    + np.int32(pub["window"] + 1 - restored_w))
            elif step is None:
                return
            self._window_host = int(np.asarray(self._state.window))
            log.info("restored federation aggregate (checkpoint step %s, "
                     "next window %d, %d agents in the ledger)", step,
                     self._window_host, len(self._ledger))
        except Exception as exc:
            log.error("aggregator checkpoint restore failed "
                      "(starting a fresh window): %s", exc)
            if self._metrics is not None:
                self._metrics.count_error("federation")
            self._quarantine_checkpoints()

    def _quarantine_checkpoints(self) -> None:
        """An unrestorable checkpoint directory must not stay live: the
        fresh process restarts its window counter at 0, so orbax retention
        (highest steps win) would garbage-collect every NEW checkpoint
        while latest_step() kept answering the corrupt high step — the
        next restart would retry the same broken restore forever. Move the
        directory aside (kept for forensics) and checkpoint into a clean
        one; if even the rename fails, disable checkpointing rather than
        write into a poisoned dir."""
        import os
        try:
            self._ckpt.close()
        except Exception:
            pass
        dest = f"{self._ckpt_dir}.corrupt-{os.getpid()}-{time.time_ns()}"
        try:
            os.rename(self._ckpt_dir, dest)
            from netobserv_tpu.sketch.checkpoint import SketchCheckpointer
            self._ckpt = SketchCheckpointer(self._ckpt_dir)
            log.warning("quarantined unrestorable checkpoint dir to %s; "
                        "checkpointing continues into a fresh %s",
                        dest, self._ckpt_dir)
        except Exception as exc:
            self._ckpt = None
            log.error("could not quarantine checkpoint dir %s (%s) — "
                      "checkpointing DISABLED for this run",
                      self._ckpt_dir, exc)

    def _apply_restored_meta(self, meta: dict) -> None:
        """Re-seat the delivery ledger + agent view from checkpointed
        metadata (the roll-time sidecar, or the newer publish marker)."""
        self._ledger = {a: dict(v)
                        for a, v in (meta.get("ledger") or {}).items()}
        # re-seat agent liveness from wall-clock last_ms: monotonic
        # deadlines do not survive a process, so staleness restarts
        # from the checkpointed wall gap (clamped at 0)
        now_ms, now_mono = time.time() * 1e3, time.monotonic()
        self._agents.clear()
        for a, info in (meta.get("agents") or {}).items():
            gap_s = max(0.0, (now_ms - float(info.get("last_ms", 0.0)))
                        / 1e3)
            self._agents[a] = {
                "frames": int(info.get("frames", 0)),
                "window": int(info.get("window", 0)),
                "last_ms": float(info.get("last_ms", 0.0)),
                "last_mono": now_mono - gap_s}

    def _delivery_meta_locked(self) -> dict:
        """JSON-able ledger + agent view (caller holds self._lock)."""
        return {"ledger": {a: dict(v) for a, v in self._ledger.items()},
                "agents": {a: {"frames": v["frames"], "window": v["window"],
                               "last_ms": v["last_ms"]}
                           for a, v in self._agents.items()}}

    def _stage_checkpoint_locked(self, report) -> None:
        """Stage this roll's checkpoint UNDER self._lock: later folds
        DONATE self._state into the jitted merge, so the save must work
        from a private device-side copy taken before any post-roll fold
        can run. The disk I/O itself happens OFF the lock
        (_run_pending_checkpoint, timer thread) — a HUNG checkpoint
        filesystem stalls only the supervised timer thread (heartbeat
        stops, supervisor flips DEGRADED), never delta ingest, which
        would otherwise deadlock fleet-wide behind this lock."""
        import jax
        import jax.numpy as jnp

        snap = jax.tree.map(jnp.copy, self._state)
        jax.block_until_ready(snap)  # the copy must land before unlock
        self._pending_ckpt = (int(np.asarray(report.window)),
                              self._delivery_meta_locked(), snap)

    def _run_pending_checkpoint(self) -> None:
        """Persist the staged (ledger sidecar, then state) pair, OFF
        self._lock, before any queued publish (durable checkpoint, then
        publish — exactly-once across a restart). A checkpoint failure is
        swallowed + counted: a wedged disk loses durability, never the
        live plane."""
        with self._lock:
            payload, self._pending_ckpt = self._pending_ckpt, None
        if payload is None or self._ckpt is None:
            return
        step, meta, snap = payload
        m = self._metrics
        try:
            faultinject.fire("federation.checkpoint")
            self._ckpt.save_metadata(step, meta)
            # wait=True: the checkpoint is DURABLE before this window
            # publishes — a kill any time after restores this boundary
            self._ckpt.save(step, snap, wait=True)
            if m is not None:
                m.federation_checkpoints_total.labels("ok").inc()
        except Exception as exc:
            log.error("federation checkpoint failed (window keeps "
                      "rolling without durability): %s", exc)
            if m is not None:
                m.federation_checkpoints_total.labels("error").inc()
                m.count_error("federation")

    # --- delta ingest (gRPC handler) ------------------------------------
    def ingest_frame(self, data: bytes) -> sketch_delta_pb2.DeltaAck:
        """Decode + validate + ledger-check + merge one frame; always
        returns an ack. Idempotent: a redelivered v2 frame (same agent /
        epoch / window_seq / frame_uuid) acks accepted+duplicate without
        merging, and an out-of-order stale window acks-and-discards — a
        sender retrying after an ambiguous DEADLINE_EXCEEDED can never
        double-count a window."""
        t0 = time.perf_counter()
        trace = tracing.start_trace("delta")
        # the continued CROSS-PROCESS trace (the frame's optional
        # trace_ctx): resolved right after decode; NULL_TRACE until then
        # and on every unsampled/context-less frame — one is-None-shaped
        # check per frame, the zero-cost bar
        cont = tracing.NULL_TRACE
        parked = False
        try:
            data = faultinject.fire("federation.delta_ingest", data)
            try:
                with trace.stage("delta_decode"):
                    frame = fdelta.decode_frame(data)
                    # legacy (v1/v2) frames normalize to the current table
                    # layout HERE — zero-filled churn tensors, padded
                    # scalars — so the fixed-signature jitted merge sees
                    # one layout for every supported version (no retrace)
                    frame = frame._replace(
                        tables=fdelta.upgrade_tables(frame))
            except fdelta.DeltaVersionError as exc:
                return self._reject("version_mismatch", str(exc))
            except fdelta.DeltaFrameError as exc:
                return self._reject("decode_error", str(exc))
            cont = tracing.continue_trace(frame.trace_ctx,
                                          "federation_delta")
            if cont.sampled and self._metrics is not None:
                self._metrics.trace_context_propagated_total.labels(
                    "continued").inc()
            # validate/ledger/merge spans land on BOTH the local delta
            # trace and the continued agent trace (group collapses to one
            # object — the shared NULL_TRACE — when neither is sampled)
            tr = tracing.group(trace, cont)
            try:
                with tr.stage("delta_validate"):
                    fdelta.validate_shapes(frame, self._expected_shapes)
                    if frame.dims != self._dims:
                        raise fdelta.DeltaFrameError(
                            f"frame geometry {frame.dims} != aggregator's "
                            f"{self._dims} (agent {frame.agent_id!r})")
            except fdelta.DeltaFrameError as exc:
                return self._reject("shape_mismatch", str(exc))
            try:
                with tr.stage("delta_merge_dispatch"):
                    result = self._merge_frame(frame, tr)
            except Exception as exc:
                log.error("delta merge failed (frame from %r dropped): %s",
                          frame.agent_id, exc)
                return self._reject("merge_error", str(exc))
            # a MERGED frame's continued trace parks until this window
            # closes: the roll/publish spans attach there, completing the
            # agent->cluster journey under one trace id
            if cont.sampled and result in ("ok", "legacy"):
                parked = self._park_window_trace(cont)
        finally:
            trace.finish()
            if cont.sampled and not parked:
                cont.finish()
        m = self._metrics
        if m is not None:
            m.federation_deltas_total.labels(result).inc()
            m.federation_delta_bytes_total.inc(len(data))
            if result in ("ok", "legacy"):
                # only real merges feed the histogram: discarded frames
                # are near-no-ops and would bury the step change the docs
                # say to watch for (retraces)
                m.federation_merge_seconds.observe(time.perf_counter() - t0)
        return sketch_delta_pb2.DeltaAck(
            accepted=1, version=fdelta.DELTA_FORMAT_VERSION,
            duplicate=1 if result in ("duplicate", "stale") else 0,
            reason=(fdelta.ACK_REASON_DUPLICATE if result == "duplicate"
                    else fdelta.ACK_REASON_STALE if result == "stale"
                    else ""))

    def _reject(self, result: str,
                reason: str) -> sketch_delta_pb2.DeltaAck:
        log.warning("delta frame rejected (%s): %s", result, reason)
        if self._metrics is not None:
            self._metrics.federation_deltas_total.labels(result).inc()
        return sketch_delta_pb2.DeltaAck(
            accepted=0, version=fdelta.DELTA_FORMAT_VERSION, reason=reason)

    def _ledger_verdict_locked(self, frame: fdelta.DeltaFrame) -> str:
        """Classify a frame against the last-applied ledger (caller holds
        self._lock). Returns one of:

        - ``legacy``    v1 frame — no delivery header; merge unconditionally
        - ``ok``        first delivery of a new window (or a new epoch —
                        a returning agent re-registers cleanly)
        - ``duplicate`` same (epoch, window_seq, frame_uuid) already
                        applied — redelivery after an ambiguous deadline
        - ``stale``     window_seq at-or-behind the last applied one (or a
                        dead epoch's straggler) — out-of-order delivery;
                        ack-and-discard, never merge
        """
        if frame.version < 2:
            return "legacy"
        # tenant planes ledger independently (fdelta.source_key): a
        # multi-tenant agent's N frames per window share agent_id, epoch
        # and window_seq — keyed by bare agent_id, tenants 1..N-1 would
        # read as stale deliveries of tenant 0's frame and be discarded
        last = self._ledger.get(fdelta.source_key(frame))
        if last is None or frame.agent_epoch > last["epoch"]:
            return "ok"
        if frame.agent_epoch < last["epoch"]:
            return "stale"
        if frame.window_seq > last["window_seq"]:
            return "ok"
        if (frame.window_seq == last["window_seq"]
                and frame.frame_uuid == last["frame_uuid"]):
            return "duplicate"
        return "stale"

    def _note_discard_locked(self, frame: fdelta.DeltaFrame,
                             verdict: str) -> None:
        """Bookkeeping for a discarded frame (caller holds self._lock).
        A DUPLICATE refreshes liveness — the agent is alive, its window
        just doesn't contribute twice. A STALE frame deliberately does
        NOT: if an agent's epoch ever regresses (a wall-clock step-back
        across a restart), every frame it sends reads stale, and the only
        self-healing path is the TTL eviction forgetting the poisoned
        ledger entry so the agent can re-register — stale frames keeping
        it 'alive' would block that forever."""
        src = fdelta.source_key(frame)
        last = self._ledger.get(src)
        if last is not None and frame.agent_epoch < last["epoch"]:
            log.warning(
                "agent %r sent epoch %d below its ledger epoch %d (clock "
                "step-back across a restart?) — frames discarded as stale "
                "until the FEDERATION_AGENT_TTL eviction re-admits it",
                src, frame.agent_epoch, last["epoch"])
        if verdict == "duplicate" and src in self._agents:
            info = self._agents[src]
            info["last_ms"] = time.time() * 1e3
            info["last_mono"] = time.monotonic()

    def _park_window_trace(self, cont) -> bool:
        """Hold a continued (sampled, merged) agent trace until the window
        it contributed to closes — the roll/publish spans attach there.
        Bounded: past the cap the oldest parked trace seals early (its
        ingest spans are already evidence) so a hot window cannot grow the
        list without bound. Returns True when parked (the caller must not
        finish it)."""
        with self._lock:
            self._window_traces.append(cont)
            shed = (self._window_traces.pop(0)
                    if len(self._window_traces) > self._max_window_traces
                    else None)
        if shed is not None:
            shed.finish()
        return True

    def _merge_frame(self, frame: fdelta.DeltaFrame,
                     tr=tracing.NULL_TRACE) -> str:
        import jax

        # advisory pre-check: a redelivered/stale frame must not pay the
        # host->device transfer of the whole table set just to be
        # discarded under the lock (a retry flood would otherwise steal
        # transfer bandwidth from real merges)
        with tr.stage("delta_ledger"):
            with self._lock:
                early = self._ledger_verdict_locked(frame)
                if early in ("duplicate", "stale"):
                    self._note_discard_locked(frame, early)
                    return early
        # churn tensors re-base into the CLUSTER window domain: the
        # aggregate's own slot_roll maintains the cluster prev baseline
        # (summing agents' agent-window prevs would double-count every
        # persistent key), and first_seen stamps the cluster window a key
        # first reached this table (fdelta.localize_churn)
        host_tables = fdelta.localize_churn(frame.tables, self._window_host)
        if self._distributed:
            tables = {name: self._pm.put_replicated(
                self._mesh, np.ascontiguousarray(arr))
                for name, arr in host_tables.items()}
            owner = self._pm.put_replicated(self._mesh, np.asarray(
                [agent_owner_shard(fdelta.source_key(frame),
                                   self._ndata)], np.int32))
        else:
            tables = {name: jax.device_put(arr)
                      for name, arr in host_tables.items()}
        with self._lock:
            # authoritative verdict + fold + ledger update are ONE critical
            # section: two racing copies of the same frame serialize here,
            # the second sees the first's ledger entry and discards
            verdict = self._ledger_verdict_locked(frame)
            if verdict not in ("ok", "legacy"):
                self._note_discard_locked(frame, verdict)
                return verdict
            if self._distributed:
                self._state = self._fold(self._state, tables, owner)
            else:
                self._state = self._fold(self._state, tables)
            src = fdelta.source_key(frame)
            if verdict == "ok":
                self._ledger[src] = {
                    "epoch": frame.agent_epoch,
                    "window_seq": frame.window_seq,
                    "frame_uuid": frame.frame_uuid}
            self._frames_total += 1
            self._window_agents.add(src)
            info = self._agents.setdefault(
                src, {"frames": 0, "window": 0, "last_ms": 0.0,
                      "last_mono": 0.0})
            info["frames"] += 1
            info["window"] = frame.window
            info["last_ms"] = time.time() * 1e3
            info["last_mono"] = time.monotonic()
            if frame.telemetry is not None:
                # latest-wins per-agent health block (the fleet table's
                # row); frames without one leave the previous block in
                # place (mixed-fleet rollouts keep their last report)
                info["telemetry"] = frame.telemetry
            if time.monotonic() >= self._window_deadline:
                self._close_window_locked()
        return verdict

    # --- window roll ----------------------------------------------------
    def start_window_timer(self) -> None:
        self._timer = threading.Thread(
            target=self._window_loop, name="federation-window", daemon=True)
        self._timer.start()

    @property
    def _window_poll_s(self) -> float:
        return min(1.0, self._window_s / 10)

    def register_supervised(self, supervisor, heartbeat_timeout_s=None,
                            **kwargs) -> None:
        beat = supervisor.register(
            "federation-window", restart=self.start_window_timer,
            thread_getter=lambda: self._timer,
            heartbeat_timeout_s=(heartbeat_timeout_s or 10.0)
            + self._window_poll_s,
            **kwargs)
        self.heartbeat = beat

    def _window_loop(self) -> None:
        while not self._closed.wait(timeout=self._window_poll_s):
            self.heartbeat()
            faultinject.fire("federation.window_timer")
            try:
                faultinject.fire("federation.window_roll")
                with self._lock:
                    if time.monotonic() >= self._window_deadline:
                        self._close_window_locked()
            except Exception as exc:
                log.error("federation window roll failed (will retry): %s",
                          exc)
                if self._metrics is not None:
                    self._metrics.count_error("federation")
            self._evict_stale_agents()
            self._update_staleness()
            self._update_fleet()
            self._publish_queued()

    def _close_window_locked(self) -> None:
        """Dispatch the roll UNDER self._lock; render/publish happen on the
        timer thread outside it (delta merges never wait on a sink)."""
        # the window trace is a GROUP: the aggregator's own trace plus
        # every continued agent trace parked this window — one roll/publish
        # serves them all, so its spans land on each (group() collapses to
        # the shared NULL_TRACE when nothing is sampled)
        conts, self._window_traces = self._window_traces, []
        wtrace = tracing.group(
            tracing.start_trace("federation_window"), *conts)
        self._window_deadline = time.monotonic() + self._window_s
        try:
            with wtrace.stage("roll_dispatch"):
                self._state, report, tables = self._roll(self._state)
        except BaseException:
            wtrace.finish()
            raise
        self._window_host += 1  # keep the host mirror on the roll counter
        agents = sorted(self._window_agents)
        self._window_agents = set()
        # checkpoint the POST-roll state + the ledger at this step: a
        # restore resumes the fresh window (never re-rolls, never
        # re-publishes a closed one) and redelivered pre-crash frames
        # still dedup against the restored ledger
        if self._ckpt is not None:
            self._n_rolls += 1
            if self._n_rolls % self._ckpt_every == 0:
                self._stage_checkpoint_locked(report)
        self._reports.append((report, tables, agents, wtrace))
        while len(self._reports) > self._max_queued_reports:
            try:
                _r, _t, _a, shed = self._reports.popleft()
            except IndexError:
                break
            shed.finish()
            log.error("federation report queue full; dropping the oldest "
                      "unpublished window")
            if self._metrics is not None:
                self._metrics.count_error("federation")

    def _publish_queued(self, timeout_s: Optional[float] = None) -> None:
        # a bounded acquire (close()/shutdown path) must not deadlock
        # behind a timer thread wedged inside a hung checkpoint save —
        # the save holds this lock for the duration of its disk I/O
        if not self._publish_lock.acquire(
                timeout=-1 if timeout_s is None else timeout_s):
            log.error("publish lock busy past %.1fs (hung checkpoint "
                      "disk?) — skipping publish on this path", timeout_s)
            if self._metrics is not None:
                self._metrics.count_error("federation")
            return
        try:
            self._run_pending_checkpoint()
            while self._reports:
                try:
                    report, tables, agents, wtrace = self._reports.popleft()
                except IndexError:
                    return
                try:
                    self._publish(report, tables, agents, wtrace)
                except Exception as exc:
                    log.error("federation report publish failed "
                              "(report lost): %s", exc)
                    if self._metrics is not None:
                        self._metrics.count_error("federation")
                finally:
                    wtrace.finish()
        finally:
            self._publish_lock.release()

    def _publish(self, report, tables, agents: list, wtrace) -> None:
        from netobserv_tpu.exporter.tpu_sketch import (
            heavy_identity_index, report_to_json,
        )

        with wtrace.stage("report_render"):
            obj = report_to_json(report,
                                 prev_heavy_index=self._prev_heavy_index,
                                 **self._report_kwargs)
            # cluster-tier EvictedKeys diff against the previous MERGED
            # window (same rotate-at-roll contract as the exporter)
            self._prev_heavy_index = heavy_identity_index(report)
            obj["Type"] = "federation_window_report"
            obj["Agents"] = agents
            obj["TimestampMs"] = time.time_ns() // 1_000_000
            # host copies of the merged tables the query surface reads
            # (the np.asarray touch includes the device->host transfer)
            cm_bytes = np.asarray(tables["cm_bytes"])
            cm_pkts = np.asarray(tables["cm_pkts"])
            heavy = {k: np.asarray(tables["heavy_" + k])
                     for k in ("words", "h1", "h2", "counts", "valid",
                               "prev_counts", "first_seen", "epoch")}
        with self._snap_lock:
            self._snap_seq += 1
            seq = self._snap_seq
        snap = {
            "window": obj["Window"],
            "ts_ms": obj["TimestampMs"],
            "seq": seq,
            "report": obj,
            "agents": {a: dict(v) for a, v in self._agents_view().items()},
            "cm_bytes": cm_bytes,
            "cm_pkts": cm_pkts,
            "heavy": heavy,
            "total_records": obj["Records"],
            "total_bytes": obj["Bytes"],
        }
        with self._snap_lock:
            self._snapshot = snap
        # cluster-wide alert evaluation rides the snapshot it just
        # published (timer thread; safe_evaluate swallows+counts — a
        # failing evaluation never loses the publish or the sink
        # delivery below)
        if self.alerts is not None:
            self.alerts.safe_evaluate(snap)
        m = self._metrics
        if m is not None:
            m.federation_active_agents.set(len(agents))
            m.sketch_window_reports_total.inc()
        if self._ckpt is not None:
            # publish-commit marker, written BEFORE the sink (at-most-once
            # like the rest of the publish path): a restore from an older
            # tensor checkpoint (checkpoint_every > 1) fast-forwards past
            # this window id and keeps the ledger it committed
            try:
                with self._lock:
                    meta = self._delivery_meta_locked()
                self._ckpt.save_publish_marker(obj["Window"], meta)
            except Exception as exc:
                log.error("publish marker write failed (a restart may "
                          "re-publish window %s): %s", obj["Window"], exc)
                if m is not None:
                    m.count_error("federation")
        if self._sink is not None:
            with wtrace.stage("report_sink"):
                self._sink(obj)
        # cluster-wide warehouse write LAST, own try (the agent-side
        # ordering rule): the snapshot and sink already committed, so a
        # wedged archive disk loses only this merged window's durability —
        # counted — and stalls only this supervised timer thread, never
        # delta ingest. The tables here are the roll's outputs (staged by
        # construction), and the np.asarray copies above already landed.
        if self.archive is not None:
            try:
                faultinject.fire("sketch.archive_write")
                host_tables = {name: np.asarray(tables[name])
                               for name, _ in fdelta.TABLE_SPEC}
                self.archive.write_window(host_tables,
                                          window=int(obj["Window"]),
                                          ts_ms=int(obj["TimestampMs"]))
            except Exception as exc:
                log.error("cluster archive write failed (window %s not "
                          "archived; report already published): %s",
                          obj["Window"], exc)
                if m is not None:
                    m.count_error("federation-archive")

    def _agents_view(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {a: {"frames": v["frames"], "window": v["window"],
                        "last_ms": v["last_ms"],
                        "staleness_s": round(now - v["last_mono"], 3),
                        "stale": (now - v["last_mono"])
                        > self._stale_after_s,
                        "epoch": self._ledger.get(a, {}).get("epoch", 0),
                        "window_seq": self._ledger.get(a, {})
                        .get("window_seq", 0),
                        "telemetry": v.get("telemetry")}
                    for a, v in self._agents.items()}

    def _update_fleet(self) -> None:
        """Rebuild + swap the published fleet snapshot (timer thread; also
        run by flush() so tests/shutdown see a current table). The build
        reads the agent view under the merge lock BRIEFLY here — the
        /federation/fleet route never does: it reads only the reference
        this whole-dict seq-stamped swap publishes."""
        agents = self._agents_view()
        counts = {"agents": len(agents),
                  "stale": sum(1 for v in agents.values() if v["stale"]),
                  "overloaded": 0, "degraded": 0, "alerting": 0}
        for v in agents.values():
            tel = v.get("telemetry")
            conditions = (tel or {}).get("conditions", ())
            if "OVERLOADED" in conditions:
                counts["overloaded"] += 1
            if "DEGRADED" in conditions:
                counts["degraded"] += 1
            if "ALERTING" in conditions:
                counts["alerting"] += 1
        with self._fleet_lock:
            self._fleet_seq += 1
            self._fleet = {"seq": self._fleet_seq,
                           "ts_ms": time.time_ns() // 1_000_000,
                           "window_s": self._window_s,
                           "stale_after_s": self._stale_after_s,
                           "counts": counts,
                           "agents": agents}

    def fleet(self) -> Optional[dict]:
        """The published fleet snapshot (None before the first timer tick
        sees any state). Host-side dict only — never a device op, never
        the merge lock; an evicted agent drops out at the next rebuild."""
        with self._fleet_lock:
            return self._fleet

    def _update_staleness(self) -> None:
        m = self._metrics
        if m is None:
            return
        for agent, info in self._agents_view().items():
            m.federation_agent_staleness_seconds.labels(agent).set(
                info["staleness_s"])

    def _evict_stale_agents(self) -> None:
        """Agent lifecycle (FEDERATION_AGENT_TTL): drop agents silent past
        the TTL from the ownership view, DELETE their per-agent gauge
        series (departed agents must not pin label cardinality forever),
        and forget their ledger entry — a returning agent re-registers
        cleanly (same epoch + higher seq, or a fresh epoch after a
        restart). Counted in federation_agent_evictions_total."""
        ttl = self._agent_ttl_s
        if not ttl:
            return
        now = time.monotonic()
        with self._lock:
            dead = [a for a, v in self._agents.items()
                    if now - v["last_mono"] > ttl]
            for a in dead:
                del self._agents[a]
                self._ledger.pop(a, None)
                self._window_agents.discard(a)
        m = self._metrics
        for a in dead:
            log.warning("evicting dark agent %r (no delta for > %.0fs)",
                        a, ttl)
            if m is not None:
                m.remove_labeled(m.federation_agent_staleness_seconds, a)
                m.federation_agent_evictions_total.inc()

    # --- query surface (host-side, never a device op) -------------------
    def snapshot(self) -> Optional[dict]:
        """The last closed window's published snapshot (None before the
        first roll publishes)."""
        with self._snap_lock:
            return self._snapshot

    def status(self) -> dict:
        with self._lock:
            frames = self._frames_total
            window_agents = sorted(self._window_agents)
        snap = self.snapshot()
        out = {
            "frames_total": frames,
            "agents": self._agents_view(),
            "current_window_agents": window_agents,
            "last_published_window": None if snap is None
            else snap["window"],
            "window_s": self._window_s,
            "mesh": self._distributed,
            "format_version": fdelta.DELTA_FORMAT_VERSION,
            "supported_versions": list(fdelta.SUPPORTED_VERSIONS),
            "agent_ttl_s": self._agent_ttl_s,
            "checkpointing": self._ckpt is not None,
        }
        if self.alerts is not None:
            # one engine-view read, same read-once rule as /query/status
            out["alerts"] = self.alerts.summary()
        if self.archive is not None:
            out["archive"] = self.archive.stats()
        return out

    def query_frequency(self, src: str, dst: str, src_port: int = 0,
                        dst_port: int = 0, proto: int = 0) -> Optional[dict]:
        """CM point query with error bars against the last closed window's
        MERGED tables — delegated to the shared query core (pure host
        numpy through the hashing twins, non-blocking)."""
        snap = self.snapshot()
        if snap is None:
            return None
        from netobserv_tpu.query import core as qcore
        return qcore.frequency_payload(snap, src, dst, src_port, dst_port,
                                       proto)

    # --- lifecycle ------------------------------------------------------
    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Close the current window now and publish synchronously.
        `timeout_s` bounds the wait for the publish lock (shutdown path:
        a timer thread wedged inside a hung checkpoint save holds it —
        close() must still return)."""
        with self._lock:
            self._close_window_locked()
        self._update_fleet()
        self._publish_queued(timeout_s)

    def close(self) -> None:
        self._closed.set()
        if self._timer is not None:
            self._timer.join(timeout=2.0)
        # bounded: a hung checkpoint disk must wedge the timer thread at
        # worst, never turn shutdown into a deadlock on the publish lock
        self.flush(timeout_s=10.0)
        if self._ckpt is not None:
            try:
                self._ckpt.close()
            except Exception as exc:
                log.error("checkpointer close failed: %s", exc)

    def kill(self) -> None:
        """Chaos-harness crash: stop the timer WITHOUT the final flush,
        publish, or checkpoint — everything since the last roll-time
        checkpoint is lost, exactly like a SIGKILL. Tests use this to pin
        the restore semantics; production shutdown is close()."""
        self._closed.set()
        if self._timer is not None:
            self._timer.join(timeout=2.0)
