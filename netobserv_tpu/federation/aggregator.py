"""Central TPU aggregator: the fleet's one sketch-merge plane.

Hundreds of per-host agents each stream one delta frame per closed window
(`federation.delta`); this tier decodes, validates, and hierarchically
merges them ON DEVICE:

- single device: one jitted `statemerge.merge_tables` entry (donated
  aggregate, fixed frame shapes — compiled once, watched for retraces);
- in-pod mesh (`FEDERATION_MESH_SHAPE`): agents are hash-assigned to data
  shards and folded into per-shard partials with NO collectives
  (`parallel.merge.make_fold_delta_fn`); the two-axis ICI gather at window
  roll (`parallel.merge.make_merge_fn`) reconciles — the same steady-state/
  roll split as the flow ingest, one level up;
- cross-pod: `parallel.distributed.maybe_initialize_distributed` wires the
  spanning mesh (FEDERATION_* or SKETCH_* coordinator envs), and the same
  shard_map programs run across hosts over DCN.

The aggregate IS a `SketchState` fed by deltas instead of records, so the
existing window roll and report renderer serve the cluster-wide report
unchanged. Everything query-facing is published as a HOST-side snapshot at
window roll on the timer thread — the HTTP query surface (`federation.
query`) never dispatches a device op (same off-hot-path rules as
/debug/traces).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
import zlib
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.federation import delta as fdelta
from netobserv_tpu.pb import sketch_delta_pb2
from netobserv_tpu.utils import faultinject, retrace, tracing

log = logging.getLogger("netobserv_tpu.federation.aggregator")


def agent_owner_shard(agent_id: str, n_shards: int) -> int:
    """Stable agent -> data-shard assignment (mesh mode): one agent's
    deltas always fold into the same shard's partial."""
    return zlib.crc32(agent_id.encode()) % max(1, n_shards)


class FederationAggregator:
    """Delta ingest + on-device merge + windowed cluster reports.

    Exporter-grade failure semantics: a bad frame is acked `accepted=0`
    and counted, a merge failure loses that frame (counted), a roll
    failure retries next window — nothing here ever tears down the gRPC
    stream every other agent is pushing on.
    """

    def __init__(self, sketch_cfg=None, window_s: float = 60.0,
                 mesh_shape: str = "", metrics=None,
                 sink: Optional[Callable[[dict], None]] = None,
                 stale_after_s: float = 120.0,
                 report_kwargs: Optional[dict] = None):
        from netobserv_tpu.parallel.distributed import (
            maybe_initialize_distributed,
        )
        # the aggregator tier's spanning mesh wires under its own env
        # prefix (FEDERATION_*), falling back to the shared SKETCH_* one
        maybe_initialize_distributed(prefixes=("FEDERATION_", "SKETCH_"))
        import jax

        from netobserv_tpu.sketch import state as sk

        self._sk = sk
        self._cfg = sketch_cfg or sk.SketchConfig()
        self._window_s = window_s
        self._metrics = metrics
        self._sink = sink
        self._stale_after_s = stale_after_s
        self._report_kwargs = report_kwargs or {}
        if metrics is not None:
            retrace.set_metrics(metrics)
            tracing.set_metrics(metrics)
        # frame contract: expected tensor shapes + geometry, derived from
        # THIS aggregator's config (a foreign shape must never reach the
        # fixed-shape jitted merge)
        template = sk.state_tables(sk.init_state(self._cfg))
        self._expected_shapes = fdelta.expected_shapes(template)
        self._dims = {"cm_depth": self._cfg.cm_depth,
                      "cm_width": self._cfg.cm_width,
                      "hll_precision": self._cfg.hll_precision,
                      "topk": self._cfg.topk,
                      "ewma_buckets": self._cfg.ewma_buckets}

        self._distributed = bool(mesh_shape)
        if self._distributed:
            from netobserv_tpu.parallel import (
                MeshSpec, make_mesh, merge as pmerge)
            spec = MeshSpec.parse(mesh_shape, len(jax.devices()))
            self._mesh = make_mesh(spec)
            self._ndata = spec.data
            self._pm = pmerge
            self._state = pmerge.init_dist_state(self._cfg, self._mesh)
            self._fold = pmerge.make_fold_delta_fn(self._mesh, self._cfg)
            self._roll = pmerge.make_merge_fn(self._mesh, self._cfg,
                                              with_tables=True)
        else:
            from netobserv_tpu.federation import statemerge
            self._ndata = 1
            self._state = sk.init_state(self._cfg)
            self._fold = retrace.watch(
                jax.jit(statemerge.merge_tables, donate_argnums=(0,)),
                "federation_merge")
            self._roll = retrace.watch(
                sk.make_roll_fn(self._cfg, with_tables=True),
                "federation_roll")

        self._lock = threading.Lock()          # aggregate state + counters
        self._publish_lock = threading.Lock()
        self._reports: collections.deque = collections.deque()
        self._max_queued_reports = 4
        self._window_deadline = time.monotonic() + window_s
        #: agent id -> {"last_ms", "window", "frames"} (monotonic last too)
        self._agents: dict[str, dict] = {}
        self._window_agents: set[str] = set()
        self._frames_total = 0
        self._snapshot: Optional[dict] = None
        self._snap_lock = threading.Lock()
        self._closed = threading.Event()
        self.heartbeat = lambda: None
        self._timer: Optional[threading.Thread] = None
        self.start_window_timer()

    # --- delta ingest (gRPC handler) ------------------------------------
    def ingest_frame(self, data: bytes) -> sketch_delta_pb2.DeltaAck:
        """Decode + validate + merge one frame; always returns an ack."""
        t0 = time.perf_counter()
        trace = tracing.start_trace("delta")
        try:
            faultinject.fire("federation.ingest")
            try:
                with trace.stage("delta_decode"):
                    frame = fdelta.decode_frame(data)
            except fdelta.DeltaVersionError as exc:
                return self._reject("version_mismatch", str(exc))
            except fdelta.DeltaFrameError as exc:
                return self._reject("decode_error", str(exc))
            try:
                fdelta.validate_shapes(frame, self._expected_shapes)
                if frame.dims != self._dims:
                    raise fdelta.DeltaFrameError(
                        f"frame geometry {frame.dims} != aggregator's "
                        f"{self._dims} (agent {frame.agent_id!r})")
            except fdelta.DeltaFrameError as exc:
                return self._reject("shape_mismatch", str(exc))
            try:
                with trace.stage("delta_merge_dispatch"):
                    self._merge_frame(frame)
            except Exception as exc:
                log.error("delta merge failed (frame from %r dropped): %s",
                          frame.agent_id, exc)
                return self._reject("merge_error", str(exc))
        finally:
            trace.finish()
        m = self._metrics
        if m is not None:
            m.federation_deltas_total.labels("ok").inc()
            m.federation_delta_bytes_total.inc(len(data))
            m.federation_merge_seconds.observe(time.perf_counter() - t0)
        return sketch_delta_pb2.DeltaAck(
            accepted=1, version=fdelta.DELTA_FORMAT_VERSION)

    def _reject(self, result: str,
                reason: str) -> sketch_delta_pb2.DeltaAck:
        log.warning("delta frame rejected (%s): %s", result, reason)
        if self._metrics is not None:
            self._metrics.federation_deltas_total.labels(result).inc()
        return sketch_delta_pb2.DeltaAck(
            accepted=0, version=fdelta.DELTA_FORMAT_VERSION, reason=reason)

    def _merge_frame(self, frame: fdelta.DeltaFrame) -> None:
        import jax

        if self._distributed:
            tables = {name: self._pm.put_replicated(
                self._mesh, np.ascontiguousarray(arr))
                for name, arr in frame.tables.items()}
            owner = self._pm.put_replicated(self._mesh, np.asarray(
                [agent_owner_shard(frame.agent_id, self._ndata)], np.int32))
        else:
            tables = {name: jax.device_put(arr)
                      for name, arr in frame.tables.items()}
        with self._lock:
            if self._distributed:
                self._state = self._fold(self._state, tables, owner)
            else:
                self._state = self._fold(self._state, tables)
            self._frames_total += 1
            self._window_agents.add(frame.agent_id)
            info = self._agents.setdefault(
                frame.agent_id, {"frames": 0, "window": 0, "last_ms": 0.0,
                                 "last_mono": 0.0})
            info["frames"] += 1
            info["window"] = frame.window
            info["last_ms"] = time.time() * 1e3
            info["last_mono"] = time.monotonic()
            if time.monotonic() >= self._window_deadline:
                self._close_window_locked()

    # --- window roll ----------------------------------------------------
    def start_window_timer(self) -> None:
        self._timer = threading.Thread(
            target=self._window_loop, name="federation-window", daemon=True)
        self._timer.start()

    @property
    def _window_poll_s(self) -> float:
        return min(1.0, self._window_s / 10)

    def register_supervised(self, supervisor, heartbeat_timeout_s=None,
                            **kwargs) -> None:
        beat = supervisor.register(
            "federation-window", restart=self.start_window_timer,
            thread_getter=lambda: self._timer,
            heartbeat_timeout_s=(heartbeat_timeout_s or 10.0)
            + self._window_poll_s,
            **kwargs)
        self.heartbeat = beat

    def _window_loop(self) -> None:
        while not self._closed.wait(timeout=self._window_poll_s):
            self.heartbeat()
            faultinject.fire("federation.window_timer")
            try:
                faultinject.fire("federation.window_roll")
                with self._lock:
                    if time.monotonic() >= self._window_deadline:
                        self._close_window_locked()
            except Exception as exc:
                log.error("federation window roll failed (will retry): %s",
                          exc)
                if self._metrics is not None:
                    self._metrics.count_error("federation")
            self._update_staleness()
            self._publish_queued()

    def _close_window_locked(self) -> None:
        """Dispatch the roll UNDER self._lock; render/publish happen on the
        timer thread outside it (delta merges never wait on a sink)."""
        wtrace = tracing.start_trace("federation_window")
        self._window_deadline = time.monotonic() + self._window_s
        try:
            with wtrace.stage("roll_dispatch"):
                self._state, report, tables = self._roll(self._state)
        except BaseException:
            wtrace.finish()
            raise
        agents = sorted(self._window_agents)
        self._window_agents = set()
        self._reports.append((report, tables, agents, wtrace))
        while len(self._reports) > self._max_queued_reports:
            try:
                _r, _t, _a, shed = self._reports.popleft()
            except IndexError:
                break
            shed.finish()
            log.error("federation report queue full; dropping the oldest "
                      "unpublished window")
            if self._metrics is not None:
                self._metrics.count_error("federation")

    def _publish_queued(self) -> None:
        with self._publish_lock:
            while self._reports:
                try:
                    report, tables, agents, wtrace = self._reports.popleft()
                except IndexError:
                    return
                try:
                    self._publish(report, tables, agents, wtrace)
                except Exception as exc:
                    log.error("federation report publish failed "
                              "(report lost): %s", exc)
                    if self._metrics is not None:
                        self._metrics.count_error("federation")
                finally:
                    wtrace.finish()

    def _publish(self, report, tables, agents: list, wtrace) -> None:
        from netobserv_tpu.exporter.tpu_sketch import report_to_json

        with wtrace.stage("report_render"):
            obj = report_to_json(report, **self._report_kwargs)
            obj["Type"] = "federation_window_report"
            obj["Agents"] = agents
            obj["TimestampMs"] = time.time_ns() // 1_000_000
            # host copies of the merged tables the query surface reads
            # (the np.asarray touch includes the device->host transfer)
            cm_bytes = np.asarray(tables["cm_bytes"])
            cm_pkts = np.asarray(tables["cm_pkts"])
            heavy = {k: np.asarray(tables["heavy_" + k])
                     for k in ("words", "h1", "h2", "counts", "valid")}
        snap = {
            "window": obj["Window"],
            "ts_ms": obj["TimestampMs"],
            "report": obj,
            "agents": {a: dict(v) for a, v in self._agents_view().items()},
            "cm_bytes": cm_bytes,
            "cm_pkts": cm_pkts,
            "heavy": heavy,
            "total_records": obj["Records"],
            "total_bytes": obj["Bytes"],
        }
        with self._snap_lock:
            self._snapshot = snap
        m = self._metrics
        if m is not None:
            m.federation_active_agents.set(len(agents))
            m.sketch_window_reports_total.inc()
        if self._sink is not None:
            with wtrace.stage("report_sink"):
                self._sink(obj)

    def _agents_view(self) -> dict:
        now = time.monotonic()
        with self._lock:
            return {a: {"frames": v["frames"], "window": v["window"],
                        "last_ms": v["last_ms"],
                        "staleness_s": round(now - v["last_mono"], 3),
                        "stale": (now - v["last_mono"])
                        > self._stale_after_s}
                    for a, v in self._agents.items()}

    def _update_staleness(self) -> None:
        m = self._metrics
        if m is None:
            return
        for agent, info in self._agents_view().items():
            m.federation_agent_staleness_seconds.labels(agent).set(
                info["staleness_s"])

    # --- query surface (host-side, never a device op) -------------------
    def snapshot(self) -> Optional[dict]:
        """The last closed window's published snapshot (None before the
        first roll publishes)."""
        with self._snap_lock:
            return self._snapshot

    def status(self) -> dict:
        with self._lock:
            frames = self._frames_total
            window_agents = sorted(self._window_agents)
        snap = self.snapshot()
        return {
            "frames_total": frames,
            "agents": self._agents_view(),
            "current_window_agents": window_agents,
            "last_published_window": None if snap is None
            else snap["window"],
            "window_s": self._window_s,
            "mesh": self._distributed,
            "format_version": fdelta.DELTA_FORMAT_VERSION,
        }

    def query_frequency(self, src: str, dst: str, src_port: int = 0,
                        dst_port: int = 0, proto: int = 0) -> Optional[dict]:
        """CM point query with error bars against the last closed window's
        MERGED tables — pure host numpy (the hashing twins), non-blocking."""
        snap = self.snapshot()
        if snap is None:
            return None
        from netobserv_tpu.model import binfmt
        from netobserv_tpu.model.columnar import pack_key_words
        from netobserv_tpu.model.flow import FlowKey
        from netobserv_tpu.ops.hashing import base_hashes_multi_np

        fk = FlowKey.make(src, dst, src_port, dst_port, proto)
        karr = np.zeros(1, binfmt.FLOW_KEY_DTYPE)
        karr["src_ip"][0] = np.frombuffer(fk.src_ip, np.uint8)
        karr["dst_ip"][0] = np.frombuffer(fk.dst_ip, np.uint8)
        karr["src_port"] = src_port
        karr["dst_port"] = dst_port
        karr["proto"] = proto
        words = pack_key_words(karr)
        h = base_hashes_multi_np(words)
        cm = snap["cm_bytes"]
        d, w = cm.shape
        with np.errstate(over="ignore"):
            idx = (h["h1"][0] + np.arange(d, dtype=np.uint32) * h["h2"][0]) \
                & np.uint32(w - 1)
        est_bytes = float(np.min(snap["cm_bytes"][np.arange(d), idx]))
        est_pkts = float(np.min(snap["cm_pkts"][np.arange(d), idx]))
        # Cormode–Muthukrishnan: overestimate <= (e/w)*N with prob 1-e^-d
        n_bytes = float(np.sum(snap["cm_bytes"][0]))
        n_pkts = float(np.sum(snap["cm_pkts"][0]))
        eps = np.e / w
        return {
            "window": snap["window"],
            "est_bytes": est_bytes,
            "est_packets": est_pkts,
            "overestimate_bound_bytes": eps * n_bytes,
            "overestimate_bound_packets": eps * n_pkts,
            "confidence": 1.0 - float(np.exp(-d)),
        }

    # --- lifecycle ------------------------------------------------------
    def flush(self) -> None:
        """Close the current window now and publish synchronously."""
        with self._lock:
            self._close_window_locked()
        self._publish_queued()

    def close(self) -> None:
        self._closed.set()
        if self._timer is not None:
            self._timer.join(timeout=2.0)
        self.flush()
