"""Device-side merge of a decoded delta frame into a SketchState.

The aggregator's aggregate IS a SketchState fed by table deltas instead of
flow records: every structure merges by its native operator (CM/histograms/
rates add, HLL max, top-K concat + re-score against the merged CM), so the
existing window roll (`sketch.state.roll_window`) and report renderer
(`exporter.tpu_sketch.report_to_json`) serve the cluster-wide plane
unchanged. Pure function — `federation.aggregator` jits it (single device)
and `parallel.merge.make_fold_delta_fn` calls it inside shard_map (mesh).
"""

from __future__ import annotations

import jax.numpy as jnp

from netobserv_tpu.ops import countmin, ewma, hll, quantile, topk
from netobserv_tpu.sketch import state as sk


def merge_tables(state: sk.SketchState, t: dict,
                 query_fn=None, candidate_valid=None) -> sk.SketchState:
    """Merge one agent's delta tables `t` (federation.delta.TABLE_SPEC
    names, device arrays; `heavy_valid` may be uint32) into `state`.

    `query_fn(h1, h2) -> est` overrides the plain CM point query for the
    top-K re-score (owner-sharded meshes); `candidate_valid` additionally
    masks which delta candidates this shard may adopt (key ownership).
    EWMA baselines (mean/var) are untouched — the aggregator rolls its own
    cluster-level baselines over the merged per-window rates.
    """
    cm_b = countmin.CountMin(state.cm_bytes.counts + t["cm_bytes"])
    cm_p = countmin.CountMin(state.cm_pkts.counts + t["cm_pkts"])
    d_valid = t["heavy_valid"] != 0
    if candidate_valid is not None:
        d_valid = d_valid & candidate_valid
    # persistent-slot merge: aggregate table + delta table concat, duplicate
    # identities collapse with segmented metadata merges (prev_counts SUM —
    # per-agent partials of one key add; first_seen MIN is best-effort at
    # this tier, agents count windows independently; epoch MAX), counts
    # re-score against the merged CM (ops/topk.merge_slot_tables — the one
    # roll-time reconciliation primitive, shared with parallel/merge.py).
    # v1/v2 frames reach here with zeroed churn tensors
    # (federation.delta.upgrade_tables), which merge as "no history".
    stacked = topk.SlotTable(
        words=jnp.concatenate([state.heavy.words,
                               t["heavy_words"].astype(jnp.uint32)], axis=0),
        h1=jnp.concatenate([state.heavy.h1, t["heavy_h1"]]),
        h2=jnp.concatenate([state.heavy.h2, t["heavy_h2"]]),
        counts=jnp.concatenate([state.heavy.counts, t["heavy_counts"]]),
        prev_counts=jnp.concatenate([state.heavy.prev_counts,
                                     t["heavy_prev_counts"]]),
        first_seen=jnp.concatenate([state.heavy.first_seen,
                                    t["heavy_first_seen"]]),
        epoch=jnp.concatenate([state.heavy.epoch, t["heavy_epoch"]]),
        valid=jnp.concatenate([state.heavy.valid, d_valid]),
    )
    heavy = topk.merge_slot_tables(stacked, cm_b, state.heavy.k,
                                   query_fn=query_fn)
    scalars = t["scalars"]
    return state._replace(
        cm_bytes=cm_b, cm_pkts=cm_p, heavy=heavy,
        hll_src=hll.HLL(jnp.maximum(state.hll_src.regs, t["hll_src"])),
        hll_per_dst=hll.PerDstHLL(
            jnp.maximum(state.hll_per_dst.regs, t["hll_per_dst"])),
        hll_per_src=hll.PerDstHLL(
            jnp.maximum(state.hll_per_src.regs, t["hll_per_src"])),
        hist_rtt=quantile.LogHist(state.hist_rtt.counts + t["hist_rtt"]),
        hist_dns=quantile.LogHist(state.hist_dns.counts + t["hist_dns"]),
        ddos=ewma.EWMA(mean=state.ddos.mean, var=state.ddos.var,
                       rate=state.ddos.rate + t["ddos_rate"],
                       windows=state.ddos.windows),
        syn=ewma.EWMA(mean=state.syn.mean, var=state.syn.var,
                      rate=state.syn.rate + t["syn_rate"],
                      windows=state.syn.windows),
        synack=state.synack + t["synack"],
        drops_ewma=ewma.EWMA(mean=state.drops_ewma.mean,
                             var=state.drops_ewma.var,
                             rate=state.drops_ewma.rate + t["drops_rate"],
                             windows=state.drops_ewma.windows),
        drop_causes=state.drop_causes + t["drop_causes"],
        dscp_bytes=state.dscp_bytes + t["dscp_bytes"],
        conv_fwd=state.conv_fwd + t["conv_fwd"],
        conv_rev=state.conv_rev + t["conv_rev"],
        total_records=state.total_records + scalars[0],
        total_bytes=state.total_bytes + scalars[1],
        total_drop_bytes=state.total_drop_bytes + scalars[2],
        total_drop_packets=state.total_drop_packets + scalars[3],
        quic_records=state.quic_records + scalars[4],
        nat_records=state.nat_records + scalars[5],
        heavy_evictions=state.heavy_evictions + scalars[6],
    )
