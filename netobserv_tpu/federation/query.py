"""Cluster-wide query surface: non-blocking HTTP over the aggregator.

A thin adapter over the shared query core (`netobserv_tpu/query/core.py`)
— the CM error-bar math and victim naming exist exactly ONCE, serving both
this tier and the per-agent `/query/*` routes.

Same off-hot-path rules as /debug/traces: every route reads the HOST-side
snapshot the aggregator published at its last window roll (or pure-numpy
math over it) — a request never dispatches a device op, takes the
aggregator's merge lock, or waits on anything the delta-ingest path needs.
Also answers /healthz + /readyz with the supervised-stage semantics of
`metrics/server.py` so the aggregator tier deploys behind the same probes
as the agents.

Routes (all GET, JSON):

- /federation/topk          cluster-wide heavy hitters (?n= caps the
                            list), with CM error bars
- /federation/frequency     CM estimate + error bars for one 5-tuple
                            (?src=&dst=&src_port=&dst_port=&proto=)
- /federation/churn         cluster-wide per-key heavy-hitter churn
                            (the merged persistent-slot table's
                            cross-window diff)
- /federation/cardinality   global distinct-source estimate + totals
- /federation/victims       suspect buckets per signal with victim names
- /federation/alerts        cluster-wide continuous detection view (the
                            SAME engine core the agents mount, driven by
                            merged-window snapshots; 404 when ALERT_RULES
                            is unset)
- /federation/status        per-agent delta freshness + plane counters
- /federation/fleet         per-agent telemetry rollup (shed factor,
                            conditions, host-path rec/s EWMA, map
                            occupancy, windows published) from the
                            frames' telemetry blocks — reads ONLY the
                            seq-stamped fleet snapshot the window timer
                            publishes (never the merge lock)
- /debug/traces             the aggregator's flight recorder (same
                            ?limit=/?trace= params as the agent debug
                            server mount — a cross-process trace id
                            stamped by an agent answers here too, so one
                            id can be followed across both tiers)
- /debug/executables        the aggregator process's per-executable
                            device-accounting registry (utils/retrace)
- /federation/range         cluster-wide sketch-warehouse time-range
                            answers (?from=&to=; /federation/range/topk|
                            frequency|cardinality|victims views) — a thin
                            adapter over the archive plane's ONE body
                            builder (archive/query.py); 404 when
                            ARCHIVE_DIR is unset
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from netobserv_tpu.query import core as qcore

log = logging.getLogger("netobserv_tpu.federation.query")

_READY_STATUSES = ("Started",)
_LIVE_STATUSES = ("NotStarted", "Starting", "Started", "Degraded",
                  "Stopping")


class _Handler(BaseHTTPRequestHandler):
    aggregator = None                      # set per-server subclass
    health_source: Optional[Callable[[], dict]] = None

    def do_GET(self):  # noqa: N802 - http.server API
        url = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}
        path = url.path
        try:
            if path in ("/healthz", "/readyz"):
                self._serve_health(path)
                return
            if path in ("/", "/federation", "/federation/"):
                self._json(200, {"routes": [
                    "/federation/topk", "/federation/frequency",
                    "/federation/churn", "/federation/cardinality",
                    "/federation/victims", "/federation/alerts",
                    "/federation/range", "/federation/status",
                    "/federation/fleet", "/debug/traces",
                    "/debug/executables", "/healthz", "/readyz"]})
                return
            if path == "/federation/fleet":
                self._serve_fleet()
                return
            if path in ("/debug/traces", "/debug/executables"):
                # thin adapters over the agent debug server's body
                # builders (server/debug.py — the never-fork rule): the
                # aggregator tier mounts the SAME flight recorder and
                # executable-registry views, so a trace id stamped by an
                # agent can be followed on both tiers with one URL shape
                from netobserv_tpu.server.debug import (_executables_dump,
                                                        _traces_dump)
                dump = (_traces_dump if path == "/debug/traces"
                        else _executables_dump)
                self._json(200, json.loads(dump(q)))
                return
            if path == "/federation/range" or \
                    path.startswith("/federation/range/"):
                # thin adapter over the archive plane's ONE body builder
                # (archive/query.py route_payload — the federation/
                # query.py never-fork rule); cluster-wide history fed by
                # the aggregator's merged windows
                arch = self.aggregator.archive
                if arch is None:
                    self._json(404, {"error": "archive disabled "
                                              "(ARCHIVE_DIR unset)"})
                    return
                view = path.rpartition("/")[2] \
                    if path.startswith("/federation/range/") else None
                code, body = arch.route_payload(q, view)
                self._json(code, body)
                return
            if path == "/federation/status":
                self._json(200, self.aggregator.status())
                return
            if path == "/federation/alerts":
                # thin adapter: the one route_payload body builder the
                # agent's /query/alerts uses (never fork it back)
                eng = self.aggregator.alerts
                if eng is None:
                    self._json(404, {"error": "alerting disabled "
                                              "(ALERT_RULES unset)"})
                    return
                try:
                    code, body = eng.route_payload(q.get("window"))
                except ValueError as exc:  # malformed ?window=
                    code, body = 400, {"error": str(exc)}
                self._json(code, body)
                return
            snap = self.aggregator.snapshot()
            if path == "/federation/frequency":
                if not q.get("src") or not q.get("dst"):
                    self._json(400, {"error": "src and dst are required"})
                    return
                out = self.aggregator.query_frequency(
                    q["src"], q["dst"], int(q.get("src_port", 0)),
                    int(q.get("dst_port", 0)), int(q.get("proto", 0)))
                if out is None:
                    self._no_window()
                    return
                self._json(200, out)
                return
            if snap is None and path.startswith("/federation/"):
                self._no_window()
                return
            # every snapshot-backed route carries the publish sequence
            # number (stamped by the shared query core): the aggregator
            # swaps whole snapshots atomically, so a reader seeing (seq,
            # window, payload) from ONE dict can never observe a torn mix
            # of two windows. seq is in-memory and restarts at 1 with the
            # process; window-major ordering survives restarts only when
            # FEDERATION_CHECKPOINT_DIR is set (pollers: compare
            # (window, seq), and only across restarts of a checkpointed
            # aggregator — see the smoke's poller)
            if path == "/federation/topk":
                self._json(200, qcore.topk_payload(snap, q.get("n", 100)))
                return
            if path == "/federation/churn":
                # thin adapter over the ONE churn body builder (the
                # query/core rule: never fork the math back here)
                self._json(200, qcore.churn_payload(snap))
                return
            if path == "/federation/cardinality":
                self._json(200, qcore.cardinality_payload(snap))
                return
            if path == "/federation/victims":
                self._json(200, qcore.victims_payload(snap))
                return
            self.send_error(404)
        except Exception as exc:  # the query surface must keep answering
            log.error("federation query %s failed: %s", path, exc)
            self._json(500, {"error": str(exc)})

    def _no_window(self) -> None:
        self._json(503, {"error": "no window published yet"})

    def _serve_fleet(self) -> None:
        # reads only the published fleet reference (whole-dict seq-stamped
        # swaps on the timer thread) — never the aggregator's merge lock
        fleet = self.aggregator.fleet()
        m = getattr(self.aggregator, "_metrics", None)
        if fleet is None:
            if m is not None:
                m.federation_fleet_requests_total.labels("no_window").inc()
            self._json(503, {"error": "no fleet snapshot published yet"})
            return
        if m is not None:
            m.federation_fleet_requests_total.labels("ok").inc()
        self._json(200, fleet)

    def _serve_health(self, path: str) -> None:
        try:
            health = self.health_source() if self.health_source else {
                "status": "Started", "degraded": False, "stages": {}}
        except Exception as exc:
            health = {"status": "Unknown", "degraded": True,
                      "error": str(exc), "stages": {}}
        status = health.get("status", "Unknown")
        degraded = bool(health.get("degraded"))
        if path == "/readyz":
            ok = status in _READY_STATUSES and not degraded
        else:
            ok = status in _LIVE_STATUSES
        self._json(200 if ok else 503, health)

    def _json(self, code: int, obj: dict) -> None:
        payload = json.dumps(obj, separators=(",", ":")).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt, *args):
        log.debug("federation query http: " + fmt, *args)


def start_query_server(aggregator, port: int, address: str = "",
                       health_source: Optional[Callable[[], dict]] = None,
                       ) -> ThreadingHTTPServer:
    """Start the query surface on a daemon thread; returns the server."""
    handler = type("Handler", (_Handler,),
                   {"aggregator": aggregator,
                    "health_source": (staticmethod(health_source)
                                      if health_source is not None
                                      else None)})
    srv = ThreadingHTTPServer((address or "0.0.0.0", port), handler)
    srv.timeout = 10
    t = threading.Thread(target=srv.serve_forever,
                         name="federation-query", daemon=True)
    t.start()
    log.info("federation query surface on %s:%d", address or "0.0.0.0",
             srv.server_address[1])
    return srv
