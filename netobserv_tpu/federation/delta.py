"""Sketch-delta frame codec (versioned, protobuf-framed, endian-independent).

One frame per (agent, closed window) carries every MERGEABLE sketch table —
the structures whose merge operators are exact by construction:

- Count-Min planes           merge = elementwise add (linearity)
- HLL register banks         merge = elementwise max
- top-K candidate table      merge = concat + re-score vs the merged CM
- latency log-histograms     merge = elementwise add
- signal-plane window rates  merge = elementwise add (rates are additive)
- window totals              merge = add

EWMA *baselines* (mean/var) deliberately stay agent-local: the aggregator
keeps its own cluster-level baselines over the merged per-window rates, so a
fleet-wide surge scores against fleet history, not against any one host's.

This module is jax-free on purpose: frame bytes must be producible and
decodable on the big-endian qemu CI tier (tests/test_federation_golden.py
pins a golden frame there, alongside test_pb_golden.py), and report-side
encoding must never dispatch a device op. Tensor payloads are ALWAYS
little-endian (explicit ``<`` numpy dtypes) regardless of host order.

`TABLE_SPEC` is the canonical table-snapshot layout. The sketch checkpoint
format stamps a fingerprint of the same spec (`sketch/checkpoint.py`), so a
layout change bumps both surfaces together and both are pinned against the
same goldens.
"""

from __future__ import annotations

import uuid
import zlib
from typing import Mapping, NamedTuple, Optional

import numpy as np

from netobserv_tpu.utils import tensorcodec
from netobserv_tpu.utils.tracing import TraceContext


def _pb():
    """Lazy protobuf import: TABLE_SPEC and the codec constants here are
    the archive segment format's source of truth too (archive/segment.py),
    and that consumer must import on the big-endian qemu CI tier, where no
    protobuf package exists — only frame encode/decode need the pb."""
    from netobserv_tpu.pb import sketch_delta_pb2
    return sketch_delta_pb2

#: bump on ANY change to TABLE_SPEC, tensor encoding, or frame semantics.
#: v2 adds the idempotent-delivery header (window_seq / frame_uuid /
#: agent_epoch) so the aggregator can ack-and-discard redelivered frames
#: after an ambiguous DEADLINE_EXCEEDED instead of double-counting.
#: v3 adds the persistent-slot churn tensors (heavy_prev_counts /
#: heavy_first_seen / heavy_epoch) and the heavy_evictions scalar — the
#: per-key heavy-hitter plane rides the delta wire.
DELTA_FORMAT_VERSION = 3

#: versions decode_frame still accepts. v1 frames (pre-idempotency agents)
#: carry no delivery header; the aggregator merges them unconditionally and
#: counts them `legacy` — a mixed-version fleet keeps aggregating during a
#: rollout, it just loses dedup protection for the old agents. v1/v2 frames
#: carry no churn tensors; `upgrade_tables` zero-fills them (merging as "no
#: history": the key set and counts still aggregate exactly).
SUPPORTED_VERSIONS = (1, 2, 3)

#: ack reason strings shared by the aggregator (producer) and
#: FederationDeltaSink (consumer). Both verdicts set `duplicate=1` on the
#: wire — retrying either is pointless — but only a true duplicate was
#: MERGED; a stale discard is per-window data loss, and the reason string
#: is how the agent side tells the two apart in its sent-counter.
ACK_REASON_DUPLICATE = "window already applied"
ACK_REASON_STALE = "stale window discarded"

# the per-tensor codec is SHARED with the archive segment format
# (utils/tensorcodec.py — one tensor format, not two drifting copies);
# these aliases keep the wire constants importable from here
CODEC_RAW = tensorcodec.CODEC_RAW
CODEC_ZLIB = tensorcodec.CODEC_ZLIB

_DTYPE_TO_CODE = tensorcodec.DTYPE_TO_CODE
_CODE_TO_DTYPE = tensorcodec.CODE_TO_DTYPE

#: canonical (name, little-endian dtype) of every tensor in a frame, in
#: frame order. `sketch.state.state_tables` produces exactly these names;
#: `scalars` packs the window totals in SCALAR_FIELDS order.
TABLE_SPEC: tuple[tuple[str, str], ...] = (
    ("cm_bytes", "<f4"),
    ("cm_pkts", "<f4"),
    ("heavy_words", "<u4"),
    ("heavy_h1", "<u4"),
    ("heavy_h2", "<u4"),
    ("heavy_counts", "<f4"),
    ("heavy_valid", "<u4"),
    # persistent-slot churn metadata (v3): prev_counts merge by sum,
    # first_seen by min, epoch by max (ops/topk.merge_slot_tables)
    ("heavy_prev_counts", "<f4"),
    ("heavy_first_seen", "<i4"),
    ("heavy_epoch", "<i4"),
    ("hll_src", "<i4"),
    ("hll_per_dst", "<i4"),
    ("hll_per_src", "<i4"),
    ("hist_rtt", "<f4"),
    ("hist_dns", "<f4"),
    ("ddos_rate", "<f4"),
    ("syn_rate", "<f4"),
    ("synack", "<f4"),
    ("drops_rate", "<f4"),
    ("drop_causes", "<f4"),
    ("dscp_bytes", "<f4"),
    ("conv_fwd", "<f4"),
    ("conv_rev", "<f4"),
    ("scalars", "<f4"),
)

#: the v1/v2-era table layout — kept for DECODE COMPAT (legacy frames) and
#: for `encode_frame(version=...)` producing mixed-fleet test vectors; the
#: v2 golden stays pinned against it (tests/test_federation_golden.py)
TABLE_SPEC_V2: tuple[tuple[str, str], ...] = tuple(
    (n, d) for n, d in TABLE_SPEC
    if n not in ("heavy_prev_counts", "heavy_first_seen", "heavy_epoch"))

#: layout of the `scalars` tensor (window totals; all additive)
SCALAR_FIELDS = ("total_records", "total_bytes", "total_drop_bytes",
                 "total_drop_packets", "quic_records", "nat_records",
                 "heavy_evictions")
#: v1/v2 frames carry only the first six
SCALAR_FIELDS_V2 = SCALAR_FIELDS[:6]


def spec_for_version(version: int) -> tuple[tuple[str, str], ...]:
    """The table layout a given frame format version carries."""
    return TABLE_SPEC if version >= 3 else TABLE_SPEC_V2

#: frame-header geometry fields (validated by the aggregator BEFORE its
#: fixed-shape jitted merge ever sees the tensors)
DIM_FIELDS = ("cm_depth", "cm_width", "hll_precision", "topk",
              "ewma_buckets")


class DeltaFrameError(ValueError):
    """Malformed/incomplete frame (decode-time validation failure)."""


class DeltaVersionError(DeltaFrameError):
    """Frame format version does not match DELTA_FORMAT_VERSION."""


class DeltaFrame(NamedTuple):
    """Decoded frame: header metadata + the table dict (TABLE_SPEC names ->
    little-endian numpy arrays, read-only views over the frame buffer).
    `window_seq`/`frame_uuid`/`agent_epoch` are the v2 idempotent-delivery
    header; on v1 frames they read as proto3 defaults (0 / "" / 0) and the
    version field is how consumers tell the difference."""

    version: int
    agent_id: str
    window: int
    ts_ms: int
    dims: dict
    tables: dict
    window_seq: int = 0
    frame_uuid: str = ""
    agent_epoch: int = 0
    # fleet-observability extras (optional on the wire; None when absent —
    # a frame without them is byte-identical to the pre-fleet encoding):
    # trace_ctx is a utils.tracing.TraceContext-shaped tuple
    # (trace_id, origin, sampled); telemetry is the per-agent health dict
    trace_ctx: Optional[tuple] = None
    telemetry: Optional[dict] = None
    #: SKETCH_TENANTS plane identity: (tenant_id, n_tenants) when the
    #: frame carries one tenant plane of a multi-tenant agent; None on
    #: single-tenant frames (absent on the wire — explicit presence)
    tenant: Optional[tuple] = None


def table_spec_fingerprint() -> int:
    """Stable fingerprint of the canonical snapshot layout — stamped into
    sketch checkpoints so the two table-snapshot surfaces (delta frame,
    checkpoint) drift together or not at all."""
    text = ";".join(f"{n}:{d}" for n, d in TABLE_SPEC) + \
        "|" + ",".join(SCALAR_FIELDS)
    return zlib.crc32(text.encode())


def encode_frame(tables: Mapping[str, np.ndarray], *, agent_id: str,
                 window: int, ts_ms: int, dims: Mapping[str, int],
                 codec: int = CODEC_ZLIB, window_seq: Optional[int] = None,
                 frame_uuid: str = "", agent_epoch: int = 0,
                 version: Optional[int] = None,
                 trace_ctx=None,
                 telemetry: Optional[Mapping] = None,
                 tenant: Optional[tuple] = None) -> bytes:
    """Serialize a table snapshot into one SketchDelta frame.

    `tables` must carry every name of the frame version's spec (host numpy
    arrays; dtype is coerced to the spec's little-endian type).
    `codec=CODEC_ZLIB` deflates each tensor but keeps raw whenever deflate
    does not shrink it (the per-tensor codec field records which shipped).

    Idempotency header: `window_seq` defaults to `window` (one frame per
    closed window, the counter IS the sequence); an empty `frame_uuid`
    draws a fresh uuid4 — callers retrying the SAME frame must resend the
    same bytes, not re-encode. `agent_epoch` is the sender's boot identity
    (0 only looks legacy-ish to operators; the version field is what marks
    a frame v1).

    `version` (default: current) may name an OLDER supported version to
    produce mixed-fleet/legacy frames: a v2 frame drops the churn tensors
    and trims `scalars` to the six v2 totals; a v1 frame additionally
    carries no delivery header. Production agents always encode current.

    Fleet observability (current-version frames only): `trace_ctx` (a
    utils.tracing.TraceContext, or any (trace_id, origin, sampled)-shaped
    object) and `telemetry` (the per-agent health dict — shed_factor /
    conditions / host_records_per_s / map_occupancy / windows_published)
    are OPTIONAL message fields: None (the default) writes zero bytes, so
    a frame without them is byte-identical to the pre-fleet wire — not a
    format bump. The context encodes ONCE per frame, here — a retry
    resends the same bytes, never a re-derived context.

    `tenant` (SKETCH_TENANTS agents only): the `(tenant_id, n_tenants)`
    plane identity, same optional-message presence rules — None writes
    zero bytes. The aggregator ledgers each tenant plane as its own
    source (`source_key`), so N tenant frames per window do not read as
    N-1 stale deliveries.
    """
    version = DELTA_FORMAT_VERSION if version is None else int(version)
    if version not in SUPPORTED_VERSIONS:
        raise DeltaFrameError(f"cannot encode unsupported frame version "
                              f"{version} (supported {SUPPORTED_VERSIONS})")
    spec = spec_for_version(version)
    missing = [n for n, _ in spec if n not in tables]
    if missing:
        raise DeltaFrameError(f"table snapshot missing tensors: {missing}")
    if not frame_uuid:
        frame_uuid = uuid.uuid4().hex
    pb = _pb()
    if version >= 2:
        frame = pb.SketchDelta(
            version=version, agent_id=agent_id,
            window=int(window), ts_ms=int(ts_ms),
            window_seq=int(window if window_seq is None else window_seq),
            frame_uuid=frame_uuid, agent_epoch=int(agent_epoch))
    else:  # v1: pre-idempotency — no delivery header on the wire
        frame = pb.SketchDelta(
            version=version, agent_id=agent_id,
            window=int(window), ts_ms=int(ts_ms))
    for f in DIM_FIELDS:
        setattr(frame, f, int(dims[f]))
    if version >= 3 and trace_ctx is not None:
        frame.trace_ctx.trace_id = str(trace_ctx.trace_id)
        frame.trace_ctx.origin = str(getattr(trace_ctx, "origin", "") or "")
        frame.trace_ctx.sampled = int(
            bool(getattr(trace_ctx, "sampled", True)))
    if version >= 3 and telemetry is not None:
        tel = frame.telemetry
        tel.shed_factor = float(telemetry.get("shed_factor", 1.0))
        tel.conditions.extend(str(c) for c in
                              telemetry.get("conditions", ()))
        tel.host_records_per_s = float(
            telemetry.get("host_records_per_s", 0.0))
        tel.map_occupancy = float(telemetry.get("map_occupancy", 0.0))
        tel.windows_published = int(telemetry.get("windows_published", 0))
    if version >= 3 and tenant is not None:
        frame.tenant.id = int(tenant[0])
        frame.tenant.n_tenants = int(tenant[1])
    n_scalars = len(SCALAR_FIELDS if version >= 3 else SCALAR_FIELDS_V2)
    for name, dt in spec:
        arr = np.asarray(tables[name])
        if name == "scalars":
            arr = arr[:n_scalars]
        arr = np.ascontiguousarray(arr, dtype=dt)
        raw = arr.tobytes()
        t = frame.tensors.add()
        t.name = name
        t.dtype = _DTYPE_TO_CODE[dt]
        t.shape.extend(int(s) for s in arr.shape)
        try:
            t.codec, t.data = tensorcodec.encode_payload(raw, codec)
        except tensorcodec.TensorCodecError as exc:
            raise DeltaFrameError(str(exc)) from exc
    return frame.SerializeToString(deterministic=True)


#: hard per-tensor size ceiling (decoded bytes) — the shared codec's
#: bound (utils/tensorcodec.py): caps what a hostile/corrupt frame can
#: make the aggregator allocate BEFORE any shape validation, both via a
#: declared-huge shape and via a zlib bomb
MAX_TENSOR_BYTES = tensorcodec.MAX_TENSOR_BYTES

#: spec dtype per tensor name — decode rejects a frame whose tensor dtype
#: disagrees (a same-shape foreign dtype would otherwise reach the
#: aggregator's fixed-signature jitted merge and force a retrace)
_SPEC_DTYPES = dict(TABLE_SPEC)
_SPEC_DTYPES_V2 = dict(TABLE_SPEC_V2)


def decode_frame(data: bytes) -> DeltaFrame:
    """Parse + validate one frame. Raises DeltaVersionError on a format
    version outside SUPPORTED_VERSIONS and DeltaFrameError on anything
    structurally wrong (unknown tensor name, dtype drift from TABLE_SPEC,
    size over MAX_TENSOR_BYTES, payload/shape mismatch); the tensor arrays
    are zero-copy read-only views over the frame bytes (copy before
    mutating). v1 frames decode with an empty delivery header (proto3
    defaults) — consumers branch on `frame.version`."""
    frame = _pb().SketchDelta()
    try:
        frame.ParseFromString(data)
    except Exception as exc:
        raise DeltaFrameError(f"unparseable delta frame: {exc}") from exc
    if frame.version not in SUPPORTED_VERSIONS:
        raise DeltaVersionError(
            f"delta frame version {frame.version} not in supported "
            f"{SUPPORTED_VERSIONS} (agent {frame.agent_id!r})")
    spec = spec_for_version(frame.version)
    spec_dtypes = _SPEC_DTYPES if frame.version >= 3 else _SPEC_DTYPES_V2
    tables: dict[str, np.ndarray] = {}
    for t in frame.tensors:
        spec_dt = spec_dtypes.get(t.name)
        if spec_dt is None:
            raise DeltaFrameError(
                f"unknown tensor {t.name!r} (not in the v{frame.version} "
                "table spec)")
        dt = _CODE_TO_DTYPE.get(t.dtype)
        if dt is None:
            raise DeltaFrameError(f"tensor {t.name!r}: unknown dtype code "
                                  f"{t.dtype}")
        if dt != spec_dt:
            raise DeltaFrameError(
                f"tensor {t.name!r}: dtype {dt} != spec {spec_dt}")
        shape = tuple(int(s) for s in t.shape)
        try:
            # size-cap + bounded inflate live in the SHARED codec (the
            # archive segment decoder runs the exact same guards)
            expected = tensorcodec.declared_nbytes(t.name, shape, dt)
            raw = tensorcodec.decode_payload(t.name, t.codec, t.data,
                                             expected)
        except tensorcodec.TensorCodecError as exc:
            raise DeltaFrameError(str(exc)) from exc
        tables[t.name] = np.frombuffer(raw, dtype=dt).reshape(shape)
    missing = [n for n, _ in spec if n not in tables]
    if missing:
        raise DeltaFrameError(f"delta frame missing tensors: {missing}")
    dims = {f: int(getattr(frame, f)) for f in DIM_FIELDS}
    # optional fleet-observability fields: message presence (HasField) is
    # the absent/present signal — a zero-valued present block is still a
    # block, an absent one decodes as None
    trace_ctx = None
    if frame.HasField("trace_ctx"):
        trace_ctx = TraceContext(frame.trace_ctx.trace_id,
                                 frame.trace_ctx.origin,
                                 bool(frame.trace_ctx.sampled))
    telemetry = None
    if frame.HasField("telemetry"):
        telemetry = {
            "shed_factor": float(frame.telemetry.shed_factor),
            "conditions": list(frame.telemetry.conditions),
            "host_records_per_s": float(frame.telemetry.host_records_per_s),
            "map_occupancy": float(frame.telemetry.map_occupancy),
            "windows_published": int(frame.telemetry.windows_published),
        }
    tenant = None
    if frame.HasField("tenant"):
        tenant = (int(frame.tenant.id), int(frame.tenant.n_tenants))
    return DeltaFrame(version=int(frame.version), agent_id=frame.agent_id,
                      window=int(frame.window), ts_ms=int(frame.ts_ms),
                      dims=dims, tables=tables,
                      window_seq=int(frame.window_seq),
                      frame_uuid=frame.frame_uuid,
                      agent_epoch=int(frame.agent_epoch),
                      trace_ctx=trace_ctx, telemetry=telemetry,
                      tenant=tenant)


def source_key(frame: "DeltaFrame") -> str:
    """The aggregator-side delivery-source identity of a frame.

    A multi-tenant agent publishes N frames per closed window — same
    agent_id, same agent_epoch, same window_seq, different tenant planes.
    Keying the ledger by bare agent_id would read tenants 1..N-1 as
    duplicate/stale deliveries of tenant 0's frame and DISCARD them, so
    each tenant plane ledgers as its own source. Single-tenant frames
    (tenant absent) keep the bare agent_id — existing ledgers, checkpoint
    sidecars and fleet views are unchanged."""
    if frame.tenant is None:
        return frame.agent_id
    return f"{frame.agent_id}#t{frame.tenant[0]}"


def upgrade_tables(frame: DeltaFrame) -> dict:
    """Normalize a decoded frame's tables to the CURRENT (v3) layout.

    v1/v2 frames carry no churn tensors and six-wide scalars: the missing
    tensors zero-fill (shaped after the frame's own heavy_counts — merging
    as "no churn history"; the key set and counts still aggregate exactly)
    and `scalars` pads with zeros to the current width, so the aggregator's
    fixed-signature jitted merge sees ONE table layout for every supported
    frame version. Current frames return their table dict unchanged."""
    if frame.version >= 3:
        return frame.tables
    tables = dict(frame.tables)
    k = np.asarray(frame.tables["heavy_counts"]).shape
    tables["heavy_prev_counts"] = np.zeros(k, "<f4")
    tables["heavy_first_seen"] = np.zeros(k, "<i4")
    tables["heavy_epoch"] = np.zeros(k, "<i4")
    scal = np.asarray(frame.tables["scalars"], "<f4")
    tables["scalars"] = np.concatenate(
        [scal, np.zeros(len(SCALAR_FIELDS) - scal.shape[0], "<f4")])
    return tables


def localize_churn(tables: Mapping[str, np.ndarray],
                   window: int) -> dict:
    """Re-base a delta frame's churn tensors into the AGGREGATOR's window
    domain before merging.

    The churn baselines are tier-local by construction: an agent's
    `heavy_prev_counts` is ITS previous agent-window's mass, and the
    aggregator's own `slot_roll` already snapshots the previous CLUSTER
    window's merged counts as the aggregate's baseline — summing the
    agents' prevs on top would double-count every persistent key (and
    worse with several agent windows per federation window). Likewise
    `heavy_first_seen`/`heavy_epoch` are numbered in each agent's window/
    insertion domain, meaningless at the cluster tier. So delta frames
    merge with: prev_counts zeroed (the aggregate's own roll history IS
    the cluster baseline), first_seen set to the aggregator's CURRENT
    window (the segmented MIN keeps the aggregate's earlier stamp for
    known keys and stamps genuinely-new keys with the window they first
    reached the cluster table), epoch zeroed (the aggregate's own
    generations count)."""
    out = dict(tables)
    k = np.asarray(tables["heavy_counts"]).shape
    out["heavy_prev_counts"] = np.zeros(k, "<f4")
    out["heavy_first_seen"] = np.full(k, int(window), "<i4")
    out["heavy_epoch"] = np.zeros(k, "<i4")
    return out


def expected_shapes(template_tables: Mapping[str, np.ndarray]) -> dict:
    """Shape dict of a snapshot (the aggregator's fixed-shape contract)."""
    return {n: tuple(np.asarray(template_tables[n]).shape)
            for n, _ in TABLE_SPEC}


def validate_shapes(frame: DeltaFrame,
                    expected: Mapping[str, tuple]) -> None:
    """Reject a frame whose tensor shapes differ from the aggregator's own
    snapshot template — a foreign shape must never reach the jitted merge
    (it would retrace; the fixed-shape invariant is load-bearing)."""
    for name, shape in expected.items():
        if name not in frame.tables:
            raise DeltaFrameError(
                f"tensor {name!r} absent (upgrade_tables the frame before "
                "shape validation — legacy frames lack the churn tensors)")
        got = tuple(frame.tables[name].shape)
        if got != tuple(shape):
            raise DeltaFrameError(
                f"tensor {name!r}: shape {got} != aggregator's {shape} "
                f"(agent {frame.agent_id!r} runs a different SketchConfig)")
