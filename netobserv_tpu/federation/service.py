"""Aggregator-tier process wiring (`FEDERATION_MODE=aggregator`).

Mirrors `agent.FlowsAgent`'s shape: a status machine, a supervisor watching
every background stage (the aggregator's window timer), /healthz + /readyz
surfaced from the same snapshot contract, SIGTERM-driven shutdown via
`__main__`. Assembles: the Federation gRPC collector (delta ingest), the
`FederationAggregator` (device merge + windowed cluster reports), the HTTP
query surface, and optionally the Prometheus metrics server.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from netobserv_tpu.agent.supervisor import Supervisor
from netobserv_tpu.federation.aggregator import FederationAggregator
from netobserv_tpu.metrics.registry import Metrics, MetricsSettings

log = logging.getLogger("netobserv_tpu.federation.service")


class FederationAggregatorService:
    """The central aggregator as a runnable process."""

    def __init__(self, cfg, metrics: Optional[Metrics] = None,
                 sink=None):
        from netobserv_tpu.alerts import maybe_engine
        from netobserv_tpu.archive import maybe_archive
        from netobserv_tpu.exporter.tpu_sketch import make_report_sink
        from netobserv_tpu.sketch.state import SketchConfig

        self.cfg = cfg
        self.metrics = metrics or Metrics(MetricsSettings(
            prefix=cfg.metrics_prefix, level=cfg.metrics_level))
        self._status = "Starting"
        self._status_lock = threading.Lock()
        sketch_cfg = SketchConfig.from_agent_config(cfg)
        self.aggregator = FederationAggregator(
            alerts=maybe_engine(cfg, self.metrics, source="federation"),
            # cluster-wide sketch warehouse (ARCHIVE_DIR on the
            # aggregator archives each MERGED window; /federation/range)
            archive=maybe_archive(cfg, sketch_cfg, metrics=self.metrics,
                                  agent_id="federation"),
            sketch_cfg=sketch_cfg,
            window_s=cfg.federation_window,
            mesh_shape=cfg.federation_mesh_shape,
            metrics=self.metrics,
            sink=sink if sink is not None else make_report_sink(cfg),
            stale_after_s=cfg.federation_stale_after,
            checkpoint_dir=cfg.federation_checkpoint_dir,
            checkpoint_every=cfg.federation_checkpoint_every,
            agent_ttl_s=cfg.federation_agent_ttl,
            report_kwargs=dict(
                scan_fanout_threshold=cfg.sketch_scan_fanout,
                ddos_z_threshold=cfg.sketch_ddos_z,
                synflood_min=cfg.sketch_synflood_min,
                synflood_ratio=cfg.sketch_synflood_ratio,
                drop_z_threshold=cfg.sketch_drop_z,
                asym_min_bytes=cfg.sketch_asym_min_bytes,
                asym_ratio=cfg.sketch_asym_ratio,
                churn_ascent=cfg.sketch_churn_ascent,
                churn_min_bytes=cfg.sketch_churn_min_bytes))
        self.supervisor = Supervisor(
            metrics=self.metrics,
            check_period_s=cfg.supervisor_check_period,
            on_degraded=self._on_degraded)
        self.aggregator.register_supervised(
            self.supervisor,
            heartbeat_timeout_s=cfg.supervisor_heartbeat_timeout,
            max_restarts=cfg.supervisor_max_restarts,
            backoff_initial_s=cfg.supervisor_backoff_initial,
            backoff_max_s=cfg.supervisor_backoff_max,
            healthy_reset_s=cfg.supervisor_healthy_reset)
        self._grpc_server = None
        self._query_server = None
        self._stop = threading.Event()
        self.grpc_port = 0
        self.query_port = 0

    def _on_degraded(self, stage: str) -> None:
        with self._status_lock:
            if self._status == "Started":
                self._status = "Degraded"
        log.error("aggregator DEGRADED: stage %s exhausted its restart "
                  "budget", stage)

    def health_snapshot(self) -> dict:
        with self._status_lock:
            status = self._status
        return {"status": status,
                "degraded": self.supervisor.degraded,
                "stages": self.supervisor.snapshot()}

    def start(self) -> None:
        from netobserv_tpu.federation.query import start_query_server
        from netobserv_tpu.grpc.federation import start_federation_collector

        cfg = self.cfg
        self._grpc_server, self.grpc_port, _ = start_federation_collector(
            port=cfg.federation_listen_port,
            handler=self.aggregator.ingest_frame,
            tls_cert=cfg.metrics_tls_cert_path,
            tls_key=cfg.metrics_tls_key_path)
        if cfg.federation_query_port >= 0:
            self._query_server = start_query_server(
                self.aggregator, cfg.federation_query_port,
                health_source=self.health_snapshot)
            self.query_port = self._query_server.server_address[1]
        # NOTE: the Prometheus /metrics server is started by __main__ (the
        # same wiring every agent gets); this service only owns the two
        # federation-specific surfaces (delta ingest gRPC, query HTTP)
        if cfg.supervisor_enable:
            self.supervisor.start()
        with self._status_lock:
            self._status = "Started"
        log.info("federation aggregator up: deltas on :%d, queries on :%s",
                 self.grpc_port,
                 self.query_port if self._query_server else "disabled")

    def run(self, stop: Optional[threading.Event] = None) -> None:
        self.start()
        self._active_stop = stop = stop or self._stop
        stop.wait()
        self.shutdown()

    def stop(self) -> None:
        self._stop.set()
        active = getattr(self, "_active_stop", None)
        if active is not None:
            active.set()

    def shutdown(self) -> None:
        with self._status_lock:
            if self._status in ("Stopping", "Stopped"):
                return
            self._status = "Stopping"
        self.supervisor.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=2.0)
        self.aggregator.close()  # final window publishes synchronously
        if self._query_server is not None:
            self._query_server.shutdown()
        with self._status_lock:
            self._status = "Stopped"
