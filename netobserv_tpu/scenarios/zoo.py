"""The adversarial traffic scenario zoo: nine deterministic generators,
each producing a pcap plus machine-checkable ground truth.

Every scenario is evaluated END TO END through the agent's `/query/*`
routes (`scenarios/runner.py`): pcap -> datapath replay -> columnar feed ->
device sketch fold -> window roll -> query snapshot -> HTTP. The truth dict
states which alarms must FIRE, which must stay QUIET, the exact heavy-
hitter set and distinct-source count, and (where relevant) DNS-latency /
QUIC expectations — detection QUALITY, not throughput.

Scale note: flow volumes ride "jumbo" claimed IP lengths (synth.py), so a
megabyte elephant costs one small frame; packet counts stay in the low
thousands per scenario and the whole zoo replays in seconds.
"""

from __future__ import annotations

from netobserv_tpu.scenarios.synth import (
    PcapBuilder, canonical_ip, dns_query, dns_response, heavy_entry,
    quic_long_header, tcp, udp,
)

SYN, SYNACK, ACK, PSHACK = 0x02, 0x12, 0x10, 0x18

#: every victim-signal key of /query/victims — scenarios pick their
#: expected/quiet subsets from this. Derived from the alerting plane's
#: SIGNAL_FIELDS (the ONE signal-name map: zoo grading, /query/victims
#: and the default alert rules can never drift apart)
from netobserv_tpu.alerts.rules import SIGNAL_FIELDS  # noqa: E402

SIGNALS = tuple(SIGNAL_FIELDS)


def _benign_background(b: PcapBuilder, at_us: int = 0) -> dict:
    """Four full TCP sessions (handshake + bidirectional data) — the
    healthy traffic every scenario carries so "quiet" alarms are asserted
    against realistic flows, not silence. ~10% byte backflow keeps the
    asymmetric-conversation signal quiet (a healthy transfer's ACK/response
    stream). Returns its ground-truth contribution."""
    server = "10.0.2.1"
    srcs = []
    for c in range(4):
        client = f"10.0.1.{c + 1}"
        srcs.append(client)
        sport, t = 40000 + c, at_us + c * 400
        b.add(t, client, server, 6, tcp(sport, 443, SYN),
              sport=sport, dport=443)
        b.add(t + 50, server, client, 6, tcp(443, sport, SYNACK),
              sport=443, dport=sport)
        b.add(t + 90, client, server, 6, tcp(sport, 443, ACK),
              sport=sport, dport=443)
        for i in range(3):
            b.add(t + 150 + i * 40, client, server, 6,
                  tcp(sport, 443, PSHACK), claim_len=30_000,
                  sport=sport, dport=443)
            b.add(t + 170 + i * 40, server, client, 6,
                  tcp(443, sport, PSHACK), claim_len=3_000,
                  sport=443, dport=sport)
    srcs.append(server)  # the server's response flows make it a source too
    return {"distinct_srcs": srcs}


def build_syn_flood(path: str) -> dict:
    """Spoofed SYN flood: 400 sources, one victim, zero SYN-ACKs. The
    offered:accepted flood ratio must fire and name the victim; the scan
    and asymmetry signals must stay quiet (one tiny probe per source)."""
    b = PcapBuilder()
    bg = _benign_background(b)
    victim = "10.0.0.80"
    for i in range(400):
        src = f"172.16.{i % 200}.{i // 200 + 1}"
        b.add(2000 + i * 50, src, victim, 6, tcp(2000 + i, 80, SYN),
              sport=2000 + i, dport=80)
    b.write(path)
    return {
        "name": "syn_flood",
        "expect_alarms": ["syn_flood"],
        "quiet_alarms": ["port_scan", "asym_conv", "drop_storm"],
        "victim": victim,
        "victim_signal": "syn_flood",
        "distinct_src": 400 + len(bg["distinct_srcs"]),
        "distinct_tol": 0.15,
        "min_records": 400,
    }


def build_dns_flood(path: str) -> dict:
    """DNS query flood against one resolver, with the latency collapse a
    real flood causes: legitimate clients' answers come back 120ms late
    (all answered — the latency histogram sees the spike), the flood's
    fat ANY-style queries are never answered (pure one-way mass — the
    UDP-flood/asymmetry signal). SYN-flood and scan signals stay quiet."""
    b = PcapBuilder()
    server = "10.0.0.53"
    tx = 1
    # legitimate lookups, answered late (the spike)
    legit = 20
    for c in range(legit):
        client = f"10.0.3.{c + 1}"
        for q in range(2):
            sport, t = 33000 + c, c * 900 + q * 300
            b.add(t, client, server, 17,
                  udp(sport, 53, dns_query(tx)), sport=sport, dport=53)
            b.add(t + 120_000, server, client, 17,
                  udp(53, sport, dns_response(tx)), sport=53, dport=sport)
            tx += 1
    # the flood: 160 spoofed sources x 12 fat queries, never answered
    flood = 160
    for i in range(flood):
        src = f"172.20.{i % 160}.{i // 160 + 1}"
        sport = 1500 + i
        for q in range(12):
            b.add(40_000 + i * 120 + q * 7, src, server, 17,
                  udp(sport, 53, dns_query(tx, pad=288)),
                  sport=sport, dport=53)
            tx += 1
    b.write(path)
    return {
        "name": "dns_flood",
        "expect_alarms": ["asym_conv"],
        "quiet_alarms": ["syn_flood", "port_scan"],
        "dns_p50_min_us": 50_000,
        "distinct_src": flood + legit + 1,  # + the resolver's responses
        "distinct_tol": 0.15,
        "min_records": flood + legit,
    }


def build_port_scan(path: str) -> dict:
    """One scanner sweeping 800 distinct (address, port) targets with lone
    SYNs. The per-source fan-out grid must flag the scanner; the SYN-flood
    signal must stay quiet — no single victim accumulates attempts."""
    b = PcapBuilder()
    bg = _benign_background(b)
    scanner = "10.0.9.9"
    targets = 800
    for i in range(targets):
        dst = f"198.18.{i // 250}.{i % 250 + 1}"
        b.add(3000 + i * 30, scanner, dst, 6,
              tcp(55555, 1000 + i, SYN), sport=55555, dport=1000 + i)
    b.write(path)
    return {
        "name": "port_scan",
        "expect_alarms": ["port_scan"],
        "quiet_alarms": ["syn_flood", "asym_conv", "drop_storm"],
        "distinct_src": 1 + len(bg["distinct_srcs"]),
        "distinct_tol": 0.3,
        "min_records": targets,
    }


def build_elephant_mice(path: str) -> dict:
    """16 elephant transfers over 2000 mice: the heavy-hitter table must
    recall >= 0.9 of the elephants in its top 16, the CM frequency route
    must answer within its stated error bar, and every alarm stays quiet
    (elephants carry healthy ~9% backflow; mice are tiny)."""
    b = PcapBuilder()
    server, mice_sink = "10.0.6.1", "10.0.6.2"
    heavy = []
    for e in range(16):
        client, sport = f"10.0.5.{e + 1}", 50000 + e
        t = e * 700
        b.add(t, client, server, 6, tcp(sport, 443, SYN),
              sport=sport, dport=443)
        b.add(t + 40, server, client, 6, tcp(443, sport, SYNACK),
              sport=443, dport=sport)
        b.add(t + 80, client, server, 6, tcp(sport, 443, ACK),
              sport=sport, dport=443)
        for i in range(20):
            b.add(t + 120 + i * 25, client, server, 6,
                  tcp(sport, 443, PSHACK), claim_len=60_000,
                  sport=sport, dport=443)
        for i in range(4):
            b.add(t + 140 + i * 120, server, client, 6,
                  tcp(443, sport, PSHACK), claim_len=30_000,
                  sport=443, dport=sport)
        heavy.append(heavy_entry(client, server, sport, 443, 6))
    mice_srcs = 500
    for m in range(mice_srcs):
        src = f"10.1.{m % 200}.{m // 200 + 1}"
        for f in range(4):
            b.add(12_000 + m * 60 + f * 9, src, mice_sink, 17,
                  udp(20000 + f, 8080, b"\x00" * 172),
                  sport=20000 + f, dport=8080)
    probe = heavy[0]
    b.write(path)
    return {
        "name": "elephant_mice",
        "heavy": heavy,
        "topk_n": 16,
        "min_recall": 0.9,
        "quiet_alarms": list(SIGNALS),
        "frequency_probe": {
            **probe,
            "true_bytes": b.flow_bytes[(probe["SrcAddr"], probe["DstAddr"],
                                        probe["SrcPort"], probe["DstPort"],
                                        6)]},
        "distinct_src": 16 + mice_srcs + 1,  # + the elephant server
        "distinct_tol": 0.1,
        "min_records": 16 + 4 * mice_srcs,
    }


def build_nat_churn(path: str) -> dict:
    """One NAT'd address churning through 600 source ports of short,
    COMPLETE sessions. The discriminator scenario: 600 SYNs hit one server
    — but every one is answered, so the flood ratio stays quiet; 600 flows
    to one (addr, port) pair is fan-out 1 — the scan grid stays quiet; and
    the distinct-source estimate must stay ~2, not 600 (churn is ports,
    not hosts)."""
    b = PcapBuilder()
    nat, server = "203.0.113.7", "10.0.7.1"
    flows = 600
    for i in range(flows):
        sport, t = 20000 + i, i * 150
        b.add(t, nat, server, 6, tcp(sport, 443, SYN),
              sport=sport, dport=443)
        b.add(t + 30, server, nat, 6, tcp(443, sport, SYNACK),
              sport=443, dport=sport)
        b.add(t + 60, nat, server, 6, tcp(sport, 443, PSHACK),
              claim_len=2_000, sport=sport, dport=443)
        b.add(t + 90, server, nat, 6, tcp(443, sport, PSHACK),
              claim_len=1_500, sport=443, dport=sport)
    b.write(path)
    return {
        "name": "nat_churn",
        "quiet_alarms": list(SIGNALS),
        "distinct_src": 2,
        "distinct_tol": 0.5,
        "min_records": 2 * flows,
    }


def build_quic_heavy(path: str) -> dict:
    """QUIC-dominant mix: 12 long-header UDP/443 elephants over small
    web-ish mice. The datapath's QUIC marker must surface in the window's
    QuicRecords, the elephants must chart, and nothing alarms — heavy
    encrypted UDP is a workload, not an attack."""
    b = PcapBuilder()
    server = "10.0.9.1"
    heavy = []
    for e in range(12):
        client, sport = f"10.0.8.{e + 1}", 44000 + e
        t = e * 600
        for i in range(10):
            b.add(t + i * 40, client, server, 17,
                  udp(sport, 443, quic_long_header()), claim_len=30_000,
                  sport=sport, dport=443)
        for i in range(4):
            b.add(t + 60 + i * 90, server, client, 17,
                  udp(443, sport, quic_long_header()), claim_len=15_000,
                  sport=443, dport=sport)
        heavy.append(heavy_entry(client, server, sport, 443, 17))
    mice_srcs = 100
    for m in range(mice_srcs):
        src = f"10.2.{m % 100}.{m // 100 + 1}"
        for f in range(2):
            b.add(9_000 + m * 70 + f * 11, src, "10.0.9.2", 17,
                  udp(21000 + f, 8080, b"\x00" * 150),
                  sport=21000 + f, dport=8080)
    b.write(path)
    return {
        "name": "quic_heavy",
        "heavy": heavy,
        "topk_n": 12,
        "min_recall": 0.9,
        "quiet_alarms": list(SIGNALS),
        "quic_min_records": 12,
        "distinct_src": 12 + mice_srcs + 1,
        "distinct_tol": 0.15,
        "min_records": 12 + 2 * mice_srcs,
    }


def build_ipv6_heavy(path: str) -> dict:
    """IPv6-dominant mixed traffic (ROADMAP "richer workloads"): ten v6
    elephants with healthy ~9% backflow over v6 AND v4 mice plus the v4
    benign background. Nothing alarms — heavy v6 volume is a workload,
    not an attack — while the top-K must chart the v6 elephants (exact
    16-byte keys through the whole plane) and the distinct-source
    estimate must count v6 sources. Plumbing pin: the resident feed's hot
    rows are slot-id based and KEY-AGNOSTIC — v6 keys ride the full-width
    new-key lane like any other — so a v6-heavy mix must produce ZERO
    dense fallbacks (`sketch_dense_fallback_total`); only the compact
    feed degrades on v6 (its documented spill-overflow behavior). The
    runner reports the spill/fallback counters so the artifact shows the
    v6 plumbing, and grades the fallback count at 0."""
    b = PcapBuilder()
    bg = _benign_background(b)
    server = "2001:db8::10"
    heavy = []
    for e in range(10):
        client = f"2001:db8:0:1::{e + 1:x}"
        sport, t = 46000 + e, 2000 + e * 600
        b.add(t, client, server, 6, tcp(sport, 443, SYN),
              sport=sport, dport=443)
        b.add(t + 40, server, client, 6, tcp(443, sport, SYNACK),
              sport=443, dport=sport)
        b.add(t + 80, client, server, 6, tcp(sport, 443, ACK),
              sport=sport, dport=443)
        for i in range(18):
            b.add(t + 120 + i * 30, client, server, 6,
                  tcp(sport, 443, PSHACK), claim_len=50_000,
                  sport=sport, dport=443)
        for i in range(4):
            b.add(t + 140 + i * 110, server, client, 6,
                  tcp(443, sport, PSHACK), claim_len=22_000,
                  sport=443, dport=sport)
        heavy.append(heavy_entry(canonical_ip(client), canonical_ip(server),
                                 sport, 443, 6))
    mice6, sink6 = 180, "2001:db8::20"
    for m in range(mice6):
        src = f"2001:db8:aa::{m + 1:x}"
        for f in range(2):
            b.add(15_000 + m * 55 + f * 9, src, sink6, 17,
                  udp(23000 + f, 8080, b"\x00" * 160),
                  sport=23000 + f, dport=8080)
    mice4 = 60  # the mix stays honestly MIXED: the v4 hot-row path stays hot
    for m in range(mice4):
        src = f"10.3.{m % 60}.{m // 60 + 1}"
        b.add(28_000 + m * 40, src, "10.0.6.9", 17,
              udp(24000, 8080, b"\x00" * 150), sport=24000, dport=8080)
    b.write(path)
    return {
        "name": "ipv6_heavy",
        "heavy": heavy,
        "topk_n": 16,
        "min_recall": 0.9,
        "quiet_alarms": list(SIGNALS),
        # 10 elephant clients + their server's responder flows + v6/v4
        # mice + the benign background's sources
        "distinct_src": 10 + 1 + mice6 + mice4 + len(bg["distinct_srcs"]),
        "distinct_tol": 0.15,
        "min_records": 10 + 2 * mice6 + mice4,
        # the resident feed must NEVER wholesale-degrade on v6 traffic
        # (hot rows are key-agnostic; spill volume is cold-start/new-key
        # geometry, deployment-shape dependent, so it is reported but not
        # pinned)
        "max_dense_fallbacks": 0,
    }


def build_overlay_syn_scan(path: str) -> dict:
    """Mixed-attack OVERLAY (the ROADMAP leftover): a spoofed SYN flood
    AND an independent port scan run simultaneously in one pcap. BOTH
    alarms must fire with correct victim attribution — the flood names
    its victim, the scan grid flags the scanner's fan-out — while the
    dns/drop/asymmetry signals stay quiet (no cross-talk: the scanner's
    800 one-SYN targets must not read as flood victims, the flood's 400
    one-probe sources must not read as scanners), all under the zoo's ONE
    shared threshold set."""
    b = PcapBuilder()
    bg = _benign_background(b)
    victim = "10.0.0.80"
    flood_srcs = 400
    for i in range(flood_srcs):
        src = f"172.16.{i % 200}.{i // 200 + 1}"
        b.add(2000 + i * 50, src, victim, 6, tcp(2000 + i, 80, SYN),
              sport=2000 + i, dport=80)
    scanner = "10.0.9.9"
    targets = 800
    for i in range(targets):
        dst = f"198.18.{i // 250}.{i % 250 + 1}"
        # interleaved with the flood in time (a real mixed attack), still
        # inside the one 5s replay window
        b.add(2500 + i * 30, scanner, dst, 6,
              tcp(55555, 1000 + i, SYN), sport=55555, dport=1000 + i)
    b.write(path)
    return {
        "name": "overlay_syn_scan",
        "expect_alarms": ["syn_flood", "port_scan"],
        "quiet_alarms": ["asym_conv", "drop_storm"],
        "victim": victim,
        "victim_signal": "syn_flood",
        "distinct_src": flood_srcs + 1 + len(bg["distinct_srcs"]),
        "distinct_tol": 0.15,
        "min_records": flood_srcs + targets,
    }


def build_flow_ascent(path: str) -> dict:
    """A mouse flow ramping into an elephant MID-RUN — the persistent-slot
    churn scenario (ISSUE 13). One 5-tuple trickles ~600B per replay
    window through the first sketch window, then ramps to ~360KB per
    window; the slot table keeps the key's identity across the roll, so
    the window-over-window count:prev ratio explodes and the
    `flow_ascent` alert must RAISE — live, mid-window, with the exact key
    named — while `new_heavy_key` stays quiet (the key is NOT new: its
    slot's first_seen is window 0, which is exactly the new-vs-ascending
    discrimination the per-slot metadata buys). SYN/scan/drop/asym stay
    quiet (complete handshake, ~10%% backflow both phases); the DDoS
    z-signal is deliberately un-asserted — a 300x volume ramp to one
    destination is a legitimate surge either way.

    Timing contract with the runner: replay windows are 5s virtual and
    drain at ~0.25s wall each, so the phase boundary at virtual window 48
    lands ~12s wall — safely AFTER the 10s sketch-window roll the
    `runner` overrides configure (drains can lag but never lead, so the
    elephant phase can only land later, never before the roll; the mouse
    phase can only need window-0 mass, which the first drains deliver
    seconds before the roll)."""
    b = PcapBuilder()
    bg = _benign_background(b)
    client, server = "10.0.5.50", "10.0.6.1"
    sport = 51000
    # one replay window in virtual us. DELIBERATELY > the runner's 5s
    # replay window: the parser splits on a STRICT > 5s gap from each
    # window's first packet, so exactly-5s spacing would merge adjacent
    # windows pairwise and halve the drain count the phase timing needs
    W = 5_050_000
    mouse_w, total_w, mice = 48, 68, 3
    b.add(100, client, server, 6, tcp(sport, 443, SYN),
          sport=sport, dport=443)
    b.add(140, server, client, 6, tcp(443, sport, SYNACK),
          sport=443, dport=sport)
    b.add(180, client, server, 6, tcp(sport, 443, ACK),
          sport=sport, dport=443)
    # ONE time-ordered sweep: the pcap writer emits packets in call order
    # and the replay parser windows a monotone timestamp stream (real
    # captures are time-ordered) — interleaving per window keeps it so
    for w in range(total_w):
        if w < mouse_w:            # phase 1: the mouse (~600B/window)
            b.add(w * W + 500, client, server, 6, tcp(sport, 443, PSHACK),
                  claim_len=600, sport=sport, dport=443)
            # tiny response keeps the pair bucket two-way (~10% backflow)
            b.add(w * W + 700, server, client, 6, tcp(443, sport, PSHACK),
                  claim_len=64, sport=443, dport=sport)
        else:                      # phase 2: the elephant (~360KB/window)
            for i in range(12):
                b.add(w * W + 500 + i * 200, client, server, 6,
                      tcp(sport, 443, PSHACK), claim_len=30_000,
                      sport=sport, dport=443)
            b.add(w * W + 3200, server, client, 6, tcp(443, sport, PSHACK),
                  claim_len=36_000, sport=443, dport=sport)
        if w % 5 == 0:             # steady mice, sparse enough that their
            #                        one-way pair buckets stay under the
            #                        asym volume floor in every window
            for m in range(mice):
                b.add(w * W + 2000 + m * 50, f"10.1.9.{m + 1}", "10.0.6.2",
                      17, udp(22000 + m, 8080, b"\x00" * 160),
                      sport=22000 + m, dport=8080)
    b.write(path)
    key = heavy_entry(client, server, sport, 443, 6)
    return {
        "name": "flow_ascent",
        "expect_alarms": ["flow_ascent"],
        # ddos deliberately absent from BOTH lists (see docstring)
        "quiet_alarms": ["syn_flood", "port_scan", "drop_storm",
                         "asym_conv", "new_heavy_key"],
        "ascent_key": key,
        "heavy": [key],
        "topk_n": 4,
        "min_recall": 1.0,
        "distinct_src": 2 + mice + len(bg["distinct_srcs"]),
        "distinct_tol": 0.3,
        "min_records": 50,
        # multi-window runner shape: two ~10s sketch windows; detection
        # must land inside window 1 (the attack window) = sub-window
        # relative to the ramp, budgeted as 2 x window_s from replay start
        "runner": {"window_s": 10.0, "deadline_s": 120.0},
        "ttd_budget_s": 20.0,
    }


#: name -> builder(path) -> truth; the runner, tests, and bench all
#: iterate this registry
SCENARIOS = {
    "syn_flood": build_syn_flood,
    "dns_flood": build_dns_flood,
    "port_scan": build_port_scan,
    "elephant_mice": build_elephant_mice,
    "nat_churn": build_nat_churn,
    "quic_heavy": build_quic_heavy,
    "ipv6_heavy": build_ipv6_heavy,
    "overlay_syn_scan": build_overlay_syn_scan,
    "flow_ascent": build_flow_ascent,
}
