"""Scenario runner: replay one zoo pcap through a FULL in-process agent and
grade detection quality through the live `/query/*` HTTP routes.

The pipeline under test is the real one — PcapReplayFetcher -> MapTracer ->
CapacityLimiter -> QueueExporter -> TpuSketchExporter (columnar fast path,
resident feed) -> window roll -> query snapshot -> metrics-server HTTP —
with the supervisor running, the mid-window refresh enabled, and the
CONTINUOUS DETECTION PLANE mounted (default alert rules over the same
snapshots), so every scenario also exercises "the query plane answers
during sustained ingest" AND "the agent raises its own alarms without
being polled for them". The runner records a per-scenario time-to-detect
(replay start -> first observed RAISE through `/query/alerts`); with the
refresh enabled, attack scenarios must detect in under one window period
— sub-window detection is the plane's point.

Used by tests/test_scenarios.py (one fast smoke in tier-1, the full zoo in
the slow tier) and `bench.py --scenarios` (the per-scenario quality
artifact)."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request

from netobserv_tpu.scenarios.zoo import SCENARIOS, SIGNALS

log = logging.getLogger("netobserv_tpu.scenarios")

#: one shared detection config for the WHOLE zoo — floods must fire and
#: benign mixes stay quiet under the SAME thresholds, or the assertions
#: prove nothing
THRESHOLDS = dict(
    synflood_min=64,
    synflood_ratio=8.0,
    scan_fanout_threshold=256,
    asym_min_bytes=2048,
    asym_ratio=0.95,
    # heavy-hitter churn gates (persistent-slot plane): the flow_ascent /
    # new_heavy_key alert rules fire on lists rendered under exactly these
    churn_ascent=8.0,
    churn_min_bytes=256 * 1024,
)


def _sketch_cfg():
    from netobserv_tpu.sketch.state import SketchConfig
    # compile-friendly but honest geometry (width >= 16*topk, the
    # documented precision floor)
    return SketchConfig(cm_depth=4, cm_width=16384, hll_precision=12,
                        topk=256)


def run_scenario(name: str, workdir: str, window_s: float = 600.0,
                 evict_s: float = 0.25, query_refresh_s: float = 0.5,
                 deadline_s: float = 240.0) -> dict:
    """Build the scenario pcap, run the agent over it, poll /query/* while
    the window is LIVE, and return the graded quality dict.

    The window deliberately outlives the replay (a one-shot pcap's data
    window would otherwise be queryable only until the next roll swapped in
    an empty one): the mid-window refresh serves the ACCUMULATING live
    window — the "query plane answers during sustained ingest" claim — and
    the agent's shutdown flush closes the window, publishing the final
    ROLL snapshot, which is graded too."""
    from netobserv_tpu.agent.agent import FlowsAgent
    from netobserv_tpu.alerts import AlertEngine, LogSink, MetricsSink
    from netobserv_tpu.alerts.rules import default_rules
    from netobserv_tpu.config import AgentConfig
    from netobserv_tpu.datapath.replay import PcapReplayFetcher
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.metrics.registry import Metrics
    from netobserv_tpu.metrics.server import start_metrics_server
    from netobserv_tpu.utils import retrace

    build = SCENARIOS[name]
    pcap = os.path.join(workdir, f"{name}.pcap")
    truth = build(pcap)
    # multi-window scenarios (flow_ascent: the churn diff needs a ROLL
    # between its phases) override the runner shape through their truth —
    # thresholds stay the ONE shared set above
    overrides = truth.get("runner") or {}
    window_s = overrides.get("window_s", window_s)
    deadline_s = overrides.get("deadline_s", deadline_s)

    cfg = AgentConfig(export="tpu-sketch", cache_active_timeout=evict_s)
    metrics = Metrics()
    # one replay window: every scenario keeps its packets inside the
    # virtual 5s span, so the whole pcap lands in ONE eviction and
    # therefore ONE sketch window — deterministic per-window assertions
    fetcher = PcapReplayFetcher(pcap, window_s=5.0)
    if not query_refresh_s:
        raise ValueError("the scenario runner grades the LIVE window "
                         "through mid-window refreshes; query_refresh_s "
                         "must be > 0")
    # the alerting plane runs with its DEFAULT rules: they fire on the
    # report's suspect lists, which the exporter renders under the zoo's
    # ONE shared threshold set below — grading and alerting read the same
    # truth by construction (alerts/rules.py one-truth note)
    engine = AlertEngine(default_rules(), metrics=metrics,
                         sinks=[LogSink(), MetricsSink(metrics)])
    exporter = TpuSketchExporter(
        batch_size=512, window_s=window_s, sketch_cfg=_sketch_cfg(),
        metrics=metrics, sink=lambda obj: None,
        query_refresh_s=query_refresh_s, alerts=engine,
        ddos_z_threshold=6.0, drop_z_threshold=6.0, **THRESHOLDS)
    agent = FlowsAgent(cfg, fetcher, exporter, metrics=metrics)
    srv = start_metrics_server(metrics.registry, port=0,
                               health_source=agent.health_snapshot,
                               query_routes=agent.query_routes)
    port = srv.server_address[1]
    retraces_before = retrace.total_retraces()

    def get(path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=10) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    stop = threading.Event()
    t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
    t.start()

    observations: list[dict] = []
    freq_obs: list[dict] = []
    min_records = truth.get("min_records", 1)
    probe = truth.get("frequency_probe")

    def observe() -> dict:
        """One full /query/* round against the current snapshot; probes
        frequency once the data window surfaced."""
        obs: dict = {}
        code, status = get("/query/status")
        if code == 200:
            obs["status"] = status
        for route in ("topk?n=64", "victims", "cardinality", "alerts",
                      "churn"):
            c, body = get(f"/query/{route}")
            if c == 200:
                obs[route.split("?")[0]] = body
        records = obs.get("cardinality", {}).get("records", 0)
        if probe is not None and records >= min_records:
            c, f = get("/query/frequency?src={SrcAddr}&dst={DstAddr}"
                       "&src_port={SrcPort}&dst_port={DstPort}"
                       "&proto={Proto}".format(**probe))
            if c == 200:
                freq_obs.append(f)
        observations.append(obs)
        return obs

    seen_seq, live_data_obs = 0, 0
    expect = set(truth.get("expect_alarms", ()))
    t_run0 = time.monotonic()  # replay start: time-to-detect is measured
    #                            from here to the first observed RAISE
    time_to_detect: float | None = None
    deadline = time.monotonic() + deadline_s
    try:
        # phase 1: poll the LIVE window through the mid-window refreshes
        # until the whole pcap is folded AND a couple more refresh
        # snapshots answered over it (sustained-ingest answering)
        while time.monotonic() < deadline and live_data_obs < 3:
            code, status = get("/query/status")
            if code == 200 and status.get("seq", 0) > seen_seq:
                seen_seq = status["seq"]
                obs = observe()
                if time_to_detect is None:
                    view = obs.get("alerts", {})
                    # an expected rule counts as detected whether it is
                    # still ACTIVE or already visible as a raise in the
                    # transitions ring (a raise that cleared between two
                    # polls must not read as "never detected")
                    if any(a.get("rule") in expect
                           for a in view.get("active", ())) or any(
                            t.get("rule") in expect
                            and t.get("action") == "raise"
                            for t in view.get("recent", ())):
                        time_to_detect = time.monotonic() - t_run0
                if (obs.get("cardinality", {}).get("records", 0)
                        >= min_records and fetcher.exhausted()):
                    live_data_obs += 1
            time.sleep(0.1)
    finally:
        stop.set()
        t.join(timeout=60)
    # phase 2: the agent's shutdown flush closed the window and published
    # the final ROLL snapshot (mid_window=False) — grade that one too
    try:
        if t.is_alive():
            log.error("agent did not stop within 60s")
        else:
            final = observe()
            if final.get("status", {}).get("mid_window", True):
                log.warning("final snapshot is still a mid-window refresh "
                            "(shutdown flush did not publish a roll?)")
    finally:
        srv.shutdown()
    retraces = retrace.total_retraces() - retraces_before
    # feed-plumbing evidence for scenarios that pin it (ipv6_heavy: the
    # resident feed must never dense-fallback on v6; spill volume is
    # reported for the artifact but not pinned — cold-start geometry)
    ring = exporter._ring
    plumbing = {
        "resident_spill_rows": int(getattr(ring, "spill_rows", 0)),
        # read the REGISTRY counter, not a ring attribute: the resident
        # ring has no dense-fallback path at all (getattr would grade a
        # vacuous 0), while the metric covers whichever feed is wired
        "dense_fallbacks": int(
            metrics.sketch_dense_fallback_total._value.get()),
        "direct_fold_rows": int(
            getattr(exporter._pending_buf, "direct_rows", 0)),
    }
    return evaluate(truth, observations, freq_obs, retraces=retraces,
                    plumbing=plumbing, time_to_detect_s=time_to_detect,
                    window_s=window_s)


def evaluate(truth: dict, observations: list[dict],
             freq_obs: list[dict] | None = None,
             retraces: int = 0, plumbing: dict | None = None,
             time_to_detect_s: float | None = None,
             window_s: float | None = None) -> dict:
    """Grade collected /query/* observations against the ground truth.
    Returns {"name", "passed", "failures": [...], ...quality metrics}.
    `plumbing` carries feed-path counters (spill rows, dense fallbacks)
    for scenarios whose truth pins them; `time_to_detect_s` the replay-
    start -> first-observed-RAISE latency (None = no raise observed), and
    `window_s` the window period the sub-window detection bar grades
    against."""
    failures: list[str] = []
    out: dict = {"name": truth.get("name", "?"), "retraces": retraces,
                 "windows_observed": len(
                     {o["status"].get("window") for o in observations
                      if "status" in o})}
    if plumbing:
        out.update(plumbing)
        want_spill = truth.get("min_resident_spill_rows")
        if want_spill is not None and \
                plumbing["resident_spill_rows"] < want_spill:
            failures.append(
                f"resident spill rows {plumbing['resident_spill_rows']} < "
                f"{want_spill} (v6 rows did not ride the spill lane?)")
        max_fb = truth.get("max_dense_fallbacks")
        if max_fb is not None and plumbing["dense_fallbacks"] > max_fb:
            failures.append(
                f"{plumbing['dense_fallbacks']} dense fallbacks > "
                f"{max_fb} (the resident feed degraded wholesale)")
    data = [o for o in observations
            if o.get("cardinality", {}).get("records", 0)
            >= truth.get("min_records", 1)]
    if not data:
        failures.append("the data window never surfaced through /query/*")
        out.update(passed=False, failures=failures)
        return out

    # --- heavy-hitter recall (through /query/topk) ---
    if truth.get("heavy"):
        want = {(h["SrcAddr"], h["DstAddr"], h["SrcPort"], h["DstPort"],
                 h["Proto"]) for h in truth["heavy"]}
        best = 0.0
        for o in data:
            top = o.get("topk", {}).get("topk", [])[:truth["topk_n"]]
            got = {(e["SrcAddr"], e["DstAddr"], e["SrcPort"], e["DstPort"],
                    e["Proto"]) for e in top}
            best = max(best, len(want & got) / len(want))
        out["topk_recall"] = best
        if best < truth.get("min_recall", 0.9):
            failures.append(
                f"top-{truth['topk_n']} recall {best:.2f} < "
                f"{truth.get('min_recall', 0.9)}")

    # --- alarms: expected must fire in a data window, quiet must stay
    # silent in EVERY observed window (including mid-window refreshes) ---
    fired = {sig: any(o.get("victims", {}).get(sig) for o in data)
             for sig in SIGNALS}
    out["alarms_fired"] = sorted(s for s, f in fired.items() if f)
    for sig in truth.get("expect_alarms", ()):
        # per-flow churn rules (flow_ascent/new_heavy_key) have no
        # /query/victims bucket list — their only surface is the alert
        # plane, graded below
        if sig in SIGNALS and not fired[sig]:
            failures.append(f"expected {sig} alarm never fired")
    for sig in truth.get("quiet_alarms", ()):
        if any(o.get("victims", {}).get(sig) for o in observations):
            failures.append(f"{sig} alarm fired on a benign signal")

    # --- continuous detection plane (through /query/alerts): expected
    # alarms must RAISE live (not just sit in suspect lists a poller
    # would have to read), quiet ones must never raise in ANY observed
    # view, and with the refresh enabled detection must land inside one
    # window period (sub-window detection is the plane's point) ---
    alert_views = [o["alerts"] for o in observations if "alerts" in o]
    if not alert_views and (truth.get("expect_alarms")
                            or truth.get("quiet_alarms")):
        # a dead /query/alerts surface must FAIL the scenario, not
        # silently skip every alert assertion — for attack scenarios AND
        # benign ones (whose whole point is proving nothing raises)
        failures.append("no /query/alerts view ever observed")
    if alert_views:
        raised = {a["rule"] for v in alert_views for a in v.get("active", ())}
        raised |= {t["rule"] for v in alert_views
                   for t in v.get("recent", ()) if t["action"] == "raise"}
        out["alerts_raised"] = sorted(raised)
        out["alert_transitions"] = max(
            v.get("transition_seq", 0) for v in alert_views)
        for sig in truth.get("expect_alarms", ()):
            if sig not in raised:
                failures.append(
                    f"expected {sig} alert never RAISED on /query/alerts")
        for sig in truth.get("quiet_alarms", ()):
            if sig in raised:
                failures.append(
                    f"{sig} alert raised on a benign signal")
        want_key = truth.get("ascent_key")
        if want_key:
            # the acceptance bar "detects with the RIGHT KEY named": a
            # raised flow_ascent whose fingerprint bucket is exactly the
            # ramping flow's 5-tuple Key string
            key = (f"{want_key['SrcAddr']}:{want_key['SrcPort']}->"
                   f"{want_key['DstAddr']}:{want_key['DstPort']}/"
                   f"{want_key['Proto']}")
            named = any(
                a.get("bucket") == key
                for v in alert_views for a in v.get("active", ())
                if a["rule"] == "flow_ascent") or any(
                t.get("bucket") == key
                for v in alert_views for t in v.get("recent", ())
                if t["rule"] == "flow_ascent" and t["action"] == "raise")
            out["ascent_key_named"] = named
            if not named:
                failures.append(
                    f"flow_ascent never raised with key {key}")
        if truth.get("victim") and truth.get("victim_signal"):
            sig = truth["victim_signal"]
            # same active-OR-ring rule as detection: a raise that cleared
            # between two polls still carries its victims in the ring
            named = any(
                truth["victim"] in a.get("victims", ())
                for v in alert_views for a in v.get("active", ())
                if a["rule"] == sig) or any(
                truth["victim"] in t.get("victims", ())
                for v in alert_views for t in v.get("recent", ())
                if t["rule"] == sig and t["action"] == "raise")
            out["alert_victim_named"] = named
            if not named:
                failures.append(
                    f"victim {truth['victim']} not named by the "
                    f"{sig} alert")
        out["time_to_detect_s"] = (
            None if time_to_detect_s is None
            else round(time_to_detect_s, 3))
        if truth.get("expect_alarms"):
            # multi-window scenarios whose attack STARTS after a roll
            # (flow_ascent) budget detection relative to the attack
            # window: truth's ttd_budget_s, else one window period
            budget = truth.get("ttd_budget_s", window_s)
            if time_to_detect_s is None:
                failures.append(
                    "no live RAISE observed during the replay "
                    "(time-to-detect unmeasurable)")
            elif budget is not None and time_to_detect_s >= budget:
                failures.append(
                    f"time-to-detect {time_to_detect_s:.1f}s is not "
                    f"sub-window (budget {budget:.0f}s)")

    # --- victim naming ---
    if truth.get("victim"):
        sig = truth["victim_signal"]
        named = any(
            truth["victim"] in b.get("probable_victims", ())
            for o in data for b in o.get("victims", {}).get(sig, ()))
        out["victim_named"] = named
        if not named:
            failures.append(
                f"victim {truth['victim']} not named in {sig} buckets")

    # --- cardinality within HLL bounds ---
    if truth.get("distinct_src"):
        est = max(o["cardinality"]["distinct_src_estimate"] for o in data)
        rel = abs(est - truth["distinct_src"]) / truth["distinct_src"]
        out["distinct_src_est"] = est
        out["distinct_src_err"] = round(rel, 4)
        if rel > truth.get("distinct_tol", 0.2):
            failures.append(
                f"distinct-src estimate {est:.0f} off ground truth "
                f"{truth['distinct_src']} by {rel:.1%}")

    # --- DNS latency spike (through /query/status quantiles) ---
    if truth.get("dns_p50_min_us"):
        p50 = max(float(o["status"]["dns_latency_quantiles_us"]["0.5"])
                  for o in data if "dns_latency_quantiles_us" in o["status"])
        out["dns_p50_us"] = p50
        if p50 < truth["dns_p50_min_us"]:
            failures.append(
                f"dns latency p50 {p50:.0f}us below the injected spike "
                f"({truth['dns_p50_min_us']}us)")

    # --- QUIC marker plumbing ---
    if truth.get("quic_min_records"):
        quic = max(float(o["status"].get("quic_records", 0)) for o in data)
        out["quic_records"] = quic
        if quic < truth["quic_min_records"]:
            failures.append(
                f"QuicRecords {quic:.0f} < {truth['quic_min_records']}")

    # --- CM frequency error-bar contract (through /query/frequency) ---
    if truth.get("frequency_probe") is not None:
        if not freq_obs:
            failures.append("frequency probe never answered on the "
                            "data window")
        else:
            true_b = truth["frequency_probe"]["true_bytes"]
            best = min(freq_obs, key=lambda f: f["est_bytes"])
            out["frequency_est_bytes"] = best["est_bytes"]
            out["frequency_true_bytes"] = true_b
            # CM never underestimates; the overestimate stays within the
            # advertised (e/w)*N bound (float32 rounding slack)
            if best["est_bytes"] < true_b * 0.999:
                failures.append(
                    f"CM estimate {best['est_bytes']:.0f} underestimates "
                    f"true {true_b}")
            bound = best["overestimate_bound_bytes"]
            if best["est_bytes"] > true_b + bound + true_b * 0.001:
                failures.append(
                    f"CM estimate {best['est_bytes']:.0f} exceeds true "
                    f"{true_b} + stated bound {bound:.0f}")

    if retraces:
        failures.append(f"{retraces} post-warmup retraces during the run")
    out.update(passed=not failures, failures=failures)
    return out
