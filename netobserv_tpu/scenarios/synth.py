"""Deterministic packet/pcap synthesis for the scenario zoo.

Everything is seed-free and arithmetic: a scenario built twice produces
byte-identical pcaps, so detection-quality assertions never chase RNG
noise. The one deliberate liberty vs a real capture: the IPv4 header's
``total_length`` may CLAIM more bytes than the frame carries ("jumbo"
accounting) — the replay parser accounts flows by the claimed IP length
(the kernel datapath's skb->len analog), which lets an elephant flow carry
megabytes without megabyte pcaps.
"""

from __future__ import annotations

import socket
import struct

from netobserv_tpu.model.packet_record import pcap_file_header

#: classic-pcap epoch base for every scenario (any fixed wall time works:
#: the replay fetcher rebases capture timestamps to the live monotonic
#: clock before the agent sees them)
T0_SEC = 1_700_000_000


def eth(proto: int = 0x0800) -> bytes:
    return b"\x02" * 6 + b"\x04" * 6 + struct.pack(">H", proto)


def ipv4(src: str, dst: str, proto: int, payload_len: int,
         claim_len: int | None = None) -> bytes:
    """20-byte IPv4 header. `claim_len` overrides the total_length field
    (jumbo accounting; defaults to the honest 20 + payload_len)."""
    total = claim_len if claim_len is not None else 20 + payload_len
    return struct.pack(">BBHHHBBH4s4s", 0x45, 0, total, 1, 0, 64, proto,
                       0, socket.inet_aton(src), socket.inet_aton(dst))


def ipv6(src: str, dst: str, proto: int, payload_len: int,
         claim_len: int | None = None) -> bytes:
    """40-byte IPv6 header. `claim_len` claims a TOTAL IP length (header +
    payload, the v4-semantics twin — the replay parser accounts
    payload_length + 40), so scenarios state jumbo bytes identically for
    both families."""
    plen = (claim_len - 40) if claim_len is not None else payload_len
    return struct.pack(">IHBB16s16s", 0x6000_0000, plen, proto, 64,
                       socket.inet_pton(socket.AF_INET6, src),
                       socket.inet_pton(socket.AF_INET6, dst))


def canonical_ip(addr: str) -> str:
    """The textual form the agent renders (`ip_from_16`): canonical
    compressed v6, dotted-quad v4 — scenarios canonicalize their truth
    through this so string comparison never chases formatting."""
    if ":" in addr:
        return socket.inet_ntop(socket.AF_INET6,
                                socket.inet_pton(socket.AF_INET6, addr))
    return addr


def tcp(sport: int, dport: int, flags: int) -> bytes:
    """20-byte TCP header with the given raw flags byte."""
    return struct.pack(">HHIIBBHHH", sport, dport, 1, 0, 0x50, flags,
                       64240, 0, 0)


def udp(sport: int, dport: int, payload: bytes = b"") -> bytes:
    return struct.pack(">HHHH", sport, dport, 8 + len(payload), 0) + payload


def dns_query(txid: int, pad: int = 68) -> bytes:
    """Minimal DNS header (QR=0) + question padding."""
    return struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 0) + b"\x00" * pad


def dns_response(txid: int, rcode: int = 0, pad: int = 80) -> bytes:
    return struct.pack(">HHHHHH", txid, 0x8180 | (rcode & 0xF),
                       1, 1, 0, 0) + b"\x00" * pad


def quic_long_header(version: int = 1, pad: int = 1195) -> bytes:
    """QUIC long-header payload (first byte 0b11......) — what the replay
    parser's UDP/443 probe recognizes, like the kernel datapath's."""
    return b"\xc3" + struct.pack(">I", version) + b"\x00" * pad


class PcapBuilder:
    """Accumulates (timestamp, frame) pairs and writes a classic pcap.
    Tracks per-flow ACCOUNTED bytes (claimed IP length + 14B ethernet, the
    replay parser's rule) so scenarios can state exact ground truth."""

    def __init__(self):
        self._packets: list[bytes] = []
        #: (src, dst, sport, dport, proto) -> accounted bytes
        self.flow_bytes: dict[tuple, int] = {}
        self.flow_packets: dict[tuple, int] = {}

    def __len__(self) -> int:
        return len(self._packets)

    def add(self, at_us: int, src: str, dst: str, proto: int, l4: bytes,
            claim_len: int | None = None, sport: int = 0,
            dport: int = 0) -> None:
        """One IP frame at T0 + at_us — IPv6 when the addresses carry a
        colon, IPv4 otherwise (mixing families in one pcap is how the
        ipv6_heavy scenario exercises the v6 spill lane under load).
        `sport`/`dport` are only for the ground-truth ledger (the l4
        bytes already carry them); `claim_len` always claims a TOTAL IP
        length, both families."""
        if ":" in src:
            ip_hdr = ipv6(src, dst, proto, len(l4), claim_len)
            frame = eth(0x86DD) + ip_hdr + l4
            honest = 40 + len(l4)
        else:
            frame = eth() + ipv4(src, dst, proto, len(l4), claim_len) + l4
            honest = 20 + len(l4)
        hdr = struct.pack("<IIII", T0_SEC + at_us // 1_000_000,
                          at_us % 1_000_000, len(frame), len(frame))
        self._packets.append(hdr + frame)
        key = (src, dst, sport, dport, proto)
        accounted = (claim_len if claim_len is not None else honest) + 14
        self.flow_bytes[key] = self.flow_bytes.get(key, 0) + accounted
        self.flow_packets[key] = self.flow_packets.get(key, 0) + 1

    def write(self, path: str) -> None:
        with open(path, "wb") as fh:
            fh.write(pcap_file_header(65535) + b"".join(self._packets))


def heavy_entry(src: str, dst: str, sport: int, dport: int,
                proto: int) -> dict:
    """A ground-truth heavy-hitter key in the /query/topk entry shape."""
    return {"SrcAddr": src, "DstAddr": dst, "SrcPort": sport,
            "DstPort": dport, "Proto": proto}
