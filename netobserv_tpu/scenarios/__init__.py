"""Adversarial traffic scenario zoo (ROADMAP "Scenario zoo + live query
surface"): deterministic pcap generators plus a full-agent replay runner
that grades detection QUALITY — top-K recall, flood-ratio alarms, victim
naming, HLL cardinality bounds, DNS-latency spikes, QUIC markers — through
the agent's live `/query/*` routes, not by peeking at internals.

- `zoo.SCENARIOS` — name -> builder(path) -> ground-truth dict
- `runner.run_scenario(name, workdir)` — replay + grade one scenario
- `runner.evaluate(truth, observations)` — the grading logic alone
"""

from netobserv_tpu.scenarios.zoo import SCENARIOS, SIGNALS  # noqa: F401
