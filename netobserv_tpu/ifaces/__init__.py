"""Interface discovery (L2 in SURVEY.md §1).

Reference analog: `pkg/ifaces/` — an Informer (watcher via netlink
subscription, or poller via periodic link dumps) feeding attach/detach events,
a Registerer caching (ifindex, MAC) -> name, and name/CIDR filters. Implemented
over raw AF_NETLINK sockets (no external deps).
"""

from netobserv_tpu.ifaces.informers import (  # noqa: F401
    Event, EventType, Interface, Poller, Watcher,
)
from netobserv_tpu.ifaces.registerer import Registerer  # noqa: F401
from netobserv_tpu.ifaces.filter import InterfaceFilter  # noqa: F401
