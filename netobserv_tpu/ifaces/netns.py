"""Named network-namespace entry (setns) for discovery and attach.

Reference analog: `pkg/ifaces/watcher.go:57-271` (per-namespace netlink
subscription + link enumeration with netns handles) and
`pkg/agent/interfaces_listener.go:272-298` (attach inside the namespace).

setns(2) affects only the CALLING THREAD, so `netns_context` is safe to use
from worker threads (listener, watcher): the thread enters the namespace, does
its work, and restores its original namespace on exit. Namespace-bound
resources created inside (netlink sockets, TCX links, tc subprocesses forked
while inside) remain bound to the target namespace afterwards.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("netobserv_tpu.ifaces.netns")

NETNS_DIR = "/var/run/netns"


class netns_context:
    """Run the calling thread inside the named netns; restore on exit.

    A falsy name is a no-op, so call sites can wrap unconditionally:

        with netns_context(iface.netns):
            ...attach/dump...
    """

    def __init__(self, name: Optional[str], netns_dir: str = NETNS_DIR):
        self._name = name
        self._dir = netns_dir
        self._saved = -1
        self._target = -1

    def __enter__(self) -> "netns_context":
        if not self._name:
            return self
        self._saved = os.open("/proc/self/ns/net", os.O_RDONLY)
        try:
            self._target = os.open(
                os.path.join(self._dir, self._name), os.O_RDONLY)
            os.setns(self._target, os.CLONE_NEWNET)
        except BaseException:
            os.close(self._saved)
            self._saved = -1
            if self._target >= 0:
                os.close(self._target)
                self._target = -1
            raise
        return self

    def __exit__(self, *exc) -> bool:
        if self._saved >= 0:
            try:
                os.setns(self._saved, os.CLONE_NEWNET)
            finally:
                os.close(self._saved)
                self._saved = -1
        if self._target >= 0:
            os.close(self._target)
            self._target = -1
        return False


def list_netns(netns_dir: str = NETNS_DIR) -> list[str]:
    try:
        return sorted(os.listdir(netns_dir))
    except OSError:
        return []


def links_in(name: str, netns_dir: str = NETNS_DIR):
    """Enumerate links inside a named namespace (enter, dump, restore)."""
    from netobserv_tpu.ifaces import netlink

    with netns_context(name, netns_dir):
        return netlink.dump_links()


def subscribe_links_in(name: str, netns_dir: str = NETNS_DIR):
    """Create a netlink RTMGRP_LINK subscription bound INSIDE the namespace;
    the socket keeps delivering that namespace's events after the thread
    returns to its original namespace."""
    from netobserv_tpu.ifaces import netlink

    with netns_context(name, netns_dir):
        return netlink.subscribe_links()
