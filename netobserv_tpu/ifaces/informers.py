"""Informers: interface lifecycle event sources.

Reference analog: `pkg/ifaces/watcher.go` (netlink subscription + netns dir
watching) and `pkg/ifaces/poller.go` (periodic LinkList diff). Both emit the
same Event stream into a queue.
"""

from __future__ import annotations

import enum
import logging
import os
import queue
import threading
from dataclasses import dataclass
from typing import Optional

from netobserv_tpu.ifaces import netlink

log = logging.getLogger("netobserv_tpu.ifaces")

NETNS_DIR = "/var/run/netns"


class EventType(enum.Enum):
    ADDED = "added"
    REMOVED = "removed"


@dataclass(frozen=True)
class Interface:
    index: int
    name: str
    mac: bytes
    netns: str = ""  # "" = default namespace


@dataclass
class Event:
    type: EventType
    interface: Interface


class _InformerBase:
    def __init__(self, out: "Optional[queue.Queue[Event]]" = None):
        self.events: "queue.Queue[Event]" = out if out is not None else queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._known: dict[tuple[str, int], Interface] = {}

    def subscribe(self) -> "queue.Queue[Event]":
        self._thread = threading.Thread(
            target=self._loop, name=type(self).__name__.lower(), daemon=True)
        self._thread.start()
        return self.events

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)

    def _emit_current(self, links: list[netlink.LinkInfo], netns: str = "") -> None:
        """Diff a full link list against known state, emitting add/remove."""
        current = {}
        for link in links:
            if not link.up:
                continue
            iface = Interface(link.index, link.name, link.mac, netns)
            current[(netns, link.index)] = iface
        for key, iface in current.items():
            if key not in self._known:
                self._known[key] = iface
                self.events.put(Event(EventType.ADDED, iface))
        for key in [k for k in self._known if k[0] == netns]:
            if key not in current:
                iface = self._known.pop(key)
                self.events.put(Event(EventType.REMOVED, iface))

    def _loop(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError


class Poller(_InformerBase):
    """Periodic full link dumps, diffed (LISTEN_INTERFACES=poll)."""

    def __init__(self, period_s: float = 10.0, **kw):
        super().__init__(**kw)
        self._period = period_s

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._emit_current(netlink.dump_links())
            except OSError as exc:
                log.warning("link dump failed: %s", exc)
            self._stop.wait(self._period)


class Watcher(_InformerBase):
    """netlink link-event subscription with an initial dump; namespaces
    appearing under /var/run/netns are ENTERED (setns): their links are
    enumerated and a per-namespace netlink subscription keeps following them
    (LISTEN_INTERFACES=watch; reference pkg/ifaces/watcher.go:57-271).
    """

    def __init__(self, netns_dir: str = NETNS_DIR, **kw):
        super().__init__(**kw)
        self._netns_dir = netns_dir
        # netns name -> its subscription socket (None when entry failed —
        # e.g. no CAP_SYS_ADMIN — and only the namespace's existence is known)
        self._netns_socks: dict[str, Optional[object]] = {}

    def _loop(self) -> None:
        try:
            sock = netlink.subscribe_links()
        except OSError as exc:
            log.warning("netlink subscription failed (%s); falling back to "
                        "polling", exc)
            self._poll_fallback()
            return
        try:
            self._emit_current(netlink.dump_links())
            self._check_netns()
            while not self._stop.is_set():
                for link in netlink.read_link_events(sock):
                    self._handle_event(link, "")
                for name, ns_sock in list(self._netns_socks.items()):
                    if ns_sock is None:
                        continue
                    try:
                        for link in netlink.read_link_events(ns_sock):
                            self._handle_event(link, name)
                    except OSError:
                        pass
                self._check_netns()
        finally:
            sock.close()
            for ns_sock in self._netns_socks.values():
                if ns_sock is not None:
                    ns_sock.close()

    def _handle_event(self, link: netlink.LinkInfo, netns: str) -> None:
        key = (netns, link.index)
        if link.change_type == netlink.RTM_DELLINK or not link.up:
            iface = self._known.pop(key, None)
            if iface is not None:
                self.events.put(Event(EventType.REMOVED, iface))
        else:
            iface = Interface(link.index, link.name, link.mac, netns)
            if key not in self._known:
                self._known[key] = iface
                self.events.put(Event(EventType.ADDED, iface))

    def _check_netns(self) -> None:
        """Follow /var/run/netns: enter each new namespace to enumerate its
        links and subscribe to its events; on namespace removal, emit REMOVED
        for its interfaces and drop the subscription."""
        from netobserv_tpu.ifaces import netns as nsmod

        try:
            names = set(os.listdir(self._netns_dir))
        except OSError:
            names = set()
        for name in names - set(self._netns_socks):
            try:
                ns_sock = nsmod.subscribe_links_in(name, self._netns_dir)
            except OSError as exc:
                import errno as _errno

                if exc.errno in (_errno.EPERM, _errno.EACCES):
                    # cannot enter (no CAP_SYS_ADMIN): permanent — remember
                    # the namespace so this doesn't retry/log every cycle
                    log.warning("cannot enter netns %s (%s); observing only",
                                name, exc)
                    self._netns_socks[name] = None
                else:
                    # transient (fd pressure, netns racing away): leave the
                    # name unknown so the next cycle retries
                    log.debug("netns %s subscribe failed (%s); will retry",
                              name, exc)
                continue
            try:
                links = nsmod.links_in(name, self._netns_dir)
            except OSError as exc:
                # transient (namespace raced away / netlink error): drop the
                # socket and leave the name unknown so the next cycle retries
                log.debug("netns %s link dump failed (%s); will retry",
                          name, exc)
                ns_sock.close()
                continue
            # drain events with a short poll so the watcher loop's cadence
            # stays driven by the default-namespace socket
            ns_sock.settimeout(0.01)
            self._emit_current(links, netns=name)
            log.info("watching network namespace %s (%d links)", name,
                     len(links))
            self._netns_socks[name] = ns_sock
        for name in set(self._netns_socks) - names:
            ns_sock = self._netns_socks.pop(name)
            if ns_sock is not None:
                ns_sock.close()
            self._emit_current([], netns=name)
            log.info("network namespace %s removed", name)

    def _poll_fallback(self) -> None:
        while not self._stop.is_set():
            try:
                self._emit_current(netlink.dump_links())
            except OSError:
                pass
            self._stop.wait(10.0)
