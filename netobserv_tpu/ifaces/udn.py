"""UDN (user-defined network) mapping for interfaces.

Reference analog: the ENABLE_UDN_MAPPING path, which resolves OVN/OVS
interface metadata to a user-defined-network name attached to flow records.
Without an OVS database in scope, the mapping source here is either:
- a JSON file (`UDN_MAPPING_FILE`, {"<iface-name>": "<udn>", ...}), or
- the OVS external-ids via `ovs-vsctl`, when the binary exists.

The result feeds `Record.udn` / the dup-list UDN column through the same
namer-style hook the interface Registerer uses.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import subprocess
import threading
import time

log = logging.getLogger("netobserv_tpu.ifaces.udn")

MAPPING_FILE_ENV = "UDN_MAPPING_FILE"
_CACHE_TTL_S = 30.0


class UdnMapper:
    def __init__(self, mapping_file: str = ""):
        self._file = mapping_file or os.environ.get(MAPPING_FILE_ENV, "")
        self._lock = threading.Lock()
        self._cache: dict[str, str] = {}
        self._loaded_at = 0.0
        self._refreshing = False
        self._refresh_sync()  # initial load before serving

    def _refresh_sync(self) -> None:
        self._do_refresh()
        with self._lock:
            self._loaded_at = time.monotonic()
            self._refreshing = False

    def _maybe_refresh_async(self) -> None:
        """Kick a background refresh when stale; callers keep the stale cache
        meanwhile — the ovs-vsctl probe (up to 5s) must never stall the
        eviction path."""
        with self._lock:
            if (time.monotonic() - self._loaded_at < _CACHE_TTL_S
                    or self._refreshing):
                return
            self._refreshing = True
        threading.Thread(target=self._refresh_sync, name="udn-refresh",
                         daemon=True).start()

    def _do_refresh(self) -> None:
        if self._file:
            try:
                with open(self._file) as fh:
                    data = json.load(fh)
                if isinstance(data, dict):
                    cache = {str(k): str(v) for k, v in data.items()}
                    with self._lock:
                        self._cache = cache
            except (OSError, ValueError) as exc:
                log.warning("UDN mapping file unreadable: %s", exc)
            return
        if shutil.which("ovs-vsctl"):
            try:
                out = subprocess.run(
                    ["ovs-vsctl", "--format=json", "--columns=name,external_ids",
                     "list", "Interface"],
                    capture_output=True, text=True, timeout=5, check=True)
                data = json.loads(out.stdout)
                cache = {}
                for row in data.get("data", []):
                    name = row[0]
                    ids = dict(row[1][1]) if isinstance(row[1], list) else {}
                    udn = ids.get("k8s.ovn.org/udn", ids.get("udn", ""))
                    if udn:
                        cache[name] = udn
                with self._lock:
                    self._cache = cache
            except (OSError, ValueError, subprocess.SubprocessError) as exc:
                log.debug("ovs-vsctl UDN probe failed: %s", exc)

    def udn_for(self, if_name: str) -> str:
        self._maybe_refresh_async()
        with self._lock:
            return self._cache.get(if_name, "")
