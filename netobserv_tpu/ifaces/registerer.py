"""Registerer: caches (ifindex, MAC) -> name for interface naming.

Reference analog: `pkg/ifaces/registerer.go` — a decorator over an informer
that remembers every interface it has seen, so flow records can be named even
after the interface disappears. MAC is part of the key because ifindexes are
reused across namespaces; when several names share an index, a matching MAC
wins, with an optional preferred-name tie-break for MAC-prefix collisions
(PREFERRED_INTERFACE_FOR_MAC_PREFIX).
"""

from __future__ import annotations

import threading

from netobserv_tpu.ifaces.informers import Event, EventType, Interface


class Registerer:
    def __init__(self, preferred_for_mac_prefix: str = ""):
        self._lock = threading.Lock()
        self._by_index: dict[int, list[Interface]] = {}
        # comma-separated "mac_prefix=name" pairs with colon-delimited MACs,
        # e.g. "0a:58=eth0,02:42=docker" (reference env-var contract)
        self._prefs: list[tuple[bytes, str]] = []
        for pair in preferred_for_mac_prefix.split(","):
            pair = pair.strip()
            if not pair or "=" not in pair:
                continue
            prefix_str, name = pair.split("=", 1)
            try:
                prefix = bytes.fromhex(prefix_str.replace(":", ""))
            except ValueError:
                continue  # malformed prefix: ignore the pair, don't crash
            if prefix and name:
                self._prefs.append((prefix, name))

    def observe(self, event: Event) -> None:
        iface = event.interface
        with self._lock:
            entries = self._by_index.setdefault(iface.index, [])
            if event.type == EventType.ADDED:
                if all(e.mac != iface.mac or e.name != iface.name
                       for e in entries):
                    entries.append(iface)
            # REMOVED keeps the cache entry: records may still reference it

    def name_for(self, if_index: int, mac: bytes) -> str:
        """The interfaceNamer hook (`model.set_interface_namer` target)."""
        with self._lock:
            entries = self._by_index.get(if_index, [])
            if not entries:
                return str(if_index)
            matches = [e for e in entries if e.mac == mac]
            if not matches:
                return entries[-1].name
            if len(matches) > 1:
                for prefix, pref_name in self._prefs:
                    if not mac.startswith(prefix):
                        continue
                    for e in matches:
                        if e.name.startswith(pref_name):
                            return e.name
            return matches[-1].name
