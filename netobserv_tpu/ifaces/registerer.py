"""Registerer: caches (ifindex, MAC) -> name for interface naming.

Reference analog: `pkg/ifaces/registerer.go` — a decorator over an informer
that remembers every interface it has seen, so flow records can be named even
after the interface disappears. MAC is part of the key because ifindexes are
reused across namespaces; when several names share an index, a matching MAC
wins, with an optional preferred-name tie-break for MAC-prefix collisions
(PREFERRED_INTERFACE_FOR_MAC_PREFIX).
"""

from __future__ import annotations

import threading

from netobserv_tpu.ifaces.informers import Event, EventType, Interface


class Registerer:
    def __init__(self, preferred_for_mac_prefix: str = ""):
        self._lock = threading.Lock()
        self._by_index: dict[int, list[Interface]] = {}
        # "0a58:ovn-k8s-mp" style "prefix:name" preference
        self._pref_prefix = b""
        self._pref_name = ""
        if preferred_for_mac_prefix and ":" in preferred_for_mac_prefix:
            prefix, name = preferred_for_mac_prefix.split(":", 1)
            self._pref_prefix = bytes.fromhex(prefix)
            self._pref_name = name

    def observe(self, event: Event) -> None:
        iface = event.interface
        with self._lock:
            entries = self._by_index.setdefault(iface.index, [])
            if event.type == EventType.ADDED:
                if all(e.mac != iface.mac or e.name != iface.name
                       for e in entries):
                    entries.append(iface)
            # REMOVED keeps the cache entry: records may still reference it

    def name_for(self, if_index: int, mac: bytes) -> str:
        """The interfaceNamer hook (`model.set_interface_namer` target)."""
        with self._lock:
            entries = self._by_index.get(if_index, [])
            if not entries:
                return str(if_index)
            matches = [e for e in entries if e.mac == mac]
            if not matches:
                return entries[-1].name
            if (len(matches) > 1 and self._pref_prefix
                    and mac.startswith(self._pref_prefix)):
                for e in matches:
                    if e.name.startswith(self._pref_name):
                        return e.name
            return matches[-1].name
