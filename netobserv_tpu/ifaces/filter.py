"""Interface selection filters.

Reference analog: `pkg/ifaces/filter.go` — either name-based allow/exclude
lists (exact or /regex/) or selection by interface IP CIDR membership.
"""

from __future__ import annotations

import ipaddress
import re
from typing import Optional

from netobserv_tpu.ifaces import netlink
from netobserv_tpu.ifaces.informers import Interface


class InterfaceFilter:
    def __init__(self, allowed: Optional[list[str]] = None,
                 excluded: Optional[list[str]] = None,
                 ip_cidrs: Optional[list[str]] = None):
        if ip_cidrs and (allowed or excluded):
            raise ValueError(
                "INTERFACE_IPS is mutually exclusive with INTERFACES/"
                "EXCLUDE_INTERFACES")
        self._allowed = [self._compile(p) for p in (allowed or [])]
        self._excluded = [self._compile(p) for p in (excluded or [])]
        self._cidrs = [ipaddress.ip_network(c, strict=False)
                       for c in (ip_cidrs or [])]

    @staticmethod
    def _compile(pattern: str):
        pattern = pattern.strip()
        if len(pattern) > 1 and pattern.startswith("/") and pattern.endswith("/"):
            return re.compile(pattern[1:-1])
        return pattern

    @staticmethod
    def _matches(pattern, name: str) -> bool:
        if isinstance(pattern, re.Pattern):
            return bool(pattern.search(name))
        return pattern == name

    def allowed(self, iface: Interface) -> bool:
        if self._cidrs:
            return self._ip_allowed(iface)
        for pattern in self._excluded:
            if self._matches(pattern, iface.name):
                return False
        if not self._allowed:
            return True
        return any(self._matches(p, iface.name) for p in self._allowed)

    def _ip_allowed(self, iface: Interface) -> bool:
        try:
            addrs = netlink.dump_addrs()
        except OSError:
            return False
        for idx, raw in addrs:
            if idx != iface.index or len(raw) not in (4, 16):
                continue
            ip = ipaddress.ip_address(raw)
            if any(ip in net for net in self._cidrs):
                return True
        return False
