"""Minimal rtnetlink client: link dumps and link-event subscription.

Speaks NETLINK_ROUTE directly over an AF_NETLINK socket — the pure-python
replacement for the reference's vishvananda/netlink dependency.
"""

from __future__ import annotations

import os
import socket
import struct
from dataclasses import dataclass
from typing import Iterator, Optional

NETLINK_ROUTE = 0
RTMGRP_LINK = 1
RTM_NEWLINK = 16
RTM_DELLINK = 17
RTM_GETLINK = 18
RTM_NEWADDR = 20
RTM_GETADDR = 22
NLM_F_REQUEST = 1
NLM_F_DUMP = 0x300
NLMSG_DONE = 3
NLMSG_ERROR = 2

IFLA_ADDRESS = 1
IFLA_IFNAME = 3
IFA_ADDRESS = 1
IFF_UP = 0x1


@dataclass
class LinkInfo:
    index: int
    name: str
    mac: bytes
    flags: int
    change_type: int = RTM_NEWLINK  # NEWLINK or DELLINK for events

    @property
    def up(self) -> bool:
        return bool(self.flags & IFF_UP)


def _align4(n: int) -> int:
    return (n + 3) & ~3


def _parse_attrs(data: bytes) -> dict[int, bytes]:
    attrs = {}
    off = 0
    while off + 4 <= len(data):
        alen, atype = struct.unpack_from("=HH", data, off)
        if alen < 4:
            break
        attrs[atype] = data[off + 4:off + alen]
        off += _align4(alen)
    return attrs


def _parse_link_msg(msg_type: int, payload: bytes) -> Optional[LinkInfo]:
    if len(payload) < 16:
        return None
    _family, _pad, _dev_type, index, flags, _change = struct.unpack_from(
        "=BBHiII", payload, 0)
    attrs = _parse_attrs(payload[16:])
    name = attrs.get(IFLA_IFNAME, b"").split(b"\x00")[0].decode(
        "ascii", "replace")
    mac = attrs.get(IFLA_ADDRESS, b"\x00" * 6)[:6].ljust(6, b"\x00")
    return LinkInfo(index=index, name=name, mac=mac, flags=flags,
                    change_type=msg_type)


def _recv_messages(sock: socket.socket) -> Iterator[tuple[int, bytes]]:
    data = sock.recv(65536)
    off = 0
    while off + 16 <= len(data):
        mlen, mtype, _flags, _seq, _pid = struct.unpack_from("=IHHII", data, off)
        if mlen < 16:
            break
        yield mtype, data[off + 16:off + mlen]
        off += _align4(mlen)


def dump_links() -> list[LinkInfo]:
    """One RTM_GETLINK dump: all interfaces in the current netns."""
    sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE)
    try:
        sock.bind((0, 0))
        req = struct.pack("=IHHIIBBHiII", 16 + 16, RTM_GETLINK,
                          NLM_F_REQUEST | NLM_F_DUMP, 1, 0,
                          socket.AF_UNSPEC, 0, 0, 0, 0, 0)
        sock.send(req)
        links = []
        done = False
        while not done:
            for mtype, payload in _recv_messages(sock):
                if mtype == NLMSG_DONE:
                    done = True
                    break
                if mtype == NLMSG_ERROR:
                    raise OSError("netlink error on RTM_GETLINK dump")
                if mtype == RTM_NEWLINK:
                    link = _parse_link_msg(mtype, payload)
                    if link is not None:
                        links.append(link)
        return links
    finally:
        sock.close()


def dump_addrs() -> list[tuple[int, bytes]]:
    """RTM_GETADDR dump: (ifindex, raw address bytes) pairs (v4 and v6)."""
    sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE)
    try:
        sock.bind((0, 0))
        req = struct.pack("=IHHIIBBBBi", 16 + 8, RTM_GETADDR,
                          NLM_F_REQUEST | NLM_F_DUMP, 1, 0,
                          socket.AF_UNSPEC, 0, 0, 0, 0)
        sock.send(req)
        out = []
        done = False
        while not done:
            for mtype, payload in _recv_messages(sock):
                if mtype == NLMSG_DONE:
                    done = True
                    break
                if mtype == NLMSG_ERROR:
                    raise OSError("netlink error on RTM_GETADDR dump")
                if mtype == RTM_NEWADDR and len(payload) >= 8:
                    _family, _plen, _flags, _scope, index = struct.unpack_from(
                        "=BBBBi", payload, 0)
                    attrs = _parse_attrs(payload[8:])
                    addr = attrs.get(IFA_ADDRESS)
                    if addr:
                        out.append((index, addr))
        return out
    finally:
        sock.close()


def subscribe_links() -> socket.socket:
    """Socket subscribed to link add/remove events (RTMGRP_LINK)."""
    sock = socket.socket(socket.AF_NETLINK, socket.SOCK_RAW, NETLINK_ROUTE)
    # port id 0: the kernel assigns a unique id, so several subscription
    # sockets (one per watched namespace) can coexist in one process
    sock.bind((0, RTMGRP_LINK))
    sock.settimeout(0.5)
    return sock


def read_link_events(sock: socket.socket) -> list[LinkInfo]:
    """Drain pending link events from a subscribed socket (may be empty)."""
    try:
        events = []
        for mtype, payload in _recv_messages(sock):
            if mtype in (RTM_NEWLINK, RTM_DELLINK):
                link = _parse_link_msg(mtype, payload)
                if link is not None:
                    events.append(link)
        return events
    except socket.timeout:
        return []
