"""Sketch warehouse: durable, queryable history of closed sketch windows.

The query plane answers "what is happening"; this package answers "what
happened" — over any archived time range, with honest Count-Min error
bars, across restarts. Three pieces (docs/architecture.md "Sketch
warehouse"):

- `segment.py`  — the on-disk snapshot format (TABLE_SPEC tensors through
  the SHARED per-tensor codec, endian-independent, golden-pinned);
- `store.py`    — append-only directory with hierarchical RRD-style
  retention (raw windows compact into super-windows; disk stays bounded,
  old history survives coarser);
- `query.py`    — the warmed device merge ladder behind
  ``/query/range`` / ``/federation/range`` and the compactor.

`SketchArchive` below is the plane's one facade: the tpu-sketch exporter
(and the federation aggregator, for cluster-wide history) writes each
closed window through it on the timer thread — off the exporter lock,
behind the ``sketch.archive_write`` fault point — and mounts its
`route_payload` on the query surface. ``ARCHIVE_DIR`` unset means NO
archive object exists anywhere: one is-None check on the publish path,
bit-identical to the pre-archive exporter (the established zero-cost
bar).
"""

from __future__ import annotations

import logging
from typing import Optional

from netobserv_tpu.archive import segment as aseg
from netobserv_tpu.archive.query import ArchiveQueryEngine
from netobserv_tpu.archive.store import ArchiveStore

log = logging.getLogger("netobserv_tpu.archive")

__all__ = ["ArchiveQueryEngine", "ArchiveStore", "SketchArchive",
           "TenantArchiveSet", "maybe_archive", "tenant_archives"]


class SketchArchive:
    """Writer + compactor + range-query surface over one archive dir.

    `warm=True` (the production entry passes it; direct construction
    defaults off — the superbatch-ladder rule) compiles the merge ladder
    on a background daemon thread so the first compaction or range query
    never stalls the timer/HTTP thread on a cold compile."""

    def __init__(self, store: ArchiveStore, sketch_cfg, metrics=None,
                 agent_id: str = "", ladder_max: int = 16,
                 report_kwargs: Optional[dict] = None,
                 warm: bool = False):
        self._store = store
        self._agent_id = agent_id
        self.engine = ArchiveQueryEngine(store, sketch_cfg,
                                         metrics=metrics,
                                         ladder_max=ladder_max,
                                         report_kwargs=report_kwargs)
        if warm:
            import threading

            def _warm() -> None:
                try:
                    self.engine.warm()
                except Exception as exc:  # best-effort, never fatal
                    log.warning("archive merge-ladder warm failed "
                                "(entries compile on first use): %s", exc)

            threading.Thread(target=_warm, name="archive-ladder-warm",
                             daemon=True).start()

    def write_window(self, host_tables: dict, window: int,
                     ts_ms: int) -> None:
        """Land one closed window's table snapshot as a raw (level-0)
        segment, then run retention: every due compaction group merges
        through the ladder executables and the top level ages out. Timer
        thread only; callers hold HOST copies (never live donated
        buffers)."""
        seg_bytes = aseg.encode_segment(
            host_tables, agent_id=self._agent_id, level=0,
            window_from=int(window), window_to=int(window), n_windows=1,
            ts_ms=int(ts_ms), dims=self.engine.dims)
        with self.engine.lock:
            self._store.append(seg_bytes, 0, int(window), int(window))
        # bounded: each pass strictly shrinks some level, so the loop
        # terminates; steady state runs at most one compaction per window
        while self.engine.compact_once():
            pass
        with self.engine.lock:
            self._store.enforce_top_level_retention()

    def route_payload(self, params: dict,
                      view: Optional[str] = None) -> tuple[int, dict]:
        return self.engine.route_payload(params, view)

    def stats(self) -> dict:
        return self.engine.stats()


class TenantArchiveSet:
    """SKETCH_TENANTS x ARCHIVE_DIR: one `SketchArchive` per tenant, each
    over its own ``<archive_dir>/tenant-<t>`` store — segments, retention
    ladders and range answers stay tenant-local (planes are independent by
    construction; merging tenant segments would invent a cross-tenant view
    the live plane doesn't have). The exporter writes through
    `write_tenant_window`; `/query/range` resolves ``?tenant=`` here with
    the same 400/404 contract as the snapshot routes."""

    def __init__(self, archives: list):
        if not archives:
            raise ValueError("TenantArchiveSet needs >= 1 tenant archive")
        self._archives = archives

    @property
    def n_tenants(self) -> int:
        return len(self._archives)

    def write_tenant_window(self, host_tables: dict, window: int,
                            ts_ms: int, tenant: int) -> None:
        self._archives[int(tenant)].write_window(host_tables, window, ts_ms)

    def route_payload(self, params: dict,
                      view: Optional[str] = None) -> tuple[int, dict]:
        if params.get("tenant") is None:
            return 400, {
                "error": "tenant is required (SKETCH_TENANTS mode)",
                "tenants": len(self._archives)}
        try:
            tid = int(params["tenant"])
        except ValueError:
            return 400, {"error": f"bad tenant {params['tenant']!r}",
                         "tenants": len(self._archives)}
        if not 0 <= tid < len(self._archives):
            return 404, {"error": f"unknown tenant {tid}",
                         "tenants": len(self._archives)}
        return self._archives[tid].route_payload(params, view)

    def stats(self) -> dict:
        per = [a.stats() for a in self._archives]
        return {
            "tenants": len(per),
            "segments": sum(p.get("segments", 0) for p in per),
            "disk_bytes": sum(p.get("disk_bytes", 0) for p in per),
            "per_tenant": {str(t): p for t, p in enumerate(per)},
        }


def tenant_archives(cfg, sketch_cfg, n_tenants: int, metrics=None,
                    agent_id: str = "") -> Optional["TenantArchiveSet"]:
    """`maybe_archive`'s tenant-mode twin: one per-tenant store under
    ``<archive_dir>/tenant-<t>``, same retention knobs and threshold
    wiring. Ladders warm lazily (warm=False): N background compile
    threads per agent start would be the superbatch-ladder anti-pattern —
    the per-tenant engines share compiled-shape caches via jit anyway."""
    if not getattr(cfg, "archive_dir", ""):
        return None
    import os

    report_kwargs = dict(
        scan_fanout_threshold=cfg.sketch_scan_fanout,
        ddos_z_threshold=cfg.sketch_ddos_z,
        synflood_min=cfg.sketch_synflood_min,
        synflood_ratio=cfg.sketch_synflood_ratio,
        drop_z_threshold=cfg.sketch_drop_z,
        asym_min_bytes=cfg.sketch_asym_min_bytes,
        asym_ratio=cfg.sketch_asym_ratio,
        churn_ascent=cfg.sketch_churn_ascent,
        churn_min_bytes=cfg.sketch_churn_min_bytes)
    archives = []
    for t in range(int(n_tenants)):
        store = ArchiveStore(os.path.join(cfg.archive_dir, f"tenant-{t}"),
                             raw_windows=cfg.archive_raw_windows,
                             compact_group=cfg.archive_compact_group,
                             max_levels=cfg.archive_max_levels,
                             metrics=metrics)
        archives.append(SketchArchive(
            store, sketch_cfg, metrics=metrics,
            agent_id=agent_id or cfg.federation_agent_id,
            ladder_max=cfg.archive_merge_ladder_max, warm=False,
            report_kwargs=report_kwargs))
    return TenantArchiveSet(archives)


def maybe_archive(cfg, sketch_cfg, metrics=None,
                  agent_id: str = "") -> Optional[SketchArchive]:
    """The ARCHIVE_DIR switch: None (unset) keeps the publish path
    bit-identical to the pre-archive exporter — no store, no engine, one
    is-None check at the call site. The report thresholds wire from the
    SAME AgentConfig fields the live renderer uses (one threshold
    truth)."""
    if not getattr(cfg, "archive_dir", ""):
        return None
    store = ArchiveStore(cfg.archive_dir,
                         raw_windows=cfg.archive_raw_windows,
                         compact_group=cfg.archive_compact_group,
                         max_levels=cfg.archive_max_levels,
                         metrics=metrics)
    return SketchArchive(
        store, sketch_cfg, metrics=metrics,
        agent_id=agent_id or cfg.federation_agent_id,
        ladder_max=cfg.archive_merge_ladder_max, warm=True,
        report_kwargs=dict(
            scan_fanout_threshold=cfg.sketch_scan_fanout,
            ddos_z_threshold=cfg.sketch_ddos_z,
            synflood_min=cfg.sketch_synflood_min,
            synflood_ratio=cfg.sketch_synflood_ratio,
            drop_z_threshold=cfg.sketch_drop_z,
            asym_min_bytes=cfg.sketch_asym_min_bytes,
            asym_ratio=cfg.sketch_asym_ratio,
            churn_ascent=cfg.sketch_churn_ascent,
            churn_min_bytes=cfg.sketch_churn_min_bytes))
