"""Sketch warehouse: durable, queryable history of closed sketch windows.

The query plane answers "what is happening"; this package answers "what
happened" — over any archived time range, with honest Count-Min error
bars, across restarts. Three pieces (docs/architecture.md "Sketch
warehouse"):

- `segment.py`  — the on-disk snapshot format (TABLE_SPEC tensors through
  the SHARED per-tensor codec, endian-independent, golden-pinned);
- `store.py`    — append-only directory with hierarchical RRD-style
  retention (raw windows compact into super-windows; disk stays bounded,
  old history survives coarser);
- `query.py`    — the warmed device merge ladder behind
  ``/query/range`` / ``/federation/range`` and the compactor.

`SketchArchive` below is the plane's one facade: the tpu-sketch exporter
(and the federation aggregator, for cluster-wide history) writes each
closed window through it on the timer thread — off the exporter lock,
behind the ``sketch.archive_write`` fault point — and mounts its
`route_payload` on the query surface. ``ARCHIVE_DIR`` unset means NO
archive object exists anywhere: one is-None check on the publish path,
bit-identical to the pre-archive exporter (the established zero-cost
bar).
"""

from __future__ import annotations

import logging
from typing import Optional

from netobserv_tpu.archive import segment as aseg
from netobserv_tpu.archive.query import ArchiveQueryEngine
from netobserv_tpu.archive.store import ArchiveStore

log = logging.getLogger("netobserv_tpu.archive")

__all__ = ["ArchiveQueryEngine", "ArchiveStore", "SketchArchive",
           "maybe_archive"]


class SketchArchive:
    """Writer + compactor + range-query surface over one archive dir.

    `warm=True` (the production entry passes it; direct construction
    defaults off — the superbatch-ladder rule) compiles the merge ladder
    on a background daemon thread so the first compaction or range query
    never stalls the timer/HTTP thread on a cold compile."""

    def __init__(self, store: ArchiveStore, sketch_cfg, metrics=None,
                 agent_id: str = "", ladder_max: int = 16,
                 report_kwargs: Optional[dict] = None,
                 warm: bool = False):
        self._store = store
        self._agent_id = agent_id
        self.engine = ArchiveQueryEngine(store, sketch_cfg,
                                         metrics=metrics,
                                         ladder_max=ladder_max,
                                         report_kwargs=report_kwargs)
        if warm:
            import threading

            def _warm() -> None:
                try:
                    self.engine.warm()
                except Exception as exc:  # best-effort, never fatal
                    log.warning("archive merge-ladder warm failed "
                                "(entries compile on first use): %s", exc)

            threading.Thread(target=_warm, name="archive-ladder-warm",
                             daemon=True).start()

    def write_window(self, host_tables: dict, window: int,
                     ts_ms: int) -> None:
        """Land one closed window's table snapshot as a raw (level-0)
        segment, then run retention: every due compaction group merges
        through the ladder executables and the top level ages out. Timer
        thread only; callers hold HOST copies (never live donated
        buffers)."""
        seg_bytes = aseg.encode_segment(
            host_tables, agent_id=self._agent_id, level=0,
            window_from=int(window), window_to=int(window), n_windows=1,
            ts_ms=int(ts_ms), dims=self.engine.dims)
        with self.engine.lock:
            self._store.append(seg_bytes, 0, int(window), int(window))
        # bounded: each pass strictly shrinks some level, so the loop
        # terminates; steady state runs at most one compaction per window
        while self.engine.compact_once():
            pass
        with self.engine.lock:
            self._store.enforce_top_level_retention()

    def route_payload(self, params: dict,
                      view: Optional[str] = None) -> tuple[int, dict]:
        return self.engine.route_payload(params, view)

    def stats(self) -> dict:
        return self.engine.stats()


def maybe_archive(cfg, sketch_cfg, metrics=None,
                  agent_id: str = "") -> Optional[SketchArchive]:
    """The ARCHIVE_DIR switch: None (unset) keeps the publish path
    bit-identical to the pre-archive exporter — no store, no engine, one
    is-None check at the call site. The report thresholds wire from the
    SAME AgentConfig fields the live renderer uses (one threshold
    truth)."""
    if not getattr(cfg, "archive_dir", ""):
        return None
    store = ArchiveStore(cfg.archive_dir,
                         raw_windows=cfg.archive_raw_windows,
                         compact_group=cfg.archive_compact_group,
                         max_levels=cfg.archive_max_levels,
                         metrics=metrics)
    return SketchArchive(
        store, sketch_cfg, metrics=metrics,
        agent_id=agent_id or cfg.federation_agent_id,
        ladder_max=cfg.archive_merge_ladder_max, warm=True,
        report_kwargs=dict(
            scan_fanout_threshold=cfg.sketch_scan_fanout,
            ddos_z_threshold=cfg.sketch_ddos_z,
            synflood_min=cfg.sketch_synflood_min,
            synflood_ratio=cfg.sketch_synflood_ratio,
            drop_z_threshold=cfg.sketch_drop_z,
            asym_min_bytes=cfg.sketch_asym_min_bytes,
            asym_ratio=cfg.sketch_asym_ratio,
            churn_ascent=cfg.sketch_churn_ascent,
            churn_min_bytes=cfg.sketch_churn_min_bytes))
