"""On-disk segment store: append-only window archive with hierarchical
(RRD-style) retention.

Layout under ``ARCHIVE_DIR``: one file per segment, named
``seg-L<level>-<window_from>-<window_to>.seg`` (zero-padded ids so a
lexical sort is a window sort), plus an atomically-replaced
``MANIFEST.json`` (utils/atomicio) for operators. The DIRECTORY SCAN is
the source of truth on open — the manifest is a cache: a crash between a
segment rename and the manifest write loses nothing, and a crash between
a compacted segment landing and its inputs' deletion is healed by the
overlap rule below.

Retention is per level: level 0 keeps the last `raw_windows` raw
segments; once a level holds `cap + group` segments its OLDEST `group`
are handed to the compactor (`pending_compaction`), whose device-merged
super-window replaces them one level up (`replace`). The top level
(`max_levels`) deletes its oldest beyond the cap instead — total disk is
bounded by (max_levels + 1) * (cap + group - 1) segments while
arbitrarily old history survives at coarser resolution.

Crash-recovery invariant: every archived window is covered by EXACTLY ONE
segment. `replace` writes the merged segment BEFORE deleting its inputs,
so the only reachable inconsistency is an overlap (merged + leftover
inputs), which the open-time scan heals by keeping the HIGHEST level and
deleting the shadowed files — never the reverse (deleting inputs first
could lose windows).

Host-side only (numpy + os): the store never touches a device; the
compactor's MERGE runs in `archive/query.py`'s ladder executables.
"""

from __future__ import annotations

import logging
import os
import re
from typing import NamedTuple, Optional

from netobserv_tpu.utils.atomicio import (
    write_bytes_atomic, write_json_atomic,
)

log = logging.getLogger("netobserv_tpu.archive.store")

_SEG_RE = re.compile(r"^seg-L(\d+)-(\d{10})-(\d{10})\.seg$")
MANIFEST = "MANIFEST.json"


class SegInfo(NamedTuple):
    """One on-disk segment's index entry (header fields ride the file)."""

    level: int
    window_from: int
    window_to: int
    path: str
    nbytes: int

    @property
    def name(self) -> str:
        return os.path.basename(self.path)


def segment_filename(level: int, window_from: int, window_to: int) -> str:
    return f"seg-L{int(level)}-{int(window_from):010d}-" \
           f"{int(window_to):010d}.seg"


class ArchiveStore:
    """Segment index + retention policy over one archive directory.

    NOT thread-safe by itself: the owning plane (exporter timer thread or
    aggregator publish path) serializes every mutation; readers go through
    the owner's lock (`archive/query.py`)."""

    def __init__(self, directory: str, raw_windows: int = 64,
                 compact_group: int = 8, max_levels: int = 3,
                 metrics=None):
        if compact_group < 2:
            raise ValueError("compact_group must be >= 2")
        if raw_windows < compact_group:
            raise ValueError("raw_windows must be >= compact_group")
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.raw_windows = int(raw_windows)
        self.compact_group = int(compact_group)
        self.max_levels = int(max_levels)
        self._metrics = metrics
        #: sorted by (window_from, level) — after the overlap heal, window
        #: ranges are disjoint, so this is also time order
        self._segments: list[SegInfo] = []
        self._scan()
        self._write_manifest()

    # --- open-time recovery ---------------------------------------------
    def _scan(self) -> None:
        found: list[SegInfo] = []
        for name in sorted(os.listdir(self._dir)):
            m = _SEG_RE.match(name)
            if not m:
                continue
            path = os.path.join(self._dir, name)
            try:
                nbytes = os.path.getsize(path)
            except OSError:
                continue
            found.append(SegInfo(int(m.group(1)), int(m.group(2)),
                                 int(m.group(3)), path, nbytes))
        # overlap heal: a crash mid-replace leaves a compacted segment AND
        # some of its (lower-level) inputs — keep the highest level, drop
        # the shadowed files (the merged segment already contains them)
        found.sort(key=lambda s: (-s.level, s.window_from))
        kept: list[SegInfo] = []
        for seg in found:
            shadowed = any(k.window_from <= seg.window_from
                           and seg.window_to <= k.window_to
                           and k.level > seg.level for k in kept)
            if shadowed:
                log.warning("archive scan: deleting %s (shadowed by a "
                            "compacted super-window — crash mid-replace)",
                            seg.name)
                self._unlink(seg)
                continue
            kept.append(seg)
        kept.sort(key=lambda s: (s.window_from, s.level))
        self._segments = kept

    def _unlink(self, seg: SegInfo) -> None:
        try:
            os.remove(seg.path)
        except OSError as exc:
            log.warning("archive: could not remove %s: %s", seg.name, exc)

    def _write_manifest(self) -> None:
        write_json_atomic(os.path.join(self._dir, MANIFEST), {
            "format": 1,
            "raw_windows": self.raw_windows,
            "compact_group": self.compact_group,
            "max_levels": self.max_levels,
            "segments": [{"file": s.name, "level": s.level,
                          "window_from": s.window_from,
                          "window_to": s.window_to, "bytes": s.nbytes}
                         for s in self._segments],
        })

    # --- mutations -------------------------------------------------------
    def append(self, seg_bytes: bytes, level: int, window_from: int,
               window_to: int) -> SegInfo:
        """Land one encoded segment durably (temp + fsync + rename + a
        directory fsync — utils/atomicio, the same discipline as every
        sidecar), THEN retire every indexed segment whose window range
        the new one intersects, then the manifest.

        The retire sweep is what keeps "every window covered by exactly
        one segment" true under BOTH writers: a compaction's merged
        super-window consumes its input group (the merged segment is
        durable before any input dies — the crash order the open-time
        heal assumes), and an agent whose window counter restarted at 0
        (no SKETCH_CHECKPOINT_DIR) overwrites the stale incarnation's
        history window-id by window-id instead of double-indexing it —
        newest write wins; a stale super-window intersecting the new id
        is forfeit (a reset counter makes its old ids ambiguous anyway)."""
        name = segment_filename(level, window_from, window_to)
        path = os.path.join(self._dir, name)
        stale = [s for s in self._segments
                 if s.window_to >= window_from
                 and s.window_from <= window_to]
        write_bytes_atomic(path, seg_bytes)
        for seg in stale:
            self._segments.remove(seg)
            if seg.path != path:  # same-id rewrite already replaced it
                self._unlink(seg)
        info = SegInfo(int(level), int(window_from), int(window_to), path,
                       len(seg_bytes))
        self._segments.append(info)
        self._segments.sort(key=lambda s: (s.window_from, s.level))
        self._write_manifest()
        if self._metrics is not None:
            self._metrics.archive_segments_total.inc()
            self._metrics.archive_bytes_total.inc(len(seg_bytes))
        return info

    def pending_compaction(self) -> Optional[tuple[int, list[SegInfo]]]:
        """(level, oldest-`group` segments) of the lowest level holding
        `cap + group` or more segments — the next compaction's input — or
        None. Levels at `max_levels` never compact (they age out via
        `enforce_top_level_retention`)."""
        for level in range(self.max_levels):
            segs = [s for s in self._segments if s.level == level]
            if len(segs) >= self.raw_windows + self.compact_group:
                return level, segs[:self.compact_group]
        return None

    def replace(self, group: list[SegInfo], merged_bytes: bytes,
                level: int, window_from: int,
                window_to: int) -> SegInfo:
        """Land a compacted super-window; append's intersection sweep
        retires the input group AFTER the merged segment is durable (the
        crash-safe order the open-time overlap heal assumes). `group` is
        advisory — the sweep retires by window range, which covers
        exactly the contiguous inputs."""
        return self.append(merged_bytes, level, window_from, window_to)

    def enforce_top_level_retention(self) -> int:
        """Delete the top level's oldest segments beyond its cap — the one
        place history is truly dropped (the disk bound's backstop).
        Returns how many were dropped."""
        top = [s for s in self._segments if s.level >= self.max_levels]
        dropped = 0
        while len(top) > self.raw_windows:
            seg = top.pop(0)
            log.info("archive retention: dropping %s (top-level cap %d)",
                     seg.name, self.raw_windows)
            self._unlink(seg)
            self._segments.remove(seg)
            dropped += 1
        if dropped:
            self._write_manifest()
        return dropped

    # --- reads -----------------------------------------------------------
    def read(self, seg: SegInfo) -> bytes:
        with open(seg.path, "rb") as fh:
            return fh.read()

    def segments(self) -> list[SegInfo]:
        return list(self._segments)

    def select(self, window_from: int, window_to: int) -> list[SegInfo]:
        """Covering segments: every segment whose window range intersects
        [window_from, window_to], oldest first. A compacted super-window
        partially inside the range is included WHOLE — range answers snap
        to segment boundaries (the payload reports the actual covered
        span)."""
        return [s for s in self._segments
                if s.window_to >= window_from
                and s.window_from <= window_to]

    def coverage(self) -> list[dict]:
        """JSON-able view of what is answerable (the 404 discovery list)."""
        return [{"level": s.level, "window_from": s.window_from,
                 "window_to": s.window_to, "bytes": s.nbytes}
                for s in self._segments]

    def total_bytes(self) -> int:
        return sum(s.nbytes for s in self._segments)

    def stats(self) -> dict:
        per_level: dict[int, int] = {}
        for s in self._segments:
            per_level[s.level] = per_level.get(s.level, 0) + 1
        return {"segments": len(self._segments),
                "segments_per_level": {str(k): v for k, v
                                       in sorted(per_level.items())},
                "disk_bytes": self.total_bytes(),
                "raw_windows": self.raw_windows,
                "compact_group": self.compact_group,
                "max_levels": self.max_levels}
