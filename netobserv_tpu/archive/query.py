"""Device-merged time-range queries over the archive, and the compactor
that shares their executables.

The range plane answers ``/query/range?from=&to=`` (and the
``topk | frequency | cardinality | victims`` views) by selecting the
covering segments and merging their K table snapshots in ONE fixed-shape
device dispatch: a warmed LADDER of merge sizes (powers of two up to
`ladder_max` — the `SKETCH_SUPERBATCH` pattern), one pre-built jit per
ladder k, every entry `retrace.watch`ed. K segments pad UP to the next
ladder size with ZERO tables (the exact merge identity: CM/hist/rates add
zeros, HLL maxes zeros, an all-invalid slot table contributes no
candidates), so shapes never depend on the request — zero post-warmup
retraces. Ranges wider than `ladder_max` CHAIN: each dispatch's merged
tables re-enter the next dispatch as one more input (the merged snapshot
has exactly the TABLE_SPEC shapes, by construction).

Merge semantics are the equivalence-pinned `federation.statemerge.
merge_tables` — CM planes/histograms/rates add, HLL max, slot tables
through `ops/topk.merge_slot_tables` — so a range answer over raw
segments is bit-exact against the union roll (tests/test_archive.py pins
it; the slot table against the table-merge replay oracle, per the chaos
suite rule). The rendered report flows through the ONE query core
(`query/core.py`): the CM error bars on a merged plane are computed from
the MERGED row sum, which IS the widened bound — the Count-Min
overestimate stays one-sided under merging (`(e/w) * N_total` over the
merged mass, confidence unchanged), the additive-error-counter result the
warehouse leans on (PAPERS.md).

Deviation from the live query plane's snapshot-only rule, by design: a
range request DOES dispatch a device op (the merge). It still never takes
the exporter lock and never touches live donated state — every input
comes off disk — and dispatches serialize under the engine's own lock
(two threads first-tracing one ladder entry would double-compile, the
spurious-retrace hazard `_roll_mutex` documents).

The COMPACTOR is the same machinery pointed at retention: a pending group
merges through the same ladder executables and the merged snapshot is
re-encoded one level up — compaction and range answers can never disagree
about what a merge means.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from netobserv_tpu.archive import segment as aseg
from netobserv_tpu.archive.store import ArchiveStore, SegInfo
from netobserv_tpu.federation import delta as fdelta
from netobserv_tpu.utils import retrace

log = logging.getLogger("netobserv_tpu.archive.query")

#: range views and their query-core payload builders ("" = summary)
VIEWS = ("", "summary", "topk", "frequency", "cardinality", "victims")


class ArchiveQueryEngine:
    """Warmed merge ladder + range rendering over one ArchiveStore."""

    def __init__(self, store: ArchiveStore, sketch_cfg, metrics=None,
                 ladder_max: int = 16,
                 report_kwargs: Optional[dict] = None):
        from netobserv_tpu.sketch import state as sk
        if ladder_max < 1 or ladder_max & (ladder_max - 1):
            raise ValueError("ladder_max must be a power of two >= 1")
        self._store = store
        self._sk = sk
        # the ladder merges decode to the canonical WIDE layout; a tiered
        # exporter archives wide snapshots (state_tables decodes), so the
        # engine always runs the wide config
        self._cfg = sketch_cfg._replace(tiered=None) \
            if getattr(sketch_cfg, "tiered", None) is not None \
            else sketch_cfg
        self._metrics = metrics
        self._report_kwargs = report_kwargs or {}
        self.ladder = tuple(1 << i
                            for i in range(ladder_max.bit_length()))
        #: one serialization point for ladder compiles, dispatches AND
        #: store mutations: queries read segment files the compactor may
        #: replace, and two threads first-tracing one ladder entry would
        #: double-compile (a spurious post-warmup retrace alarm)
        self.lock = threading.RLock()
        self._merge_fns: dict[int, object] = {}
        self._zero_tables: Optional[dict] = None
        self._expected_shapes: Optional[dict] = None
        self.dims = {"cm_depth": self._cfg.cm_depth,
                     "cm_width": self._cfg.cm_width,
                     "hll_precision": self._cfg.hll_precision,
                     "topk": self._cfg.topk,
                     "ewma_buckets": self._cfg.ewma_buckets}

    # --- ladder ----------------------------------------------------------
    def _zero_template(self) -> dict:
        """Host zero tables in spec dtypes — the pad identity."""
        if self._zero_tables is None:
            tables = self._sk.state_tables(self._sk.init_state(self._cfg))
            self._zero_tables = {
                name: np.zeros(np.asarray(tables[name]).shape, dt)
                for name, dt in fdelta.TABLE_SPEC}
            self._expected_shapes = {n: a.shape for n, a
                                     in self._zero_tables.items()}
        return self._zero_tables

    def _merge_fn(self, k: int):
        """The ladder-k executable: merge k stacked table snapshots into a
        fresh state, return (device WindowReport, merged state_tables).
        Built lazily under the engine lock; the first call per k is the
        watchdog's warmup compile, anything later alarms."""
        fn = self._merge_fns.get(k)
        if fn is not None:
            return fn
        import jax

        from netobserv_tpu.federation import statemerge
        sk, cfg = self._sk, self._cfg
        names = [n for n, _ in fdelta.TABLE_SPEC]

        def merge_k(stacked):
            state = sk.init_state(cfg)
            for i in range(k):  # fixed k: unrolls into one program
                state = statemerge.merge_tables(
                    state, {n: stacked[n][i] for n in names})
            tables = sk.state_tables(state)
            _new, report = sk.roll_window(state, cfg)
            return report, tables

        fn = retrace.watch(jax.jit(merge_k), f"archive_merge_x{k}")
        self._merge_fns[k] = fn
        return fn

    def warm(self) -> None:
        """Compile every ladder entry against zero stacks — the
        production entry (`archive.maybe_archive`) runs this on a
        background thread at construction, so the first real range query
        or compaction hits warm executables instead of stalling the HTTP
        or timer thread on a multi-second compile. The lock is taken PER
        entry: a window publish slips in between compiles instead of
        queueing behind the whole ladder. Idempotent; entries a live
        query raced to first are skipped (their first use was their
        watchdog warmup call)."""
        import jax
        for k in self.ladder:
            with self.lock:
                if k in self._merge_fns:
                    continue
                zero = self._zero_template()
                stacked = {n: np.broadcast_to(
                    z, (k,) + z.shape).copy() for n, z in zero.items()}
                report, _tables = self._merge_fn(k)(stacked)
                jax.block_until_ready(report.window)

    def _ladder_fit(self, n: int) -> int:
        for k in self.ladder:
            if k >= n:
                return k
        return self.ladder[-1]

    def _dispatch(self, table_dicts: list[dict]) -> tuple:
        """Merge up to ladder_max snapshots in one dispatch (padding with
        the zero identity). Returns (device report, device tables)."""
        k = self._ladder_fit(len(table_dicts))
        zero = self._zero_template()
        pads = [zero] * (k - len(table_dicts))
        stacked = {n: np.stack([np.asarray(t[n], dt)
                                for t in table_dicts + pads])
                   for n, dt in fdelta.TABLE_SPEC}
        return self._merge_fn(k)(stacked)

    def merge_tables_host(
            self, table_dicts: list[dict]) -> tuple[object, dict, int]:
        """Merge an arbitrary number of table snapshots, chaining
        dispatches past ladder_max. Returns (device report of the final
        merge, HOST copies of the merged tables, dispatch count). Caller
        holds the engine lock."""
        if not table_dicts:
            raise ValueError("nothing to merge")
        n_merges = 0
        cap = self.ladder[-1]
        pending = list(table_dicts)
        while True:
            chunk, pending = pending[:cap], pending[cap:]
            report, tables = self._dispatch(chunk)
            n_merges += 1
            host = {n: np.asarray(tables[n]) for n, _
                    in fdelta.TABLE_SPEC}
            if not pending:
                return report, host, n_merges
            # the merged snapshot re-enters as one more input (same
            # TABLE_SPEC shapes by construction)
            pending = [host] + pending

    # --- segment plumbing -------------------------------------------------
    def _decode_checked(self, seg: SegInfo) -> aseg.Segment:
        decoded = aseg.decode_segment(self._store.read(seg))
        self._zero_template()  # ensures _expected_shapes
        for name, arr in decoded.tables.items():
            want = self._expected_shapes[name]
            if tuple(arr.shape) != tuple(want):
                raise aseg.ArchiveSegmentError(
                    f"segment {seg.name}: tensor {name!r} shape "
                    f"{tuple(arr.shape)} != this config's {tuple(want)} "
                    "(the archive was written by a different "
                    "SketchConfig)")
        return decoded

    def compact_once(self) -> bool:
        """Merge one pending retention group into a super-window one level
        up (store.replace lands it before the inputs die). Returns True
        when a compaction ran."""
        with self.lock:
            pending = self._store.pending_compaction()
            if pending is None:
                return False
            level, group = pending
            decoded = [self._decode_checked(s) for s in group]
            _report, merged, _n = self.merge_tables_host(
                [d.tables for d in decoded])
            seg_bytes = aseg.encode_segment(
                merged, agent_id=decoded[-1].agent_id, level=level + 1,
                window_from=group[0].window_from,
                window_to=group[-1].window_to,
                n_windows=sum(d.n_windows for d in decoded),
                ts_ms=max(d.ts_ms for d in decoded), dims=self.dims)
            self._store.replace(group, seg_bytes, level + 1,
                                group[0].window_from,
                                group[-1].window_to)
        if self._metrics is not None:
            self._metrics.archive_compactions_total.inc()
        log.info("archive compaction: L%d windows [%d, %d] -> L%d",
                 level, group[0].window_from, group[-1].window_to,
                 level + 1)
        return True

    # --- range answers ----------------------------------------------------
    def range_snapshot(self, window_from: int,
                       window_to: int) -> Optional[dict]:
        """Merge the covering segments into one snapshot dict shaped like
        the live query plane's (`query/core.py` contract: window / ts_ms /
        seq / report / cm planes) plus the range metadata. None when no
        archived window intersects the range."""
        t0 = time.perf_counter()
        with self.lock:
            segs = self._store.select(window_from, window_to)
            if not segs:
                return None
            decoded = [self._decode_checked(s) for s in segs]
            report, merged, n_merges = self.merge_tables_host(
                [d.tables for d in decoded])
            from netobserv_tpu.exporter.tpu_sketch import report_to_json
            obj = report_to_json(report, **self._report_kwargs)
        covered = (segs[0].window_from, segs[-1].window_to)
        obj["Type"] = "sketch_range_report"
        obj["Window"] = covered[1]
        obj["WindowFrom"], obj["WindowTo"] = covered
        obj["TimestampMs"] = max(d.ts_ms for d in decoded)
        snap = {
            "window": covered[1],
            "ts_ms": obj["TimestampMs"],
            "seq": 0,  # range answers are derived, not published — no seq
            "report": obj,
            "cm_bytes": merged["cm_bytes"],
            "cm_pkts": merged["cm_pkts"],
            "range": {
                "requested": [int(window_from), int(window_to)],
                "covered": [covered[0], covered[1]],
                "windows_merged": sum(d.n_windows for d in decoded),
                "segments_merged": len(segs),
                "merge_dispatches": n_merges,
                "compacted": any(s.level > 0 for s in segs),
                "merge_seconds": round(time.perf_counter() - t0, 6),
            },
        }
        return snap

    def route_payload(self, params: dict,
                      view: Optional[str] = None) -> tuple[int, dict]:
        """The `/query/range` (and `/federation/range`) body builder —
        agent and federation surfaces are thin adapters over exactly this
        (the federation/query.py never-fork rule). Returns (status,
        JSON-able body); every request is counted in
        ``archive_range_requests_total{result}``."""
        code, body = self._route(params, view)
        if self._metrics is not None:
            result = ("ok" if code == 200 else
                      "bad_request" if code == 400 else
                      "not_found" if code == 404 else "error")
            self._metrics.archive_range_requests_total.labels(result).inc()
        return code, body

    def _route(self, params: dict,
               view: Optional[str]) -> tuple[int, dict]:
        view = (view or params.get("view") or "").strip()
        if view not in VIEWS:
            return 404, {"error": f"unknown range view {view!r}",
                         "views": [v for v in VIEWS if v]}
        try:
            window_from = int(params["from"])
            window_to = int(params["to"])
        except (KeyError, TypeError, ValueError):
            return 400, {"error": "from and to window ids are required "
                                  "(?from=<id>&to=<id>)"}
        if window_to < window_from:
            return 400, {"error": f"empty range [{window_from}, "
                                  f"{window_to}]"}
        try:
            snap = self.range_snapshot(window_from, window_to)
        except Exception as exc:
            log.error("range query [%d, %d] failed: %s", window_from,
                      window_to, exc)
            return 500, {"error": str(exc)}
        if snap is None:
            return 404, {"error": f"no archived windows in "
                                  f"[{window_from}, {window_to}]",
                         "coverage": self._store.coverage()}
        from netobserv_tpu.query import core as qcore
        rng = snap["range"]
        if view in ("", "summary"):
            body = qcore.cardinality_payload(snap)
            bars = qcore.cm_error_bars(snap)
            if bars is not None:
                body.update(bars)
        elif view == "topk":
            body = qcore.topk_payload(snap, params.get("n", 100))
        elif view == "cardinality":
            body = qcore.cardinality_payload(snap)
        elif view == "victims":
            body = qcore.victims_payload(snap)
        else:  # frequency
            if not params.get("src") or not params.get("dst"):
                return 400, {"error": "src and dst are required"}
            body = qcore.frequency_payload(
                snap, params["src"], params["dst"],
                int(params.get("src_port", 0)),
                int(params.get("dst_port", 0)),
                int(params.get("proto", 0)))
        body["range"] = rng
        return 200, body

    def stats(self) -> dict:
        with self.lock:
            out = self._store.stats()
        out["ladder"] = list(self.ladder)
        out["warmed"] = sorted(self._merge_fns)
        return out
