"""Archive segment codec: one closed window (or compacted super-window)
of mergeable sketch tables, serialized for the on-disk warehouse.

The segment carries exactly the delta wire's canonical table snapshot —
`federation.delta.TABLE_SPEC` names/dtypes in spec order — through the
SAME per-tensor zlib-when-smaller codec (`utils/tensorcodec.py`): one
tensor format across the wire and the warehouse, not a fifth drifting
copy. On top of the tensors sits a tiny self-describing envelope:

    8B  magic  b"NOSKARCH"
    u4< format version (SEGMENT_FORMAT_VERSION)
    u4< header length
        header: canonical JSON (sorted keys, compact separators) —
        agent_id, level, window_from/window_to/n_windows, ts_ms, the
        frame-geometry dims, and the TABLE_SPEC fingerprint
    per TABLE_SPEC entry, in spec order (names are implicit):
        u1 codec, u1 dtype code, u2< ndim, u4<*ndim shape,
        u4< payload length, payload bytes

Everything is explicit little-endian, so a segment written on any host
decodes on any other — the RAW-codec golden (tests/golden/
archive_segment_v1.hex + tests/test_archive_golden.py) pins the bytes on
the big-endian qemu CI tier exactly like the delta-frame goldens.

jax-free on purpose: segment encode runs on the exporter's timer thread
from HOST copies of the roll's table snapshot and must never dispatch a
device op; decode must work on accelerator-less hosts (and the qemu
tier). The TABLE_SPEC fingerprint in the header plays the checkpoint
stamp's role: a layout drift without a format bump refuses to decode
instead of silently misaligning tables.
"""

from __future__ import annotations

import json
import struct
from typing import Mapping, NamedTuple

import numpy as np

from netobserv_tpu.federation import delta as fdelta
from netobserv_tpu.utils import tensorcodec

MAGIC = b"NOSKARCH"
#: bump on ANY change to the envelope, the header schema, or the tensor
#: encoding. The tensor layout itself is TABLE_SPEC — a spec change moves
#: the header fingerprint AND the delta/checkpoint versions together
#: (federation/delta.py, sketch/checkpoint.py).
SEGMENT_FORMAT_VERSION = 1

#: header keys every segment must carry (sorted-key JSON keeps the golden
#: deterministic)
_HEADER_KEYS = ("agent_id", "dims", "level", "n_windows", "table_crc",
                "ts_ms", "window_from", "window_to")

CODEC_RAW = tensorcodec.CODEC_RAW
CODEC_ZLIB = tensorcodec.CODEC_ZLIB


class ArchiveSegmentError(ValueError):
    """Malformed/incompatible segment (decode-time validation failure)."""


class Segment(NamedTuple):
    """Decoded segment: header metadata + the table dict (TABLE_SPEC names
    -> little-endian numpy arrays; RAW tensors are zero-copy read-only
    views over the segment buffer — copy before mutating)."""

    agent_id: str
    level: int
    window_from: int
    window_to: int
    n_windows: int
    ts_ms: int
    dims: dict
    tables: dict


def encode_segment(tables: Mapping[str, np.ndarray], *, agent_id: str,
                   level: int, window_from: int, window_to: int,
                   n_windows: int, ts_ms: int, dims: Mapping[str, int],
                   codec: int = CODEC_ZLIB) -> bytes:
    """Serialize one table snapshot into segment bytes.

    `tables` must carry every TABLE_SPEC name (host numpy arrays; dtypes
    coerce to the spec's little-endian types). Raw (level-0) segments have
    window_from == window_to and n_windows == 1; compacted super-windows
    span the windows they merged."""
    missing = [n for n, _ in fdelta.TABLE_SPEC if n not in tables]
    if missing:
        raise ArchiveSegmentError(
            f"table snapshot missing tensors: {missing}")
    header = {
        "agent_id": str(agent_id),
        "dims": {f: int(dims[f]) for f in fdelta.DIM_FIELDS},
        "level": int(level),
        "n_windows": int(n_windows),
        "table_crc": fdelta.table_spec_fingerprint(),
        "ts_ms": int(ts_ms),
        "window_from": int(window_from),
        "window_to": int(window_to),
    }
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode()
    out = [MAGIC, struct.pack("<II", SEGMENT_FORMAT_VERSION, len(hdr)),
           hdr]
    for name, dt in fdelta.TABLE_SPEC:
        arr = np.ascontiguousarray(np.asarray(tables[name]), dtype=dt)
        try:
            code, payload = tensorcodec.encode_payload(arr.tobytes(),
                                                       codec)
        except tensorcodec.TensorCodecError as exc:
            raise ArchiveSegmentError(str(exc)) from exc
        out.append(struct.pack("<BBH", code, tensorcodec.DTYPE_TO_CODE[dt],
                               arr.ndim))
        out.append(struct.pack(f"<{arr.ndim}I", *arr.shape))
        out.append(struct.pack("<I", len(payload)))
        out.append(payload)
    return b"".join(out)


def _take(buf: bytes, off: int, n: int, what: str) -> tuple[bytes, int]:
    if off + n > len(buf):
        raise ArchiveSegmentError(
            f"truncated segment: wanted {n}B of {what} at offset {off}, "
            f"have {len(buf) - off}B")
    return buf[off:off + n], off + n


def decode_segment(data: bytes) -> Segment:
    """Parse + validate one segment. Raises ArchiveSegmentError on
    anything structurally wrong: bad magic, unknown format version, a
    TABLE_SPEC fingerprint from a different build (layout drift), dtype
    drift, truncation, oversized or bomb payloads, trailing garbage."""
    head, off = _take(data, 0, len(MAGIC), "magic")
    if head != MAGIC:
        raise ArchiveSegmentError(
            f"not an archive segment (magic {head!r})")
    raw, off = _take(data, off, 8, "version header")
    version, hdr_len = struct.unpack("<II", raw)
    if version != SEGMENT_FORMAT_VERSION:
        raise ArchiveSegmentError(
            f"segment format version {version}; this build reads "
            f"{SEGMENT_FORMAT_VERSION} — refusing to decode")
    hdr_raw, off = _take(data, off, hdr_len, "header json")
    try:
        header = json.loads(hdr_raw)
    except ValueError as exc:
        raise ArchiveSegmentError(f"unparseable segment header: {exc}") \
            from exc
    missing = [k for k in _HEADER_KEYS if k not in header]
    if missing:
        raise ArchiveSegmentError(f"segment header missing {missing}")
    crc = int(header["table_crc"])
    if crc != fdelta.table_spec_fingerprint():
        raise ArchiveSegmentError(
            f"segment stamps table-spec crc {crc} != this build's "
            f"{fdelta.table_spec_fingerprint()}: the snapshot layout "
            "changed without a segment format bump — refuse rather than "
            "decode silently-misaligned tables")
    tables: dict[str, np.ndarray] = {}
    for name, spec_dt in fdelta.TABLE_SPEC:
        raw, off = _take(data, off, 4, f"{name} tensor header")
        code, dt_code, ndim = struct.unpack("<BBH", raw)
        dt = tensorcodec.CODE_TO_DTYPE.get(dt_code)
        if dt is None:
            raise ArchiveSegmentError(
                f"tensor {name!r}: unknown dtype code {dt_code}")
        if dt != spec_dt:
            raise ArchiveSegmentError(
                f"tensor {name!r}: dtype {dt} != spec {spec_dt}")
        raw, off = _take(data, off, 4 * ndim, f"{name} shape")
        shape = struct.unpack(f"<{ndim}I", raw)
        raw, off = _take(data, off, 4, f"{name} payload length")
        (plen,) = struct.unpack("<I", raw)
        payload, off = _take(data, off, plen, f"{name} payload")
        try:
            expected = tensorcodec.declared_nbytes(name, shape, dt)
            raw_bytes = tensorcodec.decode_payload(name, code, payload,
                                                   expected)
        except tensorcodec.TensorCodecError as exc:
            raise ArchiveSegmentError(str(exc)) from exc
        tables[name] = np.frombuffer(raw_bytes, dtype=dt).reshape(shape)
    if off != len(data):
        raise ArchiveSegmentError(
            f"{len(data) - off} trailing bytes after the last tensor")
    return Segment(
        agent_id=str(header["agent_id"]), level=int(header["level"]),
        window_from=int(header["window_from"]),
        window_to=int(header["window_to"]),
        n_windows=int(header["n_windows"]), ts_ms=int(header["ts_ms"]),
        dims={f: int(header["dims"][f]) for f in fdelta.DIM_FIELDS},
        tables=tables)
