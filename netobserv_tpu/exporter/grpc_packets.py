"""gRPC packet exporter (PCA): pcap-framed packet stream to a collector.

Reference analog: `pkg/exporter/grpc_packets.go` — the pcap file header goes
out once, then each packet as a pcap-framed chunk wrapped in pbpacket.Packet.
TLS/mTLS options mirror the flow client (reference
`pkg/grpc/packet/client.go` takes the same credentials as the flow side).
"""

from __future__ import annotations

import logging
from typing import Optional

import grpc
from google.protobuf import any_pb2, wrappers_pb2

from netobserv_tpu.grpc.flow import _channel_credentials
from netobserv_tpu.model.packet_record import (
    PacketRecord, frame_packet, pcap_file_header,
)
from netobserv_tpu.pb import packet_pb2

log = logging.getLogger("netobserv_tpu.exporter.grpc_packets")

_SEND = "/pbpacket.Collector/Send"


class PacketClient:
    def __init__(self, host: str, port: int, tls_ca: str = "",
                 tls_cert: str = "", tls_key: str = ""):
        creds = _channel_credentials(tls_ca, tls_cert, tls_key)
        target = f"{host}:{port}"
        self._channel = (grpc.secure_channel(target, creds)
                         if creds is not None
                         else grpc.insecure_channel(target))
        self._send = self._channel.unary_unary(
            _SEND,
            request_serializer=packet_pb2.Packet.SerializeToString,
            response_deserializer=packet_pb2.CollectorReply.FromString)

    def send_bytes(self, payload: bytes, timeout_s: float = 10.0):
        wrapped = any_pb2.Any()
        wrapped.Pack(wrappers_pb2.BytesValue(value=payload))
        return self._send(packet_pb2.Packet(pcap=wrapped), timeout=timeout_s)

    def close(self) -> None:
        self._channel.close()


class GRPCPacketExporter:
    """Terminal for PCA packet batches."""

    name = "grpc-packets"

    def __init__(self, host: str, port: int,
                 client: Optional[PacketClient] = None,
                 tls_ca: str = "", tls_cert: str = "", tls_key: str = ""):
        self._client = client or PacketClient(host, port, tls_ca=tls_ca,
                                              tls_cert=tls_cert,
                                              tls_key=tls_key)
        self._sent_header = False

    def export_packets(self, packets: list[PacketRecord]) -> None:
        if not self._sent_header:
            self._client.send_bytes(pcap_file_header())
            self._sent_header = True
        for rec in packets:
            self._client.send_bytes(frame_packet(rec))

    def close(self) -> None:
        self._client.close()


def start_packet_collector(port: int = 0, out=None,
                           tls_cert: str = "", tls_key: str = ""):
    """In-process pbpacket collector for tests/examples; returns
    (server, bound_port, queue-of-bytes)."""
    import queue as _queue
    from concurrent import futures

    out = out if out is not None else _queue.Queue()

    def send(request: packet_pb2.Packet, context) -> packet_pb2.CollectorReply:
        val = wrappers_pb2.BytesValue()
        request.pcap.Unpack(val)
        out.put(val.value)
        return packet_pb2.CollectorReply()

    handler = grpc.method_handlers_generic_handler(
        "pbpacket.Collector",
        {"Send": grpc.unary_unary_rpc_method_handler(
            send,
            request_deserializer=packet_pb2.Packet.FromString,
            response_serializer=packet_pb2.CollectorReply.SerializeToString)})
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    server.add_generic_rpc_handlers((handler,))
    if tls_cert and tls_key:
        creds = grpc.ssl_server_credentials(
            [(open(tls_key, "rb").read(), open(tls_cert, "rb").read())])
        bound = server.add_secure_port(f"0.0.0.0:{port}", creds)
    else:
        bound = server.add_insecure_port(f"0.0.0.0:{port}")
    server.start()
    return server, bound, out
