"""Exporter base: a terminal thread consuming record batches from a queue."""

from __future__ import annotations

import logging
import queue
import threading
from typing import Optional

from netobserv_tpu.model.record import Record
from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.exporter")


class Exporter:
    """Subclasses implement export_batch(); name is the metrics label.

    Exporters that can consume raw evictions columnar-first (without Record
    materialization — the per-record decode loop is the reference's hottest
    path) set `supports_columnar` and implement export_evicted().
    """

    name = "exporter"
    supports_columnar = False

    def export_batch(self, records: list[Record]) -> None:
        raise NotImplementedError

    def export_evicted(self, evicted) -> None:  # EvictedFlows
        raise NotImplementedError

    def close(self) -> None:
        pass


class QueueExporter:
    """Runs an Exporter as the pipeline's terminal node."""

    def __init__(self, exporter: Exporter,
                 inp: "queue.Queue[list[Record]]", metrics=None):
        self._exporter = exporter
        self._in = inp
        self._metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: supervision hook: beats once per poll (agent/supervisor.py)
        self.heartbeat = lambda: None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name=f"export-{self._exporter.name}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2.0)
        self._drain()
        self._exporter.close()

    def _drain(self) -> None:
        while True:
            try:
                self._export(self._in.get_nowait())
            except queue.Empty:
                return

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat()
            # the fault point sits OUTSIDE _export's try: it simulates a bug
            # in the terminal stage itself (supervisor territory), while
            # errors raised BY the exporter stay swallowed+counted below
            faultinject.fire("exporter.loop")
            try:
                batch = self._in.get(timeout=0.2)
            except queue.Empty:
                continue
            self._export(batch)

    def _export(self, batch) -> None:
        try:
            # inside the try: an armed "exporter.export" behaves exactly
            # like a throwing exporter — swallowed and counted, never fatal
            faultinject.fire("exporter.export")
            if isinstance(batch, list):
                self._exporter.export_batch(batch)
            else:  # EvictedFlows on the columnar fast path
                self._exporter.export_evicted(batch)
            if self._metrics is not None:
                self._metrics.count_exported(self._exporter.name, len(batch))
        except Exception as exc:  # exporter errors must not kill the pipeline
            if self._metrics is not None:
                self._metrics.count_export_error(
                    self._exporter.name, type(exc).__name__)
            log.error("%s export failed: %s", self._exporter.name, exc)
