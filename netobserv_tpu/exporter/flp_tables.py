"""Full FLP string tables: TCP states, packet-drop causes, DNS rcodes.

Reference analog: `pkg/decode/decode_protobuf.go:199-464` (TCPStateToStr,
PktDropCauseToStr, DNSRcodeToStr) and
`pkg/utils/networkevents/network_events.go:133-139` (OVN custom causes).
String-for-string parity is the contract — FLP consumers match on these
exact names (pinned by tests/test_direct_flp.py parsing the reference
source). Where the reference's table diverges from the kernel's own enum
(TCPStateToStr skips TCP_TIME_WAIT, shifting 6..11), the reference wins:
ecosystem compatibility over kernel fidelity.
"""

from __future__ import annotations

# kernel include/net/dropreason.h subsystem encoding
SKB_DROP_SUBSYS_SHIFT = 16
SKB_DROP_SUBSYS_CORE = 0 << SKB_DROP_SUBSYS_SHIFT
SKB_DROP_SUBSYS_OVS = 3 << SKB_DROP_SUBSYS_SHIFT
# arbitrary private space for OVN network-event causes (reference
# network_events.go: customDropReasonSubSysOVNEvents)
OVN_EVENTS_SUBSYS = 1 << 24

TCP_STATES = {
    1: "TCP_ESTABLISHED",
    2: "TCP_SYN_SENT",
    3: "TCP_SYN_RECV",
    4: "TCP_FIN_WAIT1",
    5: "TCP_FIN_WAIT2",
    6: "TCP_CLOSE",
    7: "TCP_CLOSE_WAIT",
    8: "TCP_LAST_ACK",
    9: "TCP_LISTEN",
    10: "TCP_CLOSING",
    11: "TCP_NEW_SYN_RECV",
}
TCP_STATE_INVALID = "TCP_INVALID_STATE"

_CORE_DROP_CAUSES = {
    2: "SKB_DROP_REASON_NOT_SPECIFIED",
    3: "SKB_DROP_REASON_NO_SOCKET",
    4: "SKB_DROP_REASON_PKT_TOO_SMALL",
    5: "SKB_DROP_REASON_TCP_CSUM",
    6: "SKB_DROP_REASON_SOCKET_FILTER",
    7: "SKB_DROP_REASON_UDP_CSUM",
    8: "SKB_DROP_REASON_NETFILTER_DROP",
    9: "SKB_DROP_REASON_OTHERHOST",
    10: "SKB_DROP_REASON_IP_CSUM",
    11: "SKB_DROP_REASON_IP_INHDR",
    12: "SKB_DROP_REASON_IP_RPFILTER",
    13: "SKB_DROP_REASON_UNICAST_IN_L2_MULTICAST",
    14: "SKB_DROP_REASON_XFRM_POLICY",
    15: "SKB_DROP_REASON_IP_NOPROTO",
    16: "SKB_DROP_REASON_SOCKET_RCVBUFF",
    17: "SKB_DROP_REASON_PROTO_MEM",
    18: "SKB_DROP_REASON_TCP_MD5NOTFOUND",
    19: "SKB_DROP_REASON_TCP_MD5UNEXPECTED",
    20: "SKB_DROP_REASON_TCP_MD5FAILURE",
    21: "SKB_DROP_REASON_SOCKET_BACKLOG",
    22: "SKB_DROP_REASON_TCP_FLAGS",
    23: "SKB_DROP_REASON_TCP_ZEROWINDOW",
    24: "SKB_DROP_REASON_TCP_OLD_DATA",
    25: "SKB_DROP_REASON_TCP_OVERWINDOW",
    26: "SKB_DROP_REASON_TCP_OFOMERGE",
    27: "SKB_DROP_REASON_TCP_RFC7323_PAWS",
    28: "SKB_DROP_REASON_TCP_INVALID_SEQUENCE",
    29: "SKB_DROP_REASON_TCP_RESET",
    30: "SKB_DROP_REASON_TCP_INVALID_SYN",
    31: "SKB_DROP_REASON_TCP_CLOSE",
    32: "SKB_DROP_REASON_TCP_FASTOPEN",
    33: "SKB_DROP_REASON_TCP_OLD_ACK",
    34: "SKB_DROP_REASON_TCP_TOO_OLD_ACK",
    35: "SKB_DROP_REASON_TCP_ACK_UNSENT_DATA",
    36: "SKB_DROP_REASON_TCP_OFO_QUEUE_PRUNE",
    37: "SKB_DROP_REASON_TCP_OFO_DROP",
    38: "SKB_DROP_REASON_IP_OUTNOROUTES",
    39: "SKB_DROP_REASON_BPF_CGROUP_EGRESS",
    40: "SKB_DROP_REASON_IPV6DISABLED",
    41: "SKB_DROP_REASON_NEIGH_CREATEFAIL",
    42: "SKB_DROP_REASON_NEIGH_FAILED",
    43: "SKB_DROP_REASON_NEIGH_QUEUEFULL",
    44: "SKB_DROP_REASON_NEIGH_DEAD",
    45: "SKB_DROP_REASON_TC_EGRESS",
    46: "SKB_DROP_REASON_QDISC_DROP",
    47: "SKB_DROP_REASON_CPU_BACKLOG",
    48: "SKB_DROP_REASON_XDP",
    49: "SKB_DROP_REASON_TC_INGRESS",
    50: "SKB_DROP_REASON_UNHANDLED_PROTO",
    51: "SKB_DROP_REASON_SKB_CSUM",
    52: "SKB_DROP_REASON_SKB_GSO_SEG",
    53: "SKB_DROP_REASON_SKB_UCOPY_FAULT",
    54: "SKB_DROP_REASON_DEV_HDR",
    55: "SKB_DROP_REASON_DEV_READY",
    56: "SKB_DROP_REASON_FULL_RING",
    57: "SKB_DROP_REASON_NOMEM",
    58: "SKB_DROP_REASON_HDR_TRUNC",
    59: "SKB_DROP_REASON_TAP_FILTER",
    60: "SKB_DROP_REASON_TAP_TXFILTER",
    61: "SKB_DROP_REASON_ICMP_CSUM",
    62: "SKB_DROP_REASON_INVALID_PROTO",
    63: "SKB_DROP_REASON_IP_INADDRERRORS",
    64: "SKB_DROP_REASON_IP_INNOROUTES",
    65: "SKB_DROP_REASON_PKT_TOO_BIG",
    66: "SKB_DROP_REASON_DUP_FRAG",
    67: "SKB_DROP_REASON_FRAG_REASM_TIMEOUT",
    68: "SKB_DROP_REASON_FRAG_TOO_FAR",
    69: "SKB_DROP_REASON_TCP_MINTTL",
    70: "SKB_DROP_REASON_IPV6_BAD_EXTHDR",
    71: "SKB_DROP_REASON_IPV6_NDISC_FRAG",
    72: "SKB_DROP_REASON_IPV6_NDISC_HOP_LIMIT",
    73: "SKB_DROP_REASON_IPV6_NDISC_BAD_CODE",
    74: "SKB_DROP_REASON_IPV6_NDISC_BAD_OPTIONS",
    75: "SKB_DROP_REASON_IPV6_NDISC_NS_OTHERHOST",
    76: "SKB_DROP_REASON_QUEUE_PURGE",
    77: "SKB_DROP_REASON_TC_COOKIE_ERROR",
    78: "SKB_DROP_REASON_PACKET_SOCK_ERROR",
    79: "SKB_DROP_REASON_TC_CHAIN_NOTFOUND",
    80: "SKB_DROP_REASON_TC_RECLASSIFY_LOOP",
}

_OVS_DROP_CAUSES = {
    1: "OVS_DROP_LAST_ACTION",
    2: "OVS_DROP_ACTION_ERROR",
    3: "OVS_DROP_EXPLICIT",
    4: "OVS_DROP_EXPLICIT_WITH_ERROR",
    5: "OVS_DROP_METER",
    6: "OVS_DROP_RECURSION_LIMIT",
    7: "OVS_DROP_DEFERRED_LIMIT",
    8: "OVS_DROP_FRAG_L2_TOO_LONG",
    9: "OVS_DROP_FRAG_INVALID_PROTO",
    10: "OVS_DROP_CONNTRACK",
    11: "OVS_DROP_IP_TTL",
}

# OVN network-event causes injected into the drop-cause space (index order
# is the wire contract; reference network_events.go `causes`)
OVN_EVENT_CAUSES = [
    "Unknown",
    "EgressFirewall",
    "AdminNetworkPolicy",
    "BaselineAdminNetworkPolicy",
    "NetworkPolicy",
    "MulticastNS",
    "MulticastCluster",
    "NetpolNode",
    "NetpolNamespace",
    "UDNIsolation",
]

DROP_CAUSES = {
    **{SKB_DROP_SUBSYS_CORE + k: v for k, v in _CORE_DROP_CAUSES.items()},
    **{SKB_DROP_SUBSYS_OVS + k: v for k, v in _OVS_DROP_CAUSES.items()},
}

DNS_RCODES = {
    0: "NoError",
    1: "FormErr",
    2: "ServFail",
    3: "NXDomain",
    4: "NotImp",
    5: "Refused",
    6: "YXDomain",
    7: "YXRRSet",
    8: "NXRRSet",
    9: "NotAuth",
    10: "NotZone",
    16: "BADVERS",
    17: "BADKEY",
    18: "BADTIME",
    19: "BADMODE",
    20: "BADNAME",
    21: "BADALG",
}


def tcp_state_to_str(state: int) -> str:
    return TCP_STATES.get(state, TCP_STATE_INVALID)


def ovn_drop_reason_to_str(cause: int) -> str:
    """OVN network-event cause name, or "" when outside the OVN space
    (reference: DropReasonCodeToString)."""
    idx = cause - OVN_EVENTS_SUBSYS
    if 0 <= idx < len(OVN_EVENT_CAUSES):
        return OVN_EVENT_CAUSES[idx]
    return ""


def pkt_drop_cause_to_str(cause: int) -> str:
    name = DROP_CAUSES.get(cause)
    if name is not None:
        return name
    ovn = ovn_drop_reason_to_str(cause)
    if ovn:
        return "NetworkEvent_" + ovn
    return "SKB_DROP_UNKNOWN_CAUSE"


def dns_rcode_to_str(rcode: int) -> str:
    return DNS_RCODES.get(rcode, "UnDefined")
