"""IPFIX exporter (RFC 7011), pure-python encoder, UDP or TCP transport.

Reference analog: `pkg/exporter/ipfix.go` — v4 and v6 templates carrying the
core flow fields (IANA information elements; like the reference, feature
metrics such as DNS/RTT/drops are not part of the IPFIX schema).
"""

from __future__ import annotations

import logging
import socket
import struct
import time

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.model.flow import IP4_IN_6_PREFIX
from netobserv_tpu.model.record import Record

log = logging.getLogger("netobserv_tpu.exporter.ipfix")

IPFIX_VERSION = 10
TEMPLATE_SET_ID = 2
TEMPLATE_V4 = 256
TEMPLATE_V6 = 257

# (IANA IE id, length) — shared prefix of both templates
_COMMON_HEAD = [
    (152, 8),  # flowStartMilliseconds
    (153, 8),  # flowEndMilliseconds
    (1, 8),    # octetDeltaCount
    (2, 8),    # packetDeltaCount
    (10, 4),   # ingressInterface
    (61, 1),   # flowDirection
    (56, 6),   # sourceMacAddress
    (80, 6),   # destinationMacAddress
    (256, 2),  # ethernetType
    (4, 1),    # protocolIdentifier
    (6, 2),    # tcpControlBits
    (7, 2),    # sourceTransportPort
    (11, 2),   # destinationTransportPort
]
_V4_FIELDS = _COMMON_HEAD + [
    (8, 4),    # sourceIPv4Address
    (12, 4),   # destinationIPv4Address
    (176, 1),  # icmpTypeIPv4
    (177, 1),  # icmpCodeIPv4
]
_V6_FIELDS = _COMMON_HEAD + [
    (27, 16),  # sourceIPv6Address
    (28, 16),  # destinationIPv6Address
    (178, 1),  # icmpTypeIPv6
    (179, 1),  # icmpCodeIPv6
]


def _template_set() -> bytes:
    recs = b""
    for tid, fields in ((TEMPLATE_V4, _V4_FIELDS), (TEMPLATE_V6, _V6_FIELDS)):
        recs += struct.pack(">HH", tid, len(fields))
        for ie, length in fields:
            recs += struct.pack(">HH", ie, length)
    return struct.pack(">HH", TEMPLATE_SET_ID, 4 + len(recs)) + recs


def _data_record(r: Record, v6: bool) -> bytes:
    out = struct.pack(
        ">QQQQIB6s6sHBHHH",
        r.time_flow_start_ns // 1_000_000,
        r.time_flow_end_ns // 1_000_000,
        r.bytes_, r.packets, r.if_index, r.direction & 0xFF,
        r.src_mac, r.dst_mac, r.eth_protocol, r.key.proto,
        r.tcp_flags & 0xFFFF, r.key.src_port, r.key.dst_port)
    if v6:
        out += r.key.src_ip + r.key.dst_ip
    else:
        out += r.key.src_ip[12:16] + r.key.dst_ip[12:16]
    out += struct.pack(">BB", r.key.icmp_type, r.key.icmp_code)
    return out


class IPFIXExporter(Exporter):
    name = "ipfix"

    def __init__(self, host: str, port: int, transport: str = "udp",
                 obs_domain: int = 1, metrics=None,
                 template_refresh_s: float = 600.0):
        self._addr = (host, port)
        self._transport = transport
        self._obs_domain = obs_domain
        self._seq = 0
        self._template_refresh = template_refresh_s
        self._last_template = float("-inf")
        self._sock: socket.socket | None = None
        self._connect()

    def _connect(self) -> None:
        if self._sock is not None:
            self._sock.close()
        family = socket.AF_INET6 if ":" in self._addr[0] else socket.AF_INET
        if self._transport == "udp":
            self._sock = socket.socket(family, socket.SOCK_DGRAM)
            self._sock.connect(self._addr)
        else:
            self._sock = socket.create_connection(self._addr, timeout=10)
        self._last_template = float("-inf")  # (re)send templates on next message

    def _message(self, sets: bytes) -> bytes:
        hdr = struct.pack(
            ">HHIII", IPFIX_VERSION, 16 + len(sets), int(time.time()),
            self._seq, self._obs_domain)
        return hdr + sets

    # keep UDP datagrams MTU-safe; TCP messages can be larger
    MAX_UDP_PAYLOAD = 1400
    MAX_TCP_PAYLOAD = 32768

    def export_batch(self, records: list[Record]) -> None:
        # The v4 template can only hold records whose BOTH addresses are
        # v4-mapped; anything else (either address native-v6, or the datapath
        # tagged the frame 0x86DD) must use the v6 template — classifying on
        # src alone would let a mixed record truncate its dst address.
        def is_v6(r: Record) -> bool:
            return (r.eth_protocol == 0x86DD
                    or r.key.src_ip[:12] != IP4_IN_6_PREFIX
                    or r.key.dst_ip[:12] != IP4_IN_6_PREFIX)

        v4 = [r for r in records if not is_v6(r)]
        v6 = [r for r in records if is_v6(r)]
        limit = (self.MAX_UDP_PAYLOAD if self._transport == "udp"
                 else self.MAX_TCP_PAYLOAD)
        pending: list[tuple[int, bool, list[Record]]] = []
        for tid, recs, is6 in ((TEMPLATE_V4, v4, False), (TEMPLATE_V6, v6, True)):
            rec_size = len(_data_record(recs[0], is6)) if recs else 0
            per_msg = max((limit - 16 - 4 - len(_template_set())) // rec_size,
                          1) if rec_size else 0
            for s in range(0, len(recs), per_msg or 1):
                pending.append((tid, is6, recs[s:s + per_msg]))
        for tid, is6, chunk in pending:
            if not chunk:
                continue
            self._send_chunk(tid, is6, chunk)

    def _send_chunk(self, tid: int, is6: bool, chunk: list[Record],
                    retried: bool = False) -> None:
        sets = b""
        now = time.monotonic()
        if now - self._last_template > self._template_refresh:
            sets += _template_set()
            self._last_template = now
        payload = b"".join(_data_record(r, is6) for r in chunk)
        sets += struct.pack(">HH", tid, 4 + len(payload)) + payload
        msg = self._message(sets)
        try:
            self._sock.sendall(msg) if self._transport == "tcp" else \
                self._sock.send(msg)
        except OSError:
            if retried:
                raise
            # reconnect resets _last_template, so the rebuilt message carries
            # a template set — RFC 7011 scopes templates to the TCP session
            self._connect()
            self._send_chunk(tid, is6, chunk, retried=True)
            return
        self._seq += len(chunk)

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
