"""Kafka flow exporter: one protobuf Record per message, keyed so both
directions of a conversation land on one consumer.

Reference analog: `pkg/exporter/kafka_proto.go` (direction-normalized src+dst
partition key, `:181-191`) + the writer tuning/SASL/TLS wiring in
`pkg/agent/agent.go:283-331` and `pkg/agent/sasl.go`.
"""

from __future__ import annotations

import logging

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.pb_convert import record_to_pb
from netobserv_tpu.kafka.producer import (
    KafkaProducer, SASLSettings, TLSSettings,
)
from netobserv_tpu.model.record import Record

log = logging.getLogger("netobserv_tpu.exporter.kafka")


def partition_key(r: Record) -> bytes:
    """Direction-normalized key: sorted (src_ip, dst_ip) concatenation."""
    a, b = r.key.src_ip, r.key.dst_ip
    return a + b if a <= b else b + a


class KafkaExporter(Exporter):
    name = "kafka"

    def __init__(self, producer: KafkaProducer, batch_messages: int = 1000):
        self._producer = producer
        self._batch_messages = batch_messages

    @classmethod
    def from_config(cls, cfg, metrics=None) -> "KafkaExporter":
        sasl = SASLSettings(enable=cfg.kafka_enable_sasl,
                            mechanism=cfg.kafka_sasl_type)
        if sasl.enable:
            sasl.username = _read_secret(cfg.kafka_sasl_client_id_path)
            sasl.password = _read_secret(cfg.kafka_sasl_client_secret_path)
        producer = KafkaProducer(
            brokers=cfg.kafka_brokers, topic=cfg.kafka_topic,
            acks=0 if cfg.kafka_async else 1,
            tls=TLSSettings(
                enable=cfg.kafka_enable_tls,
                insecure_skip_verify=cfg.kafka_tls_insecure_skip_verify,
                ca_path=cfg.kafka_tls_ca_cert_path,
                cert_path=cfg.kafka_tls_user_cert_path,
                key_path=cfg.kafka_tls_user_key_path),
            sasl=sasl, compression=cfg.kafka_compression)
        return cls(producer, batch_messages=cfg.kafka_batch_messages)

    def export_batch(self, records: list[Record]) -> None:
        msgs = [(partition_key(r), record_to_pb(r).SerializeToString())
                for r in records]
        for start in range(0, len(msgs), self._batch_messages):
            self._producer.send_batch(msgs[start:start + self._batch_messages])

    def close(self) -> None:
        self._producer.close()


def _read_secret(path: str) -> str:
    if not path:
        return ""
    with open(path) as fh:
        return fh.read().strip()
