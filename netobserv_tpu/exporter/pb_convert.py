"""Record <-> pbflow protobuf converters.

Reference analog: `pkg/pbflow/proto.go:20-151` (FlowsToPB/FlowToPB/PBToFlow).
"""

from __future__ import annotations

import struct

from netobserv_tpu.model.flow import FlowFeatures, FlowKey, ip_to_16
from netobserv_tpu.model.record import Record
from netobserv_tpu.pb import flow_pb2

V4_PREFIX = b"\x00" * 10 + b"\xff\xff"


def _set_ip(pb_ip: flow_pb2.IP, raw16: bytes) -> None:
    if raw16[:12] == V4_PREFIX:
        pb_ip.ipv4 = struct.unpack(">I", raw16[12:16])[0]
    else:
        pb_ip.ipv6 = raw16


def _get_ip(pb_ip: flow_pb2.IP) -> bytes:
    if pb_ip.WhichOneof("ip_family") == "ipv4":
        return V4_PREFIX + struct.pack(">I", pb_ip.ipv4)
    return bytes(pb_ip.ipv6) if pb_ip.ipv6 else b"\x00" * 16


def _mac_to_u64(mac: bytes) -> int:
    return int.from_bytes(mac[:6], "big")


def _u64_to_mac(v: int) -> bytes:
    return v.to_bytes(8, "big")[2:]


def record_to_pb(r: Record) -> flow_pb2.Record:
    pb = flow_pb2.Record()
    pb.eth_protocol = r.eth_protocol
    pb.direction = (flow_pb2.EGRESS if r.direction == 1 else flow_pb2.INGRESS)
    pb.time_flow_start.FromNanoseconds(r.time_flow_start_ns)
    pb.time_flow_end.FromNanoseconds(r.time_flow_end_ns)
    pb.data_link.src_mac = _mac_to_u64(r.src_mac)
    pb.data_link.dst_mac = _mac_to_u64(r.dst_mac)
    _set_ip(pb.network.src_addr, r.key.src_ip)
    _set_ip(pb.network.dst_addr, r.key.dst_ip)
    pb.network.dscp = r.dscp
    pb.transport.src_port = r.key.src_port
    pb.transport.dst_port = r.key.dst_port
    pb.transport.protocol = r.key.proto
    pb.bytes = r.bytes_
    pb.packets = r.packets
    pb.interface = r.interface
    if r.agent_ip:
        _set_ip(pb.agent_ip, ip_to_16(r.agent_ip))
    pb.flags = r.tcp_flags
    pb.icmp_type = r.key.icmp_type
    pb.icmp_code = r.key.icmp_code
    pb.sampling = r.sampling
    for iface, direction, udn in r.dup_list:
        d = pb.dup_list.add()
        d.interface = iface
        d.direction = (flow_pb2.EGRESS if direction == 1 else flow_pb2.INGRESS)
        d.udn = udn
    f = r.features
    if f.drop_bytes or f.drop_packets:
        pb.pkt_drop_bytes = f.drop_bytes
        pb.pkt_drop_packets = f.drop_packets
        pb.pkt_drop_latest_flags = f.drop_latest_flags
        pb.pkt_drop_latest_state = f.drop_latest_state
        pb.pkt_drop_latest_drop_cause = f.drop_latest_cause
    if f.dns_id or f.dns_latency_ns or f.dns_errno:
        pb.dns_id = f.dns_id
        pb.dns_flags = f.dns_flags
        pb.dns_errno = f.dns_errno
        pb.dns_latency.FromNanoseconds(f.dns_latency_ns)
        pb.dns_name = f.dns_name
    if f.rtt_ns:
        pb.time_flow_rtt.FromNanoseconds(f.rtt_ns)
    from netobserv_tpu.utils.ovn_decoder import decode_event
    for ev in f.network_events:
        ne = pb.network_events_metadata.add()
        for key, val in decode_event(ev).items():
            ne.events[key] = val
    if f.xlat_src_ip:
        _set_ip(pb.xlat.src_addr, f.xlat_src_ip)
        _set_ip(pb.xlat.dst_addr, f.xlat_dst_ip)
        pb.xlat.src_port = f.xlat_src_port
        pb.xlat.dst_port = f.xlat_dst_port
        pb.xlat.zone_id = f.xlat_zone_id
    pb.ipsec_encrypted = int(f.ipsec_encrypted)
    pb.ipsec_encrypted_ret = f.ipsec_encrypted_ret
    pb.ssl_version = r.ssl_version
    pb.ssl_mismatch = r.ssl_mismatch
    pb.tls_types = r.tls_types
    pb.tls_cipher_suite = r.tls_cipher_suite
    pb.tls_key_share = r.tls_key_share
    if f.quic_version or f.quic_seen_long_hdr or f.quic_seen_short_hdr:
        pb.quic.version = f.quic_version
        pb.quic.seen_long_hdr = int(f.quic_seen_long_hdr)
        pb.quic.seen_short_hdr = int(f.quic_seen_short_hdr)
    return pb


def pb_to_record(pb: flow_pb2.Record) -> Record:
    key = FlowKey(
        src_ip=_get_ip(pb.network.src_addr),
        dst_ip=_get_ip(pb.network.dst_addr),
        src_port=pb.transport.src_port, dst_port=pb.transport.dst_port,
        proto=pb.transport.protocol,
        icmp_type=pb.icmp_type, icmp_code=pb.icmp_code)
    f = FlowFeatures(
        dns_id=pb.dns_id, dns_flags=pb.dns_flags,
        dns_latency_ns=pb.dns_latency.ToNanoseconds(),
        dns_errno=pb.dns_errno, dns_name=pb.dns_name,
        drop_bytes=pb.pkt_drop_bytes, drop_packets=pb.pkt_drop_packets,
        drop_latest_flags=pb.pkt_drop_latest_flags,
        drop_latest_state=pb.pkt_drop_latest_state,
        drop_latest_cause=pb.pkt_drop_latest_drop_cause,
        rtt_ns=pb.time_flow_rtt.ToNanoseconds(),
        ipsec_encrypted=bool(pb.ipsec_encrypted),
        ipsec_encrypted_ret=pb.ipsec_encrypted_ret,
        quic_version=pb.quic.version,
        quic_seen_long_hdr=bool(pb.quic.seen_long_hdr),
        quic_seen_short_hdr=bool(pb.quic.seen_short_hdr))
    if pb.HasField("xlat"):
        f.xlat_src_ip = _get_ip(pb.xlat.src_addr)
        f.xlat_dst_ip = _get_ip(pb.xlat.dst_addr)
        f.xlat_src_port = pb.xlat.src_port
        f.xlat_dst_port = pb.xlat.dst_port
        f.xlat_zone_id = pb.xlat.zone_id
    agent_ip = ""
    if pb.HasField("agent_ip"):
        from netobserv_tpu.model.flow import ip_from_16
        agent_ip = ip_from_16(_get_ip(pb.agent_ip))
    return Record(
        key=key, bytes_=pb.bytes, packets=pb.packets,
        eth_protocol=pb.eth_protocol, tcp_flags=pb.flags,
        direction=int(pb.direction),
        src_mac=_u64_to_mac(pb.data_link.src_mac),
        dst_mac=_u64_to_mac(pb.data_link.dst_mac),
        interface=pb.interface,
        dscp=pb.network.dscp, sampling=pb.sampling,
        time_flow_start_ns=pb.time_flow_start.ToNanoseconds(),
        time_flow_end_ns=pb.time_flow_end.ToNanoseconds(),
        agent_ip=agent_ip,
        dup_list=[(d.interface, int(d.direction), d.udn) for d in pb.dup_list],
        features=f,
        ssl_version=pb.ssl_version, ssl_mismatch=pb.ssl_mismatch,
        tls_types=pb.tls_types, tls_cipher_suite=pb.tls_cipher_suite,
        tls_key_share=pb.tls_key_share)


def records_to_pb(records: list[Record]) -> flow_pb2.Records:
    out = flow_pb2.Records()
    out.entries.extend(record_to_pb(r) for r in records)
    return out
