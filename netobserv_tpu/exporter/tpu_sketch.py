"""tpu-sketch exporter: offloads flow aggregation/analytics to JAX/TPU.

The north-star backend (BASELINE.json): record batches arriving at the exporter
seam are packed into fixed-shape columnar tensors, folded on-device into
streaming sketches (Count-Min, HLL, top-K, latency histograms, EWMA), and every
SKETCH_WINDOW seconds a cluster-wide WindowReport is emitted (top-K heavy
hitters with exact keys, cardinalities, latency quantiles, DDoS z-scores).

Multi-chip: when more than one device is visible (or SKETCH_MESH_SHAPE is set)
the state is partitioned over a Mesh and merged over ICI at window roll
(`netobserv_tpu.parallel`). Reports go to a pluggable sink (JSON lines by
default — feed it to Kafka/gRPC by passing a different sink).
"""

from __future__ import annotations

import collections
import json
import logging
import sys
import threading
import time
from typing import Callable, Optional

import numpy as np

from netobserv_tpu.alerts.rules import SIGNAL_FIELDS
from netobserv_tpu.config import (
    DEFAULT_ASYM_MIN_BYTES, DEFAULT_ASYM_RATIO, DEFAULT_CHURN_ASCENT,
    DEFAULT_CHURN_MIN_BYTES, DEFAULT_DDOS_Z, DEFAULT_DROP_Z,
    DEFAULT_SCAN_FANOUT, DEFAULT_SYNFLOOD_MIN, DEFAULT_SYNFLOOD_RATIO,
)
from netobserv_tpu.datapath import flowpack
from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.sketch import staging
from netobserv_tpu.model.columnar import FlowBatch, unpack_key_words
from netobserv_tpu.model.flow import ip_from_16
from netobserv_tpu.model.record import Record
from netobserv_tpu.utils import faultinject, retrace, tracing

log = logging.getLogger("netobserv_tpu.exporter.tpu_sketch")

#: once-per-process dedup of the multi-device SKETCH_TIERED degrade warning
#: (chaos/restart loops rebuild exporters; the queryable truth is the
#: tiered_degraded supervisor condition, not the log line)
_TIERED_DEGRADE_WARNED = False

ReportSink = Callable[[dict], None]


def _default_sink(report: dict) -> None:
    sys.stdout.write(json.dumps(report, separators=(",", ":")) + "\n")
    sys.stdout.flush()


class KafkaReportSink:
    """Publishes window reports as JSON Kafka messages; closeable."""

    def __init__(self, cfg):
        from netobserv_tpu.kafka.producer import (
            KafkaProducer, SASLSettings, TLSSettings,
        )
        sasl = SASLSettings(enable=cfg.kafka_enable_sasl,
                            mechanism=cfg.kafka_sasl_type)
        if sasl.enable:
            from netobserv_tpu.exporter.kafka import _read_secret
            sasl.username = _read_secret(cfg.kafka_sasl_client_id_path)
            sasl.password = _read_secret(cfg.kafka_sasl_client_secret_path)
        self._producer = KafkaProducer(
            brokers=cfg.kafka_brokers, topic=cfg.kafka_topic,
            acks=0 if cfg.kafka_async else 1,
            tls=TLSSettings(
                enable=cfg.kafka_enable_tls,
                insecure_skip_verify=cfg.kafka_tls_insecure_skip_verify,
                ca_path=cfg.kafka_tls_ca_cert_path,
                cert_path=cfg.kafka_tls_user_cert_path,
                key_path=cfg.kafka_tls_user_key_path),
            sasl=sasl, compression=cfg.kafka_compression)

    def __call__(self, report: dict) -> None:
        self._producer.send_batch([
            (b"sketch_report",
             json.dumps(report, separators=(",", ":")).encode())])

    def close(self) -> None:
        self._producer.close()


def make_report_sink(cfg) -> ReportSink:
    """SKETCH_REPORT_SINK switch: stdout JSON lines (default) or Kafka
    (BASELINE config 5: anomaly scores over the Kafka export path)."""
    if cfg.sketch_report_sink == "kafka":
        return KafkaReportSink(cfg)
    if cfg.sketch_report_sink not in ("", "stdout"):
        raise ValueError(
            f"SKETCH_REPORT_SINK={cfg.sketch_report_sink!r} (want stdout|kafka)")
    return _default_sink


def _slot_key_entries(words: np.ndarray, rows) -> list[dict]:
    """Render slot-table rows' packed key words into addr/port dicts, with
    a stable `Key` fingerprint string (the churn alert rules' dedup id)."""
    rows = np.asarray(rows, dtype=np.int64)
    out: list[dict] = []
    if not len(rows):
        return out
    keys = unpack_key_words(words[rows])
    for k in keys:
        src = ip_from_16(k["src_ip"].tobytes())
        dst = ip_from_16(k["dst_ip"].tobytes())
        sp, dp, proto = int(k["src_port"]), int(k["dst_port"]), \
            int(k["proto"])
        out.append({
            "SrcAddr": src, "DstAddr": dst, "SrcPort": sp, "DstPort": dp,
            "Proto": proto,
            "Key": f"{src}:{sp}->{dst}:{dp}/{proto}",
        })
    return out


def heavy_identity_index(report) -> dict:
    """(h1, h2) identity -> rendered key entry of every VALID slot — the
    previous-roll index `report_to_json` diffs against to name EVICTED
    keys (identities that left the table since the last closed window).
    Host-side numpy only; the exporter/aggregator stash one per ROLL."""
    valid = np.asarray(report.heavy.valid)
    rows = np.nonzero(valid)[0]
    h1 = np.asarray(report.heavy.h1)
    h2 = np.asarray(report.heavy.h2)
    counts = np.asarray(report.heavy.counts)
    entries = _slot_key_entries(np.asarray(report.heavy.words), rows)
    out = {}
    for j, i in enumerate(rows):
        e = dict(entries[j])
        e["EstBytes"] = float(counts[i])
        out[(int(h1[i]), int(h2[i]))] = e
    return out


def report_to_json(report, max_heavy: int = 64,
                   scan_fanout_threshold: float = DEFAULT_SCAN_FANOUT,
                   ddos_z_threshold: float = DEFAULT_DDOS_Z,
                   synflood_min: float = DEFAULT_SYNFLOOD_MIN,
                   synflood_ratio: float = DEFAULT_SYNFLOOD_RATIO,
                   drop_z_threshold: float = DEFAULT_DROP_Z,
                   asym_min_bytes: float = DEFAULT_ASYM_MIN_BYTES,
                   asym_ratio: float = DEFAULT_ASYM_RATIO,
                   churn_ascent: float = DEFAULT_CHURN_ASCENT,
                   churn_min_bytes: float = DEFAULT_CHURN_MIN_BYTES,
                   prev_heavy_index: Optional[dict] = None,
                   partial_window: bool = False) -> dict:
    """Render a device WindowReport into a host JSON object.

    The persistent-slot table makes this a per-KEY churn renderer too:
    FlowAscents / FlowDescents / NewHeavyKeys derive from each slot's
    (counts, prev_counts, first_seen) under the `churn_ascent` /
    `churn_min_bytes` gates — the ONE threshold truth the zoo runner and
    the default flow_ascent/new_heavy_key alert rules share (the
    alerts/rules.py one-truth note). `prev_heavy_index` (the previous
    ROLL's `heavy_identity_index`) names EvictedKeys by diffing identity
    sets; without it the list renders empty (first window, refresh-only
    consumers)."""
    words = np.asarray(report.heavy.words)
    valid = np.asarray(report.heavy.valid)
    counts = np.asarray(report.heavy.counts)
    prevs = np.asarray(report.heavy.prev_counts)
    first_seen = np.asarray(report.heavy.first_seen)
    window = int(report.window)
    order = np.argsort(-np.where(valid, counts, -np.inf))[:max_heavy]
    heavy = []
    sel = [i for i in order if valid[i]]
    if sel:
        keys = unpack_key_words(words[sel])
        for j, i in enumerate(sel):
            k = keys[j]
            heavy.append({
                "SrcAddr": ip_from_16(k["src_ip"].tobytes()),
                "DstAddr": ip_from_16(k["dst_ip"].tobytes()),
                "SrcPort": int(k["src_port"]),
                "DstPort": int(k["dst_port"]),
                "Proto": int(k["proto"]),
                "EstBytes": float(counts[i]),
                "PrevEstBytes": float(prevs[i]),
                "FirstSeenWindow": int(first_seen[i]),
            })
    # --- per-key churn (the device-resident heavy-hitter plane) ---
    # ascent: window-over-window growth >= churn_ascent with real current
    # mass; descent: the reciprocal collapse of a previously-heavy key;
    # new: first_seen == this window (gated to window > 0 — in the
    # table's very first window EVERYTHING is new, which is noise, and
    # prev_counts are all zero so ascents are structurally quiet too)
    asc_all = np.nonzero(valid & (prevs > 0)
                         & (counts >= churn_ascent * prevs)
                         & (counts >= churn_min_bytes))[0]
    asc_rows = asc_all[np.argsort(-counts[asc_all])][:32]
    # descents render only for CLOSED windows: a mid-window refresh
    # compares a partial window against a full previous one, so right
    # after a roll EVERY steady incumbent would read as collapsed
    # (ascents have no such problem — a partial count exceeding the full
    # previous window is real growth, and it is what makes detection
    # sub-window)
    desc_all = np.nonzero(valid & (prevs >= churn_min_bytes)
                          & (counts <= prevs / churn_ascent))[0] \
        if not partial_window else np.zeros(0, np.int64)
    desc_rows = desc_all[np.argsort(-prevs[desc_all])][:32]
    new_all = np.nonzero(valid & (first_seen == window)
                         & (counts >= churn_min_bytes))[0] \
        if window > 0 else np.zeros(0, np.int64)
    new_rows = new_all[np.argsort(-counts[new_all])][:32]

    def churn_entries(rows) -> list[dict]:
        out = _slot_key_entries(words, rows)
        for j, i in enumerate(rows):
            out[j].update({
                "EstBytes": float(counts[i]),
                "PrevEstBytes": float(prevs[i]),
                "Ratio": round(float(counts[i] / max(prevs[i], 1.0)), 3),
                "FirstSeenWindow": int(first_seen[i]),
            })
        return out

    evicted_keys: list[dict] = []
    if prev_heavy_index:
        h1a = np.asarray(report.heavy.h1)
        h2a = np.asarray(report.heavy.h2)
        cur_ids = {(int(h1a[i]), int(h2a[i]))
                   for i in np.nonzero(valid)[0]}
        gone = [e for ident, e in prev_heavy_index.items()
                if ident not in cur_ids]
        gone.sort(key=lambda e: -e.get("EstBytes", 0.0))
        evicted_keys = gone[:32]
    # best-effort victim names via the shared query core (the ONE
    # implementation — numpy hash twin under DST_BUCKET_SEED; report
    # rendering must never dispatch a device op)
    from netobserv_tpu.query.core import victim_bucket_names
    n_buckets = np.asarray(report.ddos_z).shape[0]
    dst_bucket_names = victim_bucket_names(
        words[np.asarray(sel, dtype=np.int64)] if sel
        else words[:0], heavy, n_buckets)

    def victims(bucket: int) -> list:
        return dst_bucket_names.get(int(bucket), [])

    z = np.asarray(report.ddos_z)
    suspects = np.nonzero(z > ddos_z_threshold)[0]
    suspects = suspects[np.argsort(-z[suspects])]  # worst first before [:32]
    # port-scan suspects: source buckets whose distinct-(dst addr, dst
    # port) PAIR fan-out this window exceeds the threshold (a scanner
    # touches hundreds+; a normal client a handful)
    fanout = np.asarray(report.per_src_fanout)
    scan = np.argsort(fanout)[::-1]
    scan = scan[fanout[scan] >= scan_fanout_threshold]
    # SYN-flood suspects: victim buckets offered >= synflood_min half-open
    # attempts this window while accepting (SYN-ACKing) at most 1/ratio of
    # them — the offered:accepted asymmetry IS the flood signature
    syn = np.asarray(report.syn_rate)
    synack = np.asarray(report.synack_rate)
    syn_z = np.asarray(report.syn_z)
    flood = np.nonzero((syn >= synflood_min)
                       & (syn >= synflood_ratio * (synack + 1.0)))[0]
    flood = flood[np.argsort(-syn[flood])]
    drop_z = np.asarray(report.drop_z)
    drop_anom = np.nonzero(drop_z > drop_z_threshold)[0]
    drop_anom = drop_anom[np.argsort(-drop_z[drop_anom])]  # worst first
    causes = np.asarray(report.drop_causes)
    cause_idx = np.nonzero(causes > 0)[0]
    cause_idx = cause_idx[np.argsort(-causes[cause_idx])][:16]
    from netobserv_tpu.utils.drop_reasons import drop_reason_name

    def cause_name(c: int) -> str:
        # live-kernel mapping first (the static reference table mislabels
        # on newer kernels — utils/drop_reasons.py); the histogram's last
        # bucket catches saturated/subsystem reasons (state.py N_DROP_CAUSES)
        if c == causes.shape[0] - 1:
            return "OTHER_OR_SUBSYSTEM"
        return drop_reason_name(int(c))
    # one-way conversations: pair buckets over the volume floor whose
    # byte share in one direction exceeds the ratio (exfil / UDP-flood
    # shape; a healthy TCP transfer still carries ~3-5% ACK backflow)
    fwd = np.asarray(report.conv_fwd)
    rev = np.asarray(report.conv_rev)
    conv_total = fwd + rev
    one_way_share = np.maximum(fwd, rev) / np.maximum(conv_total, 1.0)
    asym = np.nonzero((conv_total >= asym_min_bytes)
                      & (one_way_share >= asym_ratio))[0]
    asym = asym[np.argsort(-conv_total[asym])]
    dscp = np.asarray(report.dscp_bytes)
    dscp_idx = np.nonzero(dscp > 0)[0]

    def dscp_name(c: int) -> str:
        # RFC 2474/2597/3246 codepoints (stable, unlike the kernel enums);
        # unnamed codepoints print numerically
        if c == 46:
            return "EF"
        if c == 44:
            return "VOICE-ADMIT"
        if c % 8 == 0:
            return f"CS{c // 8}"
        afc, afd = c // 8, (c % 8) // 2
        if 1 <= afc <= 4 and 1 <= afd <= 3 and c % 2 == 0:
            return f"AF{afc}{afd}"
        return str(c)
    qs = [0.5, 0.9, 0.95, 0.99, 0.999]
    return {
        "Type": "sketch_window_report",
        "Window": int(report.window),
        "Records": float(report.total_records),
        "Bytes": float(report.total_bytes),
        "DistinctSrcEstimate": float(report.distinct_src),
        "DropBytes": float(report.total_drop_bytes),
        "DropPackets": float(report.total_drop_packets),
        "QuicRecords": float(report.quic_records),
        "NatRecords": float(report.nat_records),
        "HeavyHitters": heavy,
        "RttQuantilesUs": {str(q): float(v) for q, v in zip(
            qs, np.asarray(report.rtt_quantiles_us))},
        "DnsLatencyQuantilesUs": {str(q): float(v) for q, v in zip(
            qs, np.asarray(report.dns_quantiles_us))},
        "DdosSuspectBuckets": [
            {"bucket": int(b), "z": float(z[b]),
             "probable_victims": victims(b)} for b in suspects[:32]],
        "PortScanSuspectBuckets": [
            {"bucket": int(b), "distinct_dst_port_pairs": float(fanout[b])}
            for b in scan[:32]],
        "SynFloodSuspectBuckets": [
            {"bucket": int(b), "syn": float(syn[b]),
             "synack": float(synack[b]), "z": float(syn_z[b]),
             "probable_victims": victims(b)}
            for b in flood[:32]],
        "DropAnomalyBuckets": [
            {"bucket": int(b), "z": float(drop_z[b]),
             "probable_victims": victims(b)}
            for b in drop_anom[:32]],
        "AsymmetricConversationBuckets": [
            {"bucket": int(b), "bytes": float(conv_total[b]),
             "one_way_share": round(float(one_way_share[b]), 4)}
            for b in asym[:32]],
        "DropCauses": {str(int(c)): float(causes[c]) for c in cause_idx},
        "DropCauseNames": {cause_name(int(c)): float(causes[c])
                           for c in cause_idx},
        "DscpBytes": {str(int(d)): float(dscp[d]) for d in dscp_idx},
        "DscpClassBytes": {dscp_name(int(d)): float(dscp[d])
                           for d in dscp_idx},
        "FlowAscents": churn_entries(asc_rows),
        "FlowDescents": churn_entries(desc_rows),
        "NewHeavyKeys": churn_entries(new_rows),
        "EvictedKeys": evicted_keys,
        "HeavyChurn": {
            "ascents": int(len(asc_all)),
            "descents": int(len(desc_all)),
            "new": int(len(new_all)),
            "evictions": float(report.heavy_evictions),
            "tracked": int(valid.sum()),
        },
    }


class TpuSketchExporter(Exporter):
    name = "tpu-sketch"
    supports_columnar = True

    def __init__(self, batch_size: int = 8192, window_s: float = 60.0,
                 sketch_cfg=None, mesh_shape: str = "", devices: str = "",
                 sink: Optional[ReportSink] = None, metrics=None,
                 checkpoint_dir: str = "", checkpoint_every: int = 0,
                 decay_factor: Optional[float] = None,
                 scan_fanout_threshold: float = DEFAULT_SCAN_FANOUT,
                 ddos_z_threshold: float = DEFAULT_DDOS_Z,
                 synflood_min: float = DEFAULT_SYNFLOOD_MIN,
                 synflood_ratio: float = DEFAULT_SYNFLOOD_RATIO,
                 drop_z_threshold: float = DEFAULT_DROP_Z,
                 pack_threads: int = 1,
                 pack_threads_explicit: bool = True,
                 asym_min_bytes: float = DEFAULT_ASYM_MIN_BYTES,
                 asym_ratio: float = DEFAULT_ASYM_RATIO,
                 feed: str = "resident",
                 resident_slots: int = 1 << 18,
                 superbatch: tuple = (1,),
                 warm_ladder: bool = False,
                 delta_sink=None,
                 agent_id: str = "",
                 shed_watermark: float = 0.0,
                 shed_max: int = 64,
                 shed_slot_budget_s: float = 30.0,
                 shed_seed: int = 2026,
                 query_refresh_s: float = 0.0,
                 overlap_depth: int = 0,
                 query_history: int = 0,
                 alerts=None,
                 archive=None,
                 churn_ascent: float = DEFAULT_CHURN_ASCENT,
                 churn_min_bytes: float = DEFAULT_CHURN_MIN_BYTES,
                 tenants: int = 0):
        # superbatch defaults to NO ladder for direct construction: the
        # ladder costs superbatch_max-sized ring buffers, dictionaries and
        # key-table rows up front, and only pays off once warmed — the
        # production entry (`from_config`) passes the SKETCH_SUPERBATCH
        # ladder AND warms it; embedders opting in should do the same
        # jax-importing modules are pulled in lazily so the host agent can run
        # exporter-free on machines without accelerators
        from netobserv_tpu.sketch import state as sk

        self._sk = sk
        self._batch_size = batch_size
        self._window_s = window_s
        self._cfg = sketch_cfg or sk.SketchConfig()
        self._sink = sink or _default_sink
        self._scan_fanout = scan_fanout_threshold
        self._ddos_z = ddos_z_threshold
        self._synflood_min = synflood_min
        self._synflood_ratio = synflood_ratio
        self._drop_z = drop_z_threshold
        self._asym_min_bytes = asym_min_bytes
        self._asym_ratio = asym_ratio
        self._churn_ascent = churn_ascent
        self._churn_min_bytes = churn_min_bytes
        # previous ROLL's heavy identity index (EvictedKeys diff source):
        # updated only at closed-window renders — a mid-window refresh
        # diffs against the same last-closed window, never against itself
        self._prev_heavy_index: Optional[dict] = None
        #: tenant-mode twin of _prev_heavy_index: one slot per tenant (the
        #: EvictedKeys diff is per tenant plane — cross-tenant diffs would
        #: read every routed key as churned)
        self._tenant_prev_heavy: dict[int, Optional[dict]] = {}
        self._metrics = metrics
        # federation delta export (federation/delta.py): snapshot the
        # mergeable tables at roll, frame + push them on the timer thread
        self._delta_sink = delta_sink
        if agent_id:
            self._agent_id = agent_id
        else:
            import socket
            self._agent_id = socket.gethostname()
        # idempotent-delivery identity (wire v2): the epoch marks THIS
        # process incarnation (monotonic across restarts), so a restarted
        # agent's reset window counter re-registers as a fresh epoch at
        # the aggregator instead of reading as a flood of stale frames
        self._agent_epoch = time.time_ns()
        # fleet-telemetry block (frames' optional AgentTelemetry): every
        # value here is already computed elsewhere — the block is assembled
        # once per PUBLISH on the timer thread, never on the fold path.
        # _map_occupancy is a single float store per DRAIN
        # (note_map_occupancy, wired through MapTracer's occupancy sink).
        self._windows_published = 0
        self._host_rate_ewma = 0.0
        self._last_publish_mono: Optional[float] = None
        self._map_occupancy = 0.0
        if self._delta_sink is not None and decay_factor is not None:
            # decayed tables are CUMULATIVE (sliding window): pushing them
            # per window would double-count every prior window's mass at
            # the aggregator, whose merge assumes per-window deltas
            log.warning("federation delta export requires "
                        "SKETCH_WINDOW_MODE=reset (decay frames are "
                        "cumulative); disabling delta export")
            self._drop_delta_sink()
        if metrics is not None:
            # retrace alarms and span histograms land in THIS agent's
            # registry (module-level binding: one facade per process in
            # production; tests rebind freely)
            retrace.set_metrics(metrics)
            tracing.set_metrics(metrics)
        #: batch trace (flight recorder) riding the pending buffer: the
        #: first sampled eviction's trace is finished by the fold that
        #: consumes its rows
        self._pending_trace = None
        # resident pack LANES cost per-lane device key tables and only pay
        # off where parallel dictionary probes actually scale: engage them
        # for an EXPLICIT SKETCH_PACK_THREADS (the operator chose), but an
        # auto-resolved count only on hosts with enough cores (a 2-vCPU
        # box measures ~30% SLOWER with 2 lanes — docs/tpu_sketch.md)
        import os as _os
        self._lane_threads = pack_threads if (
            pack_threads_explicit or (_os.cpu_count() or 1) >= 4) else 1
        #: superbatch fold ladder (SKETCH_SUPERBATCH): queued evictions
        #: coalesce into the largest fitting k*batch superbatch and fold as
        #: ONE fixed-shape dispatch from a per-k pre-built jit
        #: (sketch/staging.py ladder; docs/tpu_sketch.md)
        self._superbatch = tuple(sorted({int(k) for k in (superbatch
                                                          or (1,))}))
        if self._superbatch[0] != 1:
            raise ValueError("superbatch ladder must include 1")
        self._lock = threading.Lock()
        # serializes CALLS into the roll executable (dispatch only — the
        # device work stays async): the window close and the mid-window
        # refresh run on different threads (with SKETCH_OVERLAP the fold
        # worker closes windows too), and two threads first-tracing the
        # same jit double-compile — a spurious post-warmup retrace alarm,
        # found live. After the first compile this is an uncontended
        # microsecond hold around a cache hit.
        self._roll_mutex = threading.Lock()
        # created BEFORE anything that spawns a background thread: the
        # ladder-warm thread polls _closed between compiles, and a warm
        # kicked off mid-__init__ must never race the attribute into
        # existence (observed live as an AttributeError killing the warm)
        self._closed = threading.Event()
        self._pending: list[Record] = []
        # rolled-but-unpublished device-side WindowReports, queued under
        # self._lock, rendered+delivered by the window-timer thread OUTSIDE
        # it — folds never wait on report_to_json or a sink. Bounded: a
        # sink that wedges forever must not pin an ever-growing set of
        # device reports (drops are counted in _roll_locked). State is
        # deliberately NOT queued with the report — later folds donate it.
        self._reports: collections.deque = collections.deque()
        self._max_queued_reports = 8
        self._publish_lock = threading.Lock()
        self._window_deadline = time.monotonic() + window_s
        self._n_windows_saved = 0
        # distributed init MUST precede anything that touches the JAX backend
        # (including orbax CheckpointManager construction)
        from netobserv_tpu.parallel.distributed import (
            maybe_initialize_distributed,
        )
        maybe_initialize_distributed()

        self._ckpt = None
        self._ckpt_every = checkpoint_every
        if checkpoint_dir:
            from netobserv_tpu.sketch.checkpoint import SketchCheckpointer
            self._ckpt = SketchCheckpointer(checkpoint_dir)

        import jax
        devs = jax.devices()
        self._distributed = len(devs) > 1 or ("x" in mesh_shape)
        #: multi-tenant sketch stack (SKETCH_TENANTS, sketch/tenancy.py):
        #: N tenant states on a leading axis, ONE vmapped dispatch folds
        #: every tenant's evictions. None (unset) keeps every path
        #: bit-identical — no stack object, one is-None check.
        self._tenancy = None
        if tenants and self._distributed:
            # no mesh-sharded stacked form yet (config.validate blocks the
            # env combination; direct construction degrades gracefully —
            # the SKETCH_TIERED pattern)
            log.warning("SKETCH_TENANTS has no mesh-sharded form; running "
                        "the mesh exporter single-tenant")
            tenants = 0
        #: True when SKETCH_TIERED was requested but degraded away (the
        #: mesh has no sharded tier form) — surfaced as a supervisor
        #: CONDITION so /healthz shows WHY resident memory is wide
        self._tiered_degraded = False
        if self._distributed and self._cfg.tiered is not None:
            # no owner-sharded tier form yet (config.validate blocks the
            # env combination; direct construction degrades gracefully —
            # exporters never crash the pipeline). The warning dedupes to
            # once per PROCESS (exporters are rebuilt on restart/chaos
            # loops; the log line is informational, the health condition
            # below is the queryable truth)
            global _TIERED_DEGRADE_WARNED
            if not _TIERED_DEGRADE_WARNED:
                _TIERED_DEGRADE_WARNED = True
                log.warning("SKETCH_TIERED has no sharded form; running the "
                            "mesh exporter with wide-resident tables")
            self._tiered_degraded = True
            self._cfg = self._cfg._replace(tiered=None)
        #: which tiered fold form this backend engages ("interior" |
        #: "decode" | None) — the /debug/executables + bench attribution
        #: for every watched ingest/roll entry (one program each, never
        #: hidden variants). Rolls always ride the wide decode.
        self._tier_form = sk.tiered_fold_form(self._cfg)
        self._tier_roll_form = "decode" if self._cfg.tiered else None
        #: previous closed-window promoted-counter masks, per CM table —
        #: the tier-promotions counter increments by NEW promotions only
        #: (host bools, timer thread; see _publish_tier_metrics). Masks
        #: are only kept when promotions PERSIST across windows (decay
        #: mode); reset mode starts every window from fresh planes, so
        #: there occupancy IS the window's new-promotion count.
        self._tier_prev_promoted: dict = {}
        self._tier_sticky_promotions = decay_factor is not None
        #: jitted decode-to-wide for checkpoint saves (tiered mode only —
        #: checkpoints keep the canonical wide SketchState layout, so the
        #: format/version stamp never moves with the resident
        #: representation). Built lazily, retrace-watched like every
        #: jitted entry the exporter constructs.
        self._tiered_decode = None
        if self._distributed:
            from netobserv_tpu.parallel import (
                MeshSpec, make_mesh, merge as pmerge)
            spec = MeshSpec.parse(mesh_shape, len(devs))
            self._mesh = make_mesh(spec)
            self._ndata = spec.data
            # fixed batch shape must split evenly over the data axis
            self._batch_size = -(-self._batch_size // spec.data) * spec.data
            self._pm = pmerge
            self._state = pmerge.init_dist_state(self._cfg, self._mesh)
            self._ingest = pmerge.make_sharded_ingest_fn(self._mesh, self._cfg)
            ingest_dense = pmerge.make_sharded_ingest_fn(
                self._mesh, self._cfg, dense=True, with_token=True)
            dense_put = lambda buf: pmerge.shard_dense(  # noqa: E731
                self._mesh, buf)
            if self._delta_sink is not None and spec.sketch > 1:
                # width-sharded CM planes are independent local-width
                # sketches — there is no whole-width snapshot to frame
                # (parallel/merge.py make_merge_fn with_tables contract)
                log.warning("federation delta export needs a data-axis-only "
                            "mesh; disabling it on this %dx%d exporter",
                            spec.data, spec.sketch)
                self._drop_delta_sink()
            # the query plane (and the delta export) need the merged
            # whole-width table snapshot; it exists only on data-axis-only
            # meshes — width-sharded CM planes are independent local-width
            # sketches (parallel/merge.py make_merge_fn contract). Without
            # tables the /query/frequency route answers 503; the
            # report-backed routes still serve.
            self._with_tables = spec.sketch == 1
            self._roll = pmerge.make_merge_fn(
                self._mesh, self._cfg, decay_factor=decay_factor,
                with_tables=self._with_tables)
            if feed == "resident":
                # resident feed over the mesh: per-data-shard dictionaries
                # + device key tables (~15B/record instead of dense's 80;
                # lookups stay shard-local — no collectives added). When
                # pack threads outnumber the data shards, each shard's rows
                # additionally split into pack LANES so every thread gets
                # its own dictionary+region (host-pack parallelism beyond
                # the mesh width)
                bps = self._batch_size // spec.data
                lanes = staging.pick_lanes(
                    bps, max(1, self._lane_threads // spec.data))
                bpl = bps // lanes
                caps = flowpack.default_resident_caps(bpl)
                ladder = self._superbatch
                ingests = {
                    k: pmerge.make_sharded_ingest_resident_fn(
                        self._mesh, self._cfg, bpl, caps, lanes=k * lanes,
                        watch_name=f"sharded_ingest_resident_x{k}")
                    for k in ladder}
                self._ring = staging.ShardedResidentStagingRing(
                    self._batch_size, spec.data, ingests,
                    key_tables=pmerge.init_resident_tables(
                        self._mesh, resident_slots,
                        lanes=max(ladder) * lanes),
                    put=dense_put,
                    caps=caps, slot_cap=resident_slots, metrics=metrics,
                    pack_threads=pack_threads, lanes=lanes, ladder=ladder,
                    lazy_ladder=True)
            else:
                if feed == "compact":
                    log.info("SKETCH_FEED=compact has no sharded form "
                             "(spill compaction breaks the row split); "
                             "using dense")
                elif feed != "dense":
                    log.warning("unknown SKETCH_FEED %r; using dense", feed)
                # dense: full-width rows, row-sharded over the data axis
                self._ring = staging.DenseStagingRing(
                    self._batch_size, ingest_dense, put=dense_put,
                    metrics=metrics, pack_threads=pack_threads)
        elif tenants:
            from netobserv_tpu.sketch import tenancy
            self._ndata = 1
            self._tenancy = tenancy.TenantStack(
                tenants, self._cfg, self._batch_size, metrics=metrics,
                decay_factor=decay_factor)
            self._state = tenancy.init_stacked_state(self._cfg, tenants)
            # the Record path routes through the stack's fold_rows; there
            # is no separate unstacked ingest entry to dispatch
            self._ingest = None
            self._with_tables = True
            # ONE stacked roll closes every tenant's window; _roll_locked
            # drives it through the same (state, report, tables) contract
            self._roll = self._tenancy.roll
            self._ring = self._tenancy
            if feed != "dense":
                log.info("tenant mode ships the dense stacked feed; "
                         "SKETCH_FEED=%r does not apply", feed)
        else:
            self._ndata = 1
            self._state = sk.init_state(self._cfg)
            # retrace watchdog: every jitted entry point the exporter can
            # dispatch is watched — its first compile is warmup, any later
            # compile alarms (sketch_retraces_total{fn=...})
            self._ingest = retrace.watch(sk.make_ingest_fn(
                use_pallas=self._cfg.use_pallas,
                enable_fanout=self._cfg.enable_fanout,
                enable_asym=self._cfg.enable_asym), "ingest",
                tiered=self._tier_form)
            # with_tables unconditionally: the pre-roll table snapshot is
            # one extra output of the same roll executable, and it feeds
            # BOTH the federation delta export and the query plane's
            # per-roll snapshot (/query/frequency needs the CM planes)
            self._with_tables = True
            self._roll = retrace.watch(
                sk.make_roll_fn(self._cfg, decay_factor=decay_factor,
                                with_tables=True),
                "roll", tiered=self._tier_roll_form)
            self._ring = self._make_single_device_ring(
                feed, resident_slots, pack_threads, metrics)
        if self._tenancy is not None and self._ckpt is not None:
            # no stacked-tenant checkpoint layout yet: a wide-era restore
            # into the (N, ...) stack (or vice versa) would tear — refuse
            # with a warning rather than save state a future single-tenant
            # agent restores corrupt (the SKETCH_TIERED degradation rule)
            log.warning("sketch checkpointing has no stacked-tenant form; "
                        "disabling it while SKETCH_TENANTS is set")
            self._ckpt.close()
            self._ckpt = None
        # zero-concat eviction accumulator (columnar fast path): rows copy
        # once into a preallocated rolling buffer instead of per-fold
        # np.concatenate over events + five feature lanes. Sized for the
        # ring's superbatch ladder: queued evictions coalesce up to
        # superbatch_max batches and fold as ONE ladder dispatch (window
        # close always flushes, so nothing waits past the window)
        self._pending_buf = staging.PendingEventBuffer(
            self._batch_size, getattr(self._ring, "superbatch_max", 1),
            metrics=metrics)
        # overload control plane (sketch/overload.py): admission control at
        # the export_evicted seam. Disabled (the default), _overload is None
        # and the shed path is one is-None check — bit-identical to the
        # unshedded exporter (no RNG, no copies). Enabled, the ring's slot
        # wait is also bounded so a wedged device drops batches (counted)
        # instead of wedging the eviction feed.
        from netobserv_tpu.sketch import overload
        self._overload = overload.maybe_controller(
            self._batch_size, shed_watermark, shed_max, metrics=metrics,
            seed=shed_seed)
        if self._overload is not None:
            self._ring.slot_wait_budget_s = shed_slot_budget_s
        # fold-duty tracking for the controller's busy weight (the depth
        # term of the pressure score only counts when the seam actually
        # spends its wall clock folding — sketch/overload.py docstring);
        # touched only when the controller exists
        self._busy_fold_s = 0.0
        self._busy_last_t: Optional[float] = None
        self._busy_ewma = 0.0
        # query plane (netobserv_tpu/query): the roll's table snapshot +
        # rendered report publish as this agent's queryable view at every
        # window close; /query/* on the metrics server reads ONLY this
        # (off the hot path, the /debug/traces rules). The optional
        # mid-window refresh (SKETCH_QUERY_REFRESH) re-runs the existing
        # roll executable on the timer thread WITHOUT adopting its state —
        # no new jitted entry, so the refresh can never retrace.
        from netobserv_tpu.query import QueryRoutes, SnapshotPublisher
        self.query = SnapshotPublisher(history=query_history)
        #: tenant-mode query plane: one publisher per tenant — every data
        #: route resolves ?tenant= to its publisher (query/routes.py); the
        #: shared `self.query` slot stays unused so no route can serve one
        #: tenant's estimates as another's
        self._tenant_query = (
            [SnapshotPublisher(history=query_history)
             for _ in range(tenants)] if self._tenancy is not None else None)
        # continuous detection plane (netobserv_tpu/alerts): the engine
        # rides EVERY snapshot publish (roll + mid-window refresh) on the
        # timer thread — host-only, no new jit, nothing on the fold path.
        # None (ALERT_RULES unset) keeps the publish path bit-identical:
        # one is-None check, no engine object (the zero-cost bar).
        self._alerts = alerts
        # sketch warehouse (netobserv_tpu/archive): each closed window's
        # table snapshot lands as an on-disk segment at publish time
        # (timer thread, own try, sketch.archive_write fault point) and
        # /query/range merges archived segments on demand. None
        # (ARCHIVE_DIR unset) keeps the publish path bit-identical: no
        # store, no engine, one is-None check (the zero-cost bar).
        if archive is not None and not self._with_tables:
            # width-sharded meshes have no whole-width table snapshot to
            # archive (the same contract that disables the delta export)
            log.warning("sketch archive needs a data-axis-only mesh; "
                        "disabling it on this exporter")
            archive = None
        if self._tenancy is not None and archive is not None and \
                not hasattr(archive, "write_tenant_window"):
            # tenant segments must land in per-tenant stores (mixing them
            # would merge tenants at range-query time); from_config builds
            # the set — a direct single-store archive degrades off
            log.warning("tenant mode needs a per-tenant archive set "
                        "(archive.tenant_archives); disabling the archive "
                        "on this exporter")
            archive = None
        self._archive = archive
        self.query_routes = QueryRoutes(self.query.get, self.query_status,
                                        metrics=metrics,
                                        history_fn=self.query.get_window,
                                        windows_fn=self.query.windows,
                                        alerts=alerts,
                                        archive=archive,
                                        tenant_publishers=self._tenant_query)
        if metrics is not None:
            if self._tenant_query is not None:
                # freshness = the most recent tenant publish (all tenants
                # publish together at roll; a refresh updates all of them)
                pubs = self._tenant_query
                metrics.query_snapshot_age_seconds.set_function(
                    lambda: min(p.age_s() for p in pubs))
            else:
                metrics.query_snapshot_age_seconds.set_function(
                    self.query.age_s)
        self._query_refresh_s = query_refresh_s
        if query_refresh_s and jax.process_count() > 1:
            # each process's timer would dispatch the roll's collectives on
            # its own schedule — divergent collective order across
            # processes is a hang, not a feature
            log.warning("SKETCH_QUERY_REFRESH disabled on multi-process "
                        "meshes (refresh rolls would run collectives on "
                        "unsynchronized timers)")
            self._query_refresh_s = 0.0
        self._next_refresh = (time.monotonic() + self._query_refresh_s
                              if self._query_refresh_s else None)
        if metrics is not None:
            # resident sketch-state footprint (shape math, no transfer):
            # the capacity story SKETCH_TIERED buys — several windows/
            # tenants resident per HBM — made visible per agent
            from netobserv_tpu.sketch.tiered import array_bytes
            metrics.sketch_resident_hbm_bytes.set(array_bytes(self._state))
        if warm_ladder:
            self.warm_superbatch_ladder()
        # the staging ring packs the next batch while the previous
        # transfers/ingests are in flight; its slot-reuse tokens also bound
        # the async dispatch queue to the ring depth, so sustained overload
        # backpressures the eviction loop (see sketch/staging.py)
        # restore prior sketch state if a checkpoint exists; an
        # incompatible checkpoint (layout change across an upgrade, e.g.
        # the owner-sharded top-K gaining a sketch-axis dim) must degrade
        # to a fresh window, not kill the agent (exporters never crash the
        # pipeline — CLAUDE.md invariant)
        if self._ckpt is not None and self._ckpt.latest_step() is not None:
            try:
                if self._cfg.tiered is not None:
                    # checkpoints are WIDE (steady-state tiers never reach
                    # disk): restore into the wide layout, then encode —
                    # a wide-era checkpoint restores into a tiered agent
                    # and vice versa, no format bump
                    from netobserv_tpu.sketch import tiered as sk_tiered
                    wide = self._ckpt.restore(self._sk.init_state(
                        self._cfg._replace(tiered=None)))
                    self._state = sk_tiered.encode_state(
                        wide, self._cfg.tiered)
                else:
                    self._state = self._ckpt.restore(self._state)
                log.info("restored sketch state from checkpoint step %s",
                         self._ckpt.latest_step())
            except Exception as exc:
                log.warning(
                    "sketch checkpoint at step %s is incompatible with this "
                    "version (%s); starting from a fresh window",
                    self._ckpt.latest_step(), exc)
        # idle-window timer: reports keep flowing even when no batches arrive
        #: supervision hook for the window timer (agent/supervisor.py)
        self.heartbeat = lambda: None
        self._timer: Optional[threading.Thread] = None
        # overlapped eviction dispatch (SKETCH_OVERLAP): with a depth, the
        # admit/buffer/fold work moves to a dedicated supervised fold
        # thread behind a bounded handoff, so the eviction feed's next
        # drain overlaps this batch's pack/dispatch (classic double buffer
        # at depth 1). A full handoff BLOCKS export_evicted — the same
        # feed backpressure as the synchronous seam, one batch deeper.
        # Disabled (depth 0, the default): no queue, no thread, one
        # is-None check — export_evicted is bit-identical to the
        # synchronous exporter.
        self._handoff = None
        self._inflight_rows = 0  # rows put but not yet picked up
        self._inflight_lock = threading.Lock()
        # fused-pipeline pack surface (EVICT_NATIVE_PIPELINE): built on
        # demand by resident_pack_surface(); None keeps every fold path
        # bit-identical (one is-None check)
        self._pack_surface: Optional[staging.ResidentPackSurface] = None
        self.fold_heartbeat = lambda: None
        self._fold_thread: Optional[threading.Thread] = None
        if overlap_depth > 0:
            import queue as _queue
            self._handoff = _queue.Queue(maxsize=overlap_depth)
            self._start_fold_worker()
        self.start_window_timer()

    def warm_superbatch_ladder(self, block: bool = False) -> None:
        """Compile every superbatch ladder entry ahead of traffic, against
        THROWAWAY zero state/tables of identical shapes (the compile cache
        keys on shapes, so the first real superbatch hits a warm
        executable instead of stalling mid-traffic on a multi-second
        compile). Runs on a background thread by default — agent startup
        isn't serialized behind the ladder — and counts as each watched
        entry's warmup call, so the no-retrace alarm stays armed.

        The exporter's ring is built `lazy_ladder`: entries beyond 1x only
        become SELECTABLE here, as each compile lands (`ring.mark_warm`) —
        an unwarmed exporter folds 1x forever rather than ever paying a
        ladder compile inside a live `export_evicted`.

        MULTI-PROCESS meshes warm synchronously regardless of `block`:
        every process must select the same ladder k for the same fold (the
        sharded ingest is one SPMD program — divergent k means divergent
        global computations and a collective hang), so availability must
        flip deterministically: all entries warmed, in ladder order, on
        every process, before any process serves traffic."""
        ring = self._ring
        if not isinstance(ring, staging.ShardedResidentStagingRing):
            return  # dense/compact feeds have no ladder (docs/tpu_sketch.md)
        import jax
        multiprocess = jax.process_count() > 1
        if multiprocess:
            block = True

        def _warm() -> None:
            import jax
            for k in ring.ladder:
                if self._closed.is_set():
                    return  # shutting down: stop compiling, exit promptly
                if k in ring._available:
                    # already selectable (k=1, or a prior warm): live folds
                    # may be tracing it RIGHT NOW — a concurrent duplicate
                    # first-trace here would fire a spurious post-warmup
                    # retrace alarm, for zero benefit
                    continue
                try:
                    if self._distributed:
                        state = self._pm.init_dist_state(self._cfg,
                                                         self._mesh)
                        tables = self._pm.init_resident_tables(
                            self._mesh, ring.slot_cap,
                            lanes=ring.superbatch_max * ring.lanes)
                    else:
                        state = self._sk.init_state(self._cfg)
                        tables = jax.device_put(self._sk.init_key_tables(
                            ring.superbatch_max * ring.lanes, ring.slot_cap))
                    nr = ring.n_shards * k * ring.lanes
                    flat = np.zeros(nr * ring._region_words, np.uint32)
                    out = ring._ingests[k](state, tables, ring._put(flat))
                    jax.block_until_ready(out[2])
                    ring.mark_warm(k)
                except Exception as exc:
                    if multiprocess:
                        # divergent availability across processes means
                        # divergent SPMD programs later — fail the startup
                        # loudly instead of hanging a collective mid-run
                        raise
                    # single process: warm is best-effort, never fatal
                    log.warning("superbatch ladder warm (k=%d) failed: %s",
                                k, exc)

        if block:
            _warm()
        else:
            self._warm_thread = threading.Thread(
                target=_warm, name="sketch-ladder-warm", daemon=True)
            self._warm_thread.start()

    def _drop_delta_sink(self) -> None:
        """Disable delta export, CLOSING the sink (from_config already
        opened its gRPC channel — dropping the reference would leak it)."""
        sink_close = getattr(self._delta_sink, "close", None)
        if sink_close is not None:
            sink_close()
        self._delta_sink = None

    @property
    def _window_poll_s(self) -> float:
        """Window timer wakeup period — the ONE definition; the heartbeat
        deadline in register_supervised rides on top of it."""
        return min(1.0, self._window_s / 10)

    def start_window_timer(self) -> None:
        """(Re)start the idle-window timer thread; the supervisor uses this
        as the sketch-window stage's restart callable."""
        self._timer = threading.Thread(
            target=self._window_loop, name="sketch-window", daemon=True)
        self._timer.start()

    def register_supervised(self, supervisor, heartbeat_timeout_s=None,
                            **kwargs) -> None:
        """Register the window timer with the agent's supervisor. The
        heartbeat deadline rides on top of the timer's own poll period."""
        beat = supervisor.register(
            "sketch-window", restart=self.start_window_timer,
            thread_getter=lambda: self._timer,
            heartbeat_timeout_s=(heartbeat_timeout_s or 10.0)
            + self._window_poll_s,
            **kwargs)
        self.heartbeat = beat
        # the OVERLOADED condition rides the supervisor's condition
        # registry so /healthz + /readyz surface it next to (and distinct
        # from) DEGRADED — shedding is deliberate graceful degradation,
        # not a dead stage
        # getattr: timer-only harnesses (tests) build the exporter via
        # __new__ and register just the window timer
        ctl = getattr(self, "_overload", None)
        if ctl is not None and hasattr(supervisor, "register_condition"):
            supervisor.register_condition(
                "overloaded",
                lambda: {"active": ctl.overloaded, **ctl.snapshot()})
        # the ALERTING condition is OVERLOADED's sibling: a raised alert
        # is the detection plane doing its job, not a failing stage —
        # /readyz stays 200 (conditions never gate readiness)
        eng = getattr(self, "_alerts", None)
        if eng is not None and hasattr(supervisor, "register_condition"):
            supervisor.register_condition("alerting", eng.condition)
        # tiered_degraded: SKETCH_TIERED was requested but the mesh has no
        # sharded tier form — /healthz shows WHY resident memory is wide.
        # A condition, never DEGRADED: the exporter made a deliberate,
        # documented fallback; readiness is untouched.
        if (getattr(self, "_tiered_degraded", False)
                and hasattr(supervisor, "register_condition")):
            supervisor.register_condition(
                "tiered_degraded",
                lambda: {"active": True,
                         "reason": "SKETCH_TIERED has no sharded form; "
                                   "resident tables are wide"})
        # the overlap fold worker is a pipeline stage like any other: a
        # crash/hang restarts it (the handoff queue survives the restart,
        # so queued evictions still fold)
        if getattr(self, "_handoff", None) is not None:
            self.fold_heartbeat = supervisor.register(
                "sketch-fold", restart=self._start_fold_worker,
                thread_getter=lambda: self._fold_thread,
                heartbeat_timeout_s=(heartbeat_timeout_s or 10.0) + 0.2,
                **kwargs)

    @classmethod
    def from_config(cls, cfg, metrics=None, sink=None):
        from netobserv_tpu.alerts import maybe_engine
        from netobserv_tpu.archive import maybe_archive
        from netobserv_tpu.sketch.state import SketchConfig
        if sink is None:
            sink = make_report_sink(cfg)
        delta_sink = None
        if cfg.federation_target:
            from netobserv_tpu.exporter.federation import FederationDeltaSink
            host, _, port = cfg.federation_target.rpartition(":")
            delta_sink = FederationDeltaSink(host or "127.0.0.1", int(port),
                                             metrics=metrics)
        sketch_cfg = SketchConfig.from_agent_config(cfg)
        archive = None
        if cfg.archive_dir:
            # width-sharded meshes ("DxS", S > 1) have no whole-width
            # table snapshot to archive — decide from the SHAPE STRING
            # alone (touching jax.devices() here would race the
            # distributed init the constructor performs) and skip the
            # store construction entirely: opening a store scans, heals
            # and rewrites the manifest, side effects a discarded
            # feature must not have
            from netobserv_tpu.parallel import MeshSpec
            try:
                width_sharded = MeshSpec.parse(
                    cfg.sketch_mesh_shape, 1).sketch > 1
            except ValueError:
                width_sharded = False  # the ctor raises the real error
            if width_sharded:
                log.warning("ARCHIVE_DIR set on a width-sharded mesh "
                            "(SKETCH_MESH_SHAPE=%s): no whole-width "
                            "table snapshot exists — archive disabled",
                            cfg.sketch_mesh_shape)
            elif cfg.sketch_tenants > 0:
                # per-tenant stores under ARCHIVE_DIR/tenant-<t>: range
                # queries stay tenant-scoped (archive.tenant_archives)
                from netobserv_tpu.archive import tenant_archives
                archive = tenant_archives(cfg, sketch_cfg,
                                          cfg.sketch_tenants,
                                          metrics=metrics)
            else:
                archive = maybe_archive(cfg, sketch_cfg, metrics=metrics)
        return cls(delta_sink=delta_sink, agent_id=cfg.federation_agent_id,
                   batch_size=cfg.sketch_batch_size, window_s=cfg.sketch_window,
                   sketch_cfg=sketch_cfg,
                   mesh_shape=cfg.sketch_mesh_shape, metrics=metrics, sink=sink,
                   checkpoint_dir=cfg.sketch_checkpoint_dir,
                   checkpoint_every=cfg.sketch_checkpoint_every,
                   scan_fanout_threshold=cfg.sketch_scan_fanout,
                   ddos_z_threshold=cfg.sketch_ddos_z,
                   synflood_min=cfg.sketch_synflood_min,
                   synflood_ratio=cfg.sketch_synflood_ratio,
                   drop_z_threshold=cfg.sketch_drop_z,
                   pack_threads=cfg.resolved_pack_threads(),
                   pack_threads_explicit=cfg.sketch_pack_threads > 0,
                   asym_min_bytes=cfg.sketch_asym_min_bytes,
                   asym_ratio=cfg.sketch_asym_ratio,
                   feed=cfg.sketch_feed,
                   resident_slots=cfg.sketch_resident_slots,
                   superbatch=cfg.parsed_superbatch_ladder(),
                   shed_watermark=cfg.sketch_shed_watermark,
                   shed_max=cfg.sketch_shed_max,
                   shed_slot_budget_s=cfg.sketch_shed_slot_budget,
                   query_refresh_s=cfg.sketch_query_refresh,
                   overlap_depth=cfg.sketch_overlap,
                   query_history=cfg.sketch_query_history,
                   alerts=maybe_engine(cfg, metrics),
                   archive=archive,
                   churn_ascent=cfg.sketch_churn_ascent,
                   churn_min_bytes=cfg.sketch_churn_min_bytes,
                   tenants=cfg.sketch_tenants,
                   warm_ladder=True,
                   decay_factor=(cfg.sketch_decay_factor
                                 if cfg.sketch_window_mode == "decay" else None))

    @property
    def overloaded(self) -> bool:
        """True while the overload controller is shedding load (the
        /healthz OVERLOADED condition; always False when disabled)."""
        return self._overload is not None and self._overload.overloaded

    def overload_snapshot(self) -> Optional[dict]:
        """Controller state for the health surface (None when disabled)."""
        return None if self._overload is None else self._overload.snapshot()

    def note_map_occupancy(self, ratio: float) -> None:
        """Record the last kernel-map drain's occupancy for the fleet
        telemetry block (MapTracer's occupancy sink; one float store per
        drain — float assignment is atomic under the GIL, no lock)."""
        self._map_occupancy = float(ratio)

    def _telemetry_block(self, records: int) -> dict:
        """Per-agent health block stamped into the delta frame. Assembled
        once per window PUBLISH on the timer thread from values the
        exporter already holds — no device op, no new clock on the fold
        path. The rec/s EWMA smooths window-records / window-elapsed over
        publishes (alpha 0.3; the first window seeds it)."""
        now = time.monotonic()
        if self._last_publish_mono is not None:
            elapsed = max(now - self._last_publish_mono, 1e-6)
            rate = records / elapsed
            self._host_rate_ewma = (rate if self._host_rate_ewma == 0.0
                                    else 0.3 * rate
                                    + 0.7 * self._host_rate_ewma)
        self._last_publish_mono = now
        conditions = []
        if self.overloaded:
            conditions.append("OVERLOADED")
        eng = self._alerts
        if eng is not None:
            try:
                if eng.condition().get("active"):
                    conditions.append("ALERTING")
            except Exception:  # telemetry must never lose the frame
                pass
        ctl = self._overload
        return {
            "shed_factor": (float(ctl.shed) if ctl is not None else 1.0),
            "conditions": conditions,
            "host_records_per_s": round(self._host_rate_ewma, 3),
            "map_occupancy": round(self._map_occupancy, 6),
            "windows_published": self._windows_published,
        }

    def resident_pack_surface(self) -> Optional[staging.ResidentPackSurface]:
        """The pack surface for the fused native drain pipeline
        (EVICT_NATIVE_PIPELINE): lets `fp_drain_to_resident` pack resident
        regions at drain time with THIS ring's dictionaries. None when the
        feed can't accept pre-packed regions — non-resident/single-lane
        feeds, no native library, or admission control enabled (the
        controller thins rows AFTER drain; a pre-packed arena can't be
        thinned, so fused drains would bypass shedding)."""
        if self._pack_surface is not None:
            return self._pack_surface
        ring = self._ring
        if not isinstance(ring, staging.ShardedResidentStagingRing):
            return None
        if self._overload is not None:
            return None
        if not flowpack.native_available():
            return None
        self._pack_surface = staging.ResidentPackSurface(ring)
        return self._pack_surface

    def _fold_packed_locked(self, packed, trace) -> bool:
        """Ship a fused-pipeline arena (caller holds the exporter lock).
        True = shipped (the eviction's raw rows are represented; don't
        buffer them). False = discarded (stale epoch / no surface): the
        caller folds the raw arrays instead — an EvictedFlows ALWAYS
        carries them regardless of packing."""
        surface = self._pack_surface
        if surface is None or self._overload is not None:
            packed.free()
            return False
        with surface.lock:
            if packed.epoch != surface.epoch:
                # an invalidation already re-zeroed `outstanding` and reset
                # the dictionaries; this arena's slot references are stale
                packed.free()
                return False
            surface.outstanding -= 1
        t0 = time.perf_counter()
        n = packed.segs  # row count rides the raw arrays; segs for logs
        owned = trace is None
        if owned:
            trace = tracing.start_trace("fold")
        try:
            with trace.stage("fold"):
                faultinject.fire("sketch.ingest")
                self._state = self._ring.fold_packed(self._state, packed,
                                                     trace=trace)
        except staging.StagingWedged as exc:
            # same adoption rule as _fold_events — dispatched segments
            # donated the state; and the surface must invalidate (this
            # arena's remaining slot definitions are dropping)
            if exc.state is not None:
                self._state = exc.state
            surface.invalidate()
            log.error("staging slot-wait budget exceeded mid packed fold "
                      "(%d segments): %s", n, exc)
            if self._metrics is not None:
                self._metrics.sketch_ingest_errors_total.inc()
                self._metrics.count_error("tpu-sketch-ingest")
            packed.free()
            return True  # rows up to the wedge shipped; never double-fold
        except Exception as exc:
            self._count_ingest_error(n, exc)  # rolls the surface epoch too
            packed.free()
            return True
        finally:
            if owned:
                trace.finish()
            if self._overload is not None:
                self._busy_fold_s += time.perf_counter() - t0
        packed.free()
        if self._metrics is not None:
            self._metrics.sketch_batches_total.inc()
            if self._tier_form == "interior":
                self._metrics.sketch_tiered_interior_folds_total.inc()
            self._metrics.sketch_ingest_seconds.observe(
                time.perf_counter() - t0)
        return True

    # --- Exporter interface ---
    def export_batch(self, records: list[Record]) -> None:
        with self._lock:
            self._pending.extend(records)
            while len(self._pending) >= self._batch_size:
                chunk, self._pending = (self._pending[:self._batch_size],
                                        self._pending[self._batch_size:])
                self._fold(chunk)
            if time.monotonic() >= self._window_deadline:
                self._close_window_locked()

    def export_evicted(self, evicted) -> None:
        """Columnar fast path: fold raw evictions without building Records.
        Full batches fold as the rolling buffer fills (zero concatenation);
        a due window only dispatches the roll here — rendering and sink I/O
        happen on the timer thread, so this never waits on a sink.

        With SKETCH_OVERLAP the eviction lands in the bounded handoff and
        this returns immediately (blocking only when the handoff is full) —
        the supervised fold thread runs the admit/buffer/fold below, so the
        caller's next drain overlaps this batch's pack/dispatch."""
        if self._handoff is not None:
            with self._inflight_lock:
                self._inflight_rows += len(evicted)
            self._handoff.put(evicted)
            return
        self._export_evicted_now(evicted)

    def _queued_overlap_rows(self) -> int:
        """Rows sitting in the overlap handoff (0 on the synchronous
        path) — part of the TRUE pending depth the overload controller
        must see. The in-hand eviction is decremented before its own
        `ctl.update` so it is never counted twice."""
        if self._handoff is None:
            return 0
        with self._inflight_lock:
            return self._inflight_rows

    def _export_evicted_now(self, evicted) -> None:
        """The admit/buffer/fold half of the columnar seam (synchronous
        callers run it inline; the overlap fold thread runs it per handoff
        item).

        Admission control (overload controller, when enabled): the
        pending-fold depth at arrival — buffered rows + this eviction +
        anything still queued in the overlap handoff — plus the ring's
        slot-wait p95 drive the AIMD shed factor, and the batch is thinned
        BEFORE buffering — surviving rows carry the factor in their
        `sampling` field, so the device de-bias keeps every estimate
        unbiased."""
        trace = getattr(evicted, "trace", None)
        with self._lock:
            packed = getattr(evicted, "packed", None)
            if packed is not None:
                # fused-pipeline arena riding the eviction: ship it in
                # place of the raw arrays (bit-exact the same fold —
                # tests/test_native_pipeline.py); a stale epoch falls
                # through to the raw path below
                evicted.packed = None
                if self._fold_packed_locked(packed, trace):
                    if trace is not None:
                        trace.finish()
                    if self._metrics is not None:
                        self._metrics.sketch_records_total.inc(len(evicted))
                    if time.monotonic() >= self._window_deadline:
                        self._close_window_locked()
                    return
            ctl = self._overload
            if ctl is not None:
                # busy = fold seconds per wall second since the previous
                # arrival (EWMA): a healthy device that folds instantly
                # zeroes the depth term no matter how large arrivals are
                now = time.perf_counter()
                last, self._busy_last_t = self._busy_last_t, now
                if last is not None:
                    inst = min(1.0, self._busy_fold_s
                               / max(now - last, 1e-6))
                    self._busy_ewma = 0.5 * self._busy_ewma + 0.5 * inst
                self._busy_fold_s = 0.0
                ctl.update(self._pending_buf.n + len(evicted)
                           + self._queued_overlap_rows(),
                           self._ring.slot_wait_p95(),
                           busy=self._busy_ewma)
                evicted = ctl.admit(evicted)
            if trace is not None:
                if self._pending_trace is None:
                    self._pending_trace = trace  # the next fold finishes it
                else:
                    trace.finish()  # rare: two sampled evictions in one fold
            self._pending_buf.append(evicted, self._fold_events)
            if time.monotonic() >= self._window_deadline:
                self._close_window_locked()

    def _start_fold_worker(self) -> None:
        """(Re)start the overlap fold thread; the supervisor uses this as
        the sketch-fold stage's restart callable."""
        self._fold_thread = threading.Thread(
            target=self._fold_loop, name="sketch-fold", daemon=True)
        self._fold_thread.start()

    def _fold_loop(self) -> None:
        import queue as _queue
        while not self._closed.is_set():
            self.fold_heartbeat()
            try:
                evicted = self._handoff.get(timeout=0.2)
            except _queue.Empty:
                continue
            try:
                with self._inflight_lock:
                    self._inflight_rows -= len(evicted)
                self._export_evicted_now(evicted)
            except Exception as exc:
                # a fold-path bug loses THIS batch (counted), never the
                # worker — the same contract as the QueueExporter loop
                log.error("overlap fold failed (batch of %d dropped): %s",
                          len(evicted), exc)
                if self._metrics is not None:
                    self._metrics.count_error("tpu-sketch")
            finally:
                self._handoff.task_done()

    def _drain_handoff(self, timeout_s: float = 30.0) -> None:
        """Wait until every queued eviction has been admitted and folded
        (flush/shutdown path). Bounded: a dead fold worker must not hang
        flush forever — leftovers are drained synchronously by close()."""
        if self._handoff is None:
            return
        deadline = time.monotonic() + timeout_s
        while self._handoff.unfinished_tasks and \
                time.monotonic() < deadline:
            if (self._fold_thread is None
                    or not self._fold_thread.is_alive()):
                return  # close() (or the supervisor) owns the leftovers
            time.sleep(0.005)

    def _fold_events(self, events, feats) -> None:
        t0 = time.perf_counter()
        n = len(events)
        # batch trace continuity: the sampled eviction trace riding the
        # pending buffer (or a fold-local sample when none) — the gap from
        # its evict span to this fold span IS the export queue wait
        trace = self._pending_trace
        self._pending_trace = None
        if trace is None:
            trace = tracing.start_trace("fold")
        try:
            with trace.stage("fold"):
                faultinject.fire("sketch.ingest")
                if self._pack_surface is not None:
                    # ship order must equal dict-mutation order: this raw
                    # fold's pack mutates the dictionaries NOW, so any
                    # fused arena still outstanding (packed earlier, not
                    # yet shipped) must not ship afterwards — no-op when
                    # none are outstanding (staging.ResidentPackSurface)
                    self._pack_surface.invalidate_for_raw_fold()
                self._state = self._ring.fold(self._state, events,
                                              trace=trace, **feats)
        except staging.StagingWedged as exc:
            # the slot-wait budget tripped at a chunk boundary: the rows
            # not yet packed drop (no dictionary slot was committed for
            # them, so no epoch roll) — a wedged device costs at most one
            # batch per fold while the eviction feed keeps its cadence.
            # ADOPT the exception's state: earlier chunks of this fold may
            # have dispatched, and their ingests DONATED the state we
            # passed in — keeping self._state would keep deleted buffers
            # (exc.state is self._state when nothing dispatched)
            if exc.state is not None:
                self._state = exc.state
            log.error("staging slot-wait budget exceeded "
                      "(up to %d rows dropped): %s", n, exc)
            if self._metrics is not None:
                self._metrics.sketch_ingest_errors_total.inc()
                self._metrics.count_error("tpu-sketch-ingest")
            return
        except Exception as exc:
            # graceful degradation: a device error loses THIS batch (counted)
            # instead of poisoning the exporter thread / window timer
            self._count_ingest_error(n, exc)
            return
        finally:
            trace.finish()
            if self._overload is not None:
                self._busy_fold_s += time.perf_counter() - t0
        if self._metrics is not None:
            self._metrics.sketch_batches_total.inc()
            if self._tier_form == "interior":
                self._metrics.sketch_tiered_interior_folds_total.inc()
            self._metrics.sketch_records_total.inc(n)
            self._metrics.sketch_ingest_seconds.observe(
                time.perf_counter() - t0)

    def _count_ingest_error(self, n: int, exc: Exception) -> None:
        log.error("sketch ingest failed (batch of %d dropped): %s", n, exc)
        if self._metrics is not None:
            self._metrics.sketch_ingest_errors_total.inc()
            self._metrics.count_error("tpu-sketch-ingest")
        # resident feed: the host dictionary may have committed slot
        # definitions the device table never received (the dropped buffer
        # carried them). Roll the epoch so every live slot is redefined
        # through the new-key lane before any hot row references it —
        # otherwise later hot rows would score against stale device keys
        # (the resident-feed contract, CLAUDE.md)
        kdicts = getattr(self._ring, "kdicts", None)
        if kdicts is None:
            kd = getattr(self._ring, "kdict", None)
            kdicts = [kd] if kd is not None else []
        for kd in kdicts:
            kd.reset()
        if kdicts:
            self._ring.dict_resets += len(kdicts)
            if self._metrics is not None:
                self._metrics.sketch_resident_dict_epochs_total.inc(
                    len(kdicts))
        surface = getattr(self, "_pack_surface", None)
        if surface is not None:
            # the reset above IS an epoch roll — outstanding fused arenas
            # were packed against the pre-reset dictionaries
            surface.note_external_reset()

    def _drain_pending_locked(self) -> None:
        if self._pending:
            self._fold(self._pending)
            self._pending = []
        self._pending_buf.flush_to(self._fold_events)
        if self._tenancy is not None:
            # ship any partially-filled tenant buffers as one last stacked
            # fold — a roll (or refresh) must never strand routed rows
            try:
                self._state = self._tenancy.flush(self._state)
            except staging.StagingWedged as exc:
                if exc.state is not None:
                    self._state = exc.state
                log.error("tenant flush hit the slot-wait budget "
                          "(buffered rows dropped): %s", exc)
                if self._metrics is not None:
                    self._metrics.sketch_ingest_errors_total.inc()
                    self._metrics.count_error("tpu-sketch-ingest")

    def _close_window_locked(self) -> None:
        """Drain pending rows and dispatch the roll, under ONE window trace
        (roll_drain + roll_dispatch spans; the render/sink spans attach when
        the queued report publishes on the timer thread)."""
        wtrace = tracing.start_trace("window")
        try:
            with wtrace.stage("roll_drain"):
                self._drain_pending_locked()
            self._roll_locked(wtrace)
        except BaseException:
            # a failed roll never reaches the report queue, so nothing else
            # will seal the trace — a failing window's spans are exactly the
            # evidence the recorder exists for
            wtrace.finish()
            raise

    def flush(self) -> None:
        """Fold pending records, close the current window now, and publish
        the report synchronously (shutdown/tests path). With the overlap
        seam, queued evictions fold first — a flush observes everything
        exported before it."""
        self._drain_handoff()
        with self._lock:
            self._close_window_locked()
        self._publish_queued()

    def close(self) -> None:
        self._closed.set()
        # overlap fold worker first: it holds evictions the flush below
        # must observe; after the join any leftovers (worker died, or
        # raced the _closed flag) drain synchronously on this thread
        if self._fold_thread is not None:
            self._drain_handoff()
            self._fold_thread.join(timeout=10.0)
            import queue as _queue
            while True:
                try:
                    evicted = self._handoff.get_nowait()
                except _queue.Empty:
                    break
                with self._inflight_lock:
                    self._inflight_rows -= len(evicted)
                try:
                    # same per-batch containment as the fold worker: the
                    # leftover drain exists for the worker-died case, and
                    # the batch that killed it would otherwise re-raise
                    # here and abort the remaining teardown joins
                    self._export_evicted_now(evicted)
                except Exception as exc:
                    log.error("close-path fold failed (batch of %d "
                              "dropped): %s", len(evicted), exc)
                    if self._metrics is not None:
                        self._metrics.count_error("tpu-sketch")
                finally:
                    self._handoff.task_done()
        # a mid-flight query refresh (roll dispatch + table transfer on the
        # timer thread) must finish before the interpreter starts tearing
        # down, or its in-flight device work on a daemon thread aborts the
        # C++ runtime at exit ("terminate called without an active
        # exception") — give the join a refresh-sized budget; without the
        # refresh the timer only ever waits on its poll tick
        self._timer.join(timeout=10.0 if self._query_refresh_s else 2.0)
        # same exit hazard for the background ladder warm: an agent
        # SIGTERMed during its first ~minute can still be compiling ladder
        # entries here — _warm skips remaining entries once _closed is
        # set, so this join only ever waits out the ONE in-flight compile
        # (bounded: a wedged backend must not wedge shutdown forever)
        warm = getattr(self, "_warm_thread", None)
        if warm is not None and warm.is_alive():
            warm.join(timeout=30.0)
        self.flush()
        if self._tenancy is not None:
            self._tenancy.close()  # per-tenant series label hygiene
        if self._ckpt is not None:
            self._ckpt.close()
        sink_close = getattr(self._sink, "close", None)
        if sink_close is not None:
            sink_close()
        if self._delta_sink is not None:
            delta_close = getattr(self._delta_sink, "close", None)
            if delta_close is not None:
                delta_close()

    def _window_loop(self) -> None:
        while not self._closed.wait(timeout=self._window_poll_s):
            self.heartbeat()
            # outside the try: a bug in the timer stage itself — the
            # supervisor's job (restart), not the swallow-and-retry path
            faultinject.fire("sketch.window_timer")
            try:
                faultinject.fire("sketch.window_roll")
                with self._lock:
                    if time.monotonic() >= self._window_deadline:
                        self._close_window_locked()
            except Exception as exc:
                # a roll failure must not kill the timer — the next window
                # retries
                log.error("window roll failed (will retry next window): %s",
                          exc)
                if self._metrics is not None:
                    self._metrics.count_error("tpu-sketch")
            # publish OUTSIDE the exporter lock: folds proceed while the
            # report transfers/renders and the sink (possibly blocking
            # Kafka I/O) delivers. A crash here is a timer-stage bug — the
            # supervisor restarts the thread and the still-queued report
            # publishes exactly once after the restart (no double-emit:
            # the deadline already advanced at roll time).
            if self._reports:
                faultinject.fire("sketch.window_publish")
            self._publish_queued()
            self._maybe_refresh_query()

    def _maybe_refresh_query(self) -> None:
        """SKETCH_QUERY_REFRESH tick (timer thread). Disabled (the
        default), this is one is-None check — the zero-cost bar. A refresh
        failure is swallowed+counted; the next tick retries."""
        nxt = getattr(self, "_next_refresh", None)
        if nxt is None or self._closed.is_set() or time.monotonic() < nxt:
            return
        self._next_refresh = time.monotonic() + self._query_refresh_s
        try:
            self._refresh_query_snapshot()
        except Exception as exc:
            log.error("mid-window query refresh failed (will retry): %s",
                      exc)
            if self._metrics is not None:
                self._metrics.count_error("tpu-sketch-query")

    # --- internals ---
    def _make_single_device_ring(self, feed: str, resident_slots: int,
                                 pack_threads: int, metrics):
        """Single-device staging ring by feed format (SKETCH_FEED):
        "resident" (default) ships ~15B/record slot-id hot rows against a
        device key table (byte budget in docs/tpu_sketch.md; lane
        overflows continue into the next chunk, a full dictionary rolls
        its epoch) — SKETCH_PACK_THREADS > 1 splits the batch into that
        many pack LANES, each with its own dictionary + device key table,
        packed in true parallel (the host-pack ceiling scales with
        threads); "compact" ships 40B v4-compact rows with a dense
        fallback; "dense" ships 80B full-width rows (the debugging
        baseline — also what sharded meshes use)."""
        import jax

        sk = self._sk
        kw = dict(use_pallas=self._cfg.use_pallas, with_token=True,
                  enable_fanout=self._cfg.enable_fanout,
                  enable_asym=self._cfg.enable_asym)
        if feed == "resident":
            lanes = staging.pick_lanes(self._batch_size, self._lane_threads)
            ladder = self._superbatch
            bpl = self._batch_size // lanes
            caps = flowpack.default_resident_caps(bpl)
            # one fixed-shape jitted entry PER ladder size, every one under
            # its own retrace watch — a post-warmup compile of any ladder
            # shape is a live alarm (sketch_retraces_total{fn=..._xk})
            ingests = {
                k: retrace.watch(sk.make_ingest_resident_lanes_fn(
                    bpl, caps, k * lanes, use_pallas=self._cfg.use_pallas,
                    enable_fanout=self._cfg.enable_fanout,
                    enable_asym=self._cfg.enable_asym),
                    f"ingest_resident_lanes_x{k}", tiered=self._tier_form)
                for k in ladder}
            return staging.ShardedResidentStagingRing(
                self._batch_size, 1, ingests,
                key_tables=jax.device_put(
                    sk.init_key_tables(max(ladder) * lanes, resident_slots)),
                put=jax.device_put, caps=caps, slot_cap=resident_slots,
                metrics=metrics, pack_threads=pack_threads, lanes=lanes,
                ladder=ladder, lazy_ladder=True)
        if feed == "compact":
            spill_cap = staging.default_spill_cap(self._batch_size)
            return staging.DenseStagingRing(
                self._batch_size,
                retrace.watch(
                    sk.make_ingest_compact_fn(self._batch_size, spill_cap,
                                              **kw), "ingest_compact",
                    tiered=self._tier_form),
                spill_cap=spill_cap,
                ingest_fallback=retrace.watch(
                    sk.make_ingest_dense_fn(**kw), "ingest_dense",
                    tiered=self._tier_form),
                metrics=metrics, pack_threads=pack_threads)
        if feed != "dense":
            log.warning("unknown SKETCH_FEED %r; using dense", feed)
        return staging.DenseStagingRing(
            self._batch_size,
            retrace.watch(sk.make_ingest_dense_fn(**kw), "ingest_dense",
                          tiered=self._tier_form),
            metrics=metrics, pack_threads=pack_threads)

    def _fold(self, records: list[Record]) -> None:
        t0 = time.perf_counter()
        trace = tracing.start_trace("fold")
        try:
            # always pad to the fixed batch size: a single static shape
            # means the jitted ingest compiles exactly once (no per-window
            # retraces). A from_records failure still propagates to the
            # caller (an export error, not an ingest error) — only the
            # trace seal is widened over it.
            with trace.stage("pack"):
                batch = FlowBatch.from_records(records,
                                               batch_size=self._batch_size)
            try:
                faultinject.fire("sketch.ingest")
                if self._tenancy is not None:
                    # Record path in tenant mode: pack through the columnar
                    # twin (arrays_to_dense IS the pinned dense layout) and
                    # route the valid rows — padding must not spend tenant
                    # fill-buffer slots
                    arrays = self._sk.batch_to_device(batch)
                    rows = self._sk.arrays_to_dense(arrays).reshape(
                        -1, self._sk.DENSE_WORDS)
                    self._state = self._tenancy.fold_rows(
                        self._state, rows[arrays["valid"]], trace=trace)
                else:
                    with trace.stage("ingest_dispatch"):
                        arrays = self._sk.batch_to_device(batch)
                        if self._distributed:
                            arrays = self._pm.shard_batch(self._mesh,
                                                          arrays)
                        self._state = self._ingest(self._state, arrays)
            except staging.StagingWedged as exc:
                # tenant path only: adopt the wedge's state (dispatched
                # stacked folds donated the reference we passed in)
                if exc.state is not None:
                    self._state = exc.state
                log.error("staging slot-wait budget exceeded (up to %d "
                          "rows dropped): %s", len(records), exc)
                if self._metrics is not None:
                    self._metrics.sketch_ingest_errors_total.inc()
                    self._metrics.count_error("tpu-sketch-ingest")
                return
            except Exception as exc:
                self._count_ingest_error(len(records), exc)
                return
        finally:
            trace.finish()
        if self._metrics is not None:
            self._metrics.sketch_batches_total.inc()
            if self._tier_form == "interior":
                self._metrics.sketch_tiered_interior_folds_total.inc()
            self._metrics.sketch_records_total.inc(len(records))
            self._metrics.sketch_ingest_seconds.observe(
                time.perf_counter() - t0)

    def _roll_locked(self, wtrace=tracing.NULL_TRACE) -> None:
        """Close the window UNDER self._lock: advance the deadline, dispatch
        the (async) device roll, swap in the fresh-window state, and queue
        the still-on-device report. No host transfer, JSON rendering, or
        sink I/O happens here — that is `_publish_queued`'s job on the
        window-timer thread, so `export_batch`/`export_evicted` callers
        blocked on this lock never wait behind a sink."""
        self._window_deadline = time.monotonic() + self._window_s
        if self._overload is not None:
            # bounded recovery: a pressure-free window snaps the shed
            # factor back to 1 even if the feed went idle (no updates)
            self._overload.window_roll()
        with wtrace.stage("roll_dispatch"):
            with self._roll_mutex:  # vs a concurrent refresh roll
                if self._with_tables:
                    self._state, report, tables = self._roll(self._state)
                else:
                    self._state, report = self._roll(self._state)
                    tables = None
        # the window trace rides the queued report; render/sink spans attach
        # at publish time on the timer thread (the gap in between is the
        # report's queue wait)
        self._reports.append((report, tables, wtrace))
        while len(self._reports) > self._max_queued_reports:
            # a wedged sink has the timer blocked mid-publish: shed the
            # OLDEST unpublished window instead of accumulating device
            # reports without bound (counted, like any lost report)
            try:
                _shed, _shed_tables, shed_trace = self._reports.popleft()
            except IndexError:
                break  # the publisher drained it between len() and pop
            shed_trace.finish()
            log.error("window report queue full (sink stalled?); "
                      "dropping the oldest unpublished report")
            if self._metrics is not None:
                # dedicated series (not the generic error counter): a
                # wedged sink shedding whole windows of reports deserves
                # its own alert line
                self._metrics.sketch_reports_shed_total.inc()
        # checkpointing stays at roll time: later folds DONATE self._state
        # into the jitted ingest, so a deferred save could read a deleted
        # buffer. orbax copies to host before save() returns; the int()
        # waits only for the roll itself, and only on checkpoint windows.
        if self._ckpt is not None and self._ckpt_every:
            self._n_windows_saved += 1
            if self._n_windows_saved % self._ckpt_every == 0:
                self._ckpt.save(int(report.window),
                                self._ckpt_state_view(self._state))

    def _publish_queued(self) -> None:
        """Render and deliver every queued window report (timer thread, or
        flush() at shutdown). A sink/render failure loses THAT report —
        counted, logged — because its window already rolled; the next
        window's report still flows."""
        with self._publish_lock:
            while self._reports:
                try:
                    report, tables, wtrace = self._reports.popleft()
                except IndexError:
                    return  # _roll_locked's shed loop emptied it first
                try:
                    self._publish_report(report, wtrace, tables=tables)
                except Exception as exc:
                    log.error("window report publish failed "
                              "(report lost): %s", exc)
                    if self._metrics is not None:
                        self._metrics.count_error("tpu-sketch")
                finally:
                    wtrace.finish()

    def _render_report(self, report, roll: bool = False,
                       tenant: Optional[int] = None) -> dict:
        """Render a device WindowReport with THIS exporter's thresholds.
        `roll=True` (closed-window publishes) additionally rotates the
        previous-roll heavy index the EvictedKeys diff reads — refreshes
        keep diffing against the last CLOSED window. `tenant` (tenant-mode
        fan-out) renders one tenant's slice of the stacked report against
        that tenant's OWN previous-roll index and stamps the id into the
        report object."""
        prev = (self._prev_heavy_index if tenant is None
                else self._tenant_prev_heavy.get(tenant))
        obj = report_to_json(
            report, scan_fanout_threshold=self._scan_fanout,
            ddos_z_threshold=self._ddos_z,
            synflood_min=self._synflood_min,
            synflood_ratio=self._synflood_ratio,
            drop_z_threshold=self._drop_z,
            asym_min_bytes=self._asym_min_bytes,
            asym_ratio=self._asym_ratio,
            churn_ascent=self._churn_ascent,
            churn_min_bytes=self._churn_min_bytes,
            prev_heavy_index=prev,
            partial_window=not roll)
        if roll:
            idx = heavy_identity_index(report)
            if tenant is None:
                self._prev_heavy_index = idx
            else:
                self._tenant_prev_heavy[tenant] = idx
        if tenant is not None:
            obj["Tenant"] = int(tenant)
        return obj

    def _publish_query_snapshot(self, obj: dict, tables,
                                mid_window: bool = False,
                                tenant: Optional[int] = None) -> None:
        """Swap in a fresh query snapshot (query/snapshot.py seq-stamps it).
        The np.asarray touch is the device->host transfer of the CM planes
        — per window (or per refresh), on the timer thread, never under
        the exporter lock. `tenant` routes the snapshot to that tenant's
        publisher (tenant-mode fan-out) and rides in the snap dict — the
        alert engine's fingerprints and /query responses carry it."""
        snap = {
            "window": obj["Window"],
            "ts_ms": obj["TimestampMs"],
            "report": obj,
            "cm_bytes": (np.asarray(tables["cm_bytes"])
                         if tables is not None else None),
            "cm_pkts": (np.asarray(tables["cm_pkts"])
                        if tables is not None else None),
        }
        if tenant is not None:
            snap["tenant"] = int(tenant)
            self._tenant_query[tenant].publish(snap, mid_window=mid_window)
        else:
            self.query.publish(snap, mid_window=mid_window)
        # alert evaluation rides the publish it just observed (timer
        # thread); safe_evaluate swallows+counts — a failing evaluation
        # can never lose the snapshot (already swapped in) or the report
        # (the caller's own try covers that separately). The
        # ``alerts.evaluate`` fault point fires inside evaluate().
        if self._alerts is not None:
            self._alerts.safe_evaluate(snap, mid_window=mid_window)

    def query_status(self) -> dict:
        """/query/status payload: snapshot freshness + plane counters.
        Reads the publisher ONCE and derives seq/window/mid_window from
        that same snapshot — stats() and a racing publish between two
        reads would otherwise mix two snapshots' fields in one response
        (the torn-read guarantee covers this route too)."""
        snap = self.query.get()
        st = self.query.stats()
        st.update({"agent_id": self._agent_id,
                   "window_s": self._window_s,
                   "refresh_s": self._query_refresh_s,
                   "overloaded": self.overloaded})
        if getattr(self, "_tiered_degraded", False):
            # mirror of the tiered_degraded supervisor condition: why
            # resident memory is wide despite SKETCH_TIERED being set
            st["tiered_degraded"] = True
        if self._alerts is not None:
            # one view read (the read-once rule): active count and last
            # transition seq come from the SAME published alert view, so a
            # poller never needs a second racy /query/alerts round-trip
            st["alerts"] = self._alerts.summary()
        if self._archive is not None:
            # warehouse discovery: segment counts/levels/disk bytes so a
            # poller can range-query without probing for 404s
            st["archive"] = self._archive.stats()
        if self._tenant_query is not None:
            # tenant discovery: which planes have published, and each one's
            # current window — read each publisher ONCE (same torn-read
            # rule as the top-level snapshot)
            snaps_t = [p.get() for p in self._tenant_query]
            st["tenants"] = {
                "n": len(self._tenant_query),
                "published": sum(1 for s in snaps_t if s is not None),
                "stacked_folds": self._tenancy.folds,
                "routed_rows": self._tenancy.routed_rows,
                "windows": {str(t): (None if s is None else s["window"])
                            for t, s in enumerate(snaps_t)},
            }
        if snap is not None:
            st.update({"published": True, "seq": snap["seq"],
                       "window": snap["window"],
                       "mid_window": snap["mid_window"]})
            rep = snap["report"]
            st.update({
                "records": rep["Records"], "bytes": rep["Bytes"],
                "distinct_src_estimate": rep["DistinctSrcEstimate"],
                "drop_bytes": rep["DropBytes"],
                "quic_records": rep["QuicRecords"],
                "nat_records": rep["NatRecords"],
                "rtt_quantiles_us": rep["RttQuantilesUs"],
                "dns_latency_quantiles_us": rep["DnsLatencyQuantilesUs"],
                "suspects": {sig: len(rep[key]) for sig, key
                             in SIGNAL_FIELDS.items()},
            })
        return st

    def _refresh_query_snapshot(self) -> None:
        """Mid-window refresh (SKETCH_QUERY_REFRESH): re-run the EXISTING
        roll executable against a STAGED device-side copy of the live
        state and publish its report + tables WITHOUT adopting the rolled
        state — the live window keeps accumulating untouched. The copy is
        load-bearing, not defensive, on EVERY deployment: the mesh roll
        donates its input, and the single-device resident INGEST donates
        the state buffers — either way a concurrent fold deletes the live
        reference under this off-lock roll (the federation checkpoint
        staging pattern, aggregator.py). Only the copy happens
        under the exporter lock; the roll dispatch, render, transfer and
        publish all run OFF the lock on the timer thread. No new jitted
        entry exists to retrace. The buffered sub-batch tail IS drained
        first (the same padded fold the window close would dispatch —
        additive merge semantics make the early fold invisible in the
        window's final totals), so the refresh reflects every exported
        row; the drain only ever runs with the refresh enabled, so the
        disabled path keeps its exact fold sequence."""
        import jax
        import jax.numpy as jnp
        with self._lock:
            self._drain_pending_locked()
            # the copy is donation protection on EVERY deployment: the
            # mesh roll donates its input, and on a single device the
            # resident INGEST donates the state buffers — a fold racing
            # this refresh off the lock would delete the captured live
            # reference mid-roll (observed live as "Array has been
            # deleted" + a spurious roll retrace). The copy is enqueued
            # under the lock, so device program order reads the buffers
            # before any later fold's donation overwrites them (the
            # federation checkpoint staging pattern).
            staged = jax.tree.map(jnp.copy, self._state)
        with self._roll_mutex:  # vs a concurrent window-close roll
            out = self._roll(staged)
        if self._with_tables:
            _discard, report, tables = out
        else:
            (_discard, report), tables = out, None
        ts_ms = time.time_ns() // 1_000_000
        if self._tenancy is not None:
            # stacked refresh: one staged roll already closed every
            # tenant's view — fan the slices out to the per-tenant
            # publishers (mid-window publishes never enter history rings)
            from netobserv_tpu.sketch import tenancy
            nt = self._tenancy.n_tenants
            reps = tenancy.split_tenants(report, nt)
            tabs = (tenancy.split_tenants(tables, nt)
                    if tables is not None else [None] * nt)
            faultinject.fire("sketch.query_snapshot")
            for t, (rep, tab) in enumerate(zip(reps, tabs)):
                obj = self._render_report(rep, tenant=t)
                obj["TimestampMs"] = ts_ms
                self._publish_query_snapshot(obj, tab, mid_window=True,
                                             tenant=t)
            return
        obj = self._render_report(report)
        obj["TimestampMs"] = ts_ms
        faultinject.fire("sketch.query_snapshot")
        self._publish_query_snapshot(obj, tables, mid_window=True)

    def _ckpt_state_view(self, state):
        """What a checkpoint saves: the state itself, or — tiered mode —
        its canonical wide decode (checkpoints never see the resident tier
        layout; format stamp unchanged). The decode is a retrace-watched
        jitted entry dispatched only on checkpoint windows."""
        if self._cfg.tiered is None:
            return state
        if self._tiered_decode is None:
            import jax

            from netobserv_tpu.sketch.tiered import decode_state
            self._tiered_decode = retrace.watch(jax.jit(decode_state),
                                                "tiered_decode",
                                                tiered="decode")
        return self._tiered_decode(state)

    def _publish_tier_metrics(self, tables, tenant=None) -> None:
        """Per-window tier telemetry from the published WIDE tables (the
        host copy the snapshot already paid for). The counter counts NEW
        promotions only: counters at/past base saturation this window that
        were NOT saturated at the previous closed-window publish — in
        decay/keep roll modes a steady heavy hitter stays promoted across
        windows and must not re-count every publish (the per-window-
        counter rule heavy_evictions pins). Reset mode clears the mask
        with the window, so there the delta equals occupancy. Timer
        thread, per window — never the fold path."""
        from netobserv_tpu.sketch.tiered import BASE_MAX
        spec = self._cfg.tiered
        for table, span in (("cm_bytes", BASE_MAX * spec.bytes_unit),
                            ("cm_pkts", BASE_MAX)):
            promoted = np.asarray(tables[table]) >= span
            fresh = promoted
            if self._tier_sticky_promotions:
                prev = self._tier_prev_promoted.get((table, tenant))
                if prev is not None:
                    fresh = promoted & ~prev
                self._tier_prev_promoted[(table, tenant)] = promoted
            self._metrics.sketch_tier_promotions_total.labels(
                table=table).inc(int(fresh.sum()))

    def _publish_report(self, report, wtrace=tracing.NULL_TRACE,
                        tables=None) -> None:
        if self._tenancy is not None:
            # stacked roll output: fan every tenant's slice out through the
            # same publish discipline (delta -> render -> snapshot -> sink
            # -> archive, each failure domain its own try)
            self._publish_report_tenants(report, wtrace, tables)
            return
        self._windows_published += 1  # telemetry: counts THIS window
        if self._delta_sink is not None and tables is not None:
            # federation delta FIRST, in its own try: a dead aggregator (or
            # a serialize bug) loses the frame — counted by the sink — but
            # never the local JSON report below. Per window, never per
            # record, like every fault point / span.
            try:
                with wtrace.stage("report_serialize"):
                    faultinject.fire("sketch.delta_export")
                    from netobserv_tpu.federation import delta as fdelta
                    # cross-process trace context: ONE check — an unsampled
                    # window answers None and the frame stays byte-identical
                    # to the context-less wire. Encoded once, here: the
                    # sink's retries resend these bytes, never a re-derived
                    # context.
                    ctx = tracing.context_of(
                        wtrace, origin=f"window@{self._agent_id}")
                    if ctx is not None and self._metrics is not None:
                        self._metrics.trace_context_propagated_total.labels(
                            "stamped").inc()
                    host_tables = {k: np.asarray(v)
                                   for k, v in tables.items()}
                    # window_seq rides the window counter (one frame per
                    # closed window); frame_uuid is drawn ONCE here — the
                    # sink's retry ladder resends these same bytes, so an
                    # ambiguous-deadline redelivery dedups at the ledger
                    frame = fdelta.encode_frame(
                        host_tables,
                        agent_id=self._agent_id,
                        window=int(np.asarray(report.window)),
                        ts_ms=time.time_ns() // 1_000_000,
                        agent_epoch=self._agent_epoch,
                        trace_ctx=ctx,
                        telemetry=self._telemetry_block(
                            int(float(host_tables["scalars"][0]))),
                        dims={"cm_depth": self._cfg.cm_depth,
                              "cm_width": self._cfg.cm_width,
                              "hll_precision": self._cfg.hll_precision,
                              "topk": self._cfg.topk,
                              "ewma_buckets": self._cfg.ewma_buckets})
                with wtrace.stage("delta_push"):
                    self._delta_sink(frame)  # sink swallows+counts inside
            except Exception as exc:
                log.error("delta frame serialize/push failed "
                          "(frame lost, report still publishes): %s", exc)
                if self._metrics is not None:
                    self._metrics.count_error("federation")
        with wtrace.stage("report_render"):
            # includes the device->host transfer of the report arrays (the
            # first np.asarray touch) — deliberately not split out, so the
            # un-traced path never adds a blocking device sync
            obj = self._render_report(report, roll=True)
        obj["TimestampMs"] = time.time_ns() // 1_000_000
        if self._metrics is not None:
            self._metrics.sketch_heavy_evictions_total.inc(
                obj["HeavyChurn"]["evictions"])
        # query-snapshot publish in its OWN try, BEFORE the sink: a failing
        # publish (the sketch.query_snapshot fault point's job to prove)
        # must never lose the window report, and a blocked sink must never
        # delay query freshness. Per window, never per record.
        try:
            with wtrace.stage("query_snapshot"):
                faultinject.fire("sketch.query_snapshot")
                self._publish_query_snapshot(obj, tables)
        except Exception as exc:
            log.error("query snapshot publish failed (window report still "
                      "publishes; /query serves the previous snapshot): %s",
                      exc)
            if self._metrics is not None:
                self._metrics.count_error("tpu-sketch-query")
        with wtrace.stage("report_sink"):
            self._sink(obj)
        # sketch-warehouse write LAST, in its own try: the report already
        # reached the sink and the query snapshot already swapped in, so a
        # failing (or wedged) archive disk loses only durability of THIS
        # window's segment — counted, never the report. A hung write
        # stalls only this supervised timer thread (heartbeat stops, the
        # supervisor flips DEGRADED); ingest folds never wait here. The
        # host copies below are the staged snapshot — the roll's table
        # OUTPUTS, never the live donated state (the federation
        # checkpoint staging rule).
        if self._archive is not None and tables is not None:
            try:
                with wtrace.stage("archive_write"):
                    faultinject.fire("sketch.archive_write")
                    self._archive.write_window(
                        {k: np.asarray(v) for k, v in tables.items()},
                        window=int(obj["Window"]),
                        ts_ms=int(obj["TimestampMs"]))
            except Exception as exc:
                log.error("archive segment write failed (window %s not "
                          "archived; report already published): %s",
                          obj["Window"], exc)
                if self._metrics is not None:
                    self._metrics.count_error("tpu-sketch-archive")
        if self._metrics is not None:
            if self._cfg.tiered is not None and tables is not None:
                try:
                    self._publish_tier_metrics(tables)
                except Exception as exc:  # telemetry never loses a report
                    log.warning("tier metrics publish failed: %s", exc)
            self._metrics.sketch_window_reports_total.inc()
            self._metrics.sketch_window_records.set(obj["Records"])
            self._metrics.sketch_window_drop_bytes.set(obj["DropBytes"])
            for sig, key in SIGNAL_FIELDS.items():
                self._metrics.sketch_window_suspects.labels(sig).set(
                    len(obj[key]))

    def _publish_report_tenants(self, report, wtrace=tracing.NULL_TRACE,
                                tables=None) -> None:
        """Tenant-mode publish: split the stacked roll outputs ONCE (one
        device pull for the whole stack, then zero-copy per-tenant views)
        and run every tenant's slice through the same publish seams as the
        single-tenant path — delta frames first (per-tenant TenantInfo on
        the wire), render with per-tenant heavy-identity rotation, per-
        tenant snapshot publishes + alert evaluations, the sink, and
        per-tenant archive segments. Each failure domain keeps its own try
        and its single-tenant semantics: a dead aggregator loses frames,
        never the reports; a failing snapshot publish loses one tenant's
        freshness, never the window."""
        from netobserv_tpu.sketch import tenancy
        n = self._tenancy.n_tenants
        self._windows_published += 1  # telemetry: counts THIS window
        with wtrace.stage("report_render"):
            reps = tenancy.split_tenants(report, n)
            tabs = (tenancy.split_tenants(tables, n)
                    if tables is not None else [None] * n)
            objs = [self._render_report(rep, roll=True, tenant=t)
                    for t, rep in enumerate(reps)]
        ts_ms = time.time_ns() // 1_000_000
        for obj in objs:
            obj["TimestampMs"] = ts_ms
        if self._delta_sink is not None and tables is not None:
            try:
                with wtrace.stage("report_serialize"):
                    faultinject.fire("sketch.delta_export")
                    from netobserv_tpu.federation import delta as fdelta
                    ctx = tracing.context_of(
                        wtrace, origin=f"window@{self._agent_id}")
                    if ctx is not None and self._metrics is not None:
                        self._metrics.trace_context_propagated_total.labels(
                            "stamped").inc()
                    # ONE telemetry block per window (the publish-rate EWMA
                    # must see one publish, not N), stamped into every
                    # tenant's frame; window_seq rides the shared window
                    # counter — the aggregator's ledger keys per
                    # (agent, tenant) source (federation.delta.source_key)
                    total = sum(int(float(tab["scalars"][0]))
                                for tab in tabs)
                    tel = self._telemetry_block(total)
                    dims = {"cm_depth": self._cfg.cm_depth,
                            "cm_width": self._cfg.cm_width,
                            "hll_precision": self._cfg.hll_precision,
                            "topk": self._cfg.topk,
                            "ewma_buckets": self._cfg.ewma_buckets}
                    window = int(reps[0].window)
                    frames = [fdelta.encode_frame(
                        {k: np.asarray(v) for k, v in tab.items()},
                        agent_id=self._agent_id, window=window,
                        ts_ms=ts_ms, agent_epoch=self._agent_epoch,
                        trace_ctx=ctx, telemetry=tel, tenant=(t, n),
                        dims=dims) for t, tab in enumerate(tabs)]
                with wtrace.stage("delta_push"):
                    for frame in frames:
                        self._delta_sink(frame)  # sink swallows+counts
            except Exception as exc:
                log.error("tenant delta frame serialize/push failed "
                          "(frames lost, reports still publish): %s", exc)
                if self._metrics is not None:
                    self._metrics.count_error("federation")
        with wtrace.stage("query_snapshot"):
            for t, (obj, tab) in enumerate(zip(objs, tabs)):
                try:
                    faultinject.fire("sketch.query_snapshot")
                    self._publish_query_snapshot(obj, tab, tenant=t)
                except Exception as exc:
                    log.error("tenant %d query snapshot publish failed "
                              "(window report still publishes): %s", t, exc)
                    if self._metrics is not None:
                        self._metrics.count_error("tpu-sketch-query")
        with wtrace.stage("report_sink"):
            for obj in objs:
                self._sink(obj)
        if self._archive is not None and tables is not None:
            try:
                with wtrace.stage("archive_write"):
                    faultinject.fire("sketch.archive_write")
                    for t, (obj, tab) in enumerate(zip(objs, tabs)):
                        self._archive.write_tenant_window(
                            {k: np.asarray(v) for k, v in tab.items()},
                            window=int(obj["Window"]), ts_ms=ts_ms,
                            tenant=t)
            except Exception as exc:
                log.error("tenant archive segment write failed (window %s "
                          "not fully archived; reports already "
                          "published): %s", objs[0]["Window"], exc)
                if self._metrics is not None:
                    self._metrics.count_error("tpu-sketch-archive")
        if self._metrics is not None:
            m = self._metrics
            m.sketch_heavy_evictions_total.inc(
                sum(o["HeavyChurn"]["evictions"] for o in objs))
            if self._cfg.tiered is not None and tables is not None:
                try:
                    for t, tab in enumerate(tabs):
                        self._publish_tier_metrics(tab, tenant=t)
                except Exception as exc:  # telemetry never loses a report
                    log.warning("tier metrics publish failed: %s", exc)
            m.sketch_window_reports_total.inc()
            # agent-level gauges aggregate across tenants; the per-tenant
            # series carries each plane's own window totals
            m.sketch_window_records.set(sum(o["Records"] for o in objs))
            m.sketch_window_drop_bytes.set(
                sum(o["DropBytes"] for o in objs))
            for t, obj in enumerate(objs):
                m.sketch_tenant_window_records.labels(str(t)).set(
                    obj["Records"])
            for sig, key in SIGNAL_FIELDS.items():
                m.sketch_window_suspects.labels(sig).set(
                    sum(len(o[key]) for o in objs))
