"""gRPC flow exporter.

Reference analog: `pkg/exporter/grpc_proto.go` — batches split at
GRPC_MESSAGE_MAX_FLOWS; optional periodic reconnect with randomization so a
load-balanced collector tier rebalances (`grpc_proto.go:84-106,131-144`).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Optional

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.pb_convert import records_to_pb
from netobserv_tpu.grpc.flow import FlowClient
from netobserv_tpu.model.record import Record

log = logging.getLogger("netobserv_tpu.exporter.grpc")


class GRPCFlowExporter(Exporter):
    name = "grpc"

    def __init__(self, host: str, port: int, max_flows_per_message: int = 10000,
                 tls_ca: str = "", tls_cert: str = "", tls_key: str = "",
                 reconnect_every_s: Optional[float] = None,
                 reconnect_randomization_s: float = 0.0, metrics=None,
                 client: Optional[FlowClient] = None):
        self._client = client or FlowClient(host, port, tls_ca, tls_cert, tls_key)
        self._max_flows = max_flows_per_message
        self._reconnect_every = reconnect_every_s
        self._reconnect_rand = reconnect_randomization_s
        self._next_reconnect = self._compute_next_reconnect()

    def _compute_next_reconnect(self) -> Optional[float]:
        if not self._reconnect_every:
            return None
        jitter = random.uniform(-1, 1) * self._reconnect_rand
        return time.monotonic() + max(self._reconnect_every + jitter, 1.0)

    def export_batch(self, records: list[Record]) -> None:
        if (self._next_reconnect is not None
                and time.monotonic() >= self._next_reconnect):
            log.debug("periodic gRPC reconnect for collector rebalancing")
            self._client.connect()
            self._next_reconnect = self._compute_next_reconnect()
        for start in range(0, len(records), self._max_flows):
            chunk = records[start:start + self._max_flows]
            self._client.send(records_to_pb(chunk))

    def close(self) -> None:
        self._client.close()
