"""Federation delta sink: streams sketch-delta frames to the aggregator.

Exporter-seam semantics, same rules as every other exporter (CLAUDE.md):
errors are swallowed + counted, never fatal — a dead aggregator must not
stall the window timer or lose the local JSON report. Each frame gets a
small bounded retry ladder with exponential backoff and a reconnect between
attempts (the aggregator tier restarts/rebalances like any collector); a
frame that exhausts its ladder is dropped and counted, because the NEXT
window's frame supersedes it anyway (deltas are per-window snapshots, not a
log — re-sending stale windows after an outage would only delay fresh
ones).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from netobserv_tpu.grpc.federation import FederationClient

log = logging.getLogger("netobserv_tpu.exporter.federation")


class FederationDeltaSink:
    """Callable `(frame_bytes) -> bool` used by TpuSketchExporter at window
    publish time (timer thread — never under the exporter lock)."""

    name = "federation"

    def __init__(self, host: str, port: int, tls_ca: str = "",
                 tls_cert: str = "", tls_key: str = "",
                 retries: int = 3, backoff_initial_s: float = 0.2,
                 backoff_max_s: float = 2.0, timeout_s: float = 10.0,
                 metrics=None,
                 client: Optional[FederationClient] = None):
        self._client = client or FederationClient(host, port, tls_ca,
                                                  tls_cert, tls_key)
        self._retries = max(1, retries)
        self._backoff_initial = backoff_initial_s
        self._backoff_max = backoff_max_s
        self._timeout = timeout_s
        self._metrics = metrics

    def __call__(self, frame: bytes) -> bool:
        """Push one frame; True when the aggregator accepted it. Never
        raises — failures are logged + counted and the frame is dropped."""
        err: Exception | None = None
        for attempt in range(self._retries):
            try:
                ack = self._client.send(frame, timeout_s=self._timeout)
                if ack.accepted:
                    self._count("ok", len(frame))
                    return True
                # the aggregator SAW the frame and said no (version/shape
                # mismatch): retrying the same bytes cannot succeed
                log.error("aggregator rejected delta frame: %s", ack.reason)
                self._count("rejected", len(frame))
                return False
            except Exception as exc:
                err = exc
                if attempt + 1 < self._retries:
                    time.sleep(min(self._backoff_initial * (2 ** attempt),
                                   self._backoff_max))
                    try:
                        self._client.connect()
                    except Exception:
                        pass  # next send() attempt surfaces the real error
        log.error("delta frame dropped after %d attempts: %s",
                  self._retries, err)
        self._count("error", len(frame))
        return False

    def _count(self, result: str, n_bytes: int) -> None:
        m = self._metrics
        if m is not None:
            m.federation_deltas_sent_total.labels(result).inc()
            if result == "error":
                m.count_export_error(self.name, "delta_push")

    def close(self) -> None:
        self._client.close()
