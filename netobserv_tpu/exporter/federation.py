"""Federation delta sink: streams sketch-delta frames to the aggregator.

Exporter-seam semantics, same rules as every other exporter (CLAUDE.md):
errors are swallowed + counted, never fatal — a dead aggregator must not
stall the window timer or lose the local JSON report. Each frame gets a
small bounded retry ladder with exponential backoff and a reconnect between
attempts (the aggregator tier restarts/rebalances like any collector); a
frame that exhausts its ladder is dropped and counted, because the NEXT
window's frame supersedes it anyway (deltas are per-window snapshots, not a
log — re-sending stale windows after an outage would only delay fresh
ones).

Failure classification (`grpc.federation.classify_rpc_error`):

- **retry-safe** (UNAVAILABLE, DEADLINE_EXCEEDED, ...): walk the ladder.
  DEADLINE_EXCEEDED is the ambiguous one — the aggregator may have applied
  the push before the deadline fired — and retrying it is safe ONLY
  because v2 frames carry an idempotency key the aggregator's ledger
  dedups on (a redelivered frame acks `accepted+duplicate`, counted here
  as `duplicate`, never double-merged). A stale-window discard acks the
  same way on the wire but its data was NOT merged — the ack reason
  (`delta.ACK_REASON_STALE`) splits it into the `stale` count so
  agent-side monitoring sees the loss.
- **terminal** (INVALID_ARGUMENT, UNIMPLEMENTED, ...): resending the same
  bytes cannot succeed; fail fast without burning the ladder.

The ladder state is PER WINDOW: every `__call__` (one frame = one closed
window) starts back at `backoff_initial_s` — a bad window must not tax the
next one's first attempt (pinned by tests/test_federation.py).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from netobserv_tpu.federation.delta import ACK_REASON_STALE
from netobserv_tpu.grpc.federation import FederationClient, classify_rpc_error

log = logging.getLogger("netobserv_tpu.exporter.federation")


class FederationDeltaSink:
    """Callable `(frame_bytes) -> bool` used by TpuSketchExporter at window
    publish time (timer thread — never under the exporter lock)."""

    name = "federation"

    def __init__(self, host: str, port: int, tls_ca: str = "",
                 tls_cert: str = "", tls_key: str = "",
                 retries: int = 3, backoff_initial_s: float = 0.2,
                 backoff_max_s: float = 2.0, timeout_s: float = 10.0,
                 metrics=None,
                 client: Optional[FederationClient] = None,
                 sleep=time.sleep):
        self._client = client or FederationClient(host, port, tls_ca,
                                                  tls_cert, tls_key)
        self._retries = max(1, retries)
        self._backoff_initial = backoff_initial_s
        self._backoff_max = backoff_max_s
        self._timeout = timeout_s
        self._metrics = metrics
        self._sleep = sleep
        #: the delays slept by the MOST RECENT __call__ — introspection for
        #: the per-window ladder-reset pin (tests), not control flow
        self.last_ladder: list[float] = []

    def __call__(self, frame: bytes) -> bool:
        """Push one frame; True when the aggregator accepted it (applied
        OR safely deduplicated). Never raises — failures are logged +
        counted and the frame is dropped."""
        err: Exception | None = None
        # ladder state is local to this window's frame: a previous
        # window's exhausted ladder never escalates this one's first try
        self.last_ladder = []
        for attempt in range(self._retries):
            try:
                ack = self._client.send(frame, timeout_s=self._timeout)
                if ack.accepted:
                    if getattr(ack, "duplicate", 0):
                        if getattr(ack, "reason", "") == ACK_REASON_STALE:
                            # acked only so we stop resending: the window
                            # was DISCARDED as stale/out-of-order, not
                            # merged — that is per-window data loss (epoch
                            # step-back, reordering) and must not hide
                            # under the benign `duplicate` count
                            log.warning("aggregator discarded delta frame "
                                        "as stale (window data lost)")
                            self._count("stale", len(frame))
                        else:
                            # an earlier (timed-out but delivered) attempt
                            # already applied this window — the ledger did
                            # its job; a success, distinctly counted
                            self._count("duplicate", len(frame))
                    else:
                        self._count("ok", len(frame))
                    return True
                # the aggregator SAW the frame and said no (version/shape
                # mismatch): retrying the same bytes cannot succeed
                log.error("aggregator rejected delta frame: %s", ack.reason)
                self._count("rejected", len(frame))
                return False
            except Exception as exc:
                err = exc
                if classify_rpc_error(exc) == "terminal":
                    log.error("delta push failed terminally (%s) — not "
                              "retrying: %s", type(exc).__name__, exc)
                    self._count("terminal", len(frame))
                    return False
                if attempt + 1 < self._retries:
                    delay = min(self._backoff_initial * (2 ** attempt),
                                self._backoff_max)
                    self.last_ladder.append(delay)
                    self._sleep(delay)
                    try:
                        self._client.connect()
                    except Exception:
                        pass  # next send() attempt surfaces the real error
        log.error("delta frame dropped after %d attempts: %s",
                  self._retries, err)
        self._count("error", len(frame))
        return False

    def _count(self, result: str, n_bytes: int) -> None:
        m = self._metrics
        if m is not None:
            m.federation_deltas_sent_total.labels(result).inc()
            if result in ("error", "terminal"):
                m.count_export_error(self.name, "delta_push")

    def close(self) -> None:
        self._client.close()
