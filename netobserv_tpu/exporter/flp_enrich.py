"""Kubernetes and GeoIP enrichment backends for the embedded FLP pipeline.

Closes the `add_kubernetes` / `add_location` gap in `direct_flp`
(reference: FLP `transform_network.go:78-126`, `kubernetes/enrich.go:37-104`,
`location/location.go`): the rules are fully implemented here against
PLUGGABLE data sources, because the data itself must come from outside the
process — a cluster API watch for Kubernetes, a GeoIP database for
location. The agent wires file-backed defaults (`FLP_KUBE_MAP`,
`FLP_LOCATION_DB`); tests and embedders inject mocks implementing the same
two-method protocols. A live-cluster informer is a `KubeDataSource` whose
`lookup` reads its watch cache — the enrichment logic is identical.
"""

from __future__ import annotations

import bisect
import csv
import ipaddress
import json
import logging
from dataclasses import dataclass, field
from typing import Optional

log = logging.getLogger("netobserv_tpu.exporter.flp_enrich")


# ---------------------------------------------------------------------------
# Kubernetes
# ---------------------------------------------------------------------------


@dataclass
class KubeInfo:
    """What the datasource knows about one IP (FLP `model.ResourceMetaData`
    subset)."""

    name: str
    kind: str = "Pod"  # Pod | Service | Node
    namespace: str = ""
    owner_name: str = ""
    owner_kind: str = ""
    network_name: str = ""
    host_ip: str = ""
    host_name: str = ""
    zone: str = ""
    uid: str = ""
    labels: dict = field(default_factory=dict)


class KubeDataSource:
    """Protocol: map an IP to Kubernetes metadata. Implementations: the
    file-backed `StaticKubeDataSource` below, test mocks, or a live
    apiserver watch (same shape as FLP's informers datasource)."""

    def lookup(self, ip: str) -> Optional[KubeInfo]:  # pragma: no cover
        raise NotImplementedError


class StaticKubeDataSource(KubeDataSource):
    """IP -> KubeInfo from a dict or JSON file:
    `{"10.0.0.5": {"name": "web-1", "kind": "Pod", "namespace": "prod",
                   "owner_name": "web", "owner_kind": "Deployment", ...}}`.
    The file-backed flavor of the informer for air-gapped / test use."""

    def __init__(self, mapping: Optional[dict] = None,
                 path: Optional[str] = None):
        if mapping is None:
            with open(path) as fh:  # type: ignore[arg-type]
                mapping = json.load(fh)
        self._by_ip = {
            ip: info if isinstance(info, KubeInfo) else KubeInfo(**info)
            for ip, info in (mapping or {}).items()}

    def lookup(self, ip: str) -> Optional[KubeInfo]:
        return self._by_ip.get(ip)


# assignee -> FLP output key suffixes (api/transform_network.go:136-163)
_FLP_KEYS = {
    "namespace": "_Namespace", "name": "_Name", "kind": "_Type",
    "owner_name": "_OwnerName", "owner_kind": "_OwnerType",
    "network_name": "_NetworkName", "host_ip": "_HostIP",
    "host_name": "_HostName", "zone": "_Zone",
}
_OTEL_KEYS = {
    "namespace": "k8s.namespace.name", "name": "k8s.name",
    "kind": "k8s.type", "owner_name": "k8s.owner.name",
    "owner_kind": "k8s.owner.type", "network_name": "k8s.net.name",
    "host_ip": "k8s.host.ip", "host_name": "k8s.host.name",
    "zone": "k8s.zone",
}


def enrich_kubernetes(entry: dict, rule: dict,
                      source: KubeDataSource) -> None:
    """Apply one `add_kubernetes` rule in place (kubernetes/enrich.go:37-87):
    resolve the rule's IP field and write namespace/name/type/owner/host
    under the rule's output prefix; optional labels under `labels_prefix`."""
    ip = entry.get(rule.get("ipField") or rule.get("input"))
    if not isinstance(ip, str):
        return
    info = source.lookup(ip)
    if info is None:
        return
    out = rule.get("output") or ""
    keys = _OTEL_KEYS if rule.get("assignee") == "otel" else _FLP_KEYS
    if info.namespace:  # NETOBSERV-666: never write empty namespaces
        entry[out + keys["namespace"]] = info.namespace
    entry[out + keys["name"]] = info.name
    entry[out + keys["kind"]] = info.kind
    entry[out + keys["owner_name"]] = info.owner_name or info.name
    entry[out + keys["owner_kind"]] = info.owner_kind or info.kind
    if info.network_name:
        entry[out + keys["network_name"]] = info.network_name
    if info.host_ip:
        entry[out + keys["host_ip"]] = info.host_ip
        if info.host_name:
            entry[out + keys["host_name"]] = info.host_name
    if rule.get("add_zone") and info.zone:
        entry[out + keys["zone"]] = info.zone
    prefix = rule.get("labels_prefix")
    if prefix:
        for k, v in info.labels.items():
            entry[f"{prefix}_{k}"] = v


# ---------------------------------------------------------------------------
# GeoIP location
# ---------------------------------------------------------------------------

LOCATION_FIELDS = ("CountryName", "CountryLongName", "RegionName",
                   "CityName", "Latitude", "Longitude")


class LocationDB:
    """Protocol: map an IP to the six FLP location fields."""

    def lookup(self, ip: str) -> Optional[dict]:  # pragma: no cover
        raise NotImplementedError


class CsvLocationDB(LocationDB):
    """Range CSV in the ip2location LITE layout the reference downloads
    (location.go:46-51): rows of
    `ip_from,ip_to,country_code,country_name,region,city,lat,lon` with
    numeric range bounds (IPv4 as u32, IPv6 as u128 — families are kept in
    separate sorted tables, binary-searched per lookup)."""

    def __init__(self, path: str):
        self._v4: list[tuple[int, int, dict]] = []
        self._v6: list[tuple[int, int, dict]] = []
        # v4-mapped space in IPv6-layout DBs: ::ffff:0:0/96 as u128 bounds
        map_lo = 0xFFFF00000000
        map_hi = map_lo + 0xFFFFFFFF
        with open(path, newline="") as fh:
            for row in csv.reader(fh):
                if (len(row) < 8 or not row[0].strip().isdigit()
                        or not row[1].strip().isdigit()):
                    continue  # malformed rows are skipped, never fatal
                lo, hi = int(row[0]), int(row[1])
                info = {
                    "CountryName": row[2].strip(),
                    "CountryLongName": row[3].strip(),
                    "RegionName": row[4].strip(),
                    "CityName": row[5].strip(),
                    "Latitude": row[6].strip(),
                    "Longitude": row[7].strip(),
                }
                if map_lo <= lo and hi <= map_hi:
                    # IPv6-layout DBs carry IPv4 as ::ffff-mapped ranges;
                    # normalize to the v4 table (lookups normalize inputs
                    # the same way)
                    self._v4.append((lo - map_lo, hi - map_lo, info))
                elif hi > 0xFFFFFFFF:
                    self._v6.append((lo, hi, info))
                else:
                    self._v4.append((lo, hi, info))
        self._v4.sort(key=lambda t: t[0])
        self._v6.sort(key=lambda t: t[0])
        self._v4_lo = [t[0] for t in self._v4]
        self._v6_lo = [t[0] for t in self._v6]

    def lookup(self, ip: str) -> Optional[dict]:
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            return None
        if addr.version == 6 and isinstance(
                addr, ipaddress.IPv6Address) and addr.ipv4_mapped:
            addr = addr.ipv4_mapped
        n = int(addr)
        table, los = ((self._v4, self._v4_lo) if addr.version == 4
                      else (self._v6, self._v6_lo))
        i = bisect.bisect_right(los, n) - 1
        if i >= 0 and table[i][0] <= n <= table[i][1]:
            return table[i][2]
        return None


def enrich_location(entry: dict, rule: dict, db: LocationDB) -> None:
    """Apply one `add_location` rule in place (transform_network.go:78-90)."""
    ip = entry.get(rule.get("input"))
    if not isinstance(ip, str):
        return
    info = db.lookup(ip)
    if info is None:
        return
    out = rule.get("output") or ""
    for f in LOCATION_FIELDS:
        entry[out + "_" + f] = info.get(f, "")
