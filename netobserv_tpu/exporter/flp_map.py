"""Record -> FLP GenericMap field naming.

Reference analog: `pkg/decode/decode_protobuf.go:57-197` (`RecordToMap`) — the
field names the flowlogs-pipeline ecosystem expects. Used by the direct-flp
exporter and by the Kafka JSON option.
"""

from __future__ import annotations

from netobserv_tpu.exporter.flp_tables import (
    dns_rcode_to_str, pkt_drop_cause_to_str, tcp_state_to_str,
)
from netobserv_tpu.model.flow import ip_from_16
from netobserv_tpu.model.record import Record
from netobserv_tpu.model import tls_types


def _mac(raw: bytes) -> str:
    return ":".join(f"{b:02X}" for b in raw)


def record_to_map(r: Record) -> dict:
    """FLP GenericMap for one flow record."""
    f = r.features
    out = {
        "FlowDirection": r.direction,
        "Bytes": r.bytes_,
        "Packets": r.packets,
        "SrcAddr": r.key.src,
        "DstAddr": r.key.dst,
        "SrcMac": _mac(r.src_mac),
        "DstMac": _mac(r.dst_mac),
        "Etype": r.eth_protocol,
        "Duplicate": False,
        "TimeFlowStartMs": r.time_flow_start_ns // 1_000_000,
        "TimeFlowEndMs": r.time_flow_end_ns // 1_000_000,
        "TimeReceived": r.time_flow_end_ns // 1_000_000_000,
        "Interface": r.interface,
        "Interfaces": [d[0] for d in r.dup_list] or [r.interface],
        "IfDirections": [d[1] for d in r.dup_list] or [r.direction],
        "AgentIP": r.agent_ip,
        "Sampling": r.sampling,
    }
    if r.udn or any(d[2] for d in r.dup_list):
        out["Udns"] = [d[2] for d in r.dup_list] or [r.udn]
    if r.dscp:
        out["Dscp"] = r.dscp
    out["Proto"] = r.key.proto
    if r.key.proto in (1, 58):  # ICMP / ICMPv6
        out["IcmpType"] = r.key.icmp_type
        out["IcmpCode"] = r.key.icmp_code
    elif r.key.proto in (6, 17, 132):  # TCP / UDP / SCTP carry ports
        out["SrcPort"] = r.key.src_port
        out["DstPort"] = r.key.dst_port
    if r.key.proto == 6:
        out["Flags"] = r.tcp_flags
    if f.drop_packets or f.drop_bytes:
        out["PktDropBytes"] = f.drop_bytes
        out["PktDropPackets"] = f.drop_packets
        out["PktDropLatestFlags"] = f.drop_latest_flags
        out["PktDropLatestState"] = tcp_state_to_str(f.drop_latest_state)
        out["PktDropLatestDropCause"] = pkt_drop_cause_to_str(
            f.drop_latest_cause)
    if f.dns_id or f.dns_latency_ns or f.dns_errno:
        out["DnsId"] = f.dns_id
        out["DnsFlags"] = f.dns_flags
        out["DnsErrno"] = f.dns_errno
        out["DnsFlagsResponseCode"] = dns_rcode_to_str(f.dns_flags & 0xF)
        if f.dns_latency_ns:
            out["DnsLatencyMs"] = f.dns_latency_ns // 1_000_000
        if f.dns_name:
            out["DnsName"] = f.dns_name
    if f.rtt_ns:
        out["TimeFlowRttNs"] = f.rtt_ns
    if f.network_events:
        from netobserv_tpu.utils.ovn_decoder import decode_event
        out["NetworkEvents"] = [decode_event(ev) for ev in f.network_events]
    if f.xlat_src_ip:
        out["XlatSrcAddr"] = ip_from_16(f.xlat_src_ip)
        out["XlatDstAddr"] = ip_from_16(f.xlat_dst_ip)
        out["XlatSrcPort"] = f.xlat_src_port
        out["XlatDstPort"] = f.xlat_dst_port
        out["ZoneId"] = f.xlat_zone_id
    if f.ipsec_encrypted or f.ipsec_encrypted_ret:
        out["IPSecRet"] = f.ipsec_encrypted_ret
        out["IPSecStatus"] = "success" if f.ipsec_encrypted else "failure"
    if r.ssl_version:
        out["TlsVersion"] = tls_types.tls_version_name(r.ssl_version)
    if r.tls_cipher_suite:
        out["TlsCipher"] = tls_types.cipher_suite_name(r.tls_cipher_suite)
    if r.tls_key_share:
        out["TlsKeyShare"] = tls_types.key_share_name(r.tls_key_share)
    if r.tls_types:
        # set for any TLS record type, hello or not (mid-connection attach)
        out["TlsTypes"] = tls_types.tls_types_names(r.tls_types)
    if r.ssl_mismatch:
        out["TlsMismatch"] = True
    if f.ssl_plaintext_events:
        out["SslPlaintextEvents"] = f.ssl_plaintext_events
        out["SslPlaintextBytes"] = f.ssl_plaintext_bytes
    if f.quic_version or f.quic_seen_long_hdr or f.quic_seen_short_hdr:
        out["QuicVersion"] = f.quic_version
        out["QuicLongHdr"] = f.quic_seen_long_hdr
        out["QuicShortHdr"] = f.quic_seen_short_hdr
    return out


def _parse_mac(v) -> bytes:
    if isinstance(v, bytes):
        return (v + b"\x00" * 6)[:6]
    try:
        return bytes(int(p, 16) for p in str(v).split(":"))[:6].ljust(6, b"\x00")
    except ValueError:
        return b"\x00" * 6


def map_to_record(entry: dict) -> Record:
    """Inverse of `record_to_map` for the fields the wire exporters carry
    (IPFIX templates, pbflow) — lets FLP write stages reuse the Record-based
    exporters on an entry stream that has passed through transform stages.
    Unknown/enriched keys are ignored; missing keys default to zero values
    (same tolerance as the reference's generic-map decode,
    pkg/decode/decode_protobuf.go)."""
    from netobserv_tpu.model.flow import FlowKey, ip_to_16

    key = FlowKey(
        src_ip=ip_to_16(entry.get("SrcAddr", "0.0.0.0")),
        dst_ip=ip_to_16(entry.get("DstAddr", "0.0.0.0")),
        src_port=int(entry.get("SrcPort", 0)),
        dst_port=int(entry.get("DstPort", 0)),
        proto=int(entry.get("Proto", 0)),
        icmp_type=int(entry.get("IcmpType", 0)),
        icmp_code=int(entry.get("IcmpCode", 0)))
    return Record(
        key=key,
        bytes_=int(entry.get("Bytes", 0)),
        packets=int(entry.get("Packets", 0)),
        eth_protocol=int(entry.get("Etype", 0)),
        tcp_flags=int(entry.get("Flags", 0)),
        direction=int(entry.get("FlowDirection", 0)),
        src_mac=_parse_mac(entry.get("SrcMac", "")),
        dst_mac=_parse_mac(entry.get("DstMac", "")),
        interface=str(entry.get("Interface", "")),
        dscp=int(entry.get("Dscp", 0)),
        sampling=int(entry.get("Sampling", 0)),
        time_flow_start_ns=int(entry.get("TimeFlowStartMs", 0)) * 1_000_000,
        time_flow_end_ns=int(entry.get("TimeFlowEndMs", 0)) * 1_000_000,
        agent_ip=str(entry.get("AgentIP", "")),
    )
