"""stdout exporter: JSON flow lines — the smoke-test surface.

(direct-flp mode, which writes FLP GenericMap-shaped entries through an
in-process pipeline, lives in `netobserv_tpu.exporter.direct_flp`.)
"""

from __future__ import annotations

import json
import sys
from typing import IO, Optional

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.model.record import Record


class StdoutJSONExporter(Exporter):
    name = "stdout"

    def __init__(self, stream: Optional[IO[str]] = None, metrics=None):
        self._stream = stream if stream is not None else sys.stdout

    def export_batch(self, records: list[Record]) -> None:
        for r in records:
            self._stream.write(
                json.dumps(r.to_json_obj(), separators=(",", ":")) + "\n")
        self._stream.flush()
