"""stdout / direct exporter: JSON lines to a stream.

The reference's smoke-test path (direct-flp with a stdout writer,
`README.md:56-80`); doubles as the e2e assertion surface here.
"""

from __future__ import annotations

import json
import sys
from typing import IO, Optional

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.flp_map import record_to_map
from netobserv_tpu.model.record import Record


class StdoutJSONExporter(Exporter):
    name = "stdout"

    def __init__(self, stream: Optional[IO[str]] = None, metrics=None,
                 flp_format: bool = False, flp_config: str = ""):
        self._stream = stream if stream is not None else sys.stdout
        self._flp = flp_format
        # flp_config (a pipeline YAML/JSON) is accepted for parity; the only
        # in-process stage implemented so far is the stdout writer
        self._flp_config = flp_config

    def export_batch(self, records: list[Record]) -> None:
        for r in records:
            obj = record_to_map(r) if self._flp else r.to_json_obj()
            self._stream.write(json.dumps(obj, separators=(",", ":")) + "\n")
        self._stream.flush()
