"""Export/communication layer (L4 in SURVEY.md §1).

Every exporter is a terminal consumer of `list[Record]` batches — the
`ExportFlows(<-chan []*model.Record)` contract (`pkg/agent/agent.go:83`). The
tpu-sketch backend plugs in at this exact seam (BASELINE.json north star), so
agent wiring is backend-agnostic.
"""

from netobserv_tpu.exporter.base import Exporter, QueueExporter  # noqa: F401
from netobserv_tpu.exporter.stdout_json import StdoutJSONExporter  # noqa: F401
from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter  # noqa: F401


def build_exporter(cfg, metrics=None):
    """Backend switch (reference analog: `pkg/agent/agent.go:246-261`)."""
    from netobserv_tpu import config as c
    if cfg.export == c.EXPORT_STDOUT:
        return StdoutJSONExporter(metrics=metrics)
    if cfg.export == c.EXPORT_DIRECT_FLP:
        from netobserv_tpu.exporter.direct_flp import DirectFLPExporter
        kube_source = location_db = None
        if cfg.flp_kube_map:
            from netobserv_tpu.exporter.flp_enrich import StaticKubeDataSource
            kube_source = StaticKubeDataSource(path=cfg.flp_kube_map)
        if cfg.flp_location_db:
            from netobserv_tpu.exporter.flp_enrich import CsvLocationDB
            location_db = CsvLocationDB(cfg.flp_location_db)
        return DirectFLPExporter(
            flp_config=cfg.flp_config,
            # encode/prom metrics surface on the agent's /metrics server
            prom_registry=metrics.registry if metrics is not None else None,
            kube_source=kube_source, location_db=location_db)
    if cfg.export == c.EXPORT_TPU_SKETCH:
        return TpuSketchExporter.from_config(cfg, metrics=metrics)
    if cfg.export == c.EXPORT_GRPC:
        from netobserv_tpu.exporter.grpc_flow import GRPCFlowExporter
        return GRPCFlowExporter(
            host=cfg.target_host, port=cfg.target_port,
            max_flows_per_message=cfg.grpc_message_max_flows,
            tls_ca=cfg.target_tls_ca_cert_path,
            tls_cert=cfg.target_tls_user_cert_path,
            tls_key=cfg.target_tls_user_key_path,
            reconnect_every_s=cfg.grpc_reconnect_timer or None,
            reconnect_randomization_s=cfg.grpc_reconnect_timer_randomization,
            metrics=metrics)
    if cfg.export in (c.EXPORT_IPFIX_UDP, c.EXPORT_IPFIX_TCP):
        from netobserv_tpu.exporter.ipfix import IPFIXExporter
        return IPFIXExporter(
            host=cfg.target_host, port=cfg.target_port,
            transport="udp" if cfg.export == c.EXPORT_IPFIX_UDP else "tcp",
            metrics=metrics)
    if cfg.export == c.EXPORT_KAFKA:
        from netobserv_tpu.exporter.kafka import KafkaExporter
        return KafkaExporter.from_config(cfg, metrics=metrics)
    raise ValueError(f"unknown exporter {cfg.export!r}")
