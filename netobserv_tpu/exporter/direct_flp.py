"""direct-flp: an embedded in-process flowlogs-pipeline.

Reference analog: `pkg/exporter/direct_flp.go` — the agent feeds records
(converted to FLP GenericMaps, `pkg/decode` field naming) into a pipeline
described by FLP_CONFIG (YAML or JSON) instead of shipping them anywhere.

Supported stage subset (the shapes the reference's smoke-test configs use):
- ingest is implicit (the agent's record stream)
- `transform` / type `filter`: rules `remove_field`, `keep_entry_if_exists`,
  `keep_entry_if_doesnt_exist`, `keep_entry_if_equal`, `keep_entry_if_not_equal`
- `transform` / type `generic`: `policy: replace_keys` with `rules` [{input,
  output}] field renaming
- `write` / type `stdout` (default when no pipeline is configured) or `ipfix`/
  `grpc` terminal re-export
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Callable, Optional

import yaml

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.flp_map import record_to_map
from netobserv_tpu.model.record import Record

log = logging.getLogger("netobserv_tpu.exporter.direct_flp")

Stage = Callable[[dict], Optional[dict]]


def _build_filter(params: dict) -> Stage:
    rules = params.get("rules", [])

    def stage(entry: dict) -> Optional[dict]:
        for rule in rules:
            rtype = rule.get("type")
            field = rule.get("removeField", rule.get(
                "keepEntryField", rule.get("input", rule.get("field"))))
            value = rule.get("keepEntryValue", rule.get("value"))
            if rtype == "remove_field":
                entry.pop(field, None)
            elif rtype == "keep_entry_if_exists":
                if field not in entry:
                    return None
            elif rtype == "keep_entry_if_doesnt_exist":
                if field in entry:
                    return None
            elif rtype == "keep_entry_if_equal":
                if entry.get(field) != value:
                    return None
            elif rtype == "keep_entry_if_not_equal":
                if entry.get(field) == value:
                    return None
        return entry

    return stage


def _build_generic(params: dict) -> Stage:
    rules = params.get("rules", [])
    policy = params.get("policy", "replace_keys")

    def stage(entry: dict) -> Optional[dict]:
        out = {} if policy == "replace_keys" else dict(entry)
        for rule in rules:
            src, dst = rule.get("input"), rule.get("output")
            if src in entry:
                out[dst or src] = entry[src]
        return out

    return stage


class DirectFLPExporter(Exporter):
    name = "direct-flp"

    def __init__(self, flp_config: str = "", stream=None):
        self._stream = stream if stream is not None else sys.stdout
        self._stages: list[Stage] = []
        if flp_config.strip():
            self._build(yaml.safe_load(flp_config))

    def _build(self, cfg: dict) -> None:
        params = {p.get("name"): p for p in cfg.get("parameters", [])}
        # follow the pipeline order; ingest stages are implicit/skipped
        for step in cfg.get("pipeline", []):
            p = params.get(step.get("name"), {})
            if "transform" in p:
                t = p["transform"]
                ttype = t.get("type")
                if ttype == "filter":
                    self._stages.append(_build_filter(t.get("filter", {})))
                elif ttype == "generic":
                    self._stages.append(_build_generic(t.get("generic", {})))
                else:
                    log.warning("unsupported transform type %r ignored", ttype)
            elif "write" in p:
                wtype = p["write"].get("type", "stdout")
                if wtype != "stdout":
                    log.warning("write type %r unsupported; using stdout", wtype)
            elif "ingest" in p or not p:
                continue

    def export_batch(self, records: list[Record]) -> None:
        for r in records:
            entry: Optional[dict] = record_to_map(r)
            for stage in self._stages:
                entry = stage(entry)
                if entry is None:
                    break
            if entry is not None:
                self._stream.write(
                    json.dumps(entry, separators=(",", ":")) + "\n")
        self._stream.flush()
