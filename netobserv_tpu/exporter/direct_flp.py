"""direct-flp: an embedded in-process flowlogs-pipeline.

Reference analog: `pkg/exporter/direct_flp.go` — the agent feeds records
(converted to FLP GenericMaps, `pkg/decode` field naming) into a pipeline
described by FLP_CONFIG (YAML or JSON) instead of shipping them anywhere.

Supported stage subset (the shapes the reference's smoke-test configs use):
- ingest is implicit (the agent's record stream)
- `transform` / type `filter`: rules `remove_field`, `keep_entry_if_exists`,
  `keep_entry_if_doesnt_exist`, `keep_entry_if_equal`, `keep_entry_if_not_equal`
- `transform` / type `generic`: `policy: replace_keys` with `rules` [{input,
  output}] field renaming
- `transform` / type `network` (FLP transform_network.go subset): rules
  `add_subnet`, `add_service`, `add_subnet_label`, `decode_tcp_flags`,
  `reinterpret_direction`, plus `add_kubernetes`/`add_location` backed by
  PLUGGABLE data sources (exporter.flp_enrich: a file-backed or injected
  Kubernetes datasource via FLP_KUBE_MAP, an ip2location-layout range CSV
  via FLP_LOCATION_DB); a rule whose backend isn't configured warns+skips
- `extract` / type `conntrack` (FLP api/conntrack.go subset): canonical
  bidirectional connection hashing, per-direction (splitAB) sum/count/min/
  max/first/last aggregates, newConnection/flowLog/heartbeat/endConnection
  records with FIN-driven and timeout-driven teardown (timers ride the
  batch cadence)
- `extract` / type `aggregates` (api/extract_aggregate.go subset): group-by
  sum/min/max/avg/count/raw_values with running totals + per-cycle recent_*
  values and group expiry; replaces the stream like FLP's Extract
- `extract` / type `timebased` (api/extract_timebased.go subset): sliding-
  window top-K over indexKeys by sum/min/max/avg/count/last/diff
- `encode` / type `prom` (FLP encode_prom.go subset): counter/gauge/
  histogram metrics with labels and equal/not_equal/presence/absence/
  match_regex filters, registered on the exporter's `prom_registry`
  (served by the agent's metrics server when one is running)
- `encode` / type `kafka` (encode_kafka.go): JSON entries produced to a
  topic through the in-repo wire producer
- `encode` / type `s3` (encode_s3.go): batched JSON objects with the FLP
  store header, SigV4-signed PUTs under the reference's object layout
- `write` / type `stdout` (default when no pipeline is configured), type
  `loki` (push-API JSON streams with label promotion and tenant header),
  type `ipfix` (v4/v6 templates through the wire exporter) or type `grpc`
  (pbflow Records to a Collector, TLS/mTLS)

Not embedded: the OTLP encode family (no OTLP SDK in this image) and FLP
ingest stages (meaningless in direct mode — the agent IS the ingest).
"""

from __future__ import annotations

import json
import logging
import sys
import time as _time
from typing import Callable, Optional

import yaml

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.flp_enrich import (
    enrich_kubernetes, enrich_location,
)
from netobserv_tpu.exporter.flp_map import record_to_map
from netobserv_tpu.model.flow import TcpFlags
from netobserv_tpu.model.record import Record

log = logging.getLogger("netobserv_tpu.exporter.direct_flp")

Stage = Callable[[dict], Optional[dict]]


def _build_filter(params: dict) -> Stage:
    rules = params.get("rules", [])

    def stage(entry: dict) -> Optional[dict]:
        for rule in rules:
            rtype = rule.get("type")
            field = rule.get("removeField", rule.get(
                "keepEntryField", rule.get("input", rule.get("field"))))
            value = rule.get("keepEntryValue", rule.get("value"))
            if rtype == "remove_field":
                entry.pop(field, None)
            elif rtype == "keep_entry_if_exists":
                if field not in entry:
                    return None
            elif rtype == "keep_entry_if_doesnt_exist":
                if field in entry:
                    return None
            elif rtype == "keep_entry_if_equal":
                if entry.get(field) != value:
                    return None
            elif rtype == "keep_entry_if_not_equal":
                if entry.get(field) == value:
                    return None
        return entry

    return stage


# FLP utils/tcp_flags.go table (incl. the synthetic combination bits) —
# derived from the model enum so the mapping cannot drift
_TCP_FLAG_NAMES = [(f.value, f.name) for f in TcpFlags]

_PROTO_NAMES = {6: "tcp", 17: "udp", 132: "sctp"}


def _build_network(params: dict, kube_source=None, location_db=None) -> Stage:
    """FLP `transform network` subset (transform_network.go:64-160).
    `kube_source`/`location_db` are the pluggable enrichment backends
    (exporter.flp_enrich); without one, the corresponding rule warns and
    skips (the data must come from outside the process)."""
    import ipaddress
    import socket as _socket

    rules = params.get("rules", [])
    subnet_labels = []
    for lbl in params.get("subnetLabels", []):
        nets = [ipaddress.ip_network(c) for c in lbl.get("cidrs", [])]
        subnet_labels.append((lbl.get("name", ""), nets))
    dir_info = params.get("directionInfo", {})
    svc_cache: dict = {}
    # resolve enrichment backends ONCE at build time: a per-record warning
    # or import in the stage loop would run at export rate
    if kube_source is None and any(
            r.get("type") == "add_kubernetes" for r in rules):
        log.warning("transform.network rule add_kubernetes needs a "
                    "Kubernetes datasource (set FLP_KUBE_MAP or inject "
                    "kube_source); rule(s) skipped")
        rules = [r for r in rules if r.get("type") != "add_kubernetes"]
    if location_db is None and any(
            r.get("type") == "add_location" for r in rules):
        log.warning("transform.network rule add_location needs a GeoIP "
                    "database (set FLP_LOCATION_DB or inject "
                    "location_db); rule(s) skipped")
        rules = [r for r in rules if r.get("type") != "add_location"]

    def service_name(port, proto) -> str:
        key = (port, proto)
        if key not in svc_cache:
            name = ""
            try:
                pnum = int(proto)
                pname = _PROTO_NAMES.get(pnum, "")
            except (TypeError, ValueError):
                pname = str(proto).lower()
            try:
                name = _socket.getservbyport(int(port), pname) if pname \
                    else _socket.getservbyport(int(port))
            except (OSError, OverflowError, ValueError):
                name = ""
            svc_cache[key] = name
        return svc_cache[key]

    def stage(entry: dict) -> Optional[dict]:
        for rule in rules:
            rtype = rule.get("type")
            if rtype == "add_subnet":
                r = rule.get("add_subnet", rule)
                ip = entry.get(r.get("input"))
                if isinstance(ip, str):
                    mask = str(r.get("parameters",
                                     r.get("subnet_mask", "/24")))
                    if not mask.startswith("/"):
                        mask = "/" + mask
                    try:
                        net = ipaddress.ip_network(ip + mask, strict=False)
                        entry[r.get("output")] = str(net)
                    except ValueError:
                        pass
            elif rtype == "add_service":
                r = rule.get("add_service", rule)
                port = entry.get(r.get("input"))
                proto = entry.get(r.get("protocol"))
                if port is not None:
                    name = service_name(port, proto)
                    if name:
                        entry[r.get("output")] = name
            elif rtype == "add_subnet_label":
                r = rule.get("add_subnet_label", rule)
                ip = entry.get(r.get("input"))
                if isinstance(ip, str):
                    try:
                        addr = ipaddress.ip_address(ip)
                    except ValueError:
                        continue
                    for name, nets in subnet_labels:
                        if any(addr in n for n in nets):
                            entry[r.get("output")] = name
                            break
            elif rtype == "decode_tcp_flags":
                r = rule.get("decode_tcp_flags", rule)
                flags = entry.get(r.get("input"))
                if flags is not None:
                    try:
                        bits = int(flags)
                    except (TypeError, ValueError):
                        continue
                    names = [n for v, n in _TCP_FLAG_NAMES if bits & v]
                    if names or r.get("output") == r.get("input"):
                        entry[r.get("output")] = names
            elif rtype == "reinterpret_direction":
                # transform_network_direction.go: per-node direction from
                # the reporter's viewpoint (0 ingress / 1 egress / 2 inner)
                fd_field = dir_info.get("flowDirectionField")
                if not fd_field:
                    continue
                if dir_info.get("ifDirectionField") and fd_field in entry:
                    entry[dir_info["ifDirectionField"]] = entry[fd_field]
                reporter = entry.get(dir_info.get("reporterIPField"))
                src = entry.get(dir_info.get("srcHostField"))
                dst = entry.get(dir_info.get("dstHostField"))
                if not reporter:
                    continue
                if src != dst:
                    if src == reporter:
                        entry[fd_field] = 1     # egress
                    elif dst == reporter:
                        entry[fd_field] = 0     # ingress
                elif src:
                    entry[fd_field] = 2         # inner
            elif rtype == "add_kubernetes":
                enrich_kubernetes(entry, rule.get("kubernetes", rule),
                                  kube_source)
            elif rtype == "add_location":
                enrich_location(entry, rule.get("add_location", rule),
                                location_db)
            else:
                # NB: add_kubernetes_infra (FLP flow-layer classification)
                # lands here — it is NOT the per-IP metadata rule and stays
                # unsupported-with-warning
                log.warning("transform.network rule %r unsupported; skipped",
                            rtype)
        return entry

    return stage


def _build_prom(params: dict, registry,
                seen_names: set | None = None) -> Stage:
    """FLP `encode prom` subset (encode_prom.go): declarative metrics from
    the entry stream, registered on `registry`. Entries pass through.
    `seen_names` spans every prom stage of ONE exporter build: a name
    declared by an earlier stage is a same-config duplicate (skip — binding
    two stages to one collector double-counts), while a name alive in the
    registry but NOT in seen_names is a rebuild survivor (adopt)."""
    import re

    from prometheus_client import Counter, Gauge, Histogram

    prefix = params.get("prefix", "")
    metrics = []
    if seen_names is None:
        seen_names = set()
    cls_for = {"counter": Counter, "gauge": Gauge,
               "histogram": Histogram, "agg_histogram": Histogram}
    for item in params.get("metrics", []):
        name = prefix + item.get("name", "")
        labels = list(item.get("labels", []))
        mtype = item.get("type", "counter")
        if mtype not in cls_for:
            log.warning("prom metric type %r unsupported; skipped", mtype)
            continue
        if name in seen_names:
            # two entries sharing a name within ONE config: binding both to
            # the same collector would double-count, so the first wins
            log.warning("prom metric %r declared twice; second skipped", name)
            continue
        kw = {"registry": registry}
        try:
            if mtype in ("histogram", "agg_histogram"):
                buckets = item.get("buckets") or Histogram.DEFAULT_BUCKETS
                m = Histogram(name, name, labels, buckets=buckets, **kw)
            else:
                m = cls_for[mtype](name, name, labels, **kw)
        except ValueError as exc:
            # already registered = an exporter REBUILD against the shared
            # agent registry (restart-in-place): adopt the live collector so
            # the new stage keeps updating it — skipping would freeze the
            # series forever; an incompatible survivor degrades to warn+skip
            # like every other unsupported-config case (never abort startup)
            existing = getattr(registry, "_names_to_collectors", {}).get(name)
            compatible = (isinstance(existing, cls_for[mtype])
                          and list(getattr(existing, "_labelnames", ()))
                          == labels)
            if compatible and isinstance(existing, Histogram):
                # bucket edits across a restart-in-place must not be
                # silently ignored — stale boundaries would misbin forever.
                # Mirror prometheus_client's normalization: +inf is only
                # appended when the declared list doesn't already end in it
                want = [float(b) for b in (item.get("buckets")
                                           or Histogram.DEFAULT_BUCKETS)]
                if not want or want[-1] != float("inf"):
                    want.append(float("inf"))
                have = list(getattr(existing, "_upper_bounds", ()))
                compatible = want == have
            if compatible:
                m = existing
                log.info("prom metric %r reused from registry", name)
            else:
                log.warning("prom metric %r not registered (%s); skipped",
                            name, exc)
                continue
        seen_names.add(name)
        filters = []
        for f in item.get("filters", []):
            ftype = f.get("type", "equal")
            key, value = f.get("key"), f.get("value")
            if ftype in ("match_regex", "not_match_regex"):
                value = re.compile(str(value))
            elif ftype in ("equal", "not_equal"):
                value = str(value)
            filters.append((ftype, key, value))
        metrics.append((m, mtype, item.get("valueKey", ""), labels, filters))

    def matches(entry: dict, filters) -> bool:
        for ftype, key, value in filters:
            present = key in entry
            ev = str(entry.get(key)) if present else ""
            if ftype == "equal" and ev != value:
                return False
            if ftype == "not_equal" and ev == value:
                return False
            if ftype == "presence" and not present:
                return False
            if ftype == "absence" and present:
                return False
            if ftype == "match_regex" and not value.search(ev):
                return False
            if ftype == "not_match_regex" and value.search(ev):
                return False
        return True

    def stage(entry: dict) -> Optional[dict]:
        for m, mtype, value_key, labels, filters in metrics:
            if not matches(entry, filters):
                continue
            if value_key:
                if value_key not in entry:
                    continue            # FLP skips on a missing value key
                try:
                    v = float(entry[value_key] or 0)
                except (TypeError, ValueError):
                    continue
            else:
                v = 1.0
            series = m.labels(*[str(entry.get(lb, "")) for lb in labels]) \
                if labels else m
            if mtype == "counter":
                series.inc(v)
            elif mtype == "gauge":
                series.set(v)
            else:
                series.observe(v)
        return entry

    return stage


class _ConnTrack:
    """FLP `extract conntrack` subset (api/conntrack.go): stitches
    unidirectional flow logs into connection records keyed by a canonical
    (bidirectional when fieldGroupARef/BRef are set) hash. Emits the
    configured record types: newConnection, flowLog, heartbeat,
    endConnection (timeout-, terminating- and FIN-driven). Timer semantics
    ride the exporter's batch cadence: sweeps run per exported batch, not on
    a wall-clock goroutine like FLP's."""

    def __init__(self, params: dict):
        kd = params.get("keyDefinition", {})
        self.groups = {g.get("name"): list(g.get("fields", []))
                       for g in kd.get("fieldGroups", [])}
        h = kd.get("hash", {})
        self.refs = [self.groups.get(r, []) for r in
                     h.get("fieldGroupRefs", [])]
        self.group_a = self.groups.get(h.get("fieldGroupARef"), [])
        self.group_b = self.groups.get(h.get("fieldGroupBRef"), [])
        self.bidi = bool(self.group_a and self.group_b)
        self.out_types = set(params.get("outputRecordTypes", ["flowLog"]))
        self.out_fields = [
            (f.get("name"), f.get("operation", "count"),
             bool(f.get("splitAB")), f.get("input") or f.get("name"))
            for f in params.get("outputFields", [])]
        sched = (params.get("scheduling") or [{}])[0]
        self.end_timeout = _duration_s(sched.get("endConnectionTimeout"), 10)
        self.term_timeout = _duration_s(sched.get("terminatingTimeout"), 5)
        self.heartbeat_s = _duration_s(sched.get("heartbeatInterval"), 30)
        # FLP default (api/conntrack.go doc): 100k; 0 stays unlimited
        self.max_tracked = int(
            params.get("maxConnectionsTracked", 100_000))
        tf = params.get("tcpFlags", {})
        self.flags_field = tf.get("fieldName", "")
        self.detect_end = bool(tf.get("detectEndConnection"))
        self.swap_ab = bool(tf.get("swapAB"))
        self.conns: dict = {}
        self._hash_n = 0
        self._overflow = 0

    def _vals(self, entry: dict, fields) -> tuple:
        return tuple(str(entry.get(f, "")) for f in fields)

    def _key(self, entry: dict):
        ref_vals = tuple(self._vals(entry, g) for g in self.refs)
        if not self.bidi:
            return (ref_vals,)
        a, b = self._vals(entry, self.group_a), self._vals(entry, self.group_b)
        return (ref_vals, tuple(sorted((a, b))))

    def _agg_init(self) -> dict:
        agg = {}
        for name, op, split, _ in self.out_fields:
            for suffix in (("_AB", "_BA") if split else ("",)):
                agg[name + suffix] = 0 if op in ("sum", "count") else None
        return agg

    def _agg_update(self, agg: dict, entry: dict, is_ab: bool) -> None:
        for name, op, split, inp in self.out_fields:
            k = name + (("_AB" if is_ab else "_BA") if split else "")
            if op == "count":
                agg[k] = (agg[k] or 0) + 1
                continue
            if inp not in entry:
                continue
            try:
                v = float(entry[inp])
            except (TypeError, ValueError):
                continue
            cur = agg[k]
            if op == "sum":
                agg[k] = (cur or 0) + v
            elif op == "min":
                agg[k] = v if cur is None else min(cur, v)
            elif op == "max":
                agg[k] = v if cur is None else max(cur, v)
            elif op == "first":
                agg[k] = v if cur is None else cur
            elif op == "last":
                agg[k] = v

    def _conn_record(self, conn: dict, rtype: str) -> dict:
        rec = dict(conn["key_fields"])
        for k, v in conn["agg"].items():
            if v is not None:
                rec[k] = v
        rec["_RecordType"] = rtype
        rec["_HashId"] = conn["hash_id"]
        return rec

    def __call__(self, entry: dict):
        now = _time.monotonic()
        out = []
        key = self._key(entry)
        conn = self.conns.get(key)
        flags = 0
        if self.flags_field:
            try:
                flags = int(entry.get(self.flags_field, 0) or 0)
            except (TypeError, ValueError):
                flags = 0
        if conn is None:
            if not self.max_tracked or len(self.conns) < self.max_tracked:
                a = self._vals(entry, self.group_a) if self.bidi else ()
                key_fields = {f: entry.get(f)
                              for g in self.groups.values() for f in g}
                # swapAB: a first flow log carrying SYN_ACK was sent by the
                # server — orient the connection from the client instead,
                # and swap the A/B field values on the connection record
                # (FLP swaps the field groups pairwise by position)
                if self.bidi and self.swap_ab and flags & 0x100:
                    a = self._vals(entry, self.group_b)
                    for fa, fb in zip(self.group_a, self.group_b):
                        key_fields[fa], key_fields[fb] = \
                            entry.get(fb), entry.get(fa)
                self._hash_n += 1
                conn = {"a": a, "agg": self._agg_init(),
                        "key_fields": key_fields,
                        "hash_id": f"{self._hash_n:08x}",
                        "last_update": now, "last_report": now,
                        "fin_seen_at": None, "new": True}
                self.conns[key] = conn
            else:
                self._overflow += 1
        if conn is not None:
            is_ab = (not self.bidi
                     or self._vals(entry, self.group_a) == conn["a"])
            self._agg_update(conn["agg"], entry, is_ab)
            conn["last_update"] = now
            if self.detect_end and flags & 0x201:       # FIN or FIN_ACK
                conn["fin_seen_at"] = conn["fin_seen_at"] or now
            if conn.pop("new", False) and \
                    "newConnection" in self.out_types:
                out.append(self._conn_record(conn, "newConnection"))
        if "flowLog" in self.out_types:
            fl = dict(entry)
            fl["_RecordType"] = "flowLog"
            if conn is not None:
                fl["_HashId"] = conn["hash_id"]
            out.append(fl)
        return out

    def sweep(self) -> list:
        """Timer pass, run once per exported batch: heartbeats and
        connection teardown (idle timeout / FIN + terminating timeout)."""
        now = _time.monotonic()
        out = []
        if self._overflow:
            log.warning("conntrack: store full (%d); %d flow logs passed "
                        "through untracked since the last sweep",
                        self.max_tracked, self._overflow)
            self._overflow = 0
        for key in list(self.conns):
            conn = self.conns[key]
            ended = (now - conn["last_update"] >= self.end_timeout
                     or (conn["fin_seen_at"] is not None
                         and now - conn["fin_seen_at"] >= self.term_timeout))
            if ended:
                if "endConnection" in self.out_types:
                    out.append(self._conn_record(conn, "endConnection"))
                del self.conns[key]
            elif (now - conn["last_report"] >= self.heartbeat_s
                    and "heartbeat" in self.out_types):
                out.append(self._conn_record(conn, "heartbeat"))
                conn["last_report"] = now
        return out

    def flush(self) -> list:
        """Shutdown: every live connection emits its endConnection."""
        out = []
        if "endConnection" in self.out_types:
            out = [self._conn_record(c, "endConnection")
                   for c in self.conns.values()]
        self.conns.clear()
        return out


class _Aggregates:
    """FLP `extract aggregates` subset (api/extract_aggregate.go): group-by
    aggregation over the flow-log stream. Like FLP's Extract, the stage
    REPLACES the stream: flow logs are absorbed and one record per active
    (definition, group) is emitted per exported batch, carrying running
    totals plus recent_* values that reset each cycle; idle groups expire
    after expiryTime (default 2m)."""

    def __init__(self, params: dict):
        default_expiry = _duration_s(params.get("defaultExpiryTime"), 120)
        self.defs = []
        for d in params.get("rules", params.get("aggregates", [])):
            self.defs.append({
                "name": d.get("name", ""),
                "by": list(d.get("groupByKeys", [])),
                "op": d.get("operationType", "count"),
                "key": d.get("operationKey", ""),
                "expiry": _duration_s(d.get("expiryTime"), default_expiry),
                "groups": {},
            })

    def __call__(self, entry: dict):
        now = _time.monotonic()
        for d in self.defs:
            gv = tuple(str(entry.get(k, "")) for k in d["by"])
            g = d["groups"].get(gv)
            if g is None:
                g = d["groups"][gv] = {
                    "total_value": None, "total_count": 0, "recent_count": 0,
                    "recent_op": None, "recent_raw": [], "last": now}
            g["last"] = now
            v = 1.0
            if d["op"] != "count":
                # an entry without the operation key contributes nothing —
                # not even to the counts, or min/avg skew toward 0 (FLP
                # skips the whole entry on a missing value key)
                if d["key"] not in entry:
                    continue
                try:
                    v = float(entry[d["key"]] or 0)
                except (TypeError, ValueError):
                    continue
            g["total_count"] += 1
            g["recent_count"] += 1
            op, cur = d["op"], g["recent_op"]
            tot = g["total_value"]
            if op in ("sum", "count"):
                inc = v if op == "sum" else 1
                g["total_value"] = (tot or 0) + inc
                g["recent_op"] = (cur or 0) + inc
            elif op == "min":
                g["total_value"] = v if tot is None else min(tot, v)
                g["recent_op"] = v if cur is None else min(cur, v)
            elif op == "max":
                g["total_value"] = v if tot is None else max(tot, v)
                g["recent_op"] = v if cur is None else max(cur, v)
            elif op == "avg":
                g["total_value"] = (tot or 0.0) + \
                    (v - (tot or 0.0)) / g["total_count"]
                g["recent_op"] = ((cur or 0) * (g["recent_count"] - 1) + v) \
                    / g["recent_count"]
            elif op == "raw_values":
                g["recent_raw"].append(v)
        return None                              # extract replaces the stream

    def sweep(self) -> list:
        now = _time.monotonic()
        out = []
        for d in self.defs:
            for gv in list(d["groups"]):
                g = d["groups"][gv]
                if now - g["last"] >= d["expiry"]:
                    del d["groups"][gv]
                    continue
                rec = {
                    "name": d["name"], "operation_type": d["op"],
                    "operation_key": d["key"], "by": ",".join(d["by"]),
                    "aggregate": ",".join(gv),
                    "total_value": g["total_value"] or 0,
                    "total_count": g["total_count"],
                    "recent_raw_values": list(g["recent_raw"]),
                    "recent_op_value": g["recent_op"] or 0,
                    "recent_count": g["recent_count"],
                    "_".join(d["by"]): ",".join(gv),
                }
                for k, v in zip(d["by"], gv):
                    rec[k] = v
                out.append(rec)
                g["recent_count"] = 0
                g["recent_op"] = None
                g["recent_raw"] = []
        return out


class _Timebased:
    """FLP `extract timebased` subset (api/extract_timebased.go): per-rule
    sliding-window (timeInterval) top-K over indexKeys by an operation on
    operationKey. Absorbs flow logs; emits one record per reported index
    value per exported batch."""

    def __init__(self, params: dict):
        self.rules = []
        for r in params.get("rules", []):
            keys = list(r.get("indexKeys", []))
            if not keys and r.get("indexKey"):
                keys = [r["indexKey"]]
            self.rules.append({
                "name": r.get("name", ""), "keys": keys,
                "op": r.get("operationType", "sum"),
                "key": r.get("operationKey", ""),
                "topk": int(r.get("topK", 0)),
                "window": _duration_s(r.get("timeInterval"), 10),
                "series": {},                    # index tuple -> [(ts, v)]
            })

    def __call__(self, entry: dict):
        now = _time.monotonic()
        for r in self.rules:
            if r["key"] not in entry:
                continue                # missing input: no data point
            idx = tuple(str(entry.get(k, "")) for k in r["keys"])
            try:
                v = float(entry[r["key"]] or 0)
            except (TypeError, ValueError):
                continue
            r["series"].setdefault(idx, []).append((now, v))
        return None

    def sweep(self) -> list:
        now = _time.monotonic()
        out = []
        for r in self.rules:
            results = []
            for idx in list(r["series"]):
                pts = [(t, v) for t, v in r["series"][idx]
                       if now - t < r["window"]]
                if not pts:
                    del r["series"][idx]
                    continue
                r["series"][idx] = pts
                vals = [v for _, v in pts]
                op = r["op"]
                if op == "sum":
                    res = sum(vals)
                elif op == "min":
                    res = min(vals)
                elif op == "max":
                    res = max(vals)
                elif op == "avg":
                    res = sum(vals) / len(vals)
                elif op == "count":
                    res = float(len(vals))
                elif op == "last":
                    res = vals[-1]
                elif op == "diff":
                    res = vals[-1] - vals[0]
                else:
                    continue
                results.append((res, idx))
            results.sort(key=lambda x: x[0], reverse=True)
            if r["topk"]:
                results = results[:r["topk"]]
            for res, idx in results:
                rec = {"name": r["name"],
                       "index_key": ",".join(r["keys"]),
                       "operation": r["op"], r["key"]: res}
                for k, v in zip(r["keys"], idx):
                    rec[k] = v
                out.append(rec)
        return out


def _duration_s(v, default: float) -> float:
    """Parse an FLP/Go duration ('30s', '1m30s', '500ms', number) to
    seconds; malformed values warn and fall back to the default."""
    from netobserv_tpu.config import parse_duration

    if v is None or v == "":
        return float(default)
    if isinstance(v, (int, float)):
        return float(v)
    try:
        return parse_duration(str(v))
    except ValueError:
        log.warning("invalid duration %r; using default %ss", v, default)
        return float(default)


def _build_generic(params: dict) -> Stage:
    rules = params.get("rules", [])
    policy = params.get("policy", "replace_keys")

    def stage(entry: dict) -> Optional[dict]:
        out = {} if policy == "replace_keys" else dict(entry)
        for rule in rules:
            src, dst = rule.get("input"), rule.get("output")
            if src in entry:
                out[dst or src] = entry[src]
        return out

    return stage


class _KafkaEncode:
    """FLP `encode kafka` (encode_kafka.go): each entry is JSON-serialized
    and produced to a topic through the in-repo wire producer
    (`kafka/producer.py`). Entries pass through to the rest of the
    pipeline. Produce failures are logged and dropped — a dead broker must
    not wedge the eviction loop (exporters never crash the pipeline)."""

    def __init__(self, params: dict, producer=None):
        self._params = params
        self._producer = producer  # tests inject; lazily built otherwise
        self._pending: list[tuple[None, bytes]] = []

    def _ensure_producer(self):
        if self._producer is None:
            from netobserv_tpu.kafka.producer import KafkaProducer
            address = self._params.get("address", "localhost:9092")
            self._producer = KafkaProducer(
                brokers=[address],
                topic=self._params.get("topic", "network-flows"))
        return self._producer

    def __call__(self, entry: dict) -> dict:
        self._pending.append(
            (None, json.dumps(entry, separators=(",", ":")).encode()))
        return entry

    def sweep(self) -> list:
        if self._pending:
            batch, self._pending = self._pending, []
            try:
                self._ensure_producer().send_batch(batch)
            except Exception as exc:
                log.warning("FLP kafka encode failed (%s); %d entries "
                            "dropped from the topic (pipeline continues)",
                            exc, len(batch))
        return []

    def close(self) -> None:
        if self._producer is not None:
            self._producer.close()


class _IPFIXWrite:
    """FLP `write ipfix` (write_ipfix.go): the entry stream becomes IPFIX
    data records through the in-repo exporter (`exporter/ipfix.py`, v4/v6
    templates, MTU split, TCP template re-send). Terminal stage. The
    exporter is built lazily inside the try-guarded push — a temporarily
    unreachable TCP collector must not crash agent startup (exporters
    never crash the pipeline)."""

    def __init__(self, params: dict, exporter=None):
        self._params = params
        self._exp = exporter

    def _ensure_exporter(self):
        if self._exp is None:
            from netobserv_tpu.exporter.ipfix import IPFIXExporter
            self._exp = IPFIXExporter(
                self._params.get("targetHost", "localhost"),
                int(self._params.get("targetPort", 4739)),
                transport=str(self._params.get("transport", "udp")).lower())
        return self._exp

    def push(self, entries: list[dict]) -> None:
        from netobserv_tpu.exporter.flp_map import map_to_record
        try:
            self._ensure_exporter().export_batch(
                [map_to_record(e) for e in entries])
        except Exception as exc:
            log.warning("FLP ipfix write failed (%s); %d records dropped",
                        exc, len(entries))

    def close(self) -> None:
        if self._exp is not None:
            self._exp.close()


def _sigv4_put(endpoint: str, secure: bool, bucket: str, key: str,
               body: bytes, access_key: str, secret_key: str,
               region: str = "us-east-1", timeout: float = 10.0,
               now=None) -> None:
    """Minimal AWS Signature V4 PUT-object over stdlib http.client — the
    S3 wire contract the reference's minio client speaks (no SDK in this
    image; the signature math is pinned by tests/test_direct_flp.py, which
    re-derives it server-side)."""
    import datetime
    import hashlib
    import hmac
    import http.client

    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    host = endpoint
    path = "/" + bucket + "/" + key
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        "PUT", path, "",
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def hm(k, msg):
        return hmac.new(k, msg.encode(), hashlib.sha256).digest()

    sig_key = hm(hm(hm(hm(("AWS4" + secret_key).encode(), datestamp),
                       region), "s3"), "aws4_request")
    signature = hmac.new(sig_key, to_sign.encode(), hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}")
    cls = http.client.HTTPSConnection if secure else http.client.HTTPConnection
    conn = cls(endpoint, timeout=timeout)
    try:
        conn.request("PUT", path, body=body,
                     headers={**headers, "Authorization": auth,
                              "Content-Length": str(len(body))})
        resp = conn.getresponse()
        resp.read()
        if resp.status >= 300:
            raise IOError(f"S3 PUT {path} -> {resp.status}")
    finally:
        conn.close()


class _S3Encode:
    """FLP `encode s3` (encode_s3.go): entries buffer until `batchSize`,
    then ship as one JSON object with the FLP store header (version,
    capture window, count, user header parameters) under the reference's
    object-name layout `account/year=/month=/day=/hour=/stream-id=/<seq>`.
    Entries pass through; PUT failures are logged and dropped."""

    def __init__(self, params: dict, put=None):
        import time as _time
        import uuid

        self._p = params
        self._batch_size = int(params.get("batchSize", 10) or 10)
        self._pending: list[dict] = []
        self._stream_id = params.get("streamId", uuid.uuid4().hex[:12])
        self._seq = 0
        self._interval_start = _time.time()
        self._put = put or self._default_put

    def _default_put(self, key: str, body: bytes) -> None:
        _sigv4_put(self._p.get("endpoint", "localhost:9000"),
                   bool(self._p.get("secure", False)),
                   self._p.get("bucket", "netobserv"), key, body,
                   self._p.get("accessKeyId", ""),
                   self._p.get("secretAccessKey", ""))

    def __call__(self, entry: dict) -> dict:
        self._pending.append(entry)
        return entry

    def _object(self, flows, start_ts, end_ts) -> dict:
        import datetime

        def rfc3339(ts):
            return datetime.datetime.fromtimestamp(
                ts, datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")

        obj = dict(self._p.get("objectHeaderParameters", {}) or {})
        obj["version"] = "v0.1"
        obj["capture_start_time"] = rfc3339(start_ts)
        obj["capture_end_time"] = rfc3339(end_ts)
        obj["number_of_flow_logs"] = len(flows)
        obj["flow_logs"] = flows
        return obj

    def _flush_batches(self, final: bool = False) -> None:
        import time as _time

        while (len(self._pending) >= self._batch_size
               or (final and self._pending)):
            batch = self._pending[:self._batch_size]
            self._pending = self._pending[self._batch_size:]
            now = _time.time()
            t = _time.gmtime(now)
            key = (f"{self._p.get('account', 'netobserv')}/"
                   f"year={t.tm_year:04d}/month={t.tm_mon:02d}/"
                   f"day={t.tm_mday:02d}/hour={t.tm_hour:02d}/"
                   f"stream-id={self._stream_id}/{self._seq:08d}")
            body = json.dumps(self._object(batch, self._interval_start, now),
                              separators=(",", ":")).encode()
            self._interval_start = now
            self._seq += 1
            try:
                self._put(key, body)
            except Exception as exc:
                log.warning("FLP s3 encode failed (%s); %d entries dropped "
                            "from the store (pipeline continues)",
                            exc, len(batch))

    def sweep(self) -> list:
        self._flush_batches()
        return []

    def flush(self) -> list:
        self._flush_batches(final=True)
        return []


class _GRPCWrite:
    """FLP `write grpc` (write_grpc.go): the entry stream leaves as pbflow
    Records to a pbflow.Collector (the in-repo flow client — TLS/mTLS via
    the `tls: {caCertPath, userCertPath, userKeyPath}` block). Terminal
    stage; lazily constructed and error-swallowing like the other
    writers."""

    def __init__(self, params: dict, client=None):
        self._params = params
        self._client = client

    def _ensure_client(self):
        if self._client is None:
            from netobserv_tpu.grpc.flow import FlowClient
            tls = self._params.get("tls", {})
            self._client = FlowClient(
                self._params.get("targetHost", "localhost"),
                int(self._params.get("targetPort", 9999)),
                tls_ca=tls.get("caCertPath", ""),
                tls_cert=tls.get("userCertPath", ""),
                tls_key=tls.get("userKeyPath", ""))
        return self._client

    def push(self, entries: list[dict]) -> None:
        from netobserv_tpu.exporter.flp_map import map_to_record
        from netobserv_tpu.exporter.pb_convert import records_to_pb
        try:
            self._ensure_client().send(
                records_to_pb([map_to_record(e) for e in entries]))
        except Exception as exc:
            log.warning("FLP grpc write failed (%s); %d records dropped",
                        exc, len(entries))

    def close(self) -> None:
        if self._client is not None:
            self._client.close()


class DirectFLPExporter(Exporter):
    name = "direct-flp"

    def __init__(self, flp_config: str = "", stream=None, prom_registry=None,
                 kube_source=None, location_db=None, kafka_producer=None):
        from prometheus_client import CollectorRegistry

        self._stream = stream if stream is not None else sys.stdout
        self._stages: list[Stage] = []
        # encode/prom metrics land here; the agent passes its own registry so
        # they surface on the existing /metrics server
        self.prom_registry = (prom_registry if prom_registry is not None
                              else CollectorRegistry())
        self._prom_names: set[str] = set()
        # pluggable enrichment backends (exporter.flp_enrich protocols)
        self._kube_source = kube_source
        self._location_db = location_db
        self._kafka_producer = kafka_producer  # tests inject a wired producer
        if flp_config.strip():
            self._build(yaml.safe_load(flp_config))

    def _build(self, cfg: dict) -> None:
        params = {p.get("name"): p for p in cfg.get("parameters", [])}
        # follow the pipeline order; ingest stages are implicit/skipped
        for step in cfg.get("pipeline", []):
            p = params.get(step.get("name"), {})
            if "transform" in p:
                t = p["transform"]
                ttype = t.get("type")
                if ttype == "filter":
                    self._stages.append(_build_filter(t.get("filter", {})))
                elif ttype == "generic":
                    self._stages.append(_build_generic(t.get("generic", {})))
                elif ttype == "network":
                    self._stages.append(_build_network(
                        t.get("network", {}),
                        kube_source=self._kube_source,
                        location_db=self._location_db))
                else:
                    log.warning("unsupported transform type %r ignored", ttype)
            elif "extract" in p:
                x = p["extract"]
                if x.get("type") == "conntrack":
                    self._stages.append(_ConnTrack(x.get("conntrack", {})))
                elif x.get("type") == "aggregates":
                    self._stages.append(_Aggregates(x.get("aggregates", {})))
                elif x.get("type") == "timebased":
                    self._stages.append(_Timebased(x.get("timebased", {})))
                else:
                    log.warning("unsupported extract type %r ignored",
                                x.get("type"))
            elif "encode" in p:
                e = p["encode"]
                if e.get("type") == "prom":
                    self._stages.append(
                        _build_prom(e.get("prom", {}), self.prom_registry,
                                    self._prom_names))
                elif e.get("type") == "kafka":
                    self._stages.append(
                        _KafkaEncode(e.get("kafka", {}),
                                     producer=self._kafka_producer))
                elif e.get("type") == "s3":
                    self._stages.append(_S3Encode(e.get("s3", {})))
                else:
                    log.warning("unsupported encode type %r ignored",
                                e.get("type"))
            elif "write" in p:
                wtype = p["write"].get("type", "stdout")
                if wtype == "loki":
                    self._writer = _LokiWriter(p["write"].get("loki", {}))
                elif wtype == "ipfix":
                    self._writer = _IPFIXWrite(p["write"].get("ipfix", {}))
                elif wtype == "grpc":
                    self._writer = _GRPCWrite(p["write"].get("grpc", {}))
                elif wtype != "stdout":
                    log.warning("write type %r unsupported; using stdout", wtype)
            elif "ingest" in p or not p:
                continue

    _writer = None  # non-stdout terminal (e.g. _LokiWriter)

    def export_batch(self, records: list[Record]) -> None:
        entries: list[dict] = [record_to_map(r) for r in records]
        self._emit(self._run_stages(entries))

    def _run_stages(self, entries: list[dict], stages=None) -> list[dict]:
        for stage in (self._stages if stages is None else stages):
            nxt: list[dict] = []
            for entry in entries:
                res = stage(entry)
                if res is None:
                    continue
                nxt.extend(res) if isinstance(res, list) else nxt.append(res)
            # stateful stages (conntrack) produce timer records per batch
            sweep = getattr(stage, "sweep", None)
            if sweep is not None:
                nxt.extend(sweep())
            entries = nxt
        return entries

    def _emit(self, out: list[dict]) -> None:
        if self._writer is not None:
            self._writer.push(out)
            return
        for entry in out:
            self._stream.write(json.dumps(entry, separators=(",", ":")) + "\n")
        self._stream.flush()

    def close(self) -> None:
        """Drain stateful stages: live connections emit endConnection
        through the remainder of the pipeline before shutdown. Never raises
        — a failed final emit must not abort agent shutdown (the fetcher
        teardown runs after this)."""
        for i, stage in enumerate(self._stages):
            flush = getattr(stage, "flush", None)
            if flush is None:
                continue
            try:
                pending = flush()
                if pending:
                    self._emit(self._run_stages(
                        pending, stages=self._stages[i + 1:]))
            except Exception as exc:
                log.warning("shutdown flush failed (%s); remaining "
                            "connection records dropped", exc)
        # release stage/writer transports (kafka producer, ipfix socket)
        for closer in (*self._stages, self._writer):
            close = getattr(closer, "close", None)
            if close is not None:
                try:
                    close()
                except Exception as exc:
                    log.warning("stage close failed: %s", exc)


class _LokiWriter:
    """FLP `write loki` subset (api/write_loki.go): push the entry stream to
    Loki's /loki/api/v1/push as JSON streams. Entries are grouped by their
    label set per batch; the agent's batching replaces batchWait/batchSize
    timers (one push per exported batch). Push failures are logged and
    dropped — an unreachable Loki must not wedge the eviction loop."""

    #: after this many consecutive failures, pushes are skipped until
    #: BACKOFF_S elapses — a dead Loki must not throttle the export queue
    #: to one TIMEOUT_S-blocked batch per drain. TIMEOUT_S stays above
    #: burst/compaction ingest latency so a merely SLOW Loki doesn't trip
    #: the breaker (a blip costs consecutive failures, not data loss).
    FAIL_THRESHOLD = 3
    BACKOFF_S = 30.0
    TIMEOUT_S = 5.0

    def __init__(self, params: dict):
        self.url = params.get("url", "http://localhost:3100").rstrip("/")
        self.tenant = params.get("tenantID", "")
        self.labels = list(params.get("labels", []))
        self.static_labels = dict(params.get("staticLabels", {}))
        self.ts_label = params.get("timestampLabel", "TimeFlowEndMs")
        # FLP timestampScale, e.g. "1s" / "1ms" -> ns multiplier
        scale = params.get("timestampScale", "1ms")
        self.ts_ns_mult = {"1s": 10**9, "1ms": 10**6, "1us": 10**3,
                           "1ns": 1}.get(scale, 10**6)
        self._consec_failures = 0
        self._backoff_until = 0.0
        self._backoff_dropped = 0

    def push(self, entries: list[dict]) -> None:
        import http.client
        import urllib.error
        import urllib.request

        if not entries:
            return
        if (self._consec_failures >= self.FAIL_THRESHOLD
                and _time.monotonic() < self._backoff_until):
            # tallied, not silent: the drop volume is reported on the next
            # dial (warning either way), so operators see what backoff cost
            self._backoff_dropped += len(entries)
            return
        streams: dict[tuple, list] = {}
        for e in entries:
            lbl = dict(self.static_labels)
            for k in self.labels:
                if k in e:
                    lbl[k] = str(e[k])
            try:
                ts = int(int(e.get(self.ts_label, 0)) * self.ts_ns_mult) \
                    or _time.time_ns()
            except (TypeError, ValueError):
                ts = _time.time_ns()
            streams.setdefault(tuple(sorted(lbl.items())), []).append(
                [str(ts), json.dumps(e, separators=(",", ":"))])
        body = json.dumps({"streams": [
            {"stream": dict(k), "values": v} for k, v in streams.items()
        ]}).encode()
        req = urllib.request.Request(
            self.url + "/loki/api/v1/push", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        if self.tenant:
            req.add_header("X-Scope-OrgID", self.tenant)
        try:
            urllib.request.urlopen(req, timeout=self.TIMEOUT_S).read()
            self._consec_failures = 0
            if self._backoff_dropped:
                log.warning("loki recovered; %d entries were dropped during "
                            "backoff", self._backoff_dropped)
                self._backoff_dropped = 0
        except (urllib.error.URLError, OSError,
                http.client.HTTPException) as exc:
            self._consec_failures += 1
            if self._consec_failures >= self.FAIL_THRESHOLD:
                self._backoff_until = _time.monotonic() + self.BACKOFF_S
            log.warning("loki push failed (%d entries dropped, %d more "
                        "during backoff): %s",
                        len(entries), self._backoff_dropped, exc)
            self._backoff_dropped = 0
