"""direct-flp: an embedded in-process flowlogs-pipeline.

Reference analog: `pkg/exporter/direct_flp.go` — the agent feeds records
(converted to FLP GenericMaps, `pkg/decode` field naming) into a pipeline
described by FLP_CONFIG (YAML or JSON) instead of shipping them anywhere.

Supported stage subset (the shapes the reference's smoke-test configs use):
- ingest is implicit (the agent's record stream)
- `transform` / type `filter`: rules `remove_field`, `keep_entry_if_exists`,
  `keep_entry_if_doesnt_exist`, `keep_entry_if_equal`, `keep_entry_if_not_equal`
- `transform` / type `generic`: `policy: replace_keys` with `rules` [{input,
  output}] field renaming
- `transform` / type `network` (FLP transform_network.go subset): rules
  `add_subnet`, `add_service`, `add_subnet_label`, `decode_tcp_flags`,
  `reinterpret_direction`; `add_location`/`add_kubernetes*` need external
  databases and are warned-and-skipped
- `encode` / type `prom` (FLP encode_prom.go subset): counter/gauge/
  histogram metrics with labels and equal/not_equal/presence/absence/
  match_regex filters, registered on the exporter's `prom_registry`
  (served by the agent's metrics server when one is running)
- `write` / type `stdout` (default when no pipeline is configured) or type
  `loki` (push-API JSON streams with label promotion and tenant header)
"""

from __future__ import annotations

import json
import logging
import sys
from typing import Callable, Optional

import yaml

from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.flp_map import record_to_map
from netobserv_tpu.model.record import Record

log = logging.getLogger("netobserv_tpu.exporter.direct_flp")

Stage = Callable[[dict], Optional[dict]]


def _build_filter(params: dict) -> Stage:
    rules = params.get("rules", [])

    def stage(entry: dict) -> Optional[dict]:
        for rule in rules:
            rtype = rule.get("type")
            field = rule.get("removeField", rule.get(
                "keepEntryField", rule.get("input", rule.get("field"))))
            value = rule.get("keepEntryValue", rule.get("value"))
            if rtype == "remove_field":
                entry.pop(field, None)
            elif rtype == "keep_entry_if_exists":
                if field not in entry:
                    return None
            elif rtype == "keep_entry_if_doesnt_exist":
                if field in entry:
                    return None
            elif rtype == "keep_entry_if_equal":
                if entry.get(field) != value:
                    return None
            elif rtype == "keep_entry_if_not_equal":
                if entry.get(field) == value:
                    return None
        return entry

    return stage


from netobserv_tpu.model.flow import TcpFlags

# FLP utils/tcp_flags.go table (incl. the synthetic combination bits) —
# derived from the model enum so the mapping cannot drift
_TCP_FLAG_NAMES = [(f.value, f.name) for f in TcpFlags]

_PROTO_NAMES = {6: "tcp", 17: "udp", 132: "sctp"}


def _build_network(params: dict) -> Stage:
    """FLP `transform network` subset (transform_network.go:64-160)."""
    import ipaddress
    import socket as _socket

    rules = params.get("rules", [])
    subnet_labels = []
    for lbl in params.get("subnetLabels", []):
        nets = [ipaddress.ip_network(c) for c in lbl.get("cidrs", [])]
        subnet_labels.append((lbl.get("name", ""), nets))
    dir_info = params.get("directionInfo", {})
    svc_cache: dict = {}

    def service_name(port, proto) -> str:
        key = (port, proto)
        if key not in svc_cache:
            name = ""
            try:
                pnum = int(proto)
                pname = _PROTO_NAMES.get(pnum, "")
            except (TypeError, ValueError):
                pname = str(proto).lower()
            try:
                name = _socket.getservbyport(int(port), pname) if pname \
                    else _socket.getservbyport(int(port))
            except (OSError, OverflowError, ValueError):
                name = ""
            svc_cache[key] = name
        return svc_cache[key]

    def stage(entry: dict) -> Optional[dict]:
        for rule in rules:
            rtype = rule.get("type")
            if rtype == "add_subnet":
                r = rule.get("add_subnet", rule)
                ip = entry.get(r.get("input"))
                if isinstance(ip, str):
                    mask = str(r.get("parameters",
                                     r.get("subnet_mask", "/24")))
                    if not mask.startswith("/"):
                        mask = "/" + mask
                    try:
                        net = ipaddress.ip_network(ip + mask, strict=False)
                        entry[r.get("output")] = str(net)
                    except ValueError:
                        pass
            elif rtype == "add_service":
                r = rule.get("add_service", rule)
                port = entry.get(r.get("input"))
                proto = entry.get(r.get("protocol"))
                if port is not None:
                    name = service_name(port, proto)
                    if name:
                        entry[r.get("output")] = name
            elif rtype == "add_subnet_label":
                r = rule.get("add_subnet_label", rule)
                ip = entry.get(r.get("input"))
                if isinstance(ip, str):
                    try:
                        addr = ipaddress.ip_address(ip)
                    except ValueError:
                        continue
                    for name, nets in subnet_labels:
                        if any(addr in n for n in nets):
                            entry[r.get("output")] = name
                            break
            elif rtype == "decode_tcp_flags":
                r = rule.get("decode_tcp_flags", rule)
                flags = entry.get(r.get("input"))
                if flags is not None:
                    try:
                        bits = int(flags)
                    except (TypeError, ValueError):
                        continue
                    names = [n for v, n in _TCP_FLAG_NAMES if bits & v]
                    if names or r.get("output") == r.get("input"):
                        entry[r.get("output")] = names
            elif rtype == "reinterpret_direction":
                # transform_network_direction.go: per-node direction from
                # the reporter's viewpoint (0 ingress / 1 egress / 2 inner)
                fd_field = dir_info.get("flowDirectionField")
                if not fd_field:
                    continue
                if dir_info.get("ifDirectionField") and fd_field in entry:
                    entry[dir_info["ifDirectionField"]] = entry[fd_field]
                reporter = entry.get(dir_info.get("reporterIPField"))
                src = entry.get(dir_info.get("srcHostField"))
                dst = entry.get(dir_info.get("dstHostField"))
                if not reporter:
                    continue
                if src != dst:
                    if src == reporter:
                        entry[fd_field] = 1     # egress
                    elif dst == reporter:
                        entry[fd_field] = 0     # ingress
                elif src:
                    entry[fd_field] = 2         # inner
            else:
                log.warning("transform.network rule %r unsupported; skipped",
                            rtype)
        return entry

    return stage


def _build_prom(params: dict, registry) -> Stage:
    """FLP `encode prom` subset (encode_prom.go): declarative metrics from
    the entry stream, registered on `registry`. Entries pass through."""
    import re

    from prometheus_client import Counter, Gauge, Histogram

    prefix = params.get("prefix", "")
    metrics = []
    for item in params.get("metrics", []):
        name = prefix + item.get("name", "")
        labels = list(item.get("labels", []))
        mtype = item.get("type", "counter")
        kw = {"registry": registry}
        if mtype == "counter":
            m = Counter(name, name, labels, **kw)
        elif mtype == "gauge":
            m = Gauge(name, name, labels, **kw)
        elif mtype in ("histogram", "agg_histogram"):
            buckets = item.get("buckets") or Histogram.DEFAULT_BUCKETS
            m = Histogram(name, name, labels, buckets=buckets, **kw)
        else:
            log.warning("prom metric type %r unsupported; skipped", mtype)
            continue
        filters = []
        for f in item.get("filters", []):
            ftype = f.get("type", "equal")
            key, value = f.get("key"), f.get("value")
            if ftype in ("match_regex", "not_match_regex"):
                value = re.compile(str(value))
            elif ftype in ("equal", "not_equal"):
                value = str(value)
            filters.append((ftype, key, value))
        metrics.append((m, mtype, item.get("valueKey", ""), labels, filters))

    def matches(entry: dict, filters) -> bool:
        for ftype, key, value in filters:
            present = key in entry
            ev = str(entry.get(key)) if present else ""
            if ftype == "equal" and ev != value:
                return False
            if ftype == "not_equal" and ev == value:
                return False
            if ftype == "presence" and not present:
                return False
            if ftype == "absence" and present:
                return False
            if ftype == "match_regex" and not value.search(ev):
                return False
            if ftype == "not_match_regex" and value.search(ev):
                return False
        return True

    def stage(entry: dict) -> Optional[dict]:
        for m, mtype, value_key, labels, filters in metrics:
            if not matches(entry, filters):
                continue
            if value_key:
                if value_key not in entry:
                    continue            # FLP skips on a missing value key
                try:
                    v = float(entry[value_key] or 0)
                except (TypeError, ValueError):
                    continue
            else:
                v = 1.0
            series = m.labels(*[str(entry.get(lb, "")) for lb in labels]) \
                if labels else m
            if mtype == "counter":
                series.inc(v)
            elif mtype == "gauge":
                series.set(v)
            else:
                series.observe(v)
        return entry

    return stage


def _build_generic(params: dict) -> Stage:
    rules = params.get("rules", [])
    policy = params.get("policy", "replace_keys")

    def stage(entry: dict) -> Optional[dict]:
        out = {} if policy == "replace_keys" else dict(entry)
        for rule in rules:
            src, dst = rule.get("input"), rule.get("output")
            if src in entry:
                out[dst or src] = entry[src]
        return out

    return stage


class DirectFLPExporter(Exporter):
    name = "direct-flp"

    def __init__(self, flp_config: str = "", stream=None, prom_registry=None):
        from prometheus_client import CollectorRegistry

        self._stream = stream if stream is not None else sys.stdout
        self._stages: list[Stage] = []
        # encode/prom metrics land here; the agent passes its own registry so
        # they surface on the existing /metrics server
        self.prom_registry = (prom_registry if prom_registry is not None
                              else CollectorRegistry())
        if flp_config.strip():
            self._build(yaml.safe_load(flp_config))

    def _build(self, cfg: dict) -> None:
        params = {p.get("name"): p for p in cfg.get("parameters", [])}
        # follow the pipeline order; ingest stages are implicit/skipped
        for step in cfg.get("pipeline", []):
            p = params.get(step.get("name"), {})
            if "transform" in p:
                t = p["transform"]
                ttype = t.get("type")
                if ttype == "filter":
                    self._stages.append(_build_filter(t.get("filter", {})))
                elif ttype == "generic":
                    self._stages.append(_build_generic(t.get("generic", {})))
                elif ttype == "network":
                    self._stages.append(_build_network(t.get("network", {})))
                else:
                    log.warning("unsupported transform type %r ignored", ttype)
            elif "encode" in p:
                e = p["encode"]
                if e.get("type") == "prom":
                    self._stages.append(
                        _build_prom(e.get("prom", {}), self.prom_registry))
                else:
                    log.warning("unsupported encode type %r ignored",
                                e.get("type"))
            elif "write" in p:
                wtype = p["write"].get("type", "stdout")
                if wtype == "loki":
                    self._writer = _LokiWriter(p["write"].get("loki", {}))
                elif wtype != "stdout":
                    log.warning("write type %r unsupported; using stdout", wtype)
            elif "ingest" in p or not p:
                continue

    _writer = None  # non-stdout terminal (e.g. _LokiWriter)

    def export_batch(self, records: list[Record]) -> None:
        out = []
        for r in records:
            entry: Optional[dict] = record_to_map(r)
            for stage in self._stages:
                entry = stage(entry)
                if entry is None:
                    break
            if entry is not None:
                out.append(entry)
        if self._writer is not None:
            self._writer.push(out)
            return
        for entry in out:
            self._stream.write(json.dumps(entry, separators=(",", ":")) + "\n")
        self._stream.flush()


class _LokiWriter:
    """FLP `write loki` subset (api/write_loki.go): push the entry stream to
    Loki's /loki/api/v1/push as JSON streams. Entries are grouped by their
    label set per batch; the agent's batching replaces batchWait/batchSize
    timers (one push per exported batch). Push failures are logged and
    dropped — an unreachable Loki must not wedge the eviction loop."""

    def __init__(self, params: dict):
        self.url = params.get("url", "http://localhost:3100").rstrip("/")
        self.tenant = params.get("tenantID", "")
        self.labels = list(params.get("labels", []))
        self.static_labels = dict(params.get("staticLabels", {}))
        self.ts_label = params.get("timestampLabel", "TimeFlowEndMs")
        # FLP timestampScale, e.g. "1s" / "1ms" -> ns multiplier
        scale = params.get("timestampScale", "1ms")
        self.ts_ns_mult = {"1s": 10**9, "1ms": 10**6, "1us": 10**3,
                           "1ns": 1}.get(scale, 10**6)

    def push(self, entries: list[dict]) -> None:
        import http.client
        import time as _time
        import urllib.error
        import urllib.request

        if not entries:
            return
        streams: dict[tuple, list] = {}
        for e in entries:
            lbl = dict(self.static_labels)
            for k in self.labels:
                if k in e:
                    lbl[k] = str(e[k])
            try:
                ts = int(int(e.get(self.ts_label, 0)) * self.ts_ns_mult) \
                    or _time.time_ns()
            except (TypeError, ValueError):
                ts = _time.time_ns()
            streams.setdefault(tuple(sorted(lbl.items())), []).append(
                [str(ts), json.dumps(e, separators=(",", ":"))])
        body = json.dumps({"streams": [
            {"stream": dict(k), "values": v} for k, v in streams.items()
        ]}).encode()
        req = urllib.request.Request(
            self.url + "/loki/api/v1/push", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        if self.tenant:
            req.add_header("X-Scope-OrgID", self.tenant)
        try:
            urllib.request.urlopen(req, timeout=10).read()
        except (urllib.error.URLError, OSError,
                http.client.HTTPException) as exc:
            log.warning("loki push failed (%d entries dropped): %s",
                        len(entries), exc)
