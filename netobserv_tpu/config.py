"""Agent configuration: a single env-var-driven settings object.

Capability parity with the reference's env-tag struct (`pkg/config/config.go:83-308`):
same variable names, same defaults, zero flags / zero files. TPU-specific knobs are
added under the ``SKETCH_*`` prefix (the `tpu-sketch` exporter backend is new).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Optional

_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_DURATION_UNITS = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
}


def parse_duration(text: str) -> float:
    """Parse a Go-style duration string ("5s", "300ms", "1m30s") into seconds."""
    text = text.strip()
    if not text:
        return 0.0
    try:
        return float(text)  # plain number = seconds
    except ValueError:
        pass
    total = 0.0
    pos = 0
    for m in _DURATION_RE.finditer(text):
        if m.start() != pos:
            raise ValueError(f"invalid duration: {text!r}")
        total += float(m.group(1)) * _DURATION_UNITS[m.group(2)]
        pos = m.end()
    if pos != len(text):
        raise ValueError(f"invalid duration: {text!r}")
    return total


def _parse_bool(text: str) -> bool:
    return text.strip().lower() in ("1", "true", "yes", "on")


def _env(name: str, default: str = "") -> dict:
    return {"metadata": {"env": name, "default": default}}


# Exporter backend names (reference: `pkg/agent/agent.go:246-261` switch).
EXPORT_GRPC = "grpc"
EXPORT_KAFKA = "kafka"
EXPORT_IPFIX_UDP = "ipfix+udp"
EXPORT_IPFIX_TCP = "ipfix+tcp"
EXPORT_DIRECT_FLP = "direct-flp"
# New in this framework: offload aggregation/analytics to TPU sketches.
EXPORT_TPU_SKETCH = "tpu-sketch"
# Debug-friendly terminal exporter (stdout JSON lines).
EXPORT_STDOUT = "stdout"

#: port-scan fan-out threshold default — the ONE definition; the
#: sketch_scan_fanout field and the tpu-sketch exporter both use it
DEFAULT_SCAN_FANOUT = 512

#: DDoS z-score threshold default — same single-definition treatment as
#: DEFAULT_SCAN_FANOUT (the two anomaly signals share an operational shape)
DEFAULT_DDOS_Z = 6.0

#: SYN-flood: minimum half-open attempts per victim bucket per window, and
#: the offered:accepted (SYN : SYN-ACK) ratio both required to report
DEFAULT_SYNFLOOD_MIN = 128
DEFAULT_SYNFLOOD_RATIO = 8.0

#: drop-anomaly z-score threshold (EWMA surge of dropped bytes per bucket)
DEFAULT_DROP_Z = 6.0

#: conversation asymmetry: minimum window bytes in a pair bucket and the
#: one-way share (max(dir)/total) at which it is reported
DEFAULT_ASYM_MIN_BYTES = 1 << 20
DEFAULT_ASYM_RATIO = 0.95

#: heavy-hitter churn (persistent-slot top-K plane): a slot whose window
#: count reaches ASCENT x its previous-window count (with at least
#: MIN_BYTES of current mass) renders as a flow ascent; the reciprocal
#: direction (prev >= MIN_BYTES, count <= prev/ASCENT) as a descent; a
#: slot first seen this window with >= MIN_BYTES as a new heavy key.
#: Single definitions — the renderer, the zoo runner, and the default
#: flow_ascent/new_heavy_key alert rules all read these
DEFAULT_CHURN_ASCENT = 8.0
DEFAULT_CHURN_MIN_BYTES = 1 << 20

VALID_EXPORTERS = (
    EXPORT_GRPC, EXPORT_KAFKA, EXPORT_IPFIX_UDP, EXPORT_IPFIX_TCP,
    EXPORT_DIRECT_FLP, EXPORT_TPU_SKETCH, EXPORT_STDOUT,
)


@dataclass
class FlowFilterRule:
    """One flow-filter rule (reference schema: `pkg/config/config.go:27-81`)."""

    ip_cidr: str = "0.0.0.0/0"
    action: str = "Accept"  # Accept | Reject
    direction: str = ""  # Ingress | Egress | ""
    protocol: str = ""  # TCP | UDP | SCTP | ICMP | ICMPv6
    source_port: int = 0
    source_port_range: str = ""
    source_ports: str = ""
    destination_port: int = 0
    destination_port_range: str = ""
    destination_ports: str = ""
    port: int = 0
    port_range: str = ""
    ports: str = ""
    icmp_type: int = 0
    icmp_code: int = 0
    peer_ip: str = ""
    peer_cidr: str = ""
    tcp_flags: str = ""  # e.g. "SYN", "SYN-ACK"
    drops: bool = False
    sample: int = 0  # per-rule sampling override

    @classmethod
    def from_json_obj(cls, obj: dict) -> "FlowFilterRule":
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in obj.items() if k in names})


def parse_filter_rules(text: str) -> list[FlowFilterRule]:
    """Parse the JSON-in-env FLOW_FILTER_RULES list (reference: `agent.go:445-474`)."""
    if not text.strip():
        return []
    data = json.loads(text)
    if not isinstance(data, list):
        raise ValueError("FLOW_FILTER_RULES must be a JSON array")
    return [FlowFilterRule.from_json_obj(o) for o in data]


@dataclass
class AgentConfig:  # noqa: PLR0902 - deliberately wide, mirrors reference
    """All agent knobs. Field metadata carries the env var name and default.

    Reference: `pkg/config/config.go:83-308` (same env names/defaults unless noted).
    """

    # --- identity / export target ---
    agent_ip: str = field(default="", **_env("AGENT_IP"))
    agent_ip_iface: str = field(default="external", **_env("AGENT_IP_IFACE", "external"))
    agent_ip_type: str = field(default="any", **_env("AGENT_IP_TYPE", "any"))
    export: str = field(default="grpc", **_env("EXPORT", "grpc"))
    target_host: str = field(default="", **_env("TARGET_HOST"))
    target_port: int = field(default=0, **_env("TARGET_PORT", "0"))
    target_tls_ca_cert_path: str = field(default="", **_env("TARGET_TLS_CA_CERT_PATH"))
    target_tls_user_cert_path: str = field(default="", **_env("TARGET_TLS_USER_CERT_PATH"))
    target_tls_user_key_path: str = field(default="", **_env("TARGET_TLS_USER_KEY_PATH"))
    grpc_message_max_flows: int = field(default=10000, **_env("GRPC_MESSAGE_MAX_FLOWS", "10000"))
    grpc_reconnect_timer: float = field(default=0.0, **_env("GRPC_RECONNECT_TIMER"))
    grpc_reconnect_timer_randomization: float = field(
        default=0.0, **_env("GRPC_RECONNECT_TIMER_RANDOMIZATION"))

    # --- interface selection ---
    interfaces: list[str] = field(default_factory=list, **_env("INTERFACES"))
    exclude_interfaces: list[str] = field(
        default_factory=lambda: ["lo"], **_env("EXCLUDE_INTERFACES", "lo"))
    interface_ips: list[str] = field(default_factory=list, **_env("INTERFACE_IPS"))
    listen_interfaces: str = field(default="watch", **_env("LISTEN_INTERFACES", "watch"))
    listen_poll_period: float = field(default=10.0, **_env("LISTEN_POLL_PERIOD", "10s"))
    preferred_interface_for_mac_prefix: str = field(
        default="", **_env("PREFERRED_INTERFACE_FOR_MAC_PREFIX"))

    # --- pipeline sizing ---
    buffers_length: int = field(default=50, **_env("BUFFERS_LENGTH", "50"))
    exporter_buffer_length: int = field(default=0, **_env("EXPORTER_BUFFER_LENGTH", "0"))
    cache_max_flows: int = field(default=5000, **_env("CACHE_MAX_FLOWS", "5000"))
    cache_active_timeout: float = field(default=5.0, **_env("CACHE_ACTIVE_TIMEOUT", "5s"))
    #: eviction drain worker lanes: each lane drains one per-CPU feature
    #: map (batched bpf(2) syscalls + native per-CPU merge, both
    #: GIL-releasing) while the calling thread drains the aggregation map;
    #: key alignment stays one vectorized join. 0 = auto (one lane per
    #: feature map, bounded by cores; 1-core hosts stay sequential),
    #: 1 = sequential drain (the pre-lane behavior, bit-identical output);
    #: an explicit N beyond the feature-map count turns the surplus into
    #: per-map merge row-shards (big-map relief)
    evict_drain_lanes: int = field(default=0, **_env("EVICT_DRAIN_LANES", "0"))
    #: fuse the whole per-drain host chain — batched bpf(2) drain, per-CPU
    #: merge, key-alignment join — into ONE GIL-releasing native call
    #: (flowpack fp_drain_to_resident) so drain lanes scale with cores
    #: instead of re-entering the interpreter between native islands.
    #: SCHEDULING ONLY: unset is bit-identical to the island chain (one
    #: is-None check); enabled output is equivalence-pinned against it
    #: (tests/test_native_pipeline.py). Requires the native library at the
    #: current ABI and kernel batch-op support — both probed on the first
    #: drain (which always runs the python chain), degrading silently to
    #: the island chain when either is missing
    evict_native_pipeline: bool = field(
        default=False, **_env("EVICT_NATIVE_PIPELINE", "false"))
    direction: str = field(default="both", **_env("DIRECTION", "both"))
    sampling: int = field(default=0, **_env("SAMPLING", "0"))
    enable_flows_ringbuf_fallback: bool = field(
        default=False, **_env("ENABLE_FLOWS_RINGBUF_FALLBACK", "false"))
    force_garbage_collection: bool = field(
        default=True, **_env("FORCE_GARBAGE_COLLECTION", "true"))
    stale_entries_evict_timeout: float = field(
        default=5.0, **_env("STALE_ENTRIES_EVICT_TIMEOUT", "5s"))

    # --- attach behavior ---
    tc_attach_mode: str = field(default="tcx", **_env("TC_ATTACH_MODE", "tcx"))
    tc_attach_retries: int = field(default=4, **_env("TC_ATTACH_RETRIES", "4"))
    tcx_attach_anchor_ingress: str = field(
        default="none", **_env("TCX_ATTACH_ANCHOR_INGRESS", "none"))
    tcx_attach_anchor_egress: str = field(
        default="none", **_env("TCX_ATTACH_ANCHOR_EGRESS", "none"))

    # --- kafka ---
    kafka_brokers: list[str] = field(default_factory=list, **_env("KAFKA_BROKERS"))
    kafka_topic: str = field(default="network-flows", **_env("KAFKA_TOPIC", "network-flows"))
    kafka_batch_messages: int = field(default=1000, **_env("KAFKA_BATCH_MESSAGES", "1000"))
    kafka_batch_size: int = field(default=1048576, **_env("KAFKA_BATCH_SIZE", "1048576"))
    kafka_async: bool = field(default=True, **_env("KAFKA_ASYNC", "true"))
    kafka_compression: str = field(default="none", **_env("KAFKA_COMPRESSION", "none"))
    kafka_enable_tls: bool = field(default=False, **_env("KAFKA_ENABLE_TLS", "false"))
    kafka_tls_insecure_skip_verify: bool = field(
        default=False, **_env("KAFKA_TLS_INSECURE_SKIP_VERIFY", "false"))
    kafka_tls_ca_cert_path: str = field(default="", **_env("KAFKA_TLS_CA_CERT_PATH"))
    kafka_tls_user_cert_path: str = field(default="", **_env("KAFKA_TLS_USER_CERT_PATH"))
    kafka_tls_user_key_path: str = field(default="", **_env("KAFKA_TLS_USER_KEY_PATH"))
    kafka_enable_sasl: bool = field(default=False, **_env("KAFKA_ENABLE_SASL", "false"))
    kafka_sasl_type: str = field(default="plain", **_env("KAFKA_SASL_TYPE", "plain"))
    kafka_sasl_client_id_path: str = field(default="", **_env("KAFKA_SASL_CLIENT_ID_PATH"))
    kafka_sasl_client_secret_path: str = field(
        default="", **_env("KAFKA_SASL_CLIENT_SECRET_PATH"))

    # --- observability ---
    log_level: str = field(default="info", **_env("LOG_LEVEL", "info"))
    pprof_addr: str = field(default="", **_env("PPROF_ADDR"))
    metrics_enable: bool = field(default=False, **_env("METRICS_ENABLE", "false"))
    metrics_level: str = field(default="info", **_env("METRICS_LEVEL", "info"))
    metrics_server_address: str = field(default="", **_env("METRICS_SERVER_ADDRESS"))
    metrics_server_port: int = field(default=9090, **_env("METRICS_SERVER_PORT", "9090"))
    metrics_tls_cert_path: str = field(default="", **_env("METRICS_TLS_CERT_PATH"))
    metrics_tls_key_path: str = field(default="", **_env("METRICS_TLS_KEY_PATH"))
    metrics_prefix: str = field(default="ebpf_agent_", **_env("METRICS_PREFIX", "ebpf_agent_"))

    # --- pipeline supervision (agent/supervisor.py; new) ---
    #: master switch for the stage supervisor (crash/hang detection,
    #: bounded restarts, DEGRADED transitions, /healthz detail)
    supervisor_enable: bool = field(
        default=True, **_env("SUPERVISOR_ENABLE", "true"))
    supervisor_check_period: float = field(
        default=0.25, **_env("SUPERVISOR_CHECK_PERIOD", "250ms"))
    #: consecutive failures a stage may accrue before it is DEGRADED
    supervisor_max_restarts: int = field(
        default=5, **_env("SUPERVISOR_MAX_RESTARTS", "5"))
    supervisor_backoff_initial: float = field(
        default=0.2, **_env("SUPERVISOR_BACKOFF_INITIAL", "200ms"))
    supervisor_backoff_max: float = field(
        default=30.0, **_env("SUPERVISOR_BACKOFF_MAX", "30s"))
    #: a stage healthy this long after a restart earns its budget back
    supervisor_healthy_reset: float = field(
        default=30.0, **_env("SUPERVISOR_HEALTHY_RESET", "30s"))
    #: hang deadline for fast-poll stages; timer-paced stages (map tracer,
    #: sketch window) get this ON TOP of their own period. The default must
    #: sit ABOVE the worst legitimate stall in a stage loop — the sketch
    #: ingest's first on-chip compile can block the exporter thread for
    #: minutes (see .claude/skills/verify) and must not be "detected"
    supervisor_heartbeat_timeout: float = field(
        default=300.0, **_env("SUPERVISOR_HEARTBEAT_TIMEOUT", "5m"))

    # --- feature enables (propagated to the datapath as compile-time consts) ---
    enable_rtt: bool = field(default=False, **_env("ENABLE_RTT", "false"))
    enable_pkt_drops: bool = field(default=False, **_env("ENABLE_PKT_DROPS", "false"))
    enable_dns_tracking: bool = field(default=False, **_env("ENABLE_DNS_TRACKING", "false"))
    dns_tracking_port: int = field(default=53, **_env("DNS_TRACKING_PORT", "53"))
    enable_network_events_monitoring: bool = field(
        default=False, **_env("ENABLE_NETWORK_EVENTS_MONITORING", "false"))
    network_events_monitoring_group_id: int = field(
        default=10, **_env("NETWORK_EVENTS_MONITORING_GROUP_ID", "10"))
    enable_pkt_translation: bool = field(
        default=False, **_env("ENABLE_PKT_TRANSLATION", "false"))
    enable_ipsec_tracking: bool = field(
        default=False, **_env("ENABLE_IPSEC_TRACKING", "false"))
    enable_openssl_tracking: bool = field(
        default=False, **_env("ENABLE_OPENSSL_TRACKING", "false"))
    openssl_path: str = field(default="/usr/bin/openssl", **_env("OPENSSL_PATH", "/usr/bin/openssl"))
    enable_tls_tracking: bool = field(default=False, **_env("ENABLE_TLS_TRACKING", "false"))
    quic_tracking_mode: int = field(default=0, **_env("QUIC_TRACKING_MODE", "0"))
    enable_udn_mapping: bool = field(default=False, **_env("ENABLE_UDN_MAPPING", "false"))

    # --- filtering ---
    flow_filter_rules: str = field(default="", **_env("FLOW_FILTER_RULES"))

    # --- program-manager (bpfman) mode ---
    ebpf_program_manager_mode: bool = field(
        default=False, **_env("EBPF_PROGRAM_MANAGER_MODE", "false"))
    bpfman_bpf_fs_path: str = field(
        default="/run/netobserv/maps", **_env("BPFMAN_BPF_FS_PATH", "/run/netobserv/maps"))

    # --- PCA (packet capture) mode ---
    enable_pca: bool = field(default=False, **_env("ENABLE_PCA", "false"))
    pca_server_port: int = field(default=0, **_env("PCA_SERVER_PORT", "0"))

    # --- direct-FLP ---
    flp_config: str = field(default="", **_env("FLP_CONFIG"))
    #: JSON file mapping IP -> Kubernetes metadata for add_kubernetes rules
    #: (the file-backed KubeDataSource; a live informer can be injected)
    flp_kube_map: str = field(default="", **_env("FLP_KUBE_MAP"))
    #: ip2location-layout range CSV for add_location rules
    flp_location_db: str = field(default="", **_env("FLP_LOCATION_DB"))

    # --- deprecated aliases (reference: `config.go:298-323`) ---
    flows_target_host: str = field(default="", **_env("FLOWS_TARGET_HOST"))
    flows_target_port: int = field(default=0, **_env("FLOWS_TARGET_PORT", "0"))

    # --- TPU sketch backend (new; no reference equivalent) ---
    sketch_batch_size: int = field(default=8192, **_env("SKETCH_BATCH_SIZE", "8192"))
    sketch_cm_depth: int = field(default=4, **_env("SKETCH_CM_DEPTH", "4"))
    sketch_cm_width: int = field(default=65536, **_env("SKETCH_CM_WIDTH", "65536"))
    sketch_hll_precision: int = field(default=14, **_env("SKETCH_HLL_PRECISION", "14"))
    sketch_topk: int = field(default=1024, **_env("SKETCH_TOPK", "1024"))
    sketch_window: float = field(default=60.0, **_env("SKETCH_WINDOW", "60s"))
    sketch_ewma_alpha: float = field(default=0.3, **_env("SKETCH_EWMA_ALPHA", "0.3"))
    sketch_checkpoint_dir: str = field(default="", **_env("SKETCH_CHECKPOINT_DIR"))
    sketch_checkpoint_every: int = field(default=0, **_env("SKETCH_CHECKPOINT_EVERY", "0"))
    sketch_mesh_shape: str = field(default="", **_env("SKETCH_MESH_SHAPE"))  # e.g. "2x4"
    sketch_devices: str = field(default="", **_env("SKETCH_DEVICES"))  # "", "cpu", "tpu"
    #: auto (default) = fused MXU kernels on TPU at widths >= 16K, XLA
    #: scatter elsewhere; true/false (any bool spelling) force one path
    sketch_use_pallas: str = field(default="auto",
                                   **_env("SKETCH_USE_PALLAS", "auto"))
    # window handling: "reset" zeroes sketches each window; "decay" multiplies
    # linear sketches by SKETCH_DECAY_FACTOR instead (sliding-window flavor)
    sketch_window_mode: str = field(default="reset", **_env("SKETCH_WINDOW_MODE", "reset"))
    #: per-window distinct-(dst addr, dst port) pair fan-out at which a
    #: source bucket is reported as a port-scan suspect
    sketch_scan_fanout: int = field(
        default=DEFAULT_SCAN_FANOUT,
        **_env("SKETCH_SCAN_FANOUT", str(DEFAULT_SCAN_FANOUT)))
    #: EWMA z-score above which a destination bucket is reported as a DDoS
    #: suspect (per-window; see exporter/tpu_sketch.py report_to_json)
    sketch_ddos_z: float = field(default=DEFAULT_DDOS_Z,
                                 **_env("SKETCH_DDOS_Z", str(DEFAULT_DDOS_Z)))
    #: SYN-flood report gates: a victim bucket is reported when its window
    #: half-open count >= MIN and >= RATIO x its SYN-ACK responses
    sketch_synflood_min: int = field(
        default=DEFAULT_SYNFLOOD_MIN,
        **_env("SKETCH_SYNFLOOD_MIN", str(DEFAULT_SYNFLOOD_MIN)))
    sketch_synflood_ratio: float = field(
        default=DEFAULT_SYNFLOOD_RATIO,
        **_env("SKETCH_SYNFLOOD_RATIO", str(DEFAULT_SYNFLOOD_RATIO)))
    #: drop-anomaly z-score threshold (EWMA surge of dropped bytes)
    sketch_drop_z: float = field(default=DEFAULT_DROP_Z,
                                 **_env("SKETCH_DROP_Z", str(DEFAULT_DROP_Z)))
    #: conversation-asymmetry report gates: bucket volume floor and the
    #: one-way byte share (max direction / total) that flags it
    sketch_asym_min_bytes: int = field(
        default=DEFAULT_ASYM_MIN_BYTES,
        **_env("SKETCH_ASYM_MIN_BYTES", str(DEFAULT_ASYM_MIN_BYTES)))
    sketch_asym_ratio: float = field(
        default=DEFAULT_ASYM_RATIO,
        **_env("SKETCH_ASYM_RATIO", str(DEFAULT_ASYM_RATIO)))
    #: heavy-hitter churn render gates (persistent-slot top-K plane): the
    #: count:prev_count growth factor that renders a slot as a flow
    #: ascent/descent, and the current-mass floor for ascent + new-heavy
    #: listings (see exporter/tpu_sketch.py report_to_json)
    sketch_churn_ascent: float = field(
        default=DEFAULT_CHURN_ASCENT,
        **_env("SKETCH_CHURN_ASCENT", str(DEFAULT_CHURN_ASCENT)))
    sketch_churn_min_bytes: int = field(
        default=DEFAULT_CHURN_MIN_BYTES,
        **_env("SKETCH_CHURN_MIN_BYTES", str(DEFAULT_CHURN_MIN_BYTES)))
    #: native packer threads (0 = auto: cpu count, max 8). Dense feed:
    #: row-sharded single-pass packs. RESIDENT feed (the default): the
    #: batch splits into this many pack LANES, each with its own
    #: dictionary + device key table, packed in true parallel — the
    #: host-pack ceiling scales with threads (docs/tpu_sketch.md
    #: "host-path ceiling"). The single-chip compact pack stays a single
    #: pass (its data-dependent spill compaction doesn't row-shard; at
    #: ~80M rec/s it sits above any realistic link anyway)
    sketch_pack_threads: int = field(default=0,
                                     **_env("SKETCH_PACK_THREADS", "0"))
    #: tiered counter planes (sketch/tiered.py): keep the RESIDENT form of
    #: the CM planes + HLL banks narrow (u8 base + u16/u32 overflow tiers
    #: with in-executable saturation promotion; 6-bit packed HLL
    #: registers) — ~4x less HBM per resident sketch window at equal
    #: geometry (docs/tpu_sketch.md "Tiered counter planes"). With the
    #: fused Pallas walks the fold runs TIER-INTERIOR, directly on the
    #: packed tiles (no wide decode temporary; width % 512 == 0 and
    #: top_group <= 512 dividing it); otherwise folds decode to the
    #: canonical wide tables transiently inside the same executable —
    #: bit-exact either way. Single-device only; unset is bit-identical
    #: to the wide-resident path.
    sketch_tiered: bool = field(default=False, **_env("SKETCH_TIERED", "false"))
    #: CM columns sharing one u16 MID overflow cell (power of two)
    sketch_tier_mid_group: int = field(
        default=32, **_env("SKETCH_TIER_MID_GROUP", "32"))
    #: CM columns sharing one u32 TOP overflow cell (power of two,
    #: > mid_group, divides SKETCH_CM_WIDTH)
    sketch_tier_top_group: int = field(
        default=256, **_env("SKETCH_TIER_TOP_GROUP", "256"))
    #: byte quantum of the bytes plane's tiered units (power of two; folds
    #: CEIL to it — overestimate-preserving). The u8 base then spans
    #: 255*unit bytes per counter per window before promotion.
    sketch_tier_bytes_unit: int = field(
        default=256, **_env("SKETCH_TIER_BYTES_UNIT", "256"))
    sketch_decay_factor: float = field(default=0.5, **_env("SKETCH_DECAY_FACTOR", "0.5"))
    #: multi-tenant sketch planes (sketch/tenancy.py): > 0 stacks that many
    #: independent tenant states on a leading axis — ONE vmapped dispatch
    #: folds every tenant's evictions (rows route by a key-derived
    #: `ops/hashing.tenant_of` owner) and ONE roll closes every tenant's
    #: window; /query/*?tenant=, alerts, archive segments and delta frames
    #: fan out per tenant. 0 (default) is bit-identical to the
    #: single-tenant path (no stack object, one is-None check).
    #: Single-device only (config.validate rejects SKETCH_MESH_SHAPE).
    sketch_tenants: int = field(default=0, **_env("SKETCH_TENANTS", "0"))
    #: host->device feed format: "resident" (default, ~15B/record
    #: slot-id rows against a device key table; sharded meshes use one
    #: dictionary+table per data shard), "compact" (40B v4-compact rows,
    #: single-device only) or "dense" (80B full-width rows).
    sketch_feed: str = field(default="resident", **_env("SKETCH_FEED", "resident"))
    #: resident-feed key-table capacity (slots; power of two <= 2^20).
    #: A full dictionary rolls its epoch — size it above the flow-cache
    #: working set (CACHE_MAX_FLOWS)
    sketch_resident_slots: int = field(
        default=1 << 18, **_env("SKETCH_RESIDENT_SLOTS", str(1 << 18)))
    # where window reports go: "stdout" (JSON lines) or "kafka" (uses the
    # KAFKA_* settings; one message per report, key = "sketch_report")
    sketch_report_sink: str = field(default="stdout", **_env("SKETCH_REPORT_SINK", "stdout"))
    #: superbatch fold ladder: comma-separated batch multiples (must
    #: include 1). Queued evictions coalesce into the largest fitting
    #: ladder shape and fold as ONE device dispatch; "1" disables
    #: coalescing (docs/tpu_sketch.md "superbatch fold coalescing")
    sketch_superbatch: str = field(default="1,2,4",
                                   **_env("SKETCH_SUPERBATCH", "1,2,4"))
    #: mid-window query-snapshot refresh period for the agent's /query/*
    #: surface (e.g. "5s"): the supervised timer thread re-runs the
    #: existing roll executable against the live state and publishes its
    #: report + tables WITHOUT closing the window. 0 (default) disables the
    #: refresh entirely — /query serves the last ROLL's snapshot and the
    #: exporter path is bit-identical to pre-query-plane behavior
    sketch_query_refresh: float = field(
        default=0.0, **_env("SKETCH_QUERY_REFRESH", "0"))
    #: closed-window snapshot ring for /query/* back-scroll: the publisher
    #: keeps the last N ROLL snapshots (mid-window refreshes never enter
    #: the ring) and `?window=<id>` serves point-in-time reads; evicted or
    #: never-seen ids answer 404. Still snapshot-only — no device op, no
    #: exporter lock. 0 disables the ring (?window= always 404s)
    sketch_query_history: int = field(
        default=8, **_env("SKETCH_QUERY_HISTORY", "8"))
    #: overlapped eviction dispatch: > 0 runs admit/buffer/fold on a
    #: dedicated supervised fold thread behind a bounded handoff of this
    #: depth, so the eviction feed's drain N+1 overlaps pack/dispatch N
    #: (1 = classic double buffer). A full handoff blocks the feed — the
    #: same backpressure as the synchronous seam, one batch deeper. 0
    #: (default) keeps the synchronous export_evicted path, bit-identical
    #: to the pre-overlap exporter
    sketch_overlap: int = field(default=0, **_env("SKETCH_OVERLAP", "0"))

    # --- overload control plane (sketch/overload.py; new) ---
    #: high watermark (in BATCHES: pending-fold depth weighted by the
    #: seam's fold-duty fraction, plus slot-wait pressure —
    #: docs/architecture.md "Overload & backpressure") above which the
    #: exporter sheds load by unbiased 1-in-N row sampling.
    #: 0 (default) disables shedding entirely: the export path is
    #: bit-identical to the unshedded agent (no RNG, no controller).
    sketch_shed_watermark: float = field(
        default=0.0, **_env("SKETCH_SHED_WATERMARK", "0"))
    #: ceiling on the AIMD shed factor N (at most 1-in-N rows admitted
    #: under sustained overload; the factor multiplies into each surviving
    #: row's `sampling` field so estimates stay unbiased)
    sketch_shed_max: int = field(default=64, **_env("SKETCH_SHED_MAX", "64"))
    #: bound on how long ONE fold may wait for a staging-ring slot when
    #: shedding is enabled — a wedged device then drops batches (counted)
    #: instead of wedging the eviction feed. Generous by default: the
    #: first on-chip compile legitimately stalls for minutes on cold
    #: caches, and the ladder warm runs in the background.
    sketch_shed_slot_budget: float = field(
        default=30.0, **_env("SKETCH_SHED_SLOT_BUDGET", "30s"))
    #: kernel aggregation-map occupancy fraction (of CACHE_MAX_FLOWS) at
    #: which the map tracer starts early evictions (at most 2x the
    #: configured cadence) to shrink the ringbuf-fallback window.
    #: 0 (default) disables pressure relief.
    map_pressure_watermark: float = field(
        default=0.0, **_env("MAP_PRESSURE_WATERMARK", "0"))

    # --- continuous detection & alerting plane (alerts/; new) ---
    #: declarative alert rule set over published query snapshots
    #: ("default" = one rule per anomaly signal; comma list picks a
    #: subset; cardinality_surge:<n> / topk_share:<f> add scalar rules —
    #: alerts/rules.py). Unset (the default) means NO engine exists: the
    #: exporter path is bit-identical to the alert-less agent (one
    #: is-None check — the tracing/fault-point zero-cost bar)
    alert_rules: str = field(default="", **_env("ALERT_RULES"))
    #: hysteresis: consecutive firing evaluations to RAISE an alert
    alert_raise_evals: int = field(default=2, **_env("ALERT_RAISE_EVALS", "2"))
    #: hysteresis: consecutive quiet CLOSED-WINDOW (roll) evaluations to
    #: CLEAR an active alert — mid-window refreshes hold state instead of
    #: counting (the signal plane resets each roll, so a sustained
    #: anomaly looks quiet while a fresh window re-accumulates)
    alert_clear_evals: int = field(default=2, **_env("ALERT_CLEAR_EVALS", "2"))
    #: transition fan-out sinks ("log,metrics" default; "webhook" POSTs
    #: JSON to ALERT_WEBHOOK_URL with per-sink rate limiting + bounded
    #: retry — alerts/sinks.py)
    alert_sinks: str = field(default="log,metrics",
                             **_env("ALERT_SINKS", "log,metrics"))
    alert_webhook_url: str = field(default="", **_env("ALERT_WEBHOOK_URL"))
    #: per-alert flap-suppression window for the webhook: a CLEAR landing
    #: within this interval of the alert's last delivery is HELD (the
    #: receiver keeps the alert visible through a flap) and reconciles
    #: once the interval expires — per-fingerprint delivery rate is
    #: bounded to ~2 per interval, distinct alerts are never throttled
    #: (alerts/sinks.py delivery discipline)
    alert_webhook_interval: float = field(
        default=1.0, **_env("ALERT_WEBHOOK_INTERVAL", "1s"))
    #: recent-transitions ring capacity (the /query/alerts "recent" list)
    alert_ring: int = field(default=256, **_env("ALERT_RING", "256"))

    # --- sketch warehouse (archive/; new) ---
    #: on-disk window archive directory ("" = no archive — the publish
    #: path is bit-identical to the pre-archive exporter). Set on a
    #: tpu-sketch agent (per-agent history) or on the federation
    #: aggregator (cluster-wide history); both mount /…/range over it
    archive_dir: str = field(default="", **_env("ARCHIVE_DIR"))
    #: RAW (per-window) segments kept per retention level before the
    #: oldest ARCHIVE_COMPACT_GROUP of them compact one level up
    archive_raw_windows: int = field(
        default=64, **_env("ARCHIVE_RAW_WINDOWS", "64"))
    #: segments merged per compaction (the RRD coarsening factor G):
    #: level-N super-windows each cover G^N raw windows
    archive_compact_group: int = field(
        default=8, **_env("ARCHIVE_COMPACT_GROUP", "8"))
    #: retention levels above raw; the top level deletes its oldest
    #: beyond the cap, bounding disk at
    #: (levels+1) * (ARCHIVE_RAW_WINDOWS + G - 1) segments
    archive_max_levels: int = field(
        default=3, **_env("ARCHIVE_MAX_LEVELS", "3"))
    #: largest single-dispatch merge size of the range-query ladder
    #: (power of two; one pre-built jit per power of two up to it —
    #: wider ranges chain dispatches)
    archive_merge_ladder_max: int = field(
        default=16, **_env("ARCHIVE_MERGE_LADDER_MAX", "16"))

    # --- sketch federation plane (federation/; new) ---
    #: "host:port" of the central aggregator's Federation gRPC endpoint;
    #: set on per-host agents to stream one delta frame per closed window
    #: (requires SKETCH_WINDOW_MODE=reset — decay frames are cumulative)
    federation_target: str = field(default="", **_env("FEDERATION_TARGET"))
    #: stable agent identity stamped into delta frames (default: hostname)
    federation_agent_id: str = field(default="",
                                     **_env("FEDERATION_AGENT_ID"))
    #: FEDERATION_MODE=aggregator turns `python -m netobserv_tpu` into the
    #: central aggregator tier instead of a flow agent
    federation_mode: str = field(default="", **_env("FEDERATION_MODE"))
    #: aggregator: Federation gRPC listen port (delta ingest)
    federation_listen_port: int = field(
        default=9999, **_env("FEDERATION_LISTEN_PORT", "9999"))
    #: aggregator: cluster-wide query surface HTTP port (0 = ephemeral,
    #: for tests; -1 disables the surface)
    federation_query_port: int = field(
        default=9998, **_env("FEDERATION_QUERY_PORT", "9998"))
    #: aggregator window period (cluster report + EWMA baseline roll)
    federation_window: float = field(default=60.0,
                                     **_env("FEDERATION_WINDOW", "60s"))
    #: aggregator device mesh ("" = single device; "4x1" shards agent
    #: ownership over the data axis and merges over ICI at window roll)
    federation_mesh_shape: str = field(default="",
                                       **_env("FEDERATION_MESH_SHAPE"))
    #: seconds without a delta before an agent counts as dark in /readyz
    #: detail and the staleness gauge commentary (2 windows by default)
    federation_stale_after: float = field(
        default=120.0, **_env("FEDERATION_STALE_AFTER", "120s"))
    #: seconds without a delta before the aggregator EVICTS an agent: it
    #: leaves the ownership view, its staleness gauge series is deleted
    #: (label cardinality stays bounded by the live fleet), and its
    #: delivery-ledger entry is forgotten. 0 disables eviction. A
    #: returning agent re-registers cleanly (fresh epoch after a restart).
    federation_agent_ttl: float = field(
        default=600.0, **_env("FEDERATION_AGENT_TTL", "600s"))
    #: aggregator checkpoint directory ("" = no checkpointing): the
    #: aggregate SketchState + per-agent delivery ledger are saved at each
    #: window roll and restored on startup — a restart loses at most the
    #: uncheckpointed partial window, never a closed one, and redelivered
    #: pre-crash frames still dedup against the restored ledger
    federation_checkpoint_dir: str = field(
        default="", **_env("FEDERATION_CHECKPOINT_DIR"))
    #: checkpoint every Nth aggregator window roll (1 = every window)
    federation_checkpoint_every: int = field(
        default=1, **_env("FEDERATION_CHECKPOINT_EVERY", "1"))

    def resolved_pack_threads(self) -> int:
        """SKETCH_PACK_THREADS with 0 = auto (cpu count, capped at 8)."""
        if self.sketch_pack_threads > 0:
            return self.sketch_pack_threads
        return min(os.cpu_count() or 1, 8)

    def parsed_superbatch_ladder(self) -> tuple:
        """SKETCH_SUPERBATCH as a sorted, deduplicated int tuple — the ONE
        parse of the ladder spec (exporter and bench both use it)."""
        try:
            ladder = tuple(sorted({int(tok) for tok in
                                   self.sketch_superbatch.split(",") if tok}))
        except ValueError as exc:
            raise ValueError(
                f"SKETCH_SUPERBATCH={self.sketch_superbatch!r}: "
                "want comma-separated ints, e.g. 1,2,4") from exc
        if not ladder or ladder[0] != 1 or any(k < 1 for k in ladder):
            raise ValueError(
                f"SKETCH_SUPERBATCH={self.sketch_superbatch!r}: the ladder "
                "must include 1 and be positive")
        if ladder[-1] > 64:
            # fail fast on a typo: every entry costs a jitted executable,
            # ring buffers and key-table rows sized k*batch — a stray
            # '400' would OOM at startup instead of erroring here
            raise ValueError(
                f"SKETCH_SUPERBATCH={self.sketch_superbatch!r}: ladder "
                "entries above 64 are almost certainly a typo (each costs "
                "k*batch-sized buffers and key-table rows)")
        return ladder

    def parsed_filter_rules(self) -> list[FlowFilterRule]:
        return parse_filter_rules(self.flow_filter_rules)

    def manage_deprecated(self) -> None:
        """Apply deprecated-key shims (reference: `config.go:310-323`)."""
        if self.flows_target_host and not self.target_host:
            self.target_host = self.flows_target_host
        if self.flows_target_port and not self.target_port:
            self.target_port = self.flows_target_port
        if self.enable_pca and self.pca_server_port and not self.target_port:
            self.target_port = self.pca_server_port

    def validate(self) -> None:
        if self.export not in VALID_EXPORTERS:
            raise ValueError(
                f"EXPORT={self.export!r} is not one of {', '.join(VALID_EXPORTERS)}")
        if self.export in (EXPORT_GRPC, EXPORT_IPFIX_UDP, EXPORT_IPFIX_TCP):
            if not self.target_host or not self.target_port:
                raise ValueError(
                    f"EXPORT={self.export}: TARGET_HOST and TARGET_PORT are required")
        if self.export == EXPORT_KAFKA and not self.kafka_brokers:
            raise ValueError("EXPORT=kafka: KAFKA_BROKERS is required")
        if self.sketch_cm_width < 2 or self.sketch_cm_width & (self.sketch_cm_width - 1):
            raise ValueError("SKETCH_CM_WIDTH must be a power of two >= 2")
        if self.sketch_tiered:
            for env_name, v, floor in (
                    ("SKETCH_TIER_MID_GROUP", self.sketch_tier_mid_group, 2),
                    ("SKETCH_TIER_TOP_GROUP", self.sketch_tier_top_group, 2),
                    ("SKETCH_TIER_BYTES_UNIT", self.sketch_tier_bytes_unit,
                     1)):
                if v < floor or v & (v - 1):
                    raise ValueError(
                        f"{env_name} must be a power of two >= {floor} "
                        f"(got {v}) — tier geometry must stay power-of-two-"
                        "compatible with SKETCH_CM_WIDTH")
            if self.sketch_tier_top_group <= self.sketch_tier_mid_group:
                raise ValueError(
                    f"SKETCH_TIER_TOP_GROUP ({self.sketch_tier_top_group}) "
                    f"must exceed SKETCH_TIER_MID_GROUP "
                    f"({self.sketch_tier_mid_group}): tiers must narrow as "
                    "counters widen")
            if self.sketch_cm_width % self.sketch_tier_top_group:
                raise ValueError(
                    f"SKETCH_TIER_TOP_GROUP ({self.sketch_tier_top_group}) "
                    f"must divide SKETCH_CM_WIDTH ({self.sketch_cm_width})")
            if self.sketch_mesh_shape:
                raise ValueError(
                    "SKETCH_TIERED has no owner-sharded form yet (tiered "
                    "counter planes are single-device); unset "
                    "SKETCH_MESH_SHAPE or SKETCH_TIERED")
        if self.sketch_tenants < 0:
            raise ValueError("SKETCH_TENANTS must be >= 0")
        if self.sketch_tenants and self.sketch_mesh_shape:
            raise ValueError(
                "SKETCH_TENANTS has no mesh-sharded form yet (the tenant "
                "stack is single-device, like SKETCH_TIERED); unset "
                "SKETCH_MESH_SHAPE or SKETCH_TENANTS")
        if not (4 <= self.sketch_hll_precision <= 18):
            raise ValueError("SKETCH_HLL_PRECISION must be in [4, 18]")
        if self.sketch_window_mode not in ("reset", "decay"):
            raise ValueError(
                f"SKETCH_WINDOW_MODE={self.sketch_window_mode!r} "
                "(want reset|decay)")
        if self.sketch_window_mode == "decay" and not (
                0.0 < self.sketch_decay_factor < 1.0):
            raise ValueError("SKETCH_DECAY_FACTOR must be in (0, 1)")
        if self.sketch_report_sink not in ("", "stdout", "kafka"):
            raise ValueError(
                f"SKETCH_REPORT_SINK={self.sketch_report_sink!r} "
                "(want stdout|kafka)")
        self.parsed_superbatch_ladder()  # raises on a malformed ladder spec
        if self.sketch_query_refresh < 0:
            raise ValueError(
                "SKETCH_QUERY_REFRESH must be >= 0 (0 disables the "
                "mid-window refresh)")
        if self.sketch_shed_watermark < 0:
            raise ValueError("SKETCH_SHED_WATERMARK must be >= 0 (0 disables)")
        if self.sketch_query_history < 0:
            raise ValueError("SKETCH_QUERY_HISTORY must be >= 0 "
                             "(0 disables the back-scroll ring)")
        if self.sketch_overlap < 0:
            raise ValueError("SKETCH_OVERLAP must be >= 0 (0 keeps the "
                             "synchronous export seam)")
        if self.evict_drain_lanes < 0:
            raise ValueError("EVICT_DRAIN_LANES must be >= 0 (0 = auto, "
                             "1 = sequential)")
        if self.sketch_shed_max < 2:
            raise ValueError("SKETCH_SHED_MAX must be >= 2 (it bounds the "
                             "1-in-N shed factor)")
        if not (0.0 <= self.map_pressure_watermark < 1.0):
            raise ValueError("MAP_PRESSURE_WATERMARK must be in [0, 1) "
                             "(a fraction of CACHE_MAX_FLOWS; 0 disables)")
        if self.alert_raise_evals < 1 or self.alert_clear_evals < 1:
            raise ValueError("ALERT_RAISE_EVALS and ALERT_CLEAR_EVALS "
                             "must be >= 1")
        if self.sketch_churn_ascent <= 1.0:
            raise ValueError("SKETCH_CHURN_ASCENT must be > 1 (it is a "
                             "window-over-window growth factor)")
        if self.sketch_churn_min_bytes < 0:
            raise ValueError("SKETCH_CHURN_MIN_BYTES must be >= 0")
        if self.alert_ring < 1:
            raise ValueError("ALERT_RING must be >= 1")
        if self.alert_webhook_interval < 0:
            raise ValueError("ALERT_WEBHOOK_INTERVAL must be >= 0")
        if self.alert_rules:
            # fail fast on a malformed rule spec or sink set (the engine
            # would only parse them at exporter construction otherwise);
            # the webhook-URL requirement is validated by the ONE sink
            # builder via a throwaway registry-less construction
            from netobserv_tpu.alerts.rules import parse_rules
            from netobserv_tpu.alerts.sinks import build_sinks
            parse_rules(self.alert_rules)
            build_sinks(self)
        if self.archive_compact_group < 2:
            raise ValueError("ARCHIVE_COMPACT_GROUP must be >= 2 (it is "
                             "the RRD coarsening factor)")
        if self.archive_raw_windows < self.archive_compact_group:
            raise ValueError(
                f"ARCHIVE_RAW_WINDOWS ({self.archive_raw_windows}) must "
                f"be >= ARCHIVE_COMPACT_GROUP "
                f"({self.archive_compact_group})")
        if self.archive_max_levels < 1:
            raise ValueError("ARCHIVE_MAX_LEVELS must be >= 1")
        v = self.archive_merge_ladder_max
        if v < 1 or v & (v - 1) or v > 64:
            raise ValueError(
                f"ARCHIVE_MERGE_LADDER_MAX must be a power of two in "
                f"[1, 64] (got {v}) — every power of two up to it costs "
                "a pre-built merge executable")
        if self.federation_mode not in ("", "aggregator"):
            raise ValueError(
                f"FEDERATION_MODE={self.federation_mode!r} "
                "(want empty|aggregator)")
        if self.federation_target and ":" not in self.federation_target:
            raise ValueError(
                f"FEDERATION_TARGET={self.federation_target!r} "
                "(want host:port)")
        if self.federation_target and self.sketch_window_mode == "decay":
            logging.getLogger("netobserv_tpu.config").warning(
                "FEDERATION_TARGET with SKETCH_WINDOW_MODE=decay: delta "
                "export is disabled (decayed tables are cumulative, the "
                "aggregator merges per-window deltas)")
        if self.sketch_cm_width < 16 * self.sketch_topk:
            # measured F1 cliff (docs/accuracy.md): top-K precision degrades
            # once Count-Min columns are shared by too many tracked keys —
            # warn, don't refuse (small-memory deployments may accept it)
            logging.getLogger("netobserv_tpu.config").warning(
                "SKETCH_CM_WIDTH=%d is below 16*SKETCH_TOPK=%d: heavy-hitter "
                "precision degrades measurably at this ratio (docs/"
                "accuracy.md); widen the sketch or shrink the top-K",
                self.sketch_cm_width, 16 * self.sketch_topk)


_DURATION_FIELDS = {
    "cache_active_timeout", "listen_poll_period", "stale_entries_evict_timeout",
    "grpc_reconnect_timer", "grpc_reconnect_timer_randomization", "sketch_window",
    "supervisor_check_period", "supervisor_backoff_initial",
    "supervisor_backoff_max", "supervisor_healthy_reset",
    "supervisor_heartbeat_timeout", "federation_window",
    "federation_stale_after", "federation_agent_ttl",
    "sketch_shed_slot_budget", "sketch_query_refresh",
    "alert_webhook_interval",
}


def _coerce(f: dataclasses.Field, raw: str) -> Any:
    if f.name in _DURATION_FIELDS:
        return parse_duration(raw)
    if f.type in ("bool", bool):
        return _parse_bool(raw)
    if f.type in ("int", int):
        return int(raw)
    if f.type in ("float", float):
        return float(raw)
    if f.type in ("list[str]",):
        return [s.strip() for s in raw.split(",") if s.strip()]
    return raw


def load_config(environ: Optional[dict] = None) -> AgentConfig:
    """Build an AgentConfig from environment variables (reference: env.Parse)."""
    environ = os.environ if environ is None else environ
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(AgentConfig):
        env_name = f.metadata.get("env")
        if not env_name:
            continue
        raw = environ.get(env_name)
        if raw is None:
            continue
        if raw == "":
            # set-but-empty clears string/list fields (e.g. EXCLUDE_INTERFACES="")
            # but cannot express a numeric/bool value — treat as unset for those.
            if f.type in ("str", str):
                kwargs[f.name] = ""
            elif f.type in ("list[str]",):
                kwargs[f.name] = []
            continue
        kwargs[f.name] = _coerce(f, raw)
    cfg = AgentConfig(**kwargs)
    cfg.manage_deprecated()
    return cfg
