"""Alert fan-out sinks: structured log, stdlib-only webhook, metrics.

Sinks receive one RAISE/CLEAR transition event dict at a time, on the
supervised timer thread (never the fold path). The stream is
edge-triggered — per (rule, bucket) fingerprint the engine only ever
emits alternating raise/clear — so per-sink throttling must reason about
RECEIVER STATE, not raw event rate. Delivery discipline, per sink:

- **state dedup**: an event whose action matches the last action
  DELIVERED to this sink for that fingerprint is skipped (the receiver
  is already in that state — e.g. a re-raise whose clear was suppressed);
- **flap suppression** (``min_interval_s``): a CLEAR arriving within the
  interval of the fingerprint's last delivery is HELD, not dropped — the
  receiver keeps showing the alert through a flap (operationally the
  right reading of a flapping alert); the engine's per-evaluation
  :meth:`AlertSink.flush` delivers the held clear once the interval
  expires, so a real clear always reconciles (never stuck-active) and a
  re-raise meanwhile just cancels the hold (never stuck-cleared). Net
  per-fingerprint delivery rate is bounded to ~2 per interval;
- **bounded retry** (``retries`` extra attempts, same thread, no backoff
  sleep beyond the webhook's own socket timeout) plus a **circuit
  breaker**: after 3 consecutive exhausted failures the sink opens for
  ``max(min_interval_s, 5s)`` and skips deliveries (counted) — a dead
  endpoint must not stall the timer thread (retries+1)*timeout per
  transition through a burst of distinct alerts;
- **parked reconciliation**: a transition that exhausts its retries (or
  lands on an open breaker) is PARKED as the fingerprint's latest
  target state and retried by ``flush()`` — symmetric for raises (a
  missed raise would hide an active detection for its whole lifetime)
  and clears (a missed terminal clear would stick the receiver active);
  a clear arriving while its raise is still parked annihilates the pair
  (the receiver never saw either, and sees nothing);
- **swallow + count**: an exhausted sink failure increments
  ``alert_sink_errors_total{sink}`` and is logged; it never propagates
  into the engine, the other sinks, or the snapshot publish that drove
  the evaluation. The ``alerts.sink`` fault point fires per delivery
  attempt so the chaos suite can prove all of this live.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request

from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.alerts")


class AlertSink:
    """Base sink: subclasses implement :meth:`deliver`. Counters are
    plain ints read by the engine's view publisher (single-writer:
    deliveries are serialized by the engine's evaluation lock)."""

    name = "base"

    #: fingerprint-map bound (transitions only come from the engine's
    #: bounded active set, so this is a belt-and-braces cap)
    MAX_TRACKED_FINGERPRINTS = 1024
    #: consecutive exhausted failures that open the circuit breaker
    BREAKER_TRIP = 3
    #: minimum breaker-open window for low/zero min_interval_s sinks
    BREAKER_MIN_OPEN_S = 5.0

    def __init__(self, min_interval_s: float = 0.0, retries: int = 1):
        self.min_interval_s = float(min_interval_s)
        self.retries = max(0, int(retries))
        self.delivered = 0
        self.rate_limited = 0
        self.errors = 0
        self.breaker_skips = 0
        #: (rule, bucket) -> (last delivered action, delivery monotonic
        #: time) — the receiver-state ledger the dedup and flap
        #: suppression reason over
        self._state_by_fp: dict[tuple, tuple[str, float]] = {}
        #: fingerprints with an UNDELIVERED latest state: flap-held
        #: clears AND transitions whose delivery failed or hit an open
        #: breaker — flush() reconciles them (symmetric: a parked raise
        #: must reach the receiver once the endpoint recovers, a parked
        #: clear must never leave it stuck-active)
        self._pending: dict[tuple, dict] = {}
        self._consec_errors = 0
        self._open_until = 0.0

    def deliver(self, event: dict) -> None:
        raise NotImplementedError

    def emit(self, event: dict, metrics=None) -> None:
        """State-dedup + flap-suppression + bounded-retry wrapper around
        :meth:`deliver` (the engine calls only this; see the module
        docstring for the delivery discipline)."""
        now = time.monotonic()
        fp = (event.get("rule"), event.get("bucket"))
        action = event.get("action")
        last = self._state_by_fp.get(fp)
        if last is not None and last[0] == action:
            # the receiver already shows this state (e.g. a re-raise
            # whose clear was suppressed mid-flap): nothing to send —
            # and ANY pending transition is now stale (a deduped
            # re-raise means the alert is live again; flushing the old
            # clear later would leave the receiver stuck-cleared)
            self._pending.pop(fp, None)
            self.rate_limited += 1
            return
        if action == "raise":
            # a raise supersedes any held clear: the flap is active
            # again and the receiver (still showing raised) is right
            self._pending.pop(fp, None)
        elif action == "clear":
            stale = self._pending.pop(fp, None)
            if stale is not None and stale.get("action") == "raise":
                # the raise never reached the receiver and the lifecycle
                # already ended: the pair annihilates — the receiver's
                # view (nothing active) is already the end state
                self.rate_limited += 1
                return
            if (self.min_interval_s and last is not None
                    and now - last[1] < self.min_interval_s):
                # flap suppression: HOLD the clear — the receiver keeps
                # the alert visible through the flap; flush() reconciles
                # once the interval expires, so a real clear is never
                # lost
                self._pending[fp] = event
                self.rate_limited += 1
                return
        self._attempt(fp, event, now, metrics)

    def flush(self, metrics=None) -> int:
        """Deliver pending transitions that are past their suppression
        interval (the engine calls this once per evaluation — state
        reconciliation for flap-held clears and failure/breaker-parked
        transitions). Returns delivered-attempt count."""
        if not self._pending:
            return 0
        now = time.monotonic()
        n = 0
        for fp, ev in list(self._pending.items()):
            last = self._state_by_fp.get(fp)
            if last is None or now - last[1] >= self.min_interval_s:
                del self._pending[fp]
                self._attempt(fp, ev, now, metrics)
                n += 1
        return n

    def _park(self, fp: tuple, event: dict) -> None:
        """Remember an undeliverable transition as the fingerprint's
        latest target state; flush() keeps retrying it. Bounded by
        evicting the OLDEST parked entry (never clear-all: a wholesale
        wipe would drop terminal clears for receivers that saw the raise
        — the stuck-active hazard the parking exists to prevent; under
        churn the oldest entry is the most likely stale one)."""
        while len(self._pending) >= self.MAX_TRACKED_FINGERPRINTS:
            self._pending.pop(next(iter(self._pending)))
        self._pending[fp] = event

    def _attempt(self, fp: tuple, event: dict, now: float,
                 metrics=None) -> None:
        if now < self._open_until:
            # circuit open: a dead endpoint must not stall the timer
            # thread (retries+1)*timeout per transition — skip, counted,
            # and PARK the transition so flush() reconciles the receiver
            # once the breaker closes (a dropped raise hides an active
            # detection; a dropped terminal clear sticks it active)
            self.breaker_skips += 1
            self._park(fp, event)
            return
        last_exc: Exception | None = None
        for _attempt in range(self.retries + 1):
            try:
                faultinject.fire("alerts.sink")
                self.deliver(event)
                self.delivered += 1
                self._consec_errors = 0
                if len(self._state_by_fp) >= self.MAX_TRACKED_FINGERPRINTS:
                    self._state_by_fp.clear()  # bounded; worst case one
                    #                            duplicate send later
                self._state_by_fp[fp] = (event.get("action"), now)
                return
            except Exception as exc:
                last_exc = exc
        self.errors += 1
        self._consec_errors += 1
        if self._consec_errors >= self.BREAKER_TRIP:
            self._open_until = now + max(self.min_interval_s,
                                         self.BREAKER_MIN_OPEN_S)
        # park for flush-retry (raise AND clear: a missed raise hides an
        # active detection for its whole lifetime, a missed clear leaves
        # the receiver stuck-active)
        self._park(fp, event)
        log.error("alert sink %s failed after %d attempt(s) "
                  "(transition parked for flush retry): %s",
                  self.name, self.retries + 1, last_exc)
        if metrics is not None:
            metrics.alert_sink_errors_total.labels(self.name).inc()

    def stats(self) -> dict:
        return {"delivered": self.delivered,
                "rate_limited": self.rate_limited,
                "errors": self.errors,
                "breaker_skips": self.breaker_skips,
                "pending_transitions": len(self._pending)}


class LogSink(AlertSink):
    """Structured log line per transition (the always-works sink): one
    JSON object on the agent log, greppable by ``alert_transition``."""

    name = "log"

    def deliver(self, event: dict) -> None:
        log.warning("alert_transition %s",
                    json.dumps(event, separators=(",", ":")))


class WebhookSink(AlertSink):
    """Stdlib-only JSON POST (no requests dependency): one transition per
    call, ``Content-Type: application/json``, bounded socket timeout so a
    dead endpoint costs at most ``(retries+1) * timeout_s`` of the timer
    thread per transition — and the rate limiter bounds how often."""

    name = "webhook"

    def __init__(self, url: str, min_interval_s: float = 1.0,
                 retries: int = 1, timeout_s: float = 2.0):
        super().__init__(min_interval_s=min_interval_s, retries=retries)
        if not url:
            raise ValueError("webhook sink needs a URL "
                             "(ALERT_WEBHOOK_URL)")
        self.url = url
        self.timeout_s = float(timeout_s)

    def deliver(self, event: dict) -> None:
        req = urllib.request.Request(
            self.url, data=json.dumps(event).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            resp.read()


class MetricsSink(AlertSink):
    """Transitions into the Prometheus registry:
    ``alerts_transitions_total{rule, action}``. The active-count gauge and
    eval-latency histogram are the engine's (they are per-eval, not
    per-transition)."""

    name = "metrics"

    def __init__(self, metrics):
        super().__init__()
        self._metrics = metrics

    def deliver(self, event: dict) -> None:
        self._metrics.alerts_transitions_total.labels(
            event["rule"], event["action"]).inc()


def build_sinks(cfg, metrics=None) -> list:
    """ALERT_SINKS wiring (``log,metrics`` default). ``webhook`` requires
    ALERT_WEBHOOK_URL; ``metrics`` is silently skipped when no registry is
    wired (a bare embedder)."""
    tokens = [t.strip() for t in cfg.alert_sinks.split(",") if t.strip()]
    if not tokens:
        # fail-fast symmetry with parse_rules: a whitespace/comma-only
        # ALERT_SINKS would silently route every transition to NOTHING
        raise ValueError("ALERT_SINKS is set but names no sinks "
                         "(want a comma list of log, metrics, webhook)")
    out = []
    for tok in tokens:
        if tok == "log":
            out.append(LogSink())
        elif tok == "metrics":
            if metrics is not None:
                out.append(MetricsSink(metrics))
        elif tok == "webhook":
            out.append(WebhookSink(cfg.alert_webhook_url,
                                   min_interval_s=cfg.alert_webhook_interval))
        else:
            raise ValueError(f"ALERT_SINKS: unknown sink {tok!r} "
                             "(one of log, metrics, webhook)")
    return out
