"""Stateful alert engine: hysteresis over every snapshot publish.

Driven by the SAME publish seam the query plane rides: the tpu-sketch
exporter calls :meth:`AlertEngine.evaluate` after each snapshot publish
(window roll AND, with ``SKETCH_QUERY_REFRESH``, mid-window refreshes) on
the supervised timer thread; the federation aggregator mounts a second
engine over its merged-window snapshots. The plane is host-only — no jit,
no device op, no exporter lock — and strictly read-only over the
published dict.

State machine (per fingerprint = (rule, victim bucket)):

- an instance firing in ``raise_evals`` CONSECUTIVE evaluations RAISEs —
  exactly one ``raise`` transition, no matter how long it keeps firing
  (every evaluation counts, including refreshes: that is what makes
  detection sub-window);
- an active alert quiet for ``clear_evals`` consecutive CLOSED-WINDOW
  evaluations CLEARs — exactly one ``clear`` transition; mid-window
  quiet evaluations hold state instead of counting, because the signal
  plane resets at each roll and a sustained anomaly looks quiet in a
  fresh window's first refreshes while it re-accumulates (clears settle
  at window granularity; counting raw evals would flap clear/re-raise
  once per window mid-attack). Quiet non-active state is forgotten (the
  tracked set stays bounded by live anomalies);
- transitions land in a bounded ring (newest last) and fan out to the
  sinks (``alerts/sinks.py`` — rate-limited, bounded-retry,
  swallow+count).

Exactly-once across restarts: the engine's state lives on the exporter
object, not the timer thread — a supervised timer restart re-drives the
SAME engine, and because snapshot publishes are themselves exactly-once
(the report-queue contract), no transition can double-fire.

Readers (the ``/query/alerts`` + ``/federation/alerts`` routes, the
``/query/status`` summary, the ``alerting`` supervisor condition) get the
same torn-read guarantee as the query snapshot: every evaluation builds a
FRESH view dict and swaps the whole reference; roll evaluations
additionally enter a closed-window ring for ``?window=`` back-scroll
(mid-window evaluations update the live view only — the back-scroll
contract of `query/snapshot.py`).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Optional

from netobserv_tpu.utils import faultinject

log = logging.getLogger("netobserv_tpu.alerts")


class _FpState:
    __slots__ = ("streak", "quiet", "active", "since_window", "since_ts_ms",
                 "raise_seq", "detail")

    def __init__(self):
        self.streak = 0
        self.quiet = 0
        self.active = False
        self.since_window = 0
        self.since_ts_ms = 0
        self.raise_seq = 0
        self.detail: dict = {}


class AlertEngine:
    """One alerting plane instance (per agent, or per aggregator tier)."""

    def __init__(self, rules, metrics=None, sinks=(), source: str = "agent",
                 history: int = 8, ring: int = 256, max_active: int = 256):
        if not rules:
            raise ValueError("AlertEngine needs at least one rule "
                             "(ALERT_RULES unset means NO engine, "
                             "not an empty one)")
        self._rules = list(rules)
        self._metrics = metrics
        self._sinks = list(sinks)
        self._source = source
        # _lock guards state + the published view (held briefly; readers
        # never wait behind sink I/O). _eval_lock serializes WHOLE
        # evaluations: after a supervisor hang-restart a superseded zombie
        # timer thread can re-enter evaluate() next to its replacement —
        # without this, the two would interleave sink deliveries (a CLEAR
        # webhook POSTed before its RAISE) and racing view re-swaps.
        self._lock = threading.Lock()
        self._eval_lock = threading.Lock()
        #: fingerprint -> _FpState (bounded by max_active)
        self._states: dict[tuple, _FpState] = {}
        self._max_active = max(1, int(max_active))
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(ring)))
        self._transition_seq = 0
        self._evals = 0
        self._dropped_fingerprints = 0
        #: rule name -> firing() exception count (a broken rule must be
        #: VISIBLE, not silently quiet — logged on first failure, counted
        #: in the view and errors_total)
        self._rule_errors: dict[str, int] = {}
        self._history_cap = max(0, int(history))
        #: window id -> closed-window view (roll evaluations only)
        self._history: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        # an engine is queryable from construction: /query/alerts answers
        # an empty active set before the first publish (the route's 503
        # belongs to the SNAPSHOT routes; alert state simply starts empty)
        self._view: dict = self._build_view_locked(
            window=None, ts_ms=0, seq=0, mid_window=False)

    # --- evaluation (timer thread; callers swallow+count) ---------------
    def evaluate(self, snap: dict, mid_window: bool = False) -> list[dict]:
        """Evaluate every rule against one published snapshot. Returns the
        transitions this evaluation produced (tests read them; production
        callers ignore the return). May raise only via the
        ``alerts.evaluate`` fault point or a bug — callers wrap it in
        their own try (the snapshot is already published; a failing
        evaluation must never lose it)."""
        faultinject.fire("alerts.evaluate")
        with self._eval_lock:
            return self._evaluate_serialized(snap, mid_window)

    def _evaluate_serialized(self, snap: dict, mid_window: bool) -> list:
        t0 = time.perf_counter()
        report = snap.get("report") or {}
        window = snap.get("window")
        ts_ms = snap.get("ts_ms") or 0
        with self._lock:
            self._evals += 1
            transitions: list[dict] = []
            firing_now: set[tuple] = set()
            erroring_rules: set[str] = set()
            for rule in self._rules:
                try:
                    instances = rule.firing(report)
                except Exception as exc:
                    # one malformed rule/field must not silence the rest —
                    # but a permanently-quiet broken rule must be VISIBLE
                    # (swallow+COUNT, the plane's own discipline): logged
                    # on its first failure, counted per rule in the view
                    # and in errors_total{component="alerts"}
                    instances = []
                    erroring_rules.add(rule.name)
                    n = self._rule_errors.get(rule.name, 0) + 1
                    self._rule_errors[rule.name] = n
                    if n == 1:
                        log.error(
                            "alert rule %s failed to evaluate (rule "
                            "stays quiet until fixed; counted in the "
                            "view's rule_errors): %s", rule.name, exc)
                    if self._metrics is not None:
                        self._metrics.count_error("alerts")
                for inst in instances:
                    # tenant-mode snapshots stamp their plane id: the
                    # fingerprint carries it so tenant A's flood and
                    # tenant B's flood on the same victim bucket raise,
                    # streak and clear INDEPENDENTLY (None otherwise —
                    # single-tenant fingerprints are unchanged)
                    fp = (rule.name, inst["bucket"], snap.get("tenant"))
                    firing_now.add(fp)
                    st = self._states.get(fp)
                    if st is None:
                        if len(self._states) >= self._max_active:
                            self._dropped_fingerprints += 1
                            continue
                        st = self._states[fp] = _FpState()
                    st.streak += 1
                    st.quiet = 0
                    st.detail = {"value": inst["value"],
                                 "victims": inst["victims"]}
                    if not st.active and st.streak >= rule.raise_evals:
                        st.active = True
                        st.since_window = window
                        st.since_ts_ms = ts_ms
                        transitions.append(self._transition_locked(
                            "raise", rule, fp, st, snap))
                        st.raise_seq = self._transition_seq
            for fp, st in list(self._states.items()):
                if fp in firing_now:
                    continue
                if fp[0] in erroring_rules:
                    # an erroring rule's verdict is INDETERMINATE, not
                    # quiet: hold its existing state (streaks and active
                    # alerts freeze) — a broken rule must never tell the
                    # sinks an ongoing anomaly "cleared"
                    continue
                st.streak = 0  # "consecutive" means consecutive
                if mid_window:
                    # quiet HYSTERESIS counts CLOSED WINDOWS only: the
                    # signal plane resets at each roll, so a sustained
                    # multi-window anomaly looks quiet in the first
                    # refreshes of every fresh window while it
                    # re-accumulates — counting those evals would flap
                    # clear/re-raise once per window mid-attack. Raises
                    # keep counting EVERY evaluation (sub-window
                    # detection); clears settle at window granularity.
                    continue
                st.quiet += 1
                rule = self._rule(fp[0])
                if st.quiet >= rule.clear_evals:
                    if st.active:
                        st.active = False
                        transitions.append(self._transition_locked(
                            "clear", rule, fp, st, snap))
                    del self._states[fp]  # quiet state stays bounded
            for ev in transitions:
                self._ring.append(ev)
            view = self._build_view_locked(window, ts_ms,
                                           snap.get("seq", 0), mid_window)
            self._view = view
            if not mid_window and self._history_cap and window is not None:
                wid = int(window)
                self._history.pop(wid, None)
                self._history[wid] = view
                while len(self._history) > self._history_cap:
                    self._history.popitem(last=False)
        # the eval latency metric covers the RULE WALK only (sink I/O is
        # excluded — the docs row's triage guidance depends on that), and
        # the active gauge reads the view built under the lock (never a
        # bare walk of self._states: a superseded zombie timer thread
        # evaluating concurrently must not race the dict iteration)
        if self._metrics is not None:
            self._metrics.alerts_active.set(len(view["active"]))
            self._metrics.alert_eval_seconds.observe(
                time.perf_counter() - t0)
        # sink fan-out OFF the engine lock: a slow webhook must not block
        # a concurrent /query/alerts read (still on the timer thread — the
        # hot path never waits on it either way). flush() first: held
        # flap-suppressed clears whose interval expired reconcile before
        # this evaluation's new transitions land.
        flushed = 0
        for sink in self._sinks:
            flushed += sink.flush(metrics=self._metrics)
        for ev in transitions:
            for sink in self._sinks:
                sink.emit(ev, metrics=self._metrics)
        if self._sinks and (transitions or flushed):
            # refresh the published view's sink stats post-delivery (a
            # fresh dict swap: the immutability contract holds; readers
            # holding the pre-delivery view just see slightly older
            # delivery counters). Identity-guarded: only THIS
            # evaluation's view is re-swapped — a stale thread must
            # never clobber a newer published view.
            with self._lock:
                if self._view is view:
                    self._view = {**view, "sinks": {
                        s.name: s.stats() for s in self._sinks}}
        return transitions

    def safe_evaluate(self, snap: dict, mid_window: bool = False) -> None:
        """The swallow+count wrapper BOTH tiers mount (the exporter's
        publish seam and the aggregator's merged-window publish): a
        failing evaluation is logged and counted, never propagated — the
        snapshot it rides is already published and must not be lost.
        Lives here so the error-handling discipline cannot drift between
        the two mounts."""
        try:
            self.evaluate(snap, mid_window=mid_window)
        except Exception as exc:
            log.error("alert evaluation failed (snapshot already "
                      "published; next publish retries): %s", exc)
            if self._metrics is not None:
                self._metrics.count_error("alerts")

    def _rule(self, name: str):
        for r in self._rules:
            if r.name == name:
                return r
        raise KeyError(name)

    def _transition_locked(self, action: str, rule, fp: tuple,
                           st: _FpState, snap: dict) -> dict:
        self._transition_seq += 1
        return {
            "seq": self._transition_seq,
            "action": action,
            "rule": rule.name,
            "severity": rule.severity,
            "source": self._source,
            "bucket": fp[1],
            "victims": list(st.detail.get("victims", ())),
            "value": st.detail.get("value", 0.0),
            "window": snap.get("window"),
            "snapshot_seq": snap.get("seq", 0),
            "ts_ms": snap.get("ts_ms") or 0,
            "since_window": st.since_window,
            **({"tenant": fp[2]} if fp[2] is not None else {}),
        }

    def _build_view_locked(self, window, ts_ms: int, seq: int,
                           mid_window: bool) -> dict:
        active = []
        for (rule_name, bucket, tenant), st in self._states.items():
            if not st.active:
                continue
            rule = self._rule(rule_name)
            active.append({
                "rule": rule_name, "severity": rule.severity,
                "bucket": bucket,
                **({"tenant": tenant} if tenant is not None else {}),
                "victims": list(st.detail.get("victims", ())),
                "value": st.detail.get("value", 0.0),
                "since_window": st.since_window,
                "since_ts_ms": st.since_ts_ms,
                "raise_seq": st.raise_seq,
                "streak": st.streak,
            })
        active.sort(key=lambda a: a["raise_seq"])
        return {
            "source": self._source,
            "window": window,
            "ts_ms": ts_ms,
            "seq": seq,
            "mid_window": bool(mid_window),
            "evals": self._evals,
            "transition_seq": self._transition_seq,
            "active": active,
            "recent": list(self._ring),
            "rules": [r.name for r in self._rules],
            "rule_errors": dict(self._rule_errors),
            "dropped_fingerprints": self._dropped_fingerprints,
            "sinks": {s.name: s.stats() for s in self._sinks},
        }

    # --- read surface (HTTP threads; snapshot-only) ---------------------
    def view(self) -> dict:
        """The live alert view (whole-dict swap: torn reads impossible)."""
        with self._lock:
            return self._view

    def window_view(self, window: int) -> Optional[dict]:
        with self._lock:
            return self._history.get(int(window))

    def windows(self) -> list[int]:
        with self._lock:
            return list(self._history.keys())

    def route_payload(self, window_param=None) -> tuple[int, dict]:
        """The ONE /query/alerts + /federation/alerts body builder (the
        thin-adapter rule: both tiers' handlers call this). ``?window=``
        follows the back-scroll contract: closed-window views only,
        evicted/unknown ids answer 404 with the available list."""
        if window_param is not None:
            wid = int(window_param)  # malformed -> ValueError -> 400
            view = self.window_view(wid)
            if view is None:
                return 404, {
                    "error": f"window {wid} not in the alert ring",
                    "windows": self.windows()}
            return 200, view
        return 200, self.view()

    def summary(self) -> dict:
        """Compact block for /query/status — derived from ONE view read
        (the read-once rule: no racy second lock acquisition)."""
        view = self.view()
        return {"active": len(view["active"]),
                "last_transition_seq": view["transition_seq"],
                "evals": view["evals"]}

    def condition(self) -> dict:
        """The ``alerting`` supervisor condition probe. Like OVERLOADED:
        a raising alert is the agent doing its job, not a failing stage —
        /readyz stays 200 (conditions never gate readiness)."""
        view = self.view()
        return {"active": bool(view["active"]),
                "active_alerts": len(view["active"]),
                "last_transition_seq": view["transition_seq"],
                "rules": view["rules"]}


def maybe_engine(cfg, metrics=None, source: str = "agent"):
    """ALERT_RULES-gated construction (the zero-cost bar: unset returns
    None and the mount point is one is-None check — no engine object, no
    sinks, nothing on any path)."""
    if not cfg.alert_rules:
        return None
    from netobserv_tpu.alerts import rules as arules, sinks as asinks
    return AlertEngine(
        arules.parse_rules(cfg.alert_rules,
                           raise_evals=cfg.alert_raise_evals,
                           clear_evals=cfg.alert_clear_evals),
        metrics=metrics, sinks=asinks.build_sinks(cfg, metrics),
        source=source, history=cfg.sketch_query_history,
        ring=cfg.alert_ring)
