"""Continuous detection & alerting plane (host-only, jax-free).

The agent's signal plane computes per-window anomaly scores and the query
plane publishes torn-read-proof snapshots at every roll and mid-window
refresh; this package WATCHES them: a declarative rule set
(`alerts/rules.py`), a hysteresis state machine driven by every snapshot
publish (`alerts/engine.py`), and fan-out sinks (`alerts/sinks.py`).
Mounted by the tpu-sketch exporter (`/query/alerts`) and the federation
aggregator (`/federation/alerts`). `ALERT_RULES` unset means no engine
exists at all — the exporter path stays bit-identical (one is-None check,
the tracing/fault-point zero-cost bar). docs/architecture.md
"Continuous detection plane" is the narrative.
"""

from netobserv_tpu.alerts.engine import AlertEngine, maybe_engine
from netobserv_tpu.alerts.rules import (
    SIGNAL_FIELDS, AlertRule, default_rules, parse_rules,
)
from netobserv_tpu.alerts.sinks import (
    AlertSink, LogSink, MetricsSink, WebhookSink, build_sinks,
)

__all__ = [
    "AlertEngine", "maybe_engine", "SIGNAL_FIELDS", "AlertRule",
    "default_rules", "parse_rules", "AlertSink", "LogSink", "MetricsSink",
    "WebhookSink", "build_sinks",
]
