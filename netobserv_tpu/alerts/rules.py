"""Declarative alert rules over published query snapshots (jax-free).

A rule names a snapshot field, a threshold, a hysteresis schedule
(``raise_evals`` consecutive firing evaluations to RAISE, ``clear_evals``
quiet ones to CLEAR) and a severity. Rules evaluate ONLY the host-side
snapshot dict the exporter (or the federation aggregator) publishes —
never a device array, never an exporter lock.

One-truth notes (drift is the failure mode this module exists to prevent):

- ``SIGNAL_FIELDS`` is THE signal-name -> report-key map. The scenario
  zoo's ``SIGNALS`` tuple, the query core's ``/query/victims`` payload and
  the default alert rules all derive from it — a new signal plane lands
  here once and every surface follows.
- The per-signal default rules carry NO numeric thresholds of their own:
  they fire on the report's suspect-bucket lists, which
  ``report_to_json`` already rendered under the exporter's configured
  thresholds (``SKETCH_SYNFLOOD_MIN`` et al — the same values
  ``scenarios/runner.THRESHOLDS`` wires into the zoo's exporter). Zoo
  grading and live alerting therefore read one threshold set by
  construction; there is no second copy to drift.
- Victim naming rides the report's ``probable_victims`` entries, which
  the renderer computed through ``query/core.victim_bucket_names``
  (`ops/hashing.DST_BUCKET_SEED`, the ONE implementation) — rules never
  re-hash an address.
"""

from __future__ import annotations

from dataclasses import dataclass

#: signal name -> rendered-report suspect-list key — the ONE map
#: (scenarios/zoo.SIGNALS and query/core.victims_payload derive from it)
SIGNAL_FIELDS = {
    "ddos": "DdosSuspectBuckets",
    "syn_flood": "SynFloodSuspectBuckets",
    "port_scan": "PortScanSuspectBuckets",
    "drop_storm": "DropAnomalyBuckets",
    "asym_conv": "AsymmetricConversationBuckets",
}

#: default severity per signal (a drop storm or flood is actionable now;
#: a scan or conversation asymmetry is investigate-next)
_SEVERITIES = {
    "ddos": "critical",
    "syn_flood": "critical",
    "drop_storm": "critical",
    "port_scan": "warning",
    "asym_conv": "warning",
}

#: per-bucket value field surfaced as the alert's ``value`` (best-effort;
#: buckets lacking the key report 0.0)
_VALUE_KEYS = {
    "ddos": "z",
    "syn_flood": "syn",
    "port_scan": "distinct_dst_port_pairs",
    "drop_storm": "z",
    "asym_conv": "bytes",
}


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule. ``kind``:

    - ``buckets`` — fire one instance per suspect bucket in
      ``report[field]`` (fingerprint = (rule, bucket id); victims ride the
      bucket's ``probable_victims``). ``threshold`` is the minimum bucket
      count for the rule to fire at all (default 1 — the render thresholds
      already gated each bucket).
    - ``scalar``  — fire one instance (fingerprint bucket None) when
      ``float(report[field]) >= threshold``.
    - ``topk_share`` — fire when the top heavy hitter's ``EstBytes`` share
      of the window's ``Bytes`` reaches ``threshold`` (a single flow
      dominating the window).
    - ``flow_keys`` — fire one instance per PER-KEY churn entry in
      ``report[field]`` (``FlowAscents`` / ``NewHeavyKeys``, rendered by
      the persistent-slot heavy-hitter plane — no host sort exists
      anywhere on this path: the slot table ships ready, the renderer
      diffs K rows). Fingerprint = (rule, the entry's ``Key`` 5-tuple
      string); victims = the flow's endpoints. For ``flow_ascent`` a
      non-zero ``threshold`` RE-FILTERS the rendered entries by their
      window-over-window ``Ratio`` (a per-rule factor on top of the
      renderer's ``SKETCH_CHURN_ASCENT`` gate — it can only tighten).
    """

    name: str
    field: str
    kind: str = "buckets"
    severity: str = "warning"
    threshold: float = 1.0
    value_key: str = ""
    raise_evals: int = 2
    clear_evals: int = 2

    def firing(self, report: dict) -> list[dict]:
        """Firing instances for this evaluation: a list of
        ``{"bucket": id-or-None, "value": float, "victims": [...]}``."""
        if self.kind == "scalar":
            value = float(report.get(self.field) or 0.0)
            if value >= self.threshold:
                return [{"bucket": None, "value": value, "victims": []}]
            return []
        if self.kind == "topk_share":
            heavy = report.get("HeavyHitters") or []
            total = float(report.get("Bytes") or 0.0)
            if not heavy or total <= 0.0:
                return []
            top = heavy[0]
            share = float(top.get("EstBytes", 0.0)) / total
            if share >= self.threshold:
                return [{"bucket": None, "value": round(share, 4),
                         "victims": [top.get("DstAddr", "")]}]
            return []
        if self.kind == "flow_keys":
            out = []
            for e in (report.get(self.field) or []):
                if self.threshold and \
                        float(e.get("Ratio", 0.0)) < self.threshold:
                    continue
                out.append({
                    "bucket": e.get("Key", ""),
                    "value": float(e.get(self.value_key, 0.0) or 0.0)
                    if self.value_key else 0.0,
                    # the flow's endpoints — rendered by report_to_json
                    # from the slot's exact key words, never re-hashed
                    "victims": [e.get("SrcAddr", ""),
                                e.get("DstAddr", "")],
                })
            return out
        buckets = report.get(self.field) or []
        if len(buckets) < self.threshold:
            return []
        return [{"bucket": int(b.get("bucket", 0)),
                 "value": float(b.get(self.value_key, 0.0) or 0.0)
                 if self.value_key else 0.0,
                 "victims": list(b.get("probable_victims", ()))}
                for b in buckets]


def signal_rule(signal: str, raise_evals: int = 2,
                clear_evals: int = 2) -> AlertRule:
    """The default rule for one anomaly signal: fire per suspect bucket of
    the rendered report list (threshold truth lives in the renderer)."""
    return AlertRule(
        name=signal, field=SIGNAL_FIELDS[signal], kind="buckets",
        severity=_SEVERITIES[signal], value_key=_VALUE_KEYS[signal],
        raise_evals=raise_evals, clear_evals=clear_evals)


def cardinality_rule(threshold: float, raise_evals: int = 2,
                     clear_evals: int = 2) -> AlertRule:
    """HLL cardinality surge: distinct-source estimate at/above
    ``threshold`` (an amplification fleet or sweep appearing)."""
    return AlertRule(
        name="cardinality_surge", field="DistinctSrcEstimate",
        kind="scalar", severity="warning", threshold=threshold,
        raise_evals=raise_evals, clear_evals=clear_evals)


def topk_share_rule(share: float, raise_evals: int = 2,
                    clear_evals: int = 2) -> AlertRule:
    """Top-K dominance: one heavy hitter carrying >= ``share`` of the
    window's bytes."""
    return AlertRule(
        name="topk_share", field="HeavyHitters", kind="topk_share",
        severity="warning", threshold=share,
        raise_evals=raise_evals, clear_evals=clear_evals)


def flow_ascent_rule(factor: float = 0.0, raise_evals: int = 1,
                     clear_evals: int = 2) -> AlertRule:
    """Per-flow ascent: a tracked key whose window count grew past the
    renderer's SKETCH_CHURN_ASCENT factor of its previous window (a mouse
    ramping into an elephant). `factor` > 0 additionally re-filters by the
    entry's rendered Ratio — a per-rule tightening knob
    (``flow_ascent:<factor>``); 0 fires on the rendered list as-is (the
    one-threshold-truth default).

    raise_evals defaults to 1, NOT the bucket rules' 2: a churn entry
    already encodes a two-window crossing (count vs the closed previous
    window), and in reset mode it exists in exactly ONE roll snapshot —
    on a roll-only deployment (SKETCH_QUERY_REFRESH unset, the default) a
    2-eval hysteresis could never accumulate two consecutive firing
    evaluations and the rule would be structurally dead."""
    return AlertRule(
        name="flow_ascent", field="FlowAscents", kind="flow_keys",
        severity="warning", threshold=factor, value_key="Ratio",
        raise_evals=raise_evals, clear_evals=clear_evals)


def new_heavy_key_rule(raise_evals: int = 1,
                       clear_evals: int = 2) -> AlertRule:
    """A key entering the heavy table for the first time this window with
    real mass (>= SKETCH_CHURN_MIN_BYTES) — a brand-new elephant.
    raise_evals defaults to 1 for the same one-roll-snapshot reason as
    `flow_ascent_rule` (first_seen matches exactly one window)."""
    return AlertRule(
        name="new_heavy_key", field="NewHeavyKeys", kind="flow_keys",
        severity="warning", value_key="EstBytes", threshold=0.0,
        raise_evals=raise_evals, clear_evals=clear_evals)


def default_rules(raise_evals: int = 2, clear_evals: int = 2) -> list:
    """One rule per anomaly signal, plus the two per-flow churn rules
    (the ALERT_RULES=default set). The churn rules are structurally quiet
    until the table has cross-window history (first window: prev_counts
    are zero and NewHeavyKeys render only for window > 0), so enabling
    them by default adds no cold-start noise."""
    # the churn rules keep their own raise_evals=1 (one-roll-snapshot
    # lifetime — see flow_ascent_rule); only the clear schedule follows
    # the global setting
    return [signal_rule(s, raise_evals, clear_evals)
            for s in SIGNAL_FIELDS] + [
        flow_ascent_rule(0.0, clear_evals=clear_evals),
        new_heavy_key_rule(clear_evals=clear_evals),
    ]


def parse_rules(spec: str, raise_evals: int = 2,
                clear_evals: int = 2) -> list:
    """Parse an ALERT_RULES spec into a rule list.

    Grammar: comma-separated tokens; ``default`` expands to the five
    signal rules plus the two per-flow churn rules; a bare signal name
    enables that one; parameterized rules spell
    ``cardinality_surge:<count>`` / ``topk_share:<fraction>`` /
    ``flow_ascent[:<factor>]``; ``new_heavy_key`` takes no parameter.
    Duplicate names keep the LAST occurrence (an override idiom)."""
    def _num(arg: str, tok: str) -> float:
        try:
            return float(arg)
        except ValueError:
            raise ValueError(
                f"ALERT_RULES: {tok!r} has a non-numeric parameter "
                f"(want e.g. cardinality_surge:50000 or topk_share:0.5)"
            ) from None

    out: dict[str, AlertRule] = {}
    for tok in filter(None, (t.strip() for t in spec.split(","))):
        name, _, arg = tok.partition(":")
        if name == "default" or name in SIGNAL_FIELDS:
            if arg:
                # fail-fast symmetry with the parameterized rules: a
                # stray ":<arg>" here is a user expecting a per-rule
                # threshold that does not exist — silently dropping it
                # would run the stock rule against their intent
                raise ValueError(
                    f"ALERT_RULES: {name!r} takes no parameter "
                    f"(got {tok!r}; signal thresholds live in the "
                    "SKETCH_* render settings)")
            if name == "default":
                for r in default_rules(raise_evals, clear_evals):
                    out[r.name] = r
            else:
                out[name] = signal_rule(name, raise_evals, clear_evals)
        elif name == "cardinality_surge":
            if not arg:
                raise ValueError(
                    "ALERT_RULES: cardinality_surge needs a threshold "
                    "(e.g. cardinality_surge:50000)")
            out[name] = cardinality_rule(_num(arg, tok), raise_evals,
                                         clear_evals)
        elif name == "topk_share":
            if not arg:
                raise ValueError("ALERT_RULES: topk_share needs a share "
                                 "(e.g. topk_share:0.5)")
            out[name] = topk_share_rule(_num(arg, tok), raise_evals,
                                        clear_evals)
        elif name == "flow_ascent":
            # optional factor: bare = the renderer's SKETCH_CHURN_ASCENT
            # gate is the one truth; a factor only tightens on top of it
            factor = _num(arg, tok) if arg else 0.0
            if arg and factor <= 1.0:
                raise ValueError(
                    f"ALERT_RULES: {tok!r} — the flow_ascent factor is a "
                    "window-over-window growth ratio and must be > 1")
            out[name] = flow_ascent_rule(factor, clear_evals=clear_evals)
        elif name == "new_heavy_key":
            if arg:
                raise ValueError(
                    f"ALERT_RULES: new_heavy_key takes no parameter "
                    f"(got {tok!r}; the mass floor lives in "
                    "SKETCH_CHURN_MIN_BYTES)")
            out[name] = new_heavy_key_rule(clear_evals=clear_evals)
        else:
            raise ValueError(
                f"ALERT_RULES: unknown rule {name!r} (one of "
                f"{', '.join(SIGNAL_FIELDS)}, cardinality_surge:<n>, "
                f"topk_share:<f>, flow_ascent[:<factor>], new_heavy_key, "
                "default)")
    if not out:
        raise ValueError("ALERT_RULES is set but names no rules")
    return list(out.values())
