"""Shared utilities (reference analog: `pkg/utils/`)."""
