"""JAX platform selection helpers.

This image's TPU plugin registers itself from sitecustomize and force-sets
`jax_platforms="axon,cpu"`, clobbering a `JAX_PLATFORMS=cpu` env request. Every
entry point that must honor an explicit CPU request (tests, dryruns, offline
bench) calls `maybe_force_cpu()` before first backend use.
"""

from __future__ import annotations

import os


def maybe_force_cpu() -> bool:
    """If the environment asks for CPU, re-apply it over the plugin's override.

    Returns True if CPU was requested. Must run before any JAX backend
    initializes (jax.devices(), first jit, ...).
    """
    if "cpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
        return False
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return True
