"""Atomic small-file writes for the checkpoint/archive sidecar family.

Every JSON sidecar the persistence planes keep next to their tensor data —
the checkpoint format stamp (``FORMAT.json``), the per-step ledger
sidecars (``META-<step>.json``), the publish-commit marker
(``PUBLISHED.json``) and the archive manifest (``MANIFEST.json``) — is a
tiny file whose TORN state is worse than its absent state: a crash
mid-write used to be able to leave half a JSON object that poisons the
next restore (the readers treat unparseable as absent, but a torn file
that still parses — e.g. truncated inside a string that happens to close —
would silently lie).

The discipline here is the classic write-temp + flush + fsync + rename:
after `os.replace` the path holds either the complete old bytes or the
complete new bytes, never a mix, even across power loss (the fsync orders
the data before the rename on journaling filesystems; the best-effort
directory fsync orders the rename itself). One helper, used by every
sidecar writer — new sidecar kinds must not re-grow unfsynced copies.
"""

from __future__ import annotations

import json
import os
from typing import Any


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a DIRECTORY (persists a rename). Platforms or
    filesystems that refuse directory fds just skip — the data-file fsync
    already happened, so the worst case is the pre-rename name surviving a
    power loss, which every sidecar reader treats as absent."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_bytes_atomic(path: str, data: bytes) -> None:
    """Atomically replace `path` with `data` (temp + fsync + rename)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")


def write_json_atomic(path: str, obj: Any) -> None:
    """Atomically replace `path` with `obj` serialized as compact JSON."""
    write_bytes_atomic(
        path, json.dumps(obj, separators=(",", ":")).encode())
