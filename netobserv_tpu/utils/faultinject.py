"""Env-gated fault-injection seam for the chaos harness.

Named fault points sit at STAGE BOUNDARIES (one per loop iteration / batch,
never per record): a stage loop calls ``fire("map_tracer.evict")`` and, when
that point is armed, the call raises, hangs, delays, or corrupts a payload.
Disarmed (the default, and always when ``FAULT_POINTS`` is unset) a fire is
a single module-bool check and an immediate return — zero allocations, no
locks, nothing on the bench host path.

Arming:

- env: ``FAULT_POINTS="map_tracer.evict:crash;exporter.loop:delay:0.05"``
  parsed once at import (and re-parsed by :func:`configure`). Spec grammar
  per point: ``name:action[:arg[:times]]``, points separated by ``;``.
- tests: :func:`arm`/:func:`clear` (what tests/test_supervision.py uses).

Actions:

- ``crash``        raise :class:`FaultInjected` at the point.
- ``hang``         block until the point is cleared (or ``arg`` seconds
                   elapse, if given), then raise SystemExit — a supervisor
                   that already replaced the hung thread must not get a
                   zombie double-processing its queue when the chaos test
                   releases it (SystemExit dies silently in a thread).
- ``delay``        sleep ``arg`` seconds, then continue normally.
- ``corrupt``      return a mangled copy of the payload (bytes are
                   truncated+bit-flipped; other payloads pass through) so
                   decode-layer robustness can be exercised end to end.

Every trigger is counted in :data:`hits` so a chaos test can assert the
point actually fired.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Optional

log = logging.getLogger("netobserv_tpu.faultinject")

_ACTIONS = ("crash", "hang", "delay", "corrupt")


class FaultInjected(RuntimeError):
    """Raised by an armed crash/hang fault point."""


class _Fault:
    __slots__ = ("name", "action", "arg", "times", "released")

    def __init__(self, name: str, action: str, arg: float = 0.0,
                 times: Optional[int] = None):
        if action not in _ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(one of {_ACTIONS})")
        self.name = name
        self.action = action
        self.arg = arg
        self.times = times  # None = unlimited
        self.released = threading.Event()  # hang release


# the hot-path gate: `_armed` is False whenever `_faults` is empty, so a
# disarmed fire() is one attribute load + one branch
_faults: dict[str, _Fault] = {}
_armed = False
_lock = threading.Lock()
hits: dict[str, int] = {}
#: hang faults currently blocking a thread; clear() releases them even
#: after a bounded-`times` hang was already popped from `_faults`
_hanging: list[_Fault] = []


def arm(name: str, action: str, arg: float = 0.0,
        times: Optional[int] = None) -> None:
    """Arm fault point `name`. `times` bounds the trigger count (e.g.
    ``times=1`` crashes a stage once and lets its restart run clean)."""
    global _armed
    with _lock:
        _faults[name] = _Fault(name, action, arg, times)
        _armed = True


def clear(name: Optional[str] = None) -> None:
    """Disarm one point (or all). Hung fire() calls are released."""
    global _armed
    with _lock:
        targets = [name] if name is not None else list(_faults)
        for n in targets:
            f = _faults.pop(n, None)
            if f is not None:
                f.released.set()
        # also release in-flight hangs (a bounded-`times` hang was already
        # popped from _faults at fire time but is still blocking a thread)
        for f in list(_hanging):
            if name is None or f.name == name:
                f.released.set()
                _hanging.remove(f)
        _armed = bool(_faults)


def configure(spec: Optional[str] = None) -> None:
    """(Re)parse a FAULT_POINTS spec string; None reads the env var."""
    clear()
    spec = os.environ.get("FAULT_POINTS", "") if spec is None else spec
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        bits = part.split(":")
        if len(bits) < 2:
            raise ValueError(f"bad FAULT_POINTS entry {part!r} "
                             "(want name:action[:arg[:times]])")
        name, action = bits[0], bits[1]
        arg = float(bits[2]) if len(bits) > 2 and bits[2] else 0.0
        times = int(bits[3]) if len(bits) > 3 else None
        arm(name, action, arg, times)
    if _faults:
        log.warning("fault injection ARMED: %s", ", ".join(sorted(_faults)))


def armed(name: str) -> bool:
    return _armed and name in _faults


def fire(name: str, payload: Any = None) -> Any:
    """The stage-boundary hook. Returns `payload` (possibly corrupted)."""
    if not _armed:  # the always-on cost: one load, one branch
        return payload
    with _lock:
        fault = _faults.get(name)
        if fault is None:
            return payload
        hits[name] = hits.get(name, 0) + 1
        if fault.action == "hang":
            _hanging.append(fault)
        if fault.times is not None:
            fault.times -= 1
            if fault.times <= 0:
                # exhausted: disarm, but DON'T release — a bounded hang
                # stays hung until clear() (that is its whole point)
                _faults.pop(name, None)
                _refresh_armed_locked()
    return _trigger(name, fault, payload)


def _refresh_armed_locked() -> None:
    global _armed
    _armed = bool(_faults)


def _trigger(name: str, fault: _Fault, payload: Any) -> Any:
    if fault.action == "crash":
        raise FaultInjected(f"injected crash at {name}")
    if fault.action == "hang":
        # block until clear() (or the optional bound); then die SILENTLY —
        # by release time the supervisor has usually replaced this thread,
        # and a zombie resuming its loop would double-process the queue
        # (threading swallows SystemExit without a traceback)
        fault.released.wait(timeout=fault.arg or None)
        raise SystemExit(f"injected hang at {name} released")
    if fault.action == "delay":
        time.sleep(fault.arg)
        return payload
    # corrupt
    if isinstance(payload, (bytes, bytearray)) and len(payload):
        mangled = bytearray(payload[:max(1, len(payload) // 2)])
        mangled[0] ^= 0xFF
        return bytes(mangled)
    return payload


# arm from the environment at import; unset -> nothing armed, fire() stays
# on the one-branch path
if os.environ.get("FAULT_POINTS"):
    configure()
