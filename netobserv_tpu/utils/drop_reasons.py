"""Kernel drop-reason names, read from the LIVE kernel when possible.

The reference decodes drop causes through a static string table generated
from one kernel version's enum (`pkg/decode/decode_protobuf.go` tables,
mirrored for FLP-name parity in `exporter/flp_tables.py`). But the kernel
enum is NOT stable across versions — e.g. on 6.18, reason 6 is
SOCKET_RCVBUFF while the reference's table era had SOCKET_FILTER there
(SOCKET_CLOSE/UNIX_* were inserted above it) — so a static table silently
mislabels drops on newer kernels, a reference bug this framework inherits
only where wire parity demands it (FLP field values).

For this framework's OWN analytics output (sketch report DropCauseNames)
correctness wins: the authoritative mapping is the running kernel's
`__print_symbolic` table in the kfree_skb tracepoint format — the same
tracefs file the drops program already parses for context offsets. The
reference-parity table remains the fallback where tracefs is unavailable
(no root / locked down).
"""

from __future__ import annotations

import re
from functools import lru_cache

_FORMAT = "/sys/kernel/tracing/events/skb/kfree_skb/format"
_SYM = re.compile(r"\{\s*(\d+)\s*,\s*\"([A-Za-z0-9_]+)\"\s*\}")


@lru_cache(maxsize=1)
def live_drop_reasons() -> dict[int, str]:
    """reason id -> SKB_DROP_REASON_* name from the running kernel's
    tracepoint print format; {} when tracefs is unreadable."""
    try:
        with open(_FORMAT) as fh:
            text = fh.read()
    except OSError:
        return {}
    return {int(num): f"SKB_DROP_REASON_{name}"
            for num, name in _SYM.findall(text)}


def drop_reason_name(cause: int) -> str:
    """Best-available name: live kernel first, reference-parity table
    second, the numeric id last."""
    live = live_drop_reasons()
    if live:
        return live.get(cause, str(cause))
    from netobserv_tpu.exporter.flp_tables import DROP_CAUSES
    return DROP_CAUSES.get(cause, str(cause))
