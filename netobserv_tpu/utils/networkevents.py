"""OVN network-event cookie decoding.

Reference analog: `pkg/utils/networkevents/network_events.go` — the psample
user cookie carries an OVN observability sample (version, action, and the
sampled object's attributes); decoded here into the map shape the FLP
ecosystem expects. Layout (OVN observability samples v1):

    byte 0: version (1)
    byte 1: action (0 allow, 1 drop, 2 pass, 3 redirect)
    byte 2: actor type (0 acl, 1 nat, ...)
    byte 3: direction (0 ingress / 1 egress) + flags
    bytes 4..7: object id (little-endian u32)
"""

from __future__ import annotations

ACTIONS = {0: "allow", 1: "drop", 2: "pass", 3: "redirect"}
ACTOR_TYPES = {0: "acl", 1: "nat", 2: "lb"}
DIRECTIONS = {0: "ingress", 1: "egress"}


def decode_cookie(cookie: bytes) -> dict:
    """Decode one network-event cookie into a string map; unknown layouts are
    surfaced raw so nothing is silently dropped."""
    if len(cookie) < 8 or cookie[0] != 1:
        return {"raw": cookie.hex()}
    action = cookie[1]
    actor = cookie[2]
    direction = cookie[3] & 0x01
    obj_id = int.from_bytes(cookie[4:8], "little")
    return {
        "Feature": "acl",  # FLP consumers match on this key
        "Action": ACTIONS.get(action, str(action)),
        "Type": ACTOR_TYPES.get(actor, str(actor)),
        "Direction": DIRECTIONS.get(direction, str(direction)),
        "Name": str(obj_id),
    }


def is_drop_event(cookie: bytes) -> bool:
    return len(cookie) >= 8 and cookie[0] == 1 and cookie[1] == 1
