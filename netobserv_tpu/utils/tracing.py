"""Flight recorder: sampled end-to-end batch tracing with stage spans.

A *trace* follows one unit of work through the pipeline — a "batch" trace is
born at map eviction and rides the EvictedFlows object through the queues to
the exporter fold; a "window" trace is born at window roll and rides the
queued device report through render and sink delivery. Each pipeline stage
wraps its work in a *span* (``with trace.stage("resident_pack"): ...``);
completed traces land in a fixed-size ring buffer (the flight recorder,
``/debug/traces`` on the debug server) and every span duration feeds the
``stage_seconds{stage=...}`` histogram family when a Metrics facade is bound
(:func:`set_metrics`, done by ``FlowsAgent.__init__``).

The inter-span *gaps* are as load-bearing as the spans: the time between the
``evict`` span's end and the ``fold`` span's start is exactly the
evicted/export queue wait — the first thing to grow when the exporter falls
behind.

Sampling and the zero-cost contract:

- ``TRACE_SAMPLE`` (env, float in [0, 1], default 0/unset = disabled) is the
  per-trace sampling rate, applied deterministically PER TRACE KIND (every
  round(1/rate)-th :func:`start_trace` call of that kind samples, so
  ``TRACE_SAMPLE=1`` traces everything, tests are reproducible, and the
  pipeline's periodic call pattern cannot alias one kind out of the
  sample).
- Disabled (the default), :func:`start_trace` is one module-bool check
  returning the shared :data:`NULL_TRACE`, whose ``stage()`` returns the
  shared :data:`NULL_SPAN` context manager — no locks, no timestamps, no
  allocations anywhere on the hot path (the same discipline as
  ``utils.faultinject``; pinned by tests/test_tracing.py and the
  ``bench.py --host-only`` A/B in docs/observability.md).
- Unsampled calls while enabled cost one int increment + one modulo.

``TRACE_RING`` (env, default 64) bounds how many completed traces the
recorder keeps; snapshots are newest-first.

Cross-process propagation (the federation seam): a sampled trace exposes a
serializable :class:`TraceContext` via :func:`context_of` (fleet-unique hex
trace id + origin span + sample bit). The agent stamps it into the delta
frame; the aggregator calls :func:`continue_trace` to keep recording child
spans under the SAME trace id, so ``/debug/traces?trace=<id>`` on either
process shows one window's journey end to end. Both helpers keep the
zero-cost bar: ``context_of(NULL_TRACE)`` is one attribute check returning
``None`` (nothing serialized, the frame stays byte-identical), and
``continue_trace`` with tracing disabled — or a ``None``/unsampled context —
returns the shared :data:`NULL_TRACE`. The sampling decision is made ONCE at
the origin: a receiver with tracing enabled always honors a propagated
sampled context (its own period applies only to traces it originates).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import NamedTuple, Optional

__all__ = [
    "NULL_SPAN", "NULL_TRACE", "Trace", "FlightRecorder", "TraceContext",
    "start_trace", "configure", "set_metrics", "snapshot", "enabled",
    "set_active", "clear_active", "active_trace",
    "context_of", "continue_trace", "group",
]


class _NullSpan:
    """Shared no-op context manager handed out whenever tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _NullTrace:
    """Shared do-nothing trace: every un-sampled batch carries this."""

    __slots__ = ()
    sampled = False

    def stage(self, name: str):
        return NULL_SPAN

    def finish(self) -> None:
        pass


NULL_TRACE = _NullTrace()


class TraceContext(NamedTuple):
    """Serializable identity of a sampled trace, for crossing a process
    boundary (the delta frame's optional ``trace_ctx`` field). ``trace_id``
    is the fleet-unique hex id (process salt + local counter), ``origin``
    names the span/process that exported it, ``sampled`` is the origin's
    sampling verdict — carried explicitly so an unsampled context decoded
    off a hand-built frame still resolves to NULL_TRACE."""

    trace_id: str
    origin: str = ""
    sampled: bool = True


class _Span:
    __slots__ = ("stage", "t0", "t1", "thread")

    def __init__(self, stage: str, t0: float, t1: float, thread: str):
        self.stage = stage
        self.t0 = t0
        self.t1 = t1
        self.thread = thread


class _SpanCtx:
    """Context manager recording one stage span onto its trace (records on
    exit even when the stage raised — a failed stage's duration is evidence,
    not noise)."""

    __slots__ = ("_trace", "_stage", "_t0")

    def __init__(self, trace: "Trace", stage: str):
        self._trace = trace
        self._stage = stage
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._trace._add(self._stage, self._t0, time.perf_counter())
        return False


class Trace:
    """One sampled unit of work. Spans may be appended from several threads
    (evict on the map-tracer thread, fold on the exporter thread, publish on
    the window timer), so appends take a per-trace lock — sampled traces are
    rare by construction, the lock never sits on the un-sampled path."""

    __slots__ = ("kind", "id", "trace_id", "origin", "unix_t0", "t0",
                 "spans", "_lock", "_done")
    sampled = True

    def __init__(self, kind: str, local_id: int,
                 trace_id: Optional[str] = None, origin: str = ""):
        self.kind = kind
        self.id = local_id
        # fleet-unique hex id: process salt + local counter for traces born
        # here; a continued trace ADOPTS the origin's id verbatim so the
        # recorder entries on both sides correlate by one string
        self.trace_id = (trace_id if trace_id is not None
                         else f"{_salt}{local_id:08x}")
        self.origin = origin
        self.unix_t0 = time.time()
        self.t0 = time.perf_counter()
        self.spans: list[_Span] = []
        self._lock = threading.Lock()
        self._done = False

    def stage(self, name: str) -> _SpanCtx:
        return _SpanCtx(self, name)

    def _add(self, stage: str, t0: float, t1: float) -> None:
        with self._lock:
            if not self._done:
                self.spans.append(_Span(
                    stage, t0, t1, threading.current_thread().name))

    def finish(self) -> None:
        """Seal the trace and hand it to the flight recorder (idempotent —
        a batch trace that merged into an already-traced fold is finished
        by whoever holds it last)."""
        with self._lock:
            if self._done:
                return
            self._done = True
            spans = list(self.spans)
        m = _metrics
        if m is not None:
            for s in spans:
                m.observe_stage(s.stage, s.t1 - s.t0)
        if spans:
            _recorder.add(self)

    def render(self) -> dict:
        """JSON-ready view: spans sorted by start, durations and the
        queue-wait gap to the previous stage in milliseconds."""
        with self._lock:
            spans = sorted(self.spans, key=lambda s: s.t0)
        stages = []
        prev_t1: Optional[float] = None
        for s in spans:
            stages.append({
                "stage": s.stage,
                "thread": s.thread,
                "offset_ms": round((s.t0 - self.t0) * 1e3, 3),
                "dur_ms": round((s.t1 - s.t0) * 1e3, 3),
                # inter-stage gap = queue wait (negative means the spans
                # overlapped across threads; reported raw, not clipped)
                "gap_ms": (round((s.t0 - prev_t1) * 1e3, 3)
                           if prev_t1 is not None else 0.0),
            })
            prev_t1 = s.t1
        total = (spans[-1].t1 - spans[0].t0) if spans else 0.0
        out = {
            "id": self.id,
            "trace_id": self.trace_id,
            "kind": self.kind,
            "start_unix_ms": int(self.unix_t0 * 1e3),
            "total_ms": round(total * 1e3, 3),
            "stages": stages,
        }
        if self.origin:
            out["origin"] = self.origin
        return out


class _GroupSpan:
    """Context manager fanning one stage span out to several traces."""

    __slots__ = ("_ctxs",)

    def __init__(self, ctxs: list):
        self._ctxs = ctxs

    def __enter__(self):
        for c in self._ctxs:
            c.__enter__()
        return self

    def __exit__(self, *exc):
        for c in self._ctxs:
            c.__exit__(*exc)
        return False


class TraceGroup:
    """Several sampled traces sharing the same spans — the aggregator's
    window close, where one roll/publish serves every agent trace continued
    into that window plus the aggregator's own window trace. stage() fans
    out to each member; finish() seals them all (Trace.finish is
    idempotent, so a member finished elsewhere is harmless)."""

    __slots__ = ("traces",)
    sampled = True

    def __init__(self, traces: list):
        self.traces = traces

    def stage(self, name: str) -> _GroupSpan:
        return _GroupSpan([t.stage(name) for t in self.traces])

    def finish(self) -> None:
        for t in self.traces:
            t.finish()


def group(*traces):
    """Combine traces for shared spans: drops unsampled members, collapses
    to the single member or the shared NULL_TRACE when possible (so the
    common nothing-sampled case allocates nothing)."""
    live = [t for t in traces if t.sampled]
    if not live:
        return NULL_TRACE
    if len(live) == 1:
        return live[0]
    return TraceGroup(live)


class FlightRecorder:
    """Fixed-size ring of completed traces."""

    def __init__(self, capacity: int = 64):
        self._dq: deque = deque(maxlen=max(1, capacity))
        self._lock = threading.Lock()

    def add(self, trace: Trace) -> None:
        with self._lock:
            self._dq.append(trace)

    def snapshot(self, limit: Optional[int] = None,
                 trace_id: Optional[str] = None) -> list[dict]:
        """Newest-first JSON-ready dump (the /debug/traces body).
        ``trace_id`` keeps only traces with that exact hex id (the
        cross-process correlation lookup); ``limit`` caps the result
        AFTER filtering."""
        with self._lock:
            traces = list(self._dq)
        out = [t.render() for t in reversed(traces)]
        if trace_id is not None:
            out = [t for t in out if t.get("trace_id") == trace_id]
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._dq)


# --- module state ----------------------------------------------------------

_enabled = False
# sample every _period-th start_trace() call PER KIND: a single shared
# counter would alias with the pipeline's periodic call pattern (each
# eviction issues one "batch" and one "fold" call, so at rate 0.5 one kind
# would land on the sampled residue every time and the other never; the
# once-per-window "window" call would pin to one residue at low rates).
# Kept >= 1 at ALL times so a concurrent configure(0) can never expose a
# modulo-by-zero to a hot-path thread that already saw _enabled=True.
_period = 1
# itertools.count: atomic under the GIL — start_trace is called from the
# map-tracer, exporter, and timer threads concurrently, and a plain `+= 1`
# would lose increments (skewing the deterministic period) and hand out
# duplicate trace ids
_counters: dict = {}
_counters_lock = threading.Lock()
_next_id = itertools.count(1)
# process-scoped salt prefixing every locally-born trace id: two agents (or
# an agent and the aggregator) must never mint the same hex id, or the
# cross-process correlation at /debug/traces?trace= aliases unrelated work
_salt = f"{os.getpid() & 0xffffffff:08x}{int.from_bytes(os.urandom(4), 'big'):08x}"
_metrics = None  # Metrics facade (set_metrics); observe_stage sink
_recorder = FlightRecorder(int(os.environ.get("TRACE_RING", "64") or 64))

recorder = _recorder  # public alias (server/debug.py, tests)


def configure(sample: Optional[float] = None,
              capacity: Optional[int] = None) -> None:
    """(Re)configure sampling; ``None`` re-reads the TRACE_SAMPLE env var.
    Rates in (0, 1] sample every round(1/rate)-th trace; 0 disables."""
    global _enabled, _period, _counters, _recorder, recorder
    if sample is None:
        sample = float(os.environ.get("TRACE_SAMPLE", "0") or 0)
    if not 0.0 <= sample <= 1.0:
        raise ValueError(f"TRACE_SAMPLE={sample!r} must be in [0, 1]")
    if capacity is not None:
        _recorder = recorder = FlightRecorder(capacity)
    _counters = {}
    if sample <= 0.0:
        _enabled = False  # _period stays >= 1 (hot-path race safety above)
    else:
        _period = max(1, round(1.0 / sample))
        _enabled = True


def enabled() -> bool:
    return _enabled


def start_trace(kind: str = "batch"):
    """The hot-path entry: returns a live :class:`Trace` for sampled calls,
    the shared :data:`NULL_TRACE` otherwise. Disabled = one bool check.
    Sampling is deterministic PER KIND (see _period above)."""
    if not _enabled:
        return NULL_TRACE
    c = _counters.get(kind)
    if c is None:
        with _counters_lock:
            c = _counters.setdefault(kind, itertools.count(1))
    if next(c) % _period:
        return NULL_TRACE
    return Trace(kind, next(_next_id))


def context_of(trace, origin: str = "") -> Optional[TraceContext]:
    """Serializable context of a sampled trace, or ``None``. The zero-cost
    gate for the wire: NULL_TRACE (tracing off or this window unsampled)
    answers None in one attribute check, and the caller stamps nothing —
    the frame stays byte-identical to the context-less encoding."""
    if not trace.sampled:
        return None
    return TraceContext(trace.trace_id, origin or trace.kind, True)


def continue_trace(ctx, kind: str = "batch"):
    """Continue a propagated trace in THIS process: a live :class:`Trace`
    adopting the context's trace id, or the shared NULL_TRACE when tracing
    is disabled here or the context is absent/unsampled. The origin's
    sampling verdict is honored as-is — the local period applies only to
    locally-born traces."""
    if not _enabled or ctx is None or not ctx.sampled or not ctx.trace_id:
        return NULL_TRACE
    return Trace(kind, next(_next_id), trace_id=ctx.trace_id,
                 origin=ctx.origin)


# Per-thread active trace: lets a deep callee (the kernel drain inside
# BpfmanFetcher.lookup_and_delete) attach child spans to the trace born in
# map_tracer WITHOUT widening the FlowFetcher protocol. Only SAMPLED traces
# are ever bound (map_tracer gates on trace.sampled), so the disabled path
# pays nothing for the binding; the callee's active_trace() lookup is one
# thread-local getattr PER DRAIN, never per record.
_active = threading.local()


def set_active(trace) -> None:
    """Bind `trace` as the calling thread's active trace (sampled only)."""
    _active.trace = trace


def clear_active() -> None:
    _active.trace = None


def active_trace():
    """The calling thread's bound trace, or the shared NULL_TRACE."""
    t = getattr(_active, "trace", None)
    return NULL_TRACE if t is None else t


def set_metrics(metrics) -> None:
    """Bind the Metrics facade whose ``observe_stage`` receives every span
    of every finished trace (stage_seconds{stage=...})."""
    global _metrics
    _metrics = metrics


def snapshot(limit: Optional[int] = None,
             trace_id: Optional[str] = None) -> list[dict]:
    """Newest-first completed traces (the /debug/traces payload); see
    :meth:`FlightRecorder.snapshot` for the filter params."""
    return _recorder.snapshot(limit=limit, trace_id=trace_id)


# arm from the environment at import; unset -> disabled, start_trace stays
# on the one-branch path
if os.environ.get("TRACE_SAMPLE"):
    configure()
