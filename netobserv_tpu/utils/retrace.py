"""Jit retrace watchdog: the "ingest must never retrace" invariant, live.

The CLAUDE.md invariant — fixed batch shapes, padding, masks, no
data-dependent shapes under jit — is enforced by tests but was never
*watched* in production, where a retrace is a multi-second ingest stall and
an unbounded compile-cache leak. This module turns it into an alarm:

- every jitted entry point the pipeline constructs is wrapped with
  :func:`watch` (``exporter/tpu_sketch.py`` for the single-device fns,
  ``parallel/merge.py`` for the sharded ones);
- a process-wide ``jax.monitoring`` listener counts XLA *lowerings*
  (``/jax/core/compile/jaxpr_to_mlir_module_duration``) and attributes each
  to the watched entry point currently executing on that thread (jit traces
  and lowers synchronously in the calling thread; lowering fires on every
  retrace even when the persistent compilation cache serves the executable,
  which ``backend_compile`` events would miss);
- each entry point's first ``warmup_calls`` calls (default 1,
  ``RETRACE_WARMUP_CALLS``) may compile freely — that is the expected
  warmup window. A compile on any later call is a retrace: it increments
  ``sketch_retraces_total{fn=...}`` (when a Metrics facade is bound via
  :func:`set_metrics`) and logs the offending abstract shapes.

``RETRACE_WATCHDOG=0`` disables wrapping entirely (``watch`` returns the
function untouched). The wrapper itself costs two thread-local attribute
writes plus one monotonic-clock pair per call — per *batch*, never per
record.

Beyond the alarm, the wrapper IS the per-executable accounting registry
(``/debug/executables`` on agent and aggregator, stamped into bench
artifacts): per watched jit it tracks dispatch count, cumulative dispatch
wall seconds (fed to ``executable_dispatch_seconds_total{fn=...}`` when a
Metrics facade is bound), cumulative compile seconds (the lowering
listener's duration, warmup included), the last abstract-shape signature
seen at a compile, and a donated-bytes estimate (sum of array-arg nbytes at
the last compile — the HBM the executable's donation reuses per dispatch).
This is the attribution surface the proof-of-performance round reads: where
wall/compile/HBM went, per executable, not per lumped stage.

Wrapped functions delegate attribute access to the underlying jit function,
so AOT introspection (``fn.lower(...)``, ``fn._cache_size()``) keeps working
(tests/test_parallel.py lowers the sharded ingest to assert the
no-collectives invariant — through the wrapper).
"""

from __future__ import annotations

import logging
import os
import threading
import time
import weakref
from typing import Any, Callable, Optional

log = logging.getLogger("netobserv_tpu.retrace")

#: fires once per jaxpr->MLIR lowering, i.e. once per (re)trace of a jitted
#: callable, regardless of persistent-compilation-cache hits
_LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"

_enabled = os.environ.get("RETRACE_WATCHDOG", "1").strip().lower() not in (
    "0", "false", "no", "off")
_default_warmup = int(os.environ.get("RETRACE_WARMUP_CALLS", "1") or 1)
_metrics = None
_installed = False
_install_lock = threading.Lock()
_tls = threading.local()
#: every live Watched wrapper, for /debug/jax and tests. Weak references:
#: the registry must not pin dead exporters' jit functions (and their
#: compile caches) for process lifetime — a torn-down wrapper just drops
#: out of the accounting
_registry: list["weakref.ref[Watched]"] = []
#: process-lifetime alarm history — survives wrapper GC (the registry is
#: weak, the verdict is not)
_retraces_total = 0


def _describe(args: tuple, limit: int = 600) -> str:
    """Abstract shapes of a call's arguments (dtype[shape] per leaf)."""
    try:
        import jax

        desc = str(jax.tree.map(
            lambda x: f"{getattr(x, 'dtype', type(x).__name__)}"
                      f"{list(getattr(x, 'shape', []))}", args))
    except Exception as exc:  # never let diagnostics break the caller
        desc = f"<unrenderable args: {exc}>"
    return desc if len(desc) <= limit else desc[:limit] + "...(truncated)"


def _donated_bytes(args: tuple) -> int:
    """Sum of array-argument bytes at compile time: the donated-buffer HBM
    estimate for one dispatch of this signature (the state arrays the fold
    ladder donates dominate; scalars contribute 0)."""
    total = 0
    try:
        import jax

        for leaf in jax.tree.leaves(args):
            nbytes = getattr(leaf, "nbytes", None)
            if nbytes is not None:
                total += int(nbytes)
    except Exception:  # never let accounting break the caller
        return 0
    return total


class Watched:
    """Callable wrapper counting compilations of one jitted entry point,
    and the per-executable accounting row behind /debug/executables."""

    __slots__ = ("_fn", "name", "warmup_calls", "calls", "compiles",
                 "retraces", "last_retrace", "dispatch_seconds",
                 "compile_seconds", "last_signature", "donated_bytes",
                 "tenants", "tiered", "__weakref__")

    def __init__(self, fn: Callable, name: str, warmup_calls: int,
                 tenants: Optional[int] = None,
                 tiered: Optional[str] = None):
        self._fn = fn
        self.name = name
        self.warmup_calls = warmup_calls
        self.calls = 0
        self.compiles = 0
        self.retraces = 0
        self.last_retrace: str = ""
        self.dispatch_seconds = 0.0
        self.compile_seconds = 0.0
        self.last_signature: str = ""
        self.donated_bytes = 0
        #: tenant count of a tenant-stacked (vmapped) executable — the
        #: /debug/executables registry reports the stacked fold as ONE fn
        #: with its tenant axis named, never N anonymous entries
        self.tenants = tenants
        #: tiered fold form of a SKETCH_TIERED executable ("interior" |
        #: "decode") — same one-program rule: the registry attributes
        #: which walk the entry compiled to, never a hidden variant
        self.tiered = tiered

    def __call__(self, *args, **kwargs):
        self.calls += 1
        prev = getattr(_tls, "active", None)
        _tls.active = self
        _tls.args = args
        t0 = time.perf_counter()
        try:
            return self._fn(*args, **kwargs)
        finally:
            # one monotonic-clock pair per DISPATCH (per batch, never per
            # record) — the wall attribution the accounting registry exists
            # for. Async dispatch means this is enqueue cost on TPU and
            # full execution on CPU; either way it is the wall the pipeline
            # thread actually spent inside this executable's call.
            dt = time.perf_counter() - t0
            self.dispatch_seconds += dt
            m = _metrics
            if m is not None:
                m.observe_dispatch(self.name, dt)
            _tls.active = prev
            _tls.args = None

    def __getattr__(self, item: str) -> Any:
        # delegate .lower / ._cache_size / __wrapped__-style access
        return getattr(object.__getattribute__(self, "_fn"), item)

    def _note_compile(self, duration: float = 0.0) -> None:
        global _retraces_total
        self.compiles += 1
        self.compile_seconds += duration
        args = getattr(_tls, "args", None) or ()
        # signature/donation refresh on EVERY compile, warmup included —
        # the registry row must describe the executable that actually
        # serves steady state, which is the last one compiled
        sig = _describe(args)
        if self.tenants is not None:
            # tenant-stacked entries prefix the axis size so the lowered
            # signature reads as one executable folding N tenants (the
            # leading dim of every stacked arg IS this count)
            sig = f"tenants={self.tenants} {sig}"
        if self.tiered is not None:
            # tiered entries prefix the fold form so the signature reads
            # as the tier-interior walk or the decode-to-wide wrap
            sig = f"tiered={self.tiered} {sig}"
        self.last_signature = sig
        self.donated_bytes = _donated_bytes(args)
        if self.calls <= self.warmup_calls:
            return  # expected warmup compile
        self.retraces += 1
        _retraces_total += 1
        self.last_retrace = self.last_signature
        log.error(
            "post-warmup XLA retrace of jitted entry %r (call %d, compile "
            "%d): the fixed-shape ingest invariant is broken; offending "
            "abstract shapes: %s",
            self.name, self.calls, self.compiles, self.last_retrace)
        m = _metrics
        if m is not None:
            m.count_retrace(self.name)

    def stats(self) -> dict:
        return {"fn": self.name, "calls": self.calls,
                "compiles": self.compiles, "retraces": self.retraces,
                "warmup_calls": self.warmup_calls,
                "dispatch_seconds": round(self.dispatch_seconds, 6),
                "compile_seconds": round(self.compile_seconds, 6),
                "donated_bytes_estimate": self.donated_bytes,
                **({"tenants": self.tenants}
                   if self.tenants is not None else {}),
                **({"tiered": self.tiered}
                   if self.tiered is not None else {}),
                **({"last_signature": self.last_signature}
                   if self.last_signature else {}),
                **({"last_retrace": self.last_retrace}
                   if self.last_retrace else {})}


def _listener(event: str, duration: float, **kwargs) -> None:
    if event != _LOWER_EVENT:
        return
    w = getattr(_tls, "active", None)
    if w is not None:
        w._note_compile(duration)


def _ensure_installed() -> None:
    global _installed
    with _install_lock:
        if _installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_listener)
        _installed = True


def watch(fn: Callable, name: str,
          warmup_calls: Optional[int] = None,
          tenants: Optional[int] = None,
          tiered: Optional[str] = None) -> Callable:
    """Wrap a jitted entry point for retrace accounting. Returns `fn`
    unchanged when the watchdog is disabled; never double-wraps.
    `tenants` marks a tenant-stacked (vmapped) executable: the registry
    reports it as one fn with the tenant count in its signature string.
    `tiered` ("interior" | "decode") marks a SKETCH_TIERED executable with
    the fold form it compiled to — one program either way, attributed."""
    if not _enabled or isinstance(fn, Watched):
        return fn
    _ensure_installed()
    w = Watched(fn, name, _default_warmup if warmup_calls is None
                else warmup_calls, tenants=tenants, tiered=tiered)
    with _install_lock:
        _registry.append(weakref.ref(w))
        if len(_registry) % 64 == 0:  # amortized sweep of dead wrappers
            _registry[:] = [r for r in _registry if r() is not None]
    return w


def _live_watched() -> list[Watched]:
    return [w for w in (r() for r in _registry) if w is not None]


def set_metrics(metrics) -> None:
    """Bind the Metrics facade whose ``count_retrace`` receives post-warmup
    retraces (sketch_retraces_total{fn=...})."""
    global _metrics
    _metrics = metrics


def configure(enabled: Optional[bool] = None,
              warmup_calls: Optional[int] = None) -> None:
    """Test/ops hook: toggle the watchdog or change the default warmup
    window for subsequently watched functions."""
    global _enabled, _default_warmup
    if enabled is not None:
        _enabled = enabled
    if warmup_calls is not None:
        _default_warmup = warmup_calls


def snapshot() -> list[dict]:
    """Per-entry-point compile accounting (live wrappers), for /debug/jax."""
    return [w.stats() for w in _live_watched()]


def total_retraces() -> int:
    """Process-lifetime post-warmup retrace count (monotonic; includes
    wrappers that have since been garbage-collected)."""
    return _retraces_total
