"""Small network formatting helpers (reference analog: `pkg/utils/utils.go`)."""

from __future__ import annotations

from netobserv_tpu.model.flow import ip_from_16


def format_addr_port(raw16: bytes, port: int) -> str:
    """Render a 16-byte address + port: v4 as a.b.c.d:p, v6 as [..]:p."""
    addr = ip_from_16(raw16)
    if ":" in addr:
        return f"[{addr}]:{port}"
    return f"{addr}:{port}"


def format_mac(raw: bytes) -> str:
    return ":".join(f"{b:02X}" for b in raw[:6])
