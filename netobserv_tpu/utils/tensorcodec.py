"""The ONE per-tensor wire codec: zlib-when-smaller payloads with a
bounded, bomb-proof inflate.

Two surfaces ship table-snapshot tensors to disk or wire — the federation
delta frame (`federation/delta.py`) and the sketch-warehouse archive
segment (`archive/segment.py`) — and both use exactly this codec, so there
is one tensor format to validate, fuzz, and golden-pin, not two drifting
copies. The delta wire's v1/v2/v3 RAW golden frames (tests/
test_federation_golden.py) pin the encode side byte-for-byte; the archive
segment golden pins the same bytes through the second consumer.

jax-free on purpose: both consumers must encode/decode on the big-endian
qemu CI tier and must never dispatch a device op. Tensor payloads are
ALWAYS little-endian (explicit ``<`` numpy dtypes) regardless of host
order; the dtype-code table below is part of both wire formats and may
only grow, never renumber.
"""

from __future__ import annotations

import zlib

import numpy as np

CODEC_RAW = 0
CODEC_ZLIB = 1

#: wire dtype codes (shared by the delta frame and the archive segment —
#: renumbering breaks both golden sets at once, which is the point)
DTYPE_TO_CODE = {"<f4": 1, "<i4": 2, "<u4": 3}
CODE_TO_DTYPE = {v: k for k, v in DTYPE_TO_CODE.items()}

#: hard per-tensor size ceiling (decoded bytes). Production tables top out
#: around cm_depth*cm_width*4 ≈ 1 MiB; this bounds what a hostile/corrupt
#: payload can make a decoder allocate BEFORE any shape validation — both
#: via a declared-huge shape and via a zlib bomb (decompression is capped
#: at the declared size, never "whatever the stream inflates to").
MAX_TENSOR_BYTES = 1 << 27  # 128 MiB


class TensorCodecError(ValueError):
    """Malformed tensor payload (decode-time validation failure). Both
    consumers re-raise it as their own frame/segment error type."""


def declared_nbytes(name: str, shape: tuple, dtype: str) -> int:
    """Byte size a declared (shape, dtype) wants, validated against the
    MAX_TENSOR_BYTES cap (negative/overflowing shapes reject too)."""
    n_elems = int(np.prod(shape, dtype=np.int64)) if shape else 1
    expected = n_elems * np.dtype(dtype).itemsize
    if not 0 <= expected <= MAX_TENSOR_BYTES:
        raise TensorCodecError(
            f"tensor {name!r}: declared shape {tuple(shape)} wants "
            f"{expected} bytes (cap {MAX_TENSOR_BYTES})")
    return expected


def encode_payload(raw: bytes, codec: int) -> tuple[int, bytes]:
    """Encode one tensor's raw little-endian bytes under `codec`.

    ``CODEC_ZLIB`` deflates but keeps RAW whenever deflate does not shrink
    the payload (the returned codec code records which actually shipped —
    the "zlib-when-smaller" rule both wire formats pin)."""
    if codec == CODEC_ZLIB:
        packed = zlib.compress(raw, 1)
        if len(packed) < len(raw):
            return CODEC_ZLIB, packed
        return CODEC_RAW, raw
    if codec == CODEC_RAW:
        return CODEC_RAW, raw
    raise TensorCodecError(f"unknown codec {codec}")


def decode_payload(name: str, codec: int, data: bytes,
                   expected: int) -> bytes:
    """Decode one tensor payload back to exactly `expected` raw bytes.

    The zlib path is a BOUNDED inflate: it never allocates past the
    declared size, and the stream must end exactly there (bomb/corruption
    guard). RAW payloads must match the declared size exactly."""
    if codec == CODEC_ZLIB:
        d = zlib.decompressobj()
        try:
            raw = d.decompress(data, expected)
        except zlib.error as exc:
            raise TensorCodecError(
                f"tensor {name!r}: bad zlib stream: {exc}") from exc
        if len(raw) != expected or not d.eof or d.unconsumed_tail:
            raise TensorCodecError(
                f"tensor {name!r}: zlib payload inflates to "
                f"{len(raw)}B (eof={d.eof}), declared {expected}B")
        return raw
    if codec == CODEC_RAW:
        if len(data) != expected:
            raise TensorCodecError(
                f"tensor {name!r}: payload is {len(data)}B, declared "
                f"{expected}B")
        return data
    raise TensorCodecError(f"tensor {name!r}: unknown codec {codec}")
