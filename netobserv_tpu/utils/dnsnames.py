"""DNS wire-format qname decoding (reference analog: `pkg/utils/utils.go`
label decode). The datapath copies the raw length-prefixed label sequence;
the host renders it dotted."""

from __future__ import annotations


def decode_qname(raw: bytes) -> str:
    """Decode a (possibly truncated) DNS qname into dotted form.

    Compression pointers (0xC0) terminate decoding — the tail lives elsewhere
    in the original packet, which we no longer have."""
    labels = []
    i = 0
    while i < len(raw):
        n = raw[i]
        if n == 0:
            break
        if n & 0xC0:
            break  # compression pointer or malformed
        label = raw[i + 1:i + 1 + n]
        if not label:
            break
        labels.append(label.decode("ascii", "replace"))
        i += 1 + n
    return ".".join(labels)
