"""Pluggable OVN network-event sample decoders.

Reference analog: `pkg/agent/agent.go:136-147` wires an
`ovnobserv.SampleDecoder` against the OVN northbound OVSDB unix socket
(`/var/run/ovn/ovnnb_db.sock`) so psample cookies resolve to live ACL
metadata (name/namespace/action) instead of bare object ids.

Three implementations behind one seam:

- `StaticCookieDecoder` — pure-bytes decode (utils/networkevents.py); always
  available, no daemon required. The default.
- `OvsdbSampleDecoder` — socket-backed: a minimal OVSDB JSON-RPC client that
  resolves the cookie's object id to an ACL row (name / action / direction /
  external_ids) with an in-memory cache. Any error degrades to the static
  decode — enrichment must never break the export path.
- any test double implementing `decode(cookie) -> dict`.

The active decoder is process-global (`set_decoder` / `active_decoder`):
exporters decode from deep inside the map-rendering path where threading a
handle through every caller would contaminate every exporter signature.
"""

from __future__ import annotations

import json
import logging
import socket
import threading
from typing import Optional, Protocol

from netobserv_tpu.utils import networkevents

log = logging.getLogger("netobserv_tpu.utils.ovn")

OVN_NB_SOCK = "/var/run/ovn/ovnnb_db.sock"
OVN_NB_DB = "OVN_Northbound"


class SampleDecoder(Protocol):
    def decode(self, cookie: bytes) -> dict: ...

    def close(self) -> None: ...


class StaticCookieDecoder:
    """Layout-only decode of the psample user cookie (no OVN daemon)."""

    def decode(self, cookie: bytes) -> dict:
        return networkevents.decode_cookie(cookie)

    def close(self) -> None:
        pass


class OvsdbSampleDecoder:
    """Resolve sample object ids against the OVN OVSDB over its unix socket.

    Speaks just enough OVSDB JSON-RPC (RFC 7047): a `transact` with a
    `select` on the ACL table filtered by the sample id. Responses are
    cached; every failure falls back to the static decode so a missing or
    wedged ovsdb-server never stalls an eviction.
    """

    def __init__(self, sock_path: str = OVN_NB_SOCK, db: str = OVN_NB_DB,
                 table: str = "ACL", timeout_s: float = 2.0,
                 cache_max: int = 4096):
        self._path = sock_path
        self._db = db
        self._table = table
        self._timeout = timeout_s
        self._sock: Optional[socket.socket] = None
        self._rpc_id = 0
        self._cache: dict[int, Optional[dict]] = {}
        self._cache_max = cache_max
        self._static = StaticCookieDecoder()
        self._lock = threading.Lock()

    # --- OVSDB JSON-RPC plumbing ------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self._timeout)
            s.connect(self._path)
            self._sock = s
        return self._sock

    def _rpc(self, method: str, params: list):
        """One JSON-RPC round trip. OVSDB frames are bare JSON values; the
        response is read until a complete value parses. Any error drops the
        connection so the next lookup reconnects (an ovsdb-server restart
        must not permanently disable enrichment)."""
        self._rpc_id += 1
        req = json.dumps({"id": self._rpc_id, "method": method,
                          "params": params}).encode()
        try:
            sock = self._connect()
            sock.sendall(req)
            buf = ""
            decoder = json.JSONDecoder()
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError("ovsdb closed mid-response")
                buf += chunk.decode(errors="replace")
                while True:
                    try:
                        obj, end = decoder.raw_decode(buf)
                    except ValueError:
                        break  # partial frame: read more
                    buf = buf[end:].lstrip()
                    if obj.get("id") != self._rpc_id:
                        continue  # notification; a pipelined reply may follow
                    if obj.get("error"):
                        raise IOError(f"ovsdb error: {obj['error']}")
                    return obj.get("result")
        except Exception:
            self.close()  # reconnect on the next lookup
            raise

    def _lookup_acl(self, obj_id: int) -> Optional[dict]:
        """Select the ACL row whose sample id matches; None when absent.
        Failures are negative-cached so a wedged ovsdb pays its timeout once
        per object, not once per eviction window."""
        if obj_id in self._cache:
            return self._cache[obj_id]
        if len(self._cache) >= self._cache_max:
            self._cache.clear()  # crude but bounded
        try:
            result = self._rpc("transact", [
                self._db,
                {"op": "select", "table": self._table,
                 "where": [["sample_new", "==", obj_id]],
                 "columns": ["name", "action", "direction", "external_ids"]},
            ])
            rows = (result or [{}])[0].get("rows", [])
            row = rows[0] if rows else None
        except Exception as exc:
            log.debug("ovsdb sample lookup failed (%s); static decode", exc)
            row = None
        self._cache[obj_id] = row
        return row

    # --- SampleDecoder -----------------------------------------------------
    def decode(self, cookie: bytes) -> dict:
        base = self._static.decode(cookie)
        obj = base.get("Name")
        if obj is None or not obj.isdigit():
            return base
        # the WHOLE enrichment is guarded: a malformed row must degrade to
        # the static decode, never crash the export path
        try:
            with self._lock:
                row = self._lookup_acl(int(obj))
            if not row:
                return base
            ext = dict(row.get("external_ids", ["map", []])[1]) \
                if isinstance(row.get("external_ids"), list) else {}
            out = dict(base)
            if row.get("name"):
                out["Name"] = row["name"]
            if row.get("action"):
                out["Action"] = row["action"]
            if row.get("direction"):
                out["Direction"] = row["direction"]
            if ext.get("k8s.ovn.org/name"):
                out["Name"] = ext["k8s.ovn.org/name"]
            if ext.get("k8s.ovn.org/namespace"):
                out["Namespace"] = ext["k8s.ovn.org/namespace"]
            return out
        except Exception as exc:
            log.debug("ovsdb enrichment failed (%s); static decode", exc)
            return base

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None


_active: SampleDecoder = StaticCookieDecoder()


def set_decoder(decoder: Optional[SampleDecoder]) -> None:
    """Install the process-wide sample decoder (None restores the static)."""
    global _active
    _active = decoder if decoder is not None else StaticCookieDecoder()


def active_decoder() -> SampleDecoder:
    return _active


def decode_event(cookie: bytes) -> dict:
    return _active.decode(cookie)


def make_decoder(cfg) -> SampleDecoder:
    """Agent wiring (reference agent.go:136-147): the socket-backed decoder
    when the OVN socket exists, static otherwise. The caller gates on the
    network-events config flag; connection itself is lazy."""
    import os

    if os.path.exists(OVN_NB_SOCK):
        log.info("OVN sample decoder: ovsdb-backed (%s)", OVN_NB_SOCK)
        return OvsdbSampleDecoder()
    return StaticCookieDecoder()
