"""Generated protobuf modules (protoc --python_out over proto/*.proto).

Regenerate with: make gen-protobuf (see Makefile).
"""
