"""Native flowpack vs numpy fallback equivalence (and the native build)."""

import numpy as np
import pytest

from netobserv_tpu.datapath import flowpack
from netobserv_tpu.model import binfmt
from tests.test_model import make_event


@pytest.fixture(scope="module")
def native():
    if not flowpack.build_native():
        pytest.skip("no g++ available to build libflowpack")
    assert flowpack.native_available()
    return True


def _events(n=17):
    events = np.zeros(n, dtype=binfmt.FLOW_EVENT_DTYPE)
    for i in range(n):
        events[i] = make_event(sport=1000 + i, nbytes=10 * i + 1, pkts=i + 1)
    events["stats"]["sampling"] = 50
    events["stats"]["dscp"] = 46
    return events


class TestPack:
    def test_native_matches_numpy(self, native):
        events = _events()
        a = flowpack.pack_events(events, batch_size=32, use_native=True)
        b = flowpack.pack_events(events, batch_size=32, use_native=False)
        for name, col in a.columns().items():
            np.testing.assert_array_equal(
                col, getattr(b, name), err_msg=f"column {name}")

    def test_pack_from_raw_bytes(self, native):
        events = _events(5)
        batch = flowpack.pack_events(events.tobytes(), use_native=True)
        assert batch.n_valid == 5
        assert batch.bytes[:5].tolist() == [1, 11, 21, 31, 41]

    def test_empty(self, native):
        batch = flowpack.pack_events(b"", batch_size=4)
        assert batch.n_valid == 0


class TestMergePercpu:
    @pytest.mark.parametrize(
        "kind", ["stats", "extra", "drops", "dns", "nevents", "xlat", "quic"])
    def test_native_matches_python(self, native, kind):
        rng = np.random.default_rng(3)
        dtype = flowpack._MERGE_FNS[kind][1]
        vals = np.zeros(4, dtype=dtype)
        # random-ish partials with valid fields
        for i in range(4):
            vals[i]["first_seen_ns"] = int(rng.integers(1, 10**9))
            vals[i]["last_seen_ns"] = int(rng.integers(10**9, 2 * 10**9))
            if kind == "stats":
                vals[i]["bytes"] = int(rng.integers(0, 10**6))
                vals[i]["packets"] = int(rng.integers(0, 1000))
                vals[i]["tcp_flags"] = int(rng.integers(0, 0xFFF))
                vals[i]["dscp"] = int(rng.integers(0, 64))
                vals[i]["ssl_version"] = int(
                    rng.choice([0, 0x0303, 0x0304]))
            elif kind == "extra":
                vals[i]["rtt_ns"] = int(rng.integers(0, 10**8))
                vals[i]["ipsec_ret"] = int(rng.integers(-2, 3))
                vals[i]["ipsec_encrypted"] = int(rng.integers(0, 2))
            elif kind == "drops":
                vals[i]["bytes"] = int(rng.integers(0, 0xFFFF))
                vals[i]["packets"] = int(rng.integers(0, 0xFFFF))
                vals[i]["latest_cause"] = int(rng.integers(0, 5))
                vals[i]["latest_flags"] = int(rng.integers(0, 0xFF))
            elif kind == "dns":
                vals[i]["latency_ns"] = int(rng.integers(0, 10**7))
                vals[i]["dns_id"] = int(rng.integers(0, 2**16))
                vals[i]["dns_flags"] = int(rng.integers(0, 2**16))
            elif kind == "nevents":
                n_ev = int(rng.integers(0, 5))
                for j in range(n_ev):
                    vals[i]["events"][j] = rng.integers(
                        1, 255, size=8, dtype=np.uint8)
                    vals[i]["bytes"][j] = int(rng.integers(1, 2000))
                    vals[i]["packets"][j] = int(rng.integers(1, 10))
                vals[i]["n_events"] = n_ev
            elif kind == "xlat":
                if rng.integers(0, 2):
                    vals[i]["src_ip"] = rng.integers(
                        1, 255, size=16, dtype=np.uint8)
                    vals[i]["dst_ip"] = rng.integers(
                        1, 255, size=16, dtype=np.uint8)
                    vals[i]["src_port"] = int(rng.integers(1, 2**16))
                    vals[i]["dst_port"] = int(rng.integers(1, 2**16))
                    vals[i]["zone_id"] = int(rng.integers(0, 2**16))
            elif kind == "quic":
                vals[i]["version"] = int(rng.integers(0, 3))
                vals[i]["seen_long_hdr"] = int(rng.integers(0, 2))
                vals[i]["seen_short_hdr"] = int(rng.integers(0, 2))
        a = flowpack.merge_percpu(kind, vals, use_native=True)
        b = flowpack.merge_percpu(kind, vals, use_native=False)
        assert a.tobytes() == b.tobytes(), kind

    def test_nevents_ring_wrap_equivalence(self, native):
        """Cursor wrap with duplicates: both implementations must agree."""
        cap = binfmt.NEVENTS_REC_DTYPE["events"].shape[0]
        vals = np.zeros(2, dtype=binfmt.NEVENTS_REC_DTYPE)
        for j in range(cap):
            vals[0]["events"][j] = [j + 1] * 8
            vals[0]["packets"][j] = 1
        vals[0]["n_events"] = 1  # wrapped cursor
        vals[1]["events"][0] = [1] * 8   # dup of slot 0
        vals[1]["events"][1] = [99] * 8  # fresh
        vals[1]["packets"][:2] = 1
        vals[1]["n_events"] = 2
        a = flowpack.merge_percpu("nevents", vals, use_native=True)
        b = flowpack.merge_percpu("nevents", vals, use_native=False)
        assert a.tobytes() == b.tobytes()

    def test_stats_saturating_and_dedup(self, native):
        vals = np.zeros(2, dtype=binfmt.FLOW_STATS_DTYPE)
        vals[0]["bytes"] = 2**64 - 10
        vals[1]["bytes"] = 100
        vals[0]["packets"] = 1
        vals[0]["n_observed_intf"] = 1
        vals[0]["observed_intf"][0] = 3
        vals[1]["n_observed_intf"] = 2
        vals[1]["observed_intf"][0] = 3
        vals[1]["observed_intf"][1] = 9
        out = flowpack.merge_percpu("stats", vals, use_native=True)
        assert int(out["bytes"]) == 2**64 - 1  # saturated
        assert int(out["n_observed_intf"]) == 2  # 3 deduped, 9 appended
