"""Native flowpack vs numpy fallback equivalence (and the native build)."""

import numpy as np
import pytest

from netobserv_tpu.datapath import flowpack
from netobserv_tpu.model import binfmt
from tests.test_model import make_event


@pytest.fixture(scope="module")
def native():
    if not flowpack.build_native():
        pytest.skip("no g++ available to build libflowpack")
    assert flowpack.native_available()
    return True


def _events(n=17):
    events = np.zeros(n, dtype=binfmt.FLOW_EVENT_DTYPE)
    for i in range(n):
        events[i] = make_event(sport=1000 + i, nbytes=10 * i + 1, pkts=i + 1)
    events["stats"]["sampling"] = 50
    events["stats"]["dscp"] = 46
    return events


class TestPack:
    def test_native_matches_numpy(self, native):
        events = _events()
        a = flowpack.pack_events(events, batch_size=32, use_native=True)
        b = flowpack.pack_events(events, batch_size=32, use_native=False)
        for name, col in a.columns().items():
            np.testing.assert_array_equal(
                col, getattr(b, name), err_msg=f"column {name}")

    def test_pack_from_raw_bytes(self, native):
        events = _events(5)
        batch = flowpack.pack_events(events.tobytes(), use_native=True)
        assert batch.n_valid == 5
        assert batch.bytes[:5].tolist() == [1, 11, 21, 31, 41]

    def test_empty(self, native):
        batch = flowpack.pack_events(b"", batch_size=4)
        assert batch.n_valid == 0


class TestPackDense:
    def _extra_dns(self, n):
        extra = np.zeros(n, dtype=binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = np.arange(n, dtype=np.uint64) * 123_000
        dns = np.zeros(n, dtype=binfmt.DNS_REC_DTYPE)
        dns["latency_ns"] = np.arange(n, dtype=np.uint64) * 77_000
        return extra, dns

    def test_native_matches_numpy(self, native):
        events = _events()
        extra, dns = self._extra_dns(len(events))
        a = flowpack.pack_dense(events, batch_size=32, extra=extra, dns=dns,
                                use_native=True)
        b = flowpack.pack_dense(events, batch_size=32, extra=extra, dns=dns,
                                use_native=False)
        np.testing.assert_array_equal(a, b)

    def test_matches_column_path(self, native):
        """The dense rows must carry exactly what batch_to_device exposes —
        the single shared definition the ingest consumes either way."""
        from netobserv_tpu.sketch import state as sk

        events = _events()
        extra, dns = self._extra_dns(len(events))
        dense = flowpack.pack_dense(events, batch_size=32, extra=extra,
                                    dns=dns)
        batch = flowpack.pack_events(events, batch_size=32, extra=extra,
                                     dns=dns)
        arrays = sk.batch_to_device(batch)
        np.testing.assert_array_equal(dense[:, :10], arrays["keys"])
        np.testing.assert_array_equal(dense[:, 10].view(np.float32),
                                      arrays["bytes"])
        np.testing.assert_array_equal(dense[:, 11].astype(np.int32),
                                      arrays["packets"])
        np.testing.assert_array_equal(dense[:, 12].astype(np.int32),
                                      arrays["rtt_us"])
        np.testing.assert_array_equal(dense[:, 13].astype(np.int32),
                                      arrays["dns_latency_us"])
        np.testing.assert_array_equal(dense[:, 14] != 0, arrays["valid"])
        np.testing.assert_array_equal(dense[:, 15].astype(np.int32),
                                      arrays["sampling"])

    def test_reused_out_buffer_zeroes_padding(self, native):
        """A preallocated out buffer is fully overwritten: stale rows from a
        bigger previous batch must never survive as phantom valid rows."""
        out = np.full((32, flowpack.DENSE_WORDS), 0xAB, np.uint32)
        flowpack.pack_dense(_events(20), batch_size=32, out=out)
        assert out[20:, 14].sum() == 0          # padding invalid
        assert (out[20:] == 0).all()
        dense2 = flowpack.pack_dense(_events(3), batch_size=32, out=out)
        assert dense2 is out
        assert (out[3:] == 0).all()

    def test_short_feature_arrays_padded(self, native):
        """extra/dns arrays shorter than the event count must not OOB-read
        (native) or broadcast-fail (numpy): missing tail rows read as 0."""
        events = _events(8)
        extra, dns = self._extra_dns(3)
        for un in (True, False):
            dense = flowpack.pack_dense(events, batch_size=8, extra=extra,
                                        dns=dns, use_native=un)
            assert (dense[3:, 12] == 0).all() and (dense[3:, 13] == 0).all()
            assert dense[2, 12] == 2 * 123 and dense[2, 13] == 2 * 77

    def test_empty(self, native):
        dense = flowpack.pack_dense(b"", batch_size=4)
        assert (dense == 0).all()

    def test_ingest_dense_equals_dict_ingest(self, native):
        """Folding the dense feed must produce bit-identical sketch state to
        the six-array dict path (same ingest, different transport)."""
        import jax

        from netobserv_tpu.sketch import state as sk

        events = _events(17)
        extra, dns = self._extra_dns(17)
        cfg = sk.SketchConfig(cm_width=1 << 10, topk=64)
        batch = flowpack.pack_events(events, batch_size=32, extra=extra,
                                     dns=dns)
        arrays = sk.batch_to_device(batch)
        s_dict = jax.jit(sk.ingest)(sk.init_state(cfg), arrays)
        dense = flowpack.pack_dense(events, batch_size=32, extra=extra,
                                    dns=dns)
        s_dense = sk.make_ingest_dense_fn(donate=False)(
            sk.init_state(cfg), dense)
        for name in sk.SketchState._fields:
            da, db = getattr(s_dict, name), getattr(s_dense, name)
            jax.tree.map(lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), da, db)


class TestMergePercpu:
    @pytest.mark.parametrize(
        "kind", ["stats", "extra", "drops", "dns", "nevents", "xlat", "quic"])
    def test_native_matches_python(self, native, kind):
        rng = np.random.default_rng(3)
        dtype = flowpack._MERGE_FNS[kind][1]
        vals = np.zeros(4, dtype=dtype)
        # random-ish partials with valid fields
        for i in range(4):
            vals[i]["first_seen_ns"] = int(rng.integers(1, 10**9))
            vals[i]["last_seen_ns"] = int(rng.integers(10**9, 2 * 10**9))
            if kind == "stats":
                vals[i]["bytes"] = int(rng.integers(0, 10**6))
                vals[i]["packets"] = int(rng.integers(0, 1000))
                vals[i]["tcp_flags"] = int(rng.integers(0, 0xFFF))
                vals[i]["dscp"] = int(rng.integers(0, 64))
                vals[i]["ssl_version"] = int(
                    rng.choice([0, 0x0303, 0x0304]))
            elif kind == "extra":
                vals[i]["rtt_ns"] = int(rng.integers(0, 10**8))
                vals[i]["ipsec_ret"] = int(rng.integers(-2, 3))
                vals[i]["ipsec_encrypted"] = int(rng.integers(0, 2))
            elif kind == "drops":
                vals[i]["bytes"] = int(rng.integers(0, 0xFFFF))
                vals[i]["packets"] = int(rng.integers(0, 0xFFFF))
                vals[i]["latest_cause"] = int(rng.integers(0, 5))
                vals[i]["latest_flags"] = int(rng.integers(0, 0xFF))
            elif kind == "dns":
                vals[i]["latency_ns"] = int(rng.integers(0, 10**7))
                vals[i]["dns_id"] = int(rng.integers(0, 2**16))
                vals[i]["dns_flags"] = int(rng.integers(0, 2**16))
            elif kind == "nevents":
                n_ev = int(rng.integers(0, 5))
                for j in range(n_ev):
                    vals[i]["events"][j] = rng.integers(
                        1, 255, size=8, dtype=np.uint8)
                    vals[i]["bytes"][j] = int(rng.integers(1, 2000))
                    vals[i]["packets"][j] = int(rng.integers(1, 10))
                vals[i]["n_events"] = n_ev
            elif kind == "xlat":
                if rng.integers(0, 2):
                    vals[i]["src_ip"] = rng.integers(
                        1, 255, size=16, dtype=np.uint8)
                    vals[i]["dst_ip"] = rng.integers(
                        1, 255, size=16, dtype=np.uint8)
                    vals[i]["src_port"] = int(rng.integers(1, 2**16))
                    vals[i]["dst_port"] = int(rng.integers(1, 2**16))
                    vals[i]["zone_id"] = int(rng.integers(0, 2**16))
            elif kind == "quic":
                vals[i]["version"] = int(rng.integers(0, 3))
                vals[i]["seen_long_hdr"] = int(rng.integers(0, 2))
                vals[i]["seen_short_hdr"] = int(rng.integers(0, 2))
        a = flowpack.merge_percpu(kind, vals, use_native=True)
        b = flowpack.merge_percpu(kind, vals, use_native=False)
        assert a.tobytes() == b.tobytes(), kind

    def test_nevents_ring_wrap_equivalence(self, native):
        """Cursor wrap with duplicates: both implementations must agree."""
        cap = binfmt.NEVENTS_REC_DTYPE["events"].shape[0]
        vals = np.zeros(2, dtype=binfmt.NEVENTS_REC_DTYPE)
        for j in range(cap):
            vals[0]["events"][j] = [j + 1] * 8
            vals[0]["packets"][j] = 1
        vals[0]["n_events"] = 1  # wrapped cursor
        vals[1]["events"][0] = [1] * 8   # dup of slot 0
        vals[1]["events"][1] = [99] * 8  # fresh
        vals[1]["packets"][:2] = 1
        vals[1]["n_events"] = 2
        a = flowpack.merge_percpu("nevents", vals, use_native=True)
        b = flowpack.merge_percpu("nevents", vals, use_native=False)
        assert a.tobytes() == b.tobytes()

    def test_stats_saturating_and_dedup(self, native):
        vals = np.zeros(2, dtype=binfmt.FLOW_STATS_DTYPE)
        vals[0]["bytes"] = 2**64 - 10
        vals[1]["bytes"] = 100
        vals[0]["packets"] = 1
        vals[0]["n_observed_intf"] = 1
        vals[0]["observed_intf"][0] = 3
        vals[1]["n_observed_intf"] = 2
        vals[1]["observed_intf"][0] = 3
        vals[1]["observed_intf"][1] = 9
        out = flowpack.merge_percpu("stats", vals, use_native=True)
        assert int(out["bytes"]) == 2**64 - 1  # saturated
        assert int(out["n_observed_intf"]) == 2  # 3 deduped, 9 appended


class TestMergePercpuBatch:
    """merge_percpu_batch API surface (the full four-form fuzz lives in
    tests/test_evict_columnar.py): batch rows == per-key calls, native ==
    columnar fallback, and shape validation."""

    @pytest.mark.parametrize(
        "kind", ["stats", "extra", "drops", "dns", "nevents", "xlat", "quic"])
    def test_batch_rows_match_single_key(self, native, kind):
        rng = np.random.default_rng(21)
        dtype = flowpack._MERGE_FNS[kind][1]
        raw = rng.integers(0, 256, (5, 4 * dtype.itemsize),
                           dtype=np.int64).astype(np.uint8)
        vals = raw.copy().view(dtype)
        if kind == "dns":
            vals["name"] = b"\x03abc"  # keep both name rules equivalent
        if kind == "nevents":
            vals["n_events"] = vals["n_events"] % 8
        for un in (True, False):
            batch = flowpack.merge_percpu_batch(kind, vals, use_native=un)
            for i in range(len(vals)):
                one = flowpack.merge_percpu(kind, vals[i], use_native=un)
                assert one.tobytes() == batch[i].tobytes(), (kind, un, i)

    def test_rejects_non_2d(self, native):
        vals = np.zeros(4, dtype=binfmt.EXTRA_REC_DTYPE)
        with pytest.raises(ValueError):
            flowpack.merge_percpu_batch("extra", vals)

    def test_empty_batch(self, native):
        vals = np.zeros((0, 4), dtype=binfmt.EXTRA_REC_DTYPE)
        for un in (True, False):
            out = flowpack.merge_percpu_batch("extra", vals, use_native=un)
            assert out.shape == (0,) and out.dtype == binfmt.EXTRA_REC_DTYPE


class TestStagingRing:
    def test_ring_matches_sequential_ingest(self, native):
        """Folding batches through the 4-slot staging ring (buffer reuse +
        async dispatch) must produce the same state as sequential dict-path
        ingest — slot reuse must never let a later batch overwrite rows an
        in-flight ingest still needs."""
        import jax

        from netobserv_tpu.sketch import state as sk
        from netobserv_tpu.sketch.staging import DenseStagingRing

        cfg = sk.SketchConfig(cm_width=1 << 10, topk=64)
        batches = []
        for s in range(11):
            ev = _events(32)
            ev["key"]["src_port"] = 2000 + 37 * s + np.arange(32)
            batches.append(ev)

        ring = DenseStagingRing(
            32, sk.make_ingest_dense_fn(donate=False, with_token=True))
        s_ring = sk.init_state(cfg)
        for ev in batches:
            s_ring = ring.fold(s_ring, ev)
        ring.drain()

        ingest = jax.jit(sk.ingest)
        s_ref = sk.init_state(cfg)
        for ev in batches:
            arrays = sk.batch_to_device(
                flowpack.pack_events(ev, batch_size=32))
            s_ref = ingest(s_ref, arrays)

        for name in sk.SketchState._fields:
            jax.tree.map(lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
                getattr(s_ring, name), getattr(s_ref, name))


class TestSamplingDebias:
    def test_sampled_volume_scaled(self):
        """A 1-in-N sampled flow must fold as N flows' worth of bytes/packets
        (reference semantics: the Sampling field scales collector-side
        estimates); unsampled (0) and 1:1 fold unscaled."""
        import jax
        import jax.numpy as jnp

        from netobserv_tpu.sketch import state as sk

        cfg = sk.SketchConfig(cm_width=1 << 10, topk=16)
        base = {
            "keys": np.arange(80, dtype=np.uint32).reshape(8, 10),
            "bytes": np.full(8, 100.0, np.float32),
            "packets": np.full(8, 3, np.int32),
            "rtt_us": np.zeros(8, np.int32),
            "dns_latency_us": np.zeros(8, np.int32),
            "valid": np.ones(8, np.bool_),
        }
        ingest = jax.jit(sk.ingest)
        s0 = ingest(sk.init_state(cfg),
                    {**base, "sampling": np.zeros(8, np.int32)})
        s1 = ingest(sk.init_state(cfg),
                    {**base, "sampling": np.full(8, 4, np.int32)})
        assert float(s1.total_bytes) == 4 * float(s0.total_bytes)
        assert float(s1.total_records) == float(s0.total_records)  # observed
        np.testing.assert_array_equal(np.asarray(s1.cm_bytes.counts),
                                      4 * np.asarray(s0.cm_bytes.counts))
        np.testing.assert_array_equal(np.asarray(s1.cm_pkts.counts),
                                      4 * np.asarray(s0.cm_pkts.counts))


def _mixed_events(n=24, n_v6=5):
    """Events with v4-mapped keys, the last n_v6 rows genuine v6."""
    events = _events(n)
    for i in range(n - n_v6, n):
        events[i]["key"]["src_ip"] = np.arange(16, dtype=np.uint8) + i
        events[i]["key"]["dst_ip"] = np.arange(16, dtype=np.uint8) * 2 + i
    return events


class TestPackCompact:
    def test_native_matches_numpy(self, native):
        events = _mixed_events()
        extra = np.zeros(len(events), dtype=binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = np.arange(len(events), dtype=np.uint64) * 9_000
        a = flowpack.pack_compact(events, batch_size=32, spill_cap=8,
                                  extra=extra, use_native=True)
        b = flowpack.pack_compact(events, batch_size=32, spill_cap=8,
                                  extra=extra, use_native=False)
        np.testing.assert_array_equal(a, b)

    def test_overflow_returns_none(self, native):
        events = _mixed_events(24, n_v6=10)
        for un in (True, False):
            assert flowpack.pack_compact(events, batch_size=32, spill_cap=4,
                                         use_native=un) is None

    def test_ingest_compact_equals_dense(self, native):
        """The compact transport must fold to bit-identical sketch state as
        the dense transport — v4 key reconstruction included."""
        import jax

        from netobserv_tpu.sketch import state as sk

        events = _mixed_events()
        cfg = sk.SketchConfig(cm_width=1 << 10, topk=64)
        dense = flowpack.pack_dense(events, batch_size=37)
        s_dense = sk.make_ingest_dense_fn(donate=False)(
            sk.init_state(cfg), dense)
        comp = flowpack.pack_compact(events, batch_size=37, spill_cap=5)
        s_comp = sk.make_ingest_compact_fn(37, 5, donate=False)(
            sk.init_state(cfg), comp)
        # the lanes permute row order, so compare order-insensitive state:
        # every sketch is row-order invariant (sums/maxes over the batch)
        for name in ("cm_bytes", "cm_pkts", "hll_src", "hll_per_dst",
                     "hist_rtt", "hist_dns", "ddos", "total_records",
                     "total_bytes"):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6),
                getattr(s_dense, name), getattr(s_comp, name))

    def test_ring_compact_with_fallback(self, native):
        """The compact staging ring (with overflow batches taking the dense
        fallback) must agree with sequential dense ingest on the linear
        (row-order-invariant) sketches."""
        import jax

        from netobserv_tpu.sketch import state as sk
        from netobserv_tpu.sketch.staging import DenseStagingRing

        cfg = sk.SketchConfig(cm_width=1 << 10, topk=64)
        batches = []
        for i in range(9):
            # batch 4 overflows the spill lane -> dense fallback
            ev = _mixed_events(24, n_v6=10 if i == 4 else 3)
            ev["key"]["src_port"] = 3000 + 41 * i + np.arange(24)
            batches.append(ev)
        spill = 4
        ring = DenseStagingRing(
            32, sk.make_ingest_compact_fn(32, spill, donate=False,
                                          with_token=True),
            spill_cap=spill,
            ingest_fallback=sk.make_ingest_dense_fn(donate=False,
                                                    with_token=True))
        s_ring = sk.init_state(cfg)
        for ev in batches:
            s_ring = ring.fold(s_ring, ev)
        ring.drain()

        ingest = jax.jit(sk.ingest)
        s_ref = sk.init_state(cfg)
        for ev in batches:
            s_ref = ingest(s_ref, sk.batch_to_device(
                flowpack.pack_events(ev, batch_size=32)))
        for name in ("cm_bytes", "cm_pkts", "hll_src", "hll_per_dst",
                     "total_records", "total_bytes"):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6),
                getattr(s_ring, name), getattr(s_ref, name))


class TestStagingStallCounter:
    def test_stall_counted_when_slot_busy(self, native):
        """A fold that finds its slot's previous ingest still in flight must
        count a stall (ring.stalls + metrics.sketch_staging_stalls_total) —
        the operator's signal that the device, not the packer, is the
        bottleneck; ready slots must not count."""
        from prometheus_client import CollectorRegistry

        from netobserv_tpu.metrics.registry import Metrics, MetricsSettings
        from netobserv_tpu.sketch import state as sk
        from netobserv_tpu.sketch.staging import DenseStagingRing

        m = Metrics(MetricsSettings(), registry=CollectorRegistry())
        cfg = sk.SketchConfig(cm_width=1 << 10, topk=64)
        ring = DenseStagingRing(
            32, sk.make_ingest_dense_fn(donate=False, with_token=True),
            metrics=m)
        state = sk.init_state(cfg)
        # a DRAINED ring never stalls: every token is ready by construction
        for _ in range(6):
            state = ring.fold(state, _events(8))
            ring.drain()
        before = ring.stalls
        ring.fold(state, _events(8))
        assert ring.stalls == before  # drained slots are ready slots

        class _BusyToken:
            def __init__(self):
                self.blocked = False

            def is_ready(self):
                return False

            def block_until_ready(self):
                self.blocked = True

        tok = _BusyToken()
        ring._tokens[ring._slot] = tok
        ring.fold(state, _events(8))
        assert ring.stalls == before + 1
        assert m.sketch_staging_stalls_total._value.get() == before + 1.0
        assert tok.blocked  # correctness guard still waited on the slot


class TestShardedPack:
    def test_sharded_pack_equivalence(self, native):
        """Row-sharded parallel pack must be byte-identical to the
        single-pass pack, including the zero-padded tail and every feature
        lane, at thread counts that do and don't divide the row count."""
        rng = np.random.default_rng(11)
        n, bs = 1000, 1024
        ev = _events(n)
        extra = np.zeros(n, binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = rng.integers(0, 10**7, n)
        drops = np.zeros(n, binfmt.DROPS_REC_DTYPE)
        drops["bytes"] = rng.integers(0, 500, n)
        drops["packets"] = (drops["bytes"] > 0).astype(np.uint16)
        drops["latest_cause"] = rng.integers(0, 1 << 17, n)  # subsys bits
        ref = flowpack.pack_dense(ev, batch_size=bs, extra=extra,
                                  drops=drops)
        for threads in (2, 3, 7):
            got = flowpack.pack_dense_sharded(
                ev, batch_size=bs, threads=threads, extra=extra, drops=drops)
            np.testing.assert_array_equal(got, ref)

    def test_sharded_pack_short_feature_arrays(self, native):
        """Feature arrays shorter than the event count zero-extend the same
        way in the sharded and single-pass packs."""
        ev = _events(64)
        dns = np.zeros(20, binfmt.DNS_REC_DTYPE)
        dns["latency_ns"] = 5_000_000
        ref = flowpack.pack_dense(ev, batch_size=64, dns=dns)
        got = flowpack.pack_dense_sharded(ev, batch_size=64, threads=4,
                                          dns=dns)
        np.testing.assert_array_equal(got, ref)


class TestCompactDropSpill:
    def test_drop_rows_spill_and_signals_match_dense(self, native):
        """Drop-carrying rows must ride the spill lane (the compact lane
        zeros drop columns by construction), and the compact transport must
        agree with the dense transport on EVERY signal plane the feature
        lane feeds — drops EWMA, cause histogram, totals, SYN, markers."""
        import jax

        from netobserv_tpu.sketch import state as sk

        events = _mixed_events(24, n_v6=3)
        events["stats"]["tcp_flags"] = 0x02  # half-open SYNs
        n = len(events)
        drops = np.zeros(n, binfmt.DROPS_REC_DTYPE)
        drops["bytes"][::5] = 700          # v4 rows with drops must spill
        drops["packets"][::5] = 2
        drops["latest_cause"][::5] = 6
        quic = np.zeros(n, binfmt.QUIC_REC_DTYPE)
        quic["version"][1] = 1
        xlat = np.zeros(n, binfmt.XLAT_REC_DTYPE)
        xlat["src_ip"][2] = 9
        xlat["dst_ip"][2] = 9

        # native and numpy compact packs agree with features present
        a = flowpack.pack_compact(events, batch_size=32, spill_cap=12,
                                  drops=drops, quic=quic, xlat=xlat,
                                  use_native=True)
        b = flowpack.pack_compact(events, batch_size=32, spill_cap=12,
                                  drops=drops, quic=quic, xlat=xlat,
                                  use_native=False)
        np.testing.assert_array_equal(a, b)

        cfg = sk.SketchConfig(cm_width=1 << 10, topk=64)
        dense = flowpack.pack_dense(events, batch_size=32, drops=drops,
                                    quic=quic, xlat=xlat)
        s_dense = sk.make_ingest_dense_fn(donate=False)(
            sk.init_state(cfg), dense)
        s_comp = sk.make_ingest_compact_fn(32, 12, donate=False)(
            sk.init_state(cfg), a)
        for name in ("drops_ewma", "drop_causes", "total_drop_bytes",
                     "total_drop_packets", "syn", "synack", "dscp_bytes",
                     "quic_records", "nat_records"):
            jax.tree.map(
                lambda x, y: np.testing.assert_allclose(
                    np.asarray(x), np.asarray(y), rtol=1e-6, err_msg=name),
                getattr(s_dense, name), getattr(s_comp, name))
        # _events stamps sampling=50: the sketches fold the de-biased
        # estimate (x50), same as fast-path volume counters
        assert float(s_comp.total_drop_bytes) == 700.0 * 50 * len(drops[::5])
        assert float(s_comp.quic_records) == 1.0
        assert float(s_comp.nat_records) == 1.0
