"""Two-tier deployment e2e: per-node agent (gRPC export) -> collector-tier
worker (gRPC ingest -> tpu-sketch reports). The distributed story from
docs/architecture.md exercised fully in-process."""

import threading
import time

import pytest

from netobserv_tpu.agent import FlowsAgent
from netobserv_tpu.config import load_config
from netobserv_tpu.datapath.fetcher import FakeFetcher
from netobserv_tpu.datapath.grpc_ingest import GrpcIngestFetcher
from netobserv_tpu.exporter import build_exporter
from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
from netobserv_tpu.sketch.state import SketchConfig
from tests.test_pipeline import make_events

# spins the full sharded tpu-sketch worker over the 8-device mesh
# (compile-heavy; VERDICT weak #4): slow tier
pytestmark = pytest.mark.slow


def test_agent_to_tpu_worker():
    reports = []

    # tier 2: worker consuming gRPC, folding into sketches
    worker_fetcher = GrpcIngestFetcher(0)
    worker_cfg = load_config(environ={
        "EXPORT": "tpu-sketch", "CACHE_ACTIVE_TIMEOUT": "150ms"})
    sketch_exp = TpuSketchExporter(
        batch_size=256, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10, hll_precision=6,
                                perdst_buckets=32, perdst_precision=4,
                                topk=16, hist_buckets=64, ewma_buckets=32),
        sink=reports.append)
    worker = FlowsAgent(worker_cfg, worker_fetcher, sketch_exp)

    # tier 1: "node" agent exporting over gRPC to the worker
    agent_cfg = load_config(environ={
        "EXPORT": "grpc", "TARGET_HOST": "127.0.0.1",
        "TARGET_PORT": str(worker_fetcher.port),
        "CACHE_ACTIVE_TIMEOUT": "100ms"})
    fake = FakeFetcher()
    agent = FlowsAgent(agent_cfg, fake, build_exporter(agent_cfg))

    stop_w, stop_a = threading.Event(), threading.Event()
    tw = threading.Thread(target=worker.run, args=(stop_w,), daemon=True)
    ta = threading.Thread(target=agent.run, args=(stop_a,), daemon=True)
    tw.start()
    ta.start()
    try:
        # node agent observes flows (incl. one elephant)
        fake.inject_events(make_events(1, sport0=7777, nbytes=900_000))
        fake.inject_events(make_events(20, nbytes=50))
        # windows reset at each flush, so aggregate across reports
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            sketch_exp.flush()
            if sum(r["Records"] for r in reports) >= 21:
                break
            time.sleep(0.3)
        assert sum(r["Records"] for r in reports) >= 21
        tops = [hh for r in reports for hh in r["HeavyHitters"]
                if hh["SrcPort"] == 7777]
        assert tops and tops[0]["EstBytes"] >= 900_000
    finally:
        stop_a.set()
        ta.join(timeout=5)
        stop_w.set()
        tw.join(timeout=5)


def test_two_agents_fan_in_to_one_worker():
    """Cluster shape: several per-node agents exporting into one collector-
    tier worker; the worker's sketch merges both streams."""
    reports = []
    worker_fetcher = GrpcIngestFetcher(0)
    worker_cfg = load_config(environ={
        "EXPORT": "tpu-sketch", "CACHE_ACTIVE_TIMEOUT": "150ms"})
    sketch_exp = TpuSketchExporter(
        batch_size=256, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10, hll_precision=6,
                                perdst_buckets=32, perdst_precision=4,
                                topk=16, hist_buckets=64, ewma_buckets=32),
        sink=reports.append)
    worker = FlowsAgent(worker_cfg, worker_fetcher, sketch_exp)

    agents, fakes, stops, threads = [], [], [], []
    stop_w = threading.Event()
    tw = threading.Thread(target=worker.run, args=(stop_w,), daemon=True)
    tw.start()
    try:
        for n in range(2):
            cfg = load_config(environ={
                "EXPORT": "grpc", "TARGET_HOST": "127.0.0.1",
                "TARGET_PORT": str(worker_fetcher.port),
                "CACHE_ACTIVE_TIMEOUT": "100ms"})
            fake = FakeFetcher()
            agent = FlowsAgent(cfg, fake, build_exporter(cfg))
            stop = threading.Event()
            t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
            t.start()
            agents.append(agent)
            fakes.append(fake)
            stops.append(stop)
            threads.append(t)
        # node 0 sees 10 flows, node 1 sees 15 (disjoint ports)
        fakes[0].inject_events(make_events(10, sport0=10_000))
        fakes[1].inject_events(make_events(15, sport0=20_000))
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            sketch_exp.flush()
            if sum(r["Records"] for r in reports) >= 25:
                break
            time.sleep(0.3)
        assert sum(r["Records"] for r in reports) >= 25
    finally:
        for stop, t in zip(stops, threads):
            stop.set()
            t.join(timeout=5)
        stop_w.set()
        tw.join(timeout=5)
