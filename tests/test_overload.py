"""Overload control plane (ISSUE 8): watermark backpressure, unbiased
load shedding, and map-pressure relief.

What is pinned here:

- the AIMD controller's schedule (multiplicative increase under pressure,
  additive recovery, snap-to-1 after a clean window) and its zero-cost
  disabled gate (`SKETCH_SHED_WATERMARK` unset -> no controller object,
  the export path is bit-identical to the unshedded agent);
- UNBIASEDNESS: shedding thins rows 1-in-N but multiplies N into each
  surviving row's `sampling` field, so the device de-bias
  (sketch/state.ingest: factor = max(sampling, 1)) keeps CM frequency
  and heavy-hitter estimates within the CM error bound of an unshed run
  over the same traffic (fixed RNG schedule -> deterministic);
- zero post-warmup retraces: shedding changes row COUNTS, never shapes —
  the padded fixed-shape fold contract holds under any shed factor;
- a wedged device trips the staging slot-wait budget and drops ONE batch
  (counted, no dictionary epoch roll) instead of wedging the eviction
  feed;
- map-pressure relief: occupancy at/above MAP_PRESSURE_WATERMARK halves
  the eviction period (cadence bounded at 2x) until pressure clears;
- MapTracer.flush() racing an in-flight timer eviction: single
  `_evict_lock` holder, no double-drain, no lost flush (the relief loop
  leans on this path);
- the OVERLOADED health condition: distinct from DEGRADED on
  /healthz + /readyz, active while shedding, recovered within one window
  of pressure clearing;
- slow tier: a 4x overdriven feed against a fault-slowed device keeps
  memory bounded, sheds, publishes, and recovers cleanly.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from netobserv_tpu.agent.supervisor import Supervisor
from netobserv_tpu.datapath.fetcher import EvictedFlows, FakeFetcher
from netobserv_tpu.metrics.registry import Metrics, MetricsSettings
from netobserv_tpu.model import binfmt
from netobserv_tpu.sketch import overload
from netobserv_tpu.utils import faultinject, retrace

from tests.test_pipeline import make_events

pytestmark = pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faultinject.clear()
    faultinject.hits.clear()


def wait_for(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# controller unit tests (no jax)
# ---------------------------------------------------------------------------


class TestController:
    def test_disabled_gate_returns_none(self):
        assert overload.maybe_controller(256, 0, 64) is None
        assert overload.maybe_controller(256, 0.0, 64) is None
        assert overload.maybe_controller(256, 2.0, 64) is not None

    def test_aimd_schedule(self):
        ctl = overload.OverloadController(256, watermark=2.0, shed_max=8)
        assert ctl.shed == 1 and not ctl.overloaded
        # multiplicative increase above the high watermark
        assert ctl.update(pending_rows=2 * 256, slot_wait_p95=0.0) == 2
        assert ctl.update(2 * 256, 0.0) == 4
        assert ctl.update(2 * 256, 0.0) == 8
        assert ctl.update(10 * 256, 0.0) == 8  # capped at shed_max
        assert ctl.overloaded
        # hold between the low and high watermarks (hysteresis band)
        assert ctl.update(int(1.5 * 256), 0.0) == 8
        # additive decrease below the low watermark
        assert ctl.update(0, 0.0) == 7
        assert ctl.update(0, 0.0) == 6
        # slot wait alone can carry the score over the watermark
        ctl2 = overload.OverloadController(256, watermark=2.0, shed_max=8)
        assert ctl2.update(0, 2 * overload.SLOT_WAIT_REF_S) == 2
        # busy weighting: a zero-duty seam zeroes the depth term — arrival
        # size alone is never pressure (the exporter measures busy)
        ctl3 = overload.OverloadController(256, watermark=2.0, shed_max=8)
        assert ctl3.update(100 * 256, 0.0, busy=0.0) == 1
        assert ctl3.update(100 * 256, 0.0, busy=1.0) == 2

    def test_window_roll_snaps_only_after_clean_window(self):
        ctl = overload.OverloadController(256, watermark=1.0, shed_max=8)
        ctl.update(4 * 256, 0.0)
        assert ctl.shed > 1
        # the window that SAW pressure ends: no snap yet
        ctl.window_roll()
        assert ctl.shed > 1
        # a full pressure-free window: snap back to 1
        ctl.window_roll()
        assert ctl.shed == 1 and not ctl.overloaded

    def test_admit_identity_at_factor_one(self):
        ctl = overload.OverloadController(256, watermark=2.0)
        ev = EvictedFlows(make_events(16))
        assert ctl.admit(ev) is ev  # zero-copy no-op below the watermark

    def test_admit_thins_scales_sampling_and_aligns_lanes(self):
        ctl = overload.OverloadController(256, watermark=1.0, shed_max=4,
                                          seed=11)
        while ctl.shed < 4:
            ctl.update(4 * 256, 0.0)
        n = 512
        events = make_events(n)
        # mixed kernel sampling: 0 (unsampled) and 3 — the shed factor
        # must compose multiplicatively on max(sampling, 1)
        events["stats"]["sampling"][: n // 2] = 0
        events["stats"]["sampling"][n // 2:] = 3
        extra = np.zeros(n, binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = np.arange(n)
        short = np.zeros(n // 2, binfmt.DNS_REC_DTYPE)
        short["dns_id"] = np.arange(n // 2)
        ev = EvictedFlows(events.copy(), extra=extra, dns=short)
        ev.trace = object()

        out = ctl.admit(ev)
        assert out is not ev
        kept = len(out.events)
        # 1-in-4 sampling: the exact count rides the seeded RNG schedule
        assert 0 < kept < n
        assert abs(kept - n / 4) < 3 * np.sqrt(n * 0.25 * 0.75)
        # surviving rows carry the composed factor
        samp = out.events["stats"]["sampling"]
        src = out.extra["rtt_ns"]  # original row index of each survivor
        assert np.all(samp[src < n // 2] == 4)        # max(0,1)*4
        assert np.all(samp[src >= n // 2] == 12)      # 3*4
        # full lane stays aligned row-for-row with events
        assert np.all(np.diff(src) > 0)
        # a SHORT lane (zero-pad contract) thins over its own prefix, in
        # the same order as the surviving events drawn from that prefix
        n_short_kept = int((src < n // 2).sum())
        assert len(out.dns) == n_short_kept
        assert np.array_equal(out.dns["dns_id"], src[src < n // 2])
        # accounting + trace continuity
        assert ctl.shed_rows == n - kept and ctl.shed_batches == 1
        assert out.trace is ev.trace
        # the source eviction is untouched (admit copies, never aliases)
        assert np.all(events["stats"]["sampling"][: n // 2] == 0)

    def test_shed_fault_point_fires_per_batch(self):
        ctl = overload.OverloadController(256, watermark=1.0)
        ctl.update(4 * 256, 0.0)
        faultinject.arm("sketch.overload_shed", "delay", 0.0)
        ctl.admit(EvictedFlows(make_events(8)))
        assert faultinject.hits.get("sketch.overload_shed") == 1


# ---------------------------------------------------------------------------
# exporter seam (jax)
# ---------------------------------------------------------------------------

from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter  # noqa: E402
from netobserv_tpu.sketch import staging  # noqa: E402
from netobserv_tpu.sketch.state import SketchConfig, state_tables  # noqa: E402

SMALL_CFG = SketchConfig(cm_depth=2, cm_width=1 << 10, hll_precision=6,
                         perdst_buckets=32, perdst_precision=4,
                         persrc_buckets=32, persrc_precision=4,
                         topk=16, hist_buckets=64, ewma_buckets=32)


def make_exporter(metrics=None, sink=None, window_s=3600.0, batch=256,
                  **kw):
    return TpuSketchExporter(batch_size=batch, window_s=window_s,
                             sketch_cfg=SMALL_CFG, metrics=metrics,
                             sink=sink or (lambda obj: None), **kw)


def synth_evictions(n_batches, rows, seed=7, n_distinct=400):
    from netobserv_tpu.datapath.replay import SyntheticFetcher
    f = SyntheticFetcher(flows_per_eviction=rows, n_distinct=n_distinct,
                         zipf_a=1.3, seed=seed)
    return [f.lookup_and_delete() for _ in range(n_batches)]


def host_tables(exp) -> dict:
    import jax
    with exp._lock:
        exp._drain_pending_locked()
    state = jax.block_until_ready(exp._state)
    return {k: np.asarray(v) for k, v in state_tables(state).items()}


class TestExporterSeam:
    def test_disabled_is_the_unshedded_exporter(self):
        exp = make_exporter()
        try:
            assert exp._overload is None
            assert exp._ring.slot_wait_budget_s is None
            assert exp.overloaded is False
            assert exp.overload_snapshot() is None
        finally:
            exp.close()

    def test_idle_controller_is_bit_identical(self):
        """An enabled controller that never crosses its watermark admits
        every batch untouched: device tables bit-equal to the disabled
        exporter over the same feed."""
        evs = synth_evictions(6, 256)
        tables = []
        for kw in ({}, {"shed_watermark": 1e9}):
            exp = make_exporter(**kw)
            try:
                for ev in evs:
                    exp.export_evicted(
                        EvictedFlows(ev.events.copy()))
                tables.append(host_tables(exp))
            finally:
                exp.close()
        a, b = tables
        assert a.keys() == b.keys()
        for k in a:
            assert np.array_equal(a[k], b[k]), f"table {k} drifted"

    def test_shed_ramps_under_pressure_recovers_and_never_retraces(self):
        metrics = Metrics(MetricsSettings())
        exp = make_exporter(metrics=metrics, window_s=0.4,
                            shed_watermark=2.0, shed_max=8)
        try:
            # warm fold first so every later compile would be a retrace
            exp.export_evicted(EvictedFlows(make_events(256)))
            # 4x overdriven evictions against a fault-slowed fold: the
            # seam's wall clock is all fold time (busy ~1), so the 4-batch
            # depth scores >= watermark at every arrival after the first
            faultinject.arm("sketch.ingest", "delay", 0.01)
            for _ in range(6):
                exp.export_evicted(EvictedFlows(make_events(1024)))
            faultinject.clear("sketch.ingest")
            assert exp.overloaded
            snap = exp.overload_snapshot()
            assert snap["shed_factor"] > 1
            assert snap["shed_rows"] > 0
            assert metrics.sketch_shed_factor._value.get() == \
                snap["shed_factor"]
            assert metrics.sketch_shed_rows_total._value.get() > 0
            assert metrics.sketch_shed_batches_total._value.get() > 0
            # pressure stops -> the window timer rolls -> one full clean
            # window later the factor snaps back to 1
            wait_for(lambda: not exp.overloaded, timeout=15,
                     msg="shed factor recovery after pressure cleared")
            assert metrics.sketch_shed_factor._value.get() == 1
        finally:
            exp.close()
        # shedding changed row counts batch to batch; shapes never moved
        for w in retrace.snapshot():
            assert w["retraces"] == 0, w

    def test_shed_is_unbiased_within_cm_error_bounds(self):
        """Frequency and heavy-hitter estimates from a shed run agree with
        the unshed run over the same traffic within the CM error budget:
        the 1-in-N thin is de-biased by the device's sampling lane."""
        evs = synth_evictions(30, 1024, seed=7, n_distinct=400)
        # exact per-key byte totals (all rows unsampled in this feed) plus
        # the per-row values, for the sampling-noise budget below
        exact: dict[bytes, float] = {}
        keyrow: dict[bytes, np.ndarray] = {}
        rows_of: dict[bytes, list] = {}
        for ev in evs:
            for row in ev.events:
                kb = row["key"].tobytes()
                b = float(row["stats"]["bytes"])
                exact[kb] = exact.get(kb, 0.0) + b
                keyrow[kb] = row["key"]
                rows_of.setdefault(kb, []).append(b)
        top = sorted(exact, key=exact.get, reverse=True)[:12]

        def run(pin_shed=None, **kw):
            exp = make_exporter(**kw)
            try:
                if pin_shed is not None:
                    # pin the factor for the whole run: THIS test pins the
                    # thin+de-bias unbiasedness contract under one fixed
                    # RNG schedule; the AIMD dynamics are pinned by the
                    # ramp/recovery/healthy-device tests (a live
                    # controller adapts to the harness's timing, which
                    # would make the keep/drop schedule nondeterministic)
                    ctl = exp._overload
                    ctl.shed = pin_shed
                    ctl.update = lambda *a, **k: pin_shed
                for ev in evs:
                    exp.export_evicted(EvictedFlows(ev.events.copy()))
                with exp._lock:
                    exp._drain_pending_locked()
                import jax
                state = jax.block_until_ready(exp._state)
                # host-side CM point queries via the numpy hash twins.
                # Under the conftest 8-virtual-device mesh the state is
                # owner-sharded: every shard indexes a key identically
                # (the hashes are shard-independent) and exactly one
                # shard took its increments, so summing the per-shard
                # tables reconstructs the union CM bit-exactly; the
                # per-shard top-K candidate sets union by flattening.
                from netobserv_tpu.model.columnar import pack_key_words
                from netobserv_tpu.ops import countmin, hashing
                counts = np.asarray(state.cm_bytes.counts)
                if counts.ndim == 3:  # [shard, d, w]
                    counts = counts.sum(axis=0)
                words = np.stack([pack_key_words(
                    keyrow[kb].reshape(1))[0] for kb in top])
                mh = hashing.base_hashes_multi_np(words)
                est = np.asarray(countmin.query(
                    countmin.CountMin(counts=jax.numpy.asarray(counts)),
                    mh["h1"], mh["h2"]))
                hwords = np.asarray(state.heavy.words)
                hvalid = np.asarray(state.heavy.valid)
                heavy = {tuple(w) for w, v in
                         zip(hwords.reshape(-1, hwords.shape[-1]),
                             hvalid.reshape(-1)) if v}
                shed = (exp._overload.shed_rows
                        if exp._overload is not None else 0)
                return est, heavy, shed
            finally:
                exp.close()

        # the synthetic fetcher aggregates duplicate keys, so each
        # 1024-draw eviction lands a few hundred unique rows — a LOW
        # watermark keeps every arrival over pressure (the AIMD ramp
        # itself is pinned separately; here we want sustained shedding)
        # shed_seed pins ONE deterministic keep/drop schedule; this one's
        # mean deviation sits near 0 (the estimator is unbiased — over 20
        # seeds the grand mean measures -0.002 ± 0.074 — but any single
        # fixed schedule carries its own sampling-noise offset)
        est_a, heavy_a, _ = run()
        est_b, heavy_b, shed_rows = run(shed_watermark=0.5, shed_max=4,
                                        shed_seed=1, pin_shed=4)
        assert shed_rows > 2_000, "the shed run did not actually shed"

        total = sum(exact.values())
        # per-key error budget = CM collision mass (classic eps*V with
        # eps = e/width; common to both runs — same seeds — so only its
        # slack leaks into the difference) + row-sampling noise. The
        # synthetic fetcher aggregates duplicate keys per eviction, so a
        # top key's volume rides ~30 LARGE rows — thinning those 1-in-N
        # has std sqrt((N-1) * sum b_i^2) even though the estimator is
        # unbiased; budget 4 sigma at the worst factor the run reached.
        cm_budget = 2 * np.e * total / SMALL_CFG.cm_width
        shed_hit = 4  # shed_max of the shed run below
        for i, kb in enumerate(top):
            diff = abs(float(est_b[i]) - float(est_a[i]))
            b = np.asarray(rows_of[kb])
            samp_sigma = np.sqrt((shed_hit - 1) * float((b * b).sum()))
            tol = cm_budget + 4 * samp_sigma
            assert diff <= tol, (
                f"key {i}: shed estimate {est_b[i]:.0f} vs unshed "
                f"{est_a[i]:.0f} (diff {diff:.0f} > tol {tol:.0f}; "
                f"exact {exact[kb]:.0f})")
        # UNBIASEDNESS has teeth in aggregate, where the per-key sampling
        # noise averages out: the mean SIGNED relative deviation over the
        # top keys sits near 0 for the de-biased thin, but at ~-(1-1/N)
        # (≈ -0.75 here) if the shed ever forgot to scale `sampling`
        rel = (est_b.astype(float) - est_a.astype(float)) / np.maximum(
            est_a.astype(float), 1.0)
        assert abs(float(rel.mean())) <= 0.15, (
            f"systematic bias: mean relative deviation {rel.mean():+.3f} "
            f"over the top {len(top)} keys (per-key: {np.round(rel, 3)})")
        # heavy-hitter recall of the exact top-8 survives the shed
        from netobserv_tpu.model.columnar import pack_key_words
        top8 = [tuple(pack_key_words(keyrow[kb].reshape(1))[0])
                for kb in top[:8]]
        rec_a = sum(t in heavy_a for t in top8) / len(top8)
        rec_b = sum(t in heavy_b for t in top8) / len(top8)
        assert rec_a >= 0.75, f"unshed recall {rec_a} (harness broken?)"
        assert rec_b >= rec_a - 0.25, (
            f"shed recall {rec_b} collapsed vs unshed {rec_a}")

    def test_healthy_device_with_large_arrivals_does_not_shed(self):
        """Arrival SIZE alone is not pressure: a device that folds
        instantly keeps the seam's busy fraction near 0, zeroing the
        depth term — many-batch evictions on a lightly-loaded agent never
        shed (shedding there would be permanent resolution loss with
        nothing to protect)."""
        exp = make_exporter(shed_watermark=2.0)
        try:
            exp.export_evicted(EvictedFlows(make_events(256)))  # warm
            for _ in range(4):
                time.sleep(0.25)  # idle gaps dwarf the fold time
                exp.export_evicted(EvictedFlows(make_events(1024)))
            assert not exp.overloaded
            snap = exp.overload_snapshot()
            assert snap["shed_rows"] == 0
            assert snap["busy"] < 0.5
        finally:
            exp.close()

    def test_wedged_continuation_adopts_partial_state(self):
        """A slot-wait budget trip on a LATER chunk of a multi-chunk fold
        hands the already-dispatched chunks' state to the exporter
        (StagingWedged.state): earlier dispatches DONATED the pre-fold
        state into the jit, so keeping the old reference would keep
        deleted buffers and poison every later fold."""

        class NeverReady:
            def is_ready(self):
                return False

        # unwarmed k=4 ladder entry: a 4-batch arrival folds as FOUR k=1
        # chunks through one _fold_events call (the multi-chunk seam);
        # astronomically high watermark = controller armed, never shedding
        exp = make_exporter(shed_watermark=1e9, shed_slot_budget_s=0.1,
                            superbatch=(1, 4))
        try:
            exp.export_evicted(EvictedFlows(make_events(256)))  # warm k=1
            pre = exp._state
            ring = exp._ring
            wedge_slot = (ring._slot + 1) % len(ring._tokens)
            real = ring._tokens[wedge_slot]
            ring._tokens[wedge_slot] = NeverReady()
            try:
                # chunk 1 dispatches (donating `pre`), chunk 2 wedges
                exp.export_evicted(EvictedFlows(make_events(1024)))
            finally:
                ring._tokens[wedge_slot] = real
            assert exp._state is not pre, \
                "exporter kept the donated-away pre-fold state"
            # the feed stays usable on the adopted state, and the device
            # accounting shows exactly warm + chunk 1 + the recovery batch
            exp.export_evicted(EvictedFlows(make_events(256)))
            tables = host_tables(exp)
            total = int(np.asarray(tables["scalars"])[0].sum())
            assert total == 256 + 256 + 256
        finally:
            exp.close()

    def test_wedged_device_drops_batch_not_the_feed(self):
        """A staging slot busy past the slot-wait budget raises
        StagingWedged: the batch drops (counted), the exporter thread
        returns within the budget, and the resident dictionary does NOT
        roll its epoch (nothing was packed for the dropped batch)."""

        class NeverReady:
            def is_ready(self):
                return False

        metrics = Metrics(MetricsSettings())
        exp = make_exporter(metrics=metrics, shed_watermark=2.0,
                            shed_slot_budget_s=0.1)
        try:
            assert exp._ring.slot_wait_budget_s == 0.1
            exp.export_evicted(EvictedFlows(make_events(256)))  # warm
            resets_before = exp._ring.dict_resets
            errs_before = metrics.sketch_ingest_errors_total._value.get()
            slot = exp._ring._slot
            real = exp._ring._tokens[slot]
            exp._ring._tokens[slot] = NeverReady()
            try:
                t0 = time.monotonic()
                exp.export_evicted(EvictedFlows(make_events(256)))
                waited = time.monotonic() - t0
            finally:
                exp._ring._tokens[slot] = real
            assert waited < 5.0, f"feed wedged for {waited:.1f}s"
            assert metrics.sketch_ingest_errors_total._value.get() == \
                errs_before + 1
            assert exp._ring.dict_resets == resets_before, \
                "wedged drop must not roll the dictionary epoch"
            # the feed keeps folding once the device recovers
            exp.export_evicted(EvictedFlows(make_events(256)))
        finally:
            exp.close()


# ---------------------------------------------------------------------------
# map-pressure relief + flush race (flow/map_tracer.py)
# ---------------------------------------------------------------------------

import queue  # noqa: E402

from netobserv_tpu.flow import MapTracer  # noqa: E402


class SizedFetcher:
    """Stub fetcher returning a fixed eviction size per drain (and
    counting concurrent drains for the race test)."""

    def __init__(self, rows: int):
        self.rows = rows
        self.calls = 0
        self.concurrent = 0
        self.max_concurrent = 0
        self.block = None  # threading.Event to hold a drain in-flight
        self._lock = threading.Lock()

    def lookup_and_delete(self) -> EvictedFlows:
        with self._lock:
            self.calls += 1
            self.concurrent += 1
            self.max_concurrent = max(self.max_concurrent, self.concurrent)
        try:
            if self.block is not None:
                self.block.wait(5)
            return EvictedFlows(make_events(self.rows))
        finally:
            with self._lock:
                self.concurrent -= 1

    def read_global_counters(self):
        return {}


class TestMapPressure:
    def test_latch_metrics_and_fault_point(self):
        metrics = Metrics(MetricsSettings())
        q: queue.Queue = queue.Queue(maxsize=100)
        tracer = MapTracer(SizedFetcher(90), q, active_timeout_s=60,
                           metrics=metrics, columnar=True,
                           map_capacity=100, pressure_watermark=0.8)
        faultinject.arm("map_tracer.pressure_evict", "delay", 0.0)
        tracer._evict_once()
        assert tracer._pressure_relief is True
        assert metrics.map_pressure_evictions_total._value.get() == 1
        assert faultinject.hits.get("map_tracer.pressure_evict") == 1
        # occupancy histogram saw the 0.9 drain
        assert metrics.map_occupancy_ratio._sum.get() == \
            pytest.approx(0.9)
        # pressure clears when occupancy falls below the watermark
        tracer._fetcher = SizedFetcher(10)
        tracer._evict_once()
        assert tracer._pressure_relief is False

    def test_pressure_halves_the_wait_and_relaxes_back(self):
        q: queue.Queue = queue.Queue(maxsize=100)
        fetcher = SizedFetcher(90)
        tracer = MapTracer(fetcher, q, active_timeout_s=0.2, columnar=True,
                           map_capacity=100, pressure_watermark=0.8)
        waits: list[float] = []
        real_wait = tracer._flush.wait

        def recording_wait(timeout=None):
            waits.append(timeout)
            return real_wait(timeout=min(timeout, 0.02))

        tracer._flush.wait = recording_wait
        tracer.start()
        try:
            wait_for(lambda: fetcher.calls >= 3, msg="pressured drains")
            # first wakeup used the configured period; every wakeup after
            # a pressured drain uses half of it (cadence bounded at 2x)
            assert waits[0] == pytest.approx(0.2)
            assert any(w == pytest.approx(0.1) for w in waits[1:])
            # relief relaxes once occupancy falls below the watermark
            tracer._fetcher = SizedFetcher(10)
            n = len(waits)
            wait_for(lambda: len(waits) > n + 2, msg="relaxed waits")
            assert waits[-1] == pytest.approx(0.2)
        finally:
            tracer.stop(final_evict=False)

    def test_latched_relief_sustains_at_half_watermark(self):
        """Halved drains accumulate roughly half the flows, so a latched
        relief sustains down to watermark/2 instead of oscillating
        latched/clear on alternating drains (any watermark > 0.5 would
        otherwise never hold); an unlatched tracer at the same occupancy
        must NOT latch."""
        q: queue.Queue = queue.Queue(maxsize=100)
        tracer = MapTracer(SizedFetcher(90), q, active_timeout_s=60,
                           columnar=True, map_capacity=100,
                           pressure_watermark=0.8)
        tracer._evict_once()
        assert tracer._pressure_relief is True    # 0.90 >= 0.8: latch
        tracer._fetcher = SizedFetcher(45)
        tracer._evict_once()
        assert tracer._pressure_relief is True    # 0.45 >= 0.4: sustain
        tracer._fetcher = SizedFetcher(30)
        tracer._evict_once()
        assert tracer._pressure_relief is False   # 0.30 < 0.4: clear
        fresh = MapTracer(SizedFetcher(45), q, active_timeout_s=60,
                          columnar=True, map_capacity=100,
                          pressure_watermark=0.8)
        fresh._evict_once()
        assert fresh._pressure_relief is False    # hysteresis only sustains

    def test_disabled_watermark_never_latches(self):
        q: queue.Queue = queue.Queue(maxsize=100)
        tracer = MapTracer(SizedFetcher(100), q, active_timeout_s=60,
                           columnar=True)  # capacity/watermark unset
        tracer._evict_once()
        assert tracer._pressure_relief is False

    def test_flush_racing_timer_eviction(self):
        """One `_evict_lock` holder at a time, no drain is lost: a flush
        raised WHILE a drain is in flight runs as its own drain right
        after — never concurrently, never swallowed."""
        q: queue.Queue = queue.Queue(maxsize=100)
        fetcher = SizedFetcher(4)
        fetcher.block = threading.Event()
        tracer = MapTracer(fetcher, q, active_timeout_s=60, columnar=True)
        tracer.start()
        try:
            tracer.flush()  # first drain: parks inside the fetcher
            wait_for(lambda: fetcher.concurrent == 1, msg="drain in flight")
            tracer.flush()  # raised mid-drain: must not be lost
            # a direct evict (the ringbuf path's flusher analog) must
            # serialize on _evict_lock with the in-flight timer drain
            direct = threading.Thread(target=tracer._evict_once)
            direct.start()
            time.sleep(0.1)
            assert fetcher.max_concurrent == 1, "double-drain"
            fetcher.block.set()
            direct.join(timeout=10)
            assert not direct.is_alive()
            wait_for(lambda: fetcher.calls >= 3, msg="flush honored")
            assert fetcher.max_concurrent == 1
        finally:
            tracer.stop(final_evict=False)
            fetcher.block.set()


class TestAgentWiring:
    def test_map_capacity_falls_back_to_cache_max_flows(self):
        from netobserv_tpu.agent.agent import FlowsAgent
        from netobserv_tpu.config import load_config
        from netobserv_tpu.exporter.base import Exporter

        class NullExporter(Exporter):
            name = "null"

            def export_batch(self, records):
                pass

        cfg = load_config(environ={
            "EXPORT": "stdout", "MAP_PRESSURE_WATERMARK": "0.75",
            "CACHE_MAX_FLOWS": "5000"})
        agent = FlowsAgent(cfg, FakeFetcher(), NullExporter())
        # FakeFetcher has no map_capacity probe: the agent sized the map
        # itself, so CACHE_MAX_FLOWS is the denominator
        assert agent.map_tracer._map_capacity == 5000
        assert agent.map_tracer._pressure_watermark == 0.75


# ---------------------------------------------------------------------------
# OVERLOADED health condition (supervisor + /healthz + /readyz)
# ---------------------------------------------------------------------------


class TestHealthSurface:
    def test_supervisor_condition_registry(self):
        sup = Supervisor(check_period_s=3600)
        assert sup.conditions() == {}
        assert sup.condition_active("overloaded") is False
        state = {"active": True, "shed_factor": 4}
        sup.register_condition("overloaded", lambda: dict(state))
        assert sup.condition_active("overloaded") is True
        assert sup.conditions()["overloaded"]["shed_factor"] == 4
        # a raising probe answers False + error, never raises through
        sup.register_condition("broken", lambda: 1 / 0)
        out = sup.conditions()["broken"]
        assert out["active"] is False and "error" in out
        assert sup.condition_active("broken") is False

    def test_exporter_registers_overloaded_condition(self):
        metrics = Metrics(MetricsSettings())
        exp = make_exporter(metrics=metrics, shed_watermark=2.0,
                            window_s=3600)
        sup = Supervisor(metrics=metrics, check_period_s=3600)
        try:
            exp.register_supervised(sup, heartbeat_timeout_s=60)
            assert sup.condition_active("overloaded") is False
            faultinject.arm("sketch.ingest", "delay", 0.01)
            for _ in range(4):
                exp.export_evicted(EvictedFlows(make_events(1024)))
            faultinject.clear("sketch.ingest")
            assert sup.condition_active("overloaded") is True
            cond = sup.conditions()["overloaded"]
            assert cond["shed_factor"] > 1
            assert cond["shed_rows"] > 0
        finally:
            sup.stop()
            exp.close()

    def test_agent_health_snapshot_hoists_overloaded(self):
        from netobserv_tpu.agent.agent import FlowsAgent
        from netobserv_tpu.config import load_config

        cfg = load_config(environ={
            "EXPORT": "stdout", "SKETCH_SHED_WATERMARK": "2"})
        exp = make_exporter(shed_watermark=2.0)
        agent = FlowsAgent(cfg, FakeFetcher(), exp)
        try:
            snap = agent.health_snapshot()
            assert snap["overloaded"] is False
            assert "conditions" in snap
            faultinject.arm("sketch.ingest", "delay", 0.01)
            for _ in range(4):
                exp.export_evicted(EvictedFlows(make_events(1024)))
            faultinject.clear("sketch.ingest")
            snap = agent.health_snapshot()
            assert snap["overloaded"] is True
            assert snap["degraded"] is False  # distinct conditions
            assert snap["conditions"]["overloaded"]["shed_factor"] > 1
        finally:
            agent.supervisor.stop()
            exp.close()

    def test_healthz_readyz_overloaded_semantics(self):
        """OVERLOADED surfaces in both bodies but fails NEITHER probe: the
        agent is alive and serving (deliberate graceful degradation);
        DEGRADED still fails readiness."""
        from prometheus_client import CollectorRegistry

        from netobserv_tpu.metrics.server import start_metrics_server

        health = {"status": "Started", "degraded": False,
                  "overloaded": True,
                  "conditions": {"overloaded": {"active": True,
                                                "shed_factor": 8}},
                  "stages": {}}
        srv = start_metrics_server(CollectorRegistry(),
                                   address="127.0.0.1", port=0,
                                   health_source=lambda: dict(health))
        try:
            port = srv.server_address[1]

            def get(path):
                try:
                    r = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=5)
                    return r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read())

            code, body = get("/healthz")
            assert code == 200 and body["overloaded"] is True
            assert body["conditions"]["overloaded"]["shed_factor"] == 8
            code, body = get("/readyz")
            assert code == 200, "overload must not pull the agent " \
                                "from rotation"
            health["degraded"] = True
            code, _ = get("/readyz")
            assert code == 503, "DEGRADED still fails readiness"
            code, _ = get("/healthz")
            assert code == 200
        finally:
            srv.shutdown()


class TestOverlapCoupling:
    """Overload controller x overlapped dispatch (ISSUE 11): the AIMD
    pressure score must see the TRUE pending depth — rows buffered + the
    in-hand eviction + rows still queued in the overlap handoff — and
    never count the in-flight eviction twice; and the thin+de-bias
    unbiasedness contract must hold when the unshed traffic rides the
    direct-to-lane route."""

    def test_pressure_depth_counts_handoff_without_double_count(self):
        exp = make_exporter(batch=256, overlap_depth=3,
                            shed_watermark=1e9)  # observe, never shed
        seen_updates: list[int] = []
        try:
            ctl = exp._overload
            orig_update = ctl.update

            def spying_update(pending_rows, wait_p95, busy=1.0):
                seen_updates.append(pending_rows)
                return orig_update(pending_rows, wait_p95, busy=busy)

            ctl.update = spying_update
            # gate the fold worker so three 256-row evictions queue up
            # before ANY is admitted — the real outstanding depth at the
            # first admission is exactly 768 rows
            gate = threading.Event()
            orig_now = exp._export_evicted_now

            def gated_now(evicted):
                assert gate.wait(10)
                orig_now(evicted)

            exp._export_evicted_now = gated_now
            for i in range(3):
                exp.export_evicted(
                    EvictedFlows(make_events(256, sport0=3000 + i)))
            # the worker holds eviction #1 at the gate: the queued count
            # already EXCLUDES the in-hand rows (the no-double-count rule)
            wait_for(lambda: exp._queued_overlap_rows() == 512,
                     msg="worker holding #1, two queued behind")
            gate.set()
            wait_for(lambda: len(seen_updates) == 3, msg="3 admissions")
            wait_for(lambda: exp._queued_overlap_rows() == 0,
                     msg="handoff drained")
            # admission i sees: its own 256 rows + the rows still queued
            # BEHIND it (the in-hand eviction was removed from the
            # in-flight count before its own update — no double count)
            assert seen_updates == [768, 512, 256], seen_updates
        finally:
            gate.set()
            exp.close()

    def test_sync_and_overlap_idle_scores_match(self):
        """An idle system's pressure observation is identical through
        both seams: the overlap path adds zero phantom depth."""
        scores = []
        for depth in (0, 2):
            exp = make_exporter(batch=256, overlap_depth=depth,
                                shed_watermark=1e9)
            try:
                exp.export_evicted(EvictedFlows(make_events(256)))
                if depth:
                    wait_for(lambda: exp._queued_overlap_rows() == 0,
                             msg="handoff drained")
                # exactly one batch in hand, nothing queued: score is the
                # depth term of one batch x busy(0 on the first arrival)
                scores.append(exp._overload.last_score)
            finally:
                exp.close()
        assert scores[0] == scores[1] == 0.0

    def test_unbiased_through_direct_route(self):
        """Batch-aligned evictions (the direct-to-lane route when unshed)
        against the same traffic thinned at a pinned factor: the
        de-biased total_bytes agree within sampling noise — the direct
        route composes with the sampling de-bias. A shed that forgot to
        scale `sampling` would read ~-50% here."""
        import jax
        evs = [make_events(512, sport0=1000 + 32 * i, nbytes=200)
               for i in range(12)]
        exact_bytes = 12 * 512 * 200.0
        totals = []
        for pin in (None, 2):
            exp = make_exporter(batch=256,
                                **({} if pin is None
                                   else {"shed_watermark": 0.5,
                                         "shed_max": 4}))
            try:
                if pin is not None:
                    ctl = exp._overload
                    ctl.shed = pin
                    ctl.update = lambda *a, **k: pin
                for rows in evs:
                    exp.export_evicted(EvictedFlows(rows.copy()))
                with exp._lock:
                    exp._drain_pending_locked()
                if pin is None:
                    # the unshed arm really rode the direct route
                    assert exp._pending_buf.direct_rows == 12 * 512
                else:
                    assert exp._overload.shed_rows > 1000
                state = jax.block_until_ready(exp._state)
                # owner-sharded under the conftest mesh: per-shard totals
                # sum to the union scalar
                totals.append(float(np.asarray(state.total_bytes).sum()))
            finally:
                exp.close()
        unshed, shed = totals
        assert abs(unshed - exact_bytes) / exact_bytes < 0.01
        rel = (shed - unshed) / unshed
        # Bernoulli(1/2) thin with x2 compensation over 6144 rows of 200B:
        # sigma ~ 50KB on 1.23MB (~4%); 20% tolerance has teeth against
        # the -50% forgot-to-scale failure
        assert abs(rel) < 0.20, f"biased through the direct route: {rel:+.3f}"


# ---------------------------------------------------------------------------
# slow tier: 4x overdriven soak against a fault-slowed device
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_overdriven_feed_bounded_sheds_and_recovers():
    """The acceptance soak: a feed arriving ~4x faster than the
    fault-slowed device folds keeps memory bounded (the pending buffer
    never grows past its preallocated capacity), sheds (OVERLOADED
    active), keeps publishing windows, and recovers to shed=1 within one
    window of the pressure clearing — with zero post-warmup retraces."""
    import resource

    reports: list = []
    metrics = Metrics(MetricsSettings())
    exp = make_exporter(metrics=metrics, window_s=0.8,
                        sink=lambda obj: reports.append(obj),
                        shed_watermark=2.0, shed_max=64)
    try:
        exp.export_evicted(EvictedFlows(make_events(256)))  # warm
        faultinject.arm("sketch.ingest", "delay", 0.01)
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        max_pending = 0
        t_end = time.monotonic() + 4.0
        i = 0
        while time.monotonic() < t_end:
            # each arrival is 4 batches' worth against a device whose
            # every fold eats an injected 10ms
            exp.export_evicted(EvictedFlows(
                make_events(1024, sport0=1000 + (i % 40))))
            max_pending = max(max_pending, exp._pending_buf.n)
            i += 1
        assert exp.overloaded, "the soak never tripped the controller"
        assert exp.overload_snapshot()["shed_rows"] > 0
        # bounded memory: the accumulator is preallocated and never grew
        assert max_pending <= exp._pending_buf.capacity
        rss_growth_mb = (resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss - rss0) / 1024
        assert rss_growth_mb < 500, f"RSS grew {rss_growth_mb:.0f}MB"
        faultinject.clear("sketch.ingest")
        # pressure cleared: recovery within one clean window
        wait_for(lambda: not exp.overloaded, timeout=20,
                 msg="recovery after the overdrive stopped")
        wait_for(lambda: len(reports) >= 2, timeout=20,
                 msg="window reports under overload")
    finally:
        faultinject.clear()
        exp.close()
    for w in retrace.snapshot():
        assert w["retraces"] == 0, w
