"""Fake-driven end-to-end pipeline tests (reference analog:
`pkg/agent/agent_test.go` — full in-process pipeline over injected data)."""

import io
import json
import queue
import threading
import time

import numpy as np
import pytest

from netobserv_tpu.agent import FlowsAgent, Status
from netobserv_tpu.config import load_config
from netobserv_tpu.datapath.fetcher import EvictedFlows, FakeFetcher
from netobserv_tpu.exporter.base import Exporter
from netobserv_tpu.exporter.stdout_json import StdoutJSONExporter
from netobserv_tpu.model import binfmt
from netobserv_tpu.model.flow import GlobalCounter, ip_to_16


def make_events(n, sport0=1000, nbytes=100):
    events = np.zeros(n, dtype=binfmt.FLOW_EVENT_DTYPE)
    now = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
    for i in range(n):
        events[i]["key"]["src_ip"] = np.frombuffer(ip_to_16("10.0.0.1"), np.uint8)
        events[i]["key"]["dst_ip"] = np.frombuffer(ip_to_16("10.0.0.2"), np.uint8)
        events[i]["key"]["src_port"] = sport0 + i
        events[i]["key"]["dst_port"] = 443
        events[i]["key"]["proto"] = 6
        events[i]["stats"]["bytes"] = nbytes
        events[i]["stats"]["packets"] = 2
        events[i]["stats"]["first_seen_ns"] = now - 10**9
        events[i]["stats"]["last_seen_ns"] = now
        events[i]["stats"]["eth_protocol"] = 0x0800
        events[i]["stats"]["if_index_first"] = 1
    return events


class CollectExporter(Exporter):
    name = "collect"

    def __init__(self):
        self.batches: "queue.Queue[list]" = queue.Queue()

    def export_batch(self, records):
        self.batches.put(records)


def make_agent(fake, exporter, **env):
    cfg = load_config(environ={
        "EXPORT": "stdout", "CACHE_ACTIVE_TIMEOUT": "100ms",
        "BUFFERS_LENGTH": "10", **env})
    return FlowsAgent(cfg, fake, exporter)


class TestAgentPipeline:
    def test_end_to_end_map_path(self):
        fake = FakeFetcher()
        out = CollectExporter()
        agent = make_agent(fake, out)
        stop = threading.Event()
        t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
        t.start()
        try:
            fake.bump_counter(GlobalCounter.FILTER_ACCEPT, 5)
            fake.inject_events(make_events(3))
            batch = out.batches.get(timeout=3)
            assert len(batch) == 3
            assert batch[0].key.src == "10.0.0.1"
            assert batch[0].bytes_ == 100
            assert agent.status == Status.STARTED
        finally:
            stop.set()
            t.join(timeout=5)
        assert agent.status == Status.STOPPED
        assert fake.closed

    def test_ringbuf_fallback_path(self):
        fake = FakeFetcher()
        out = CollectExporter()
        # a 2s accounter window: both pre-queued singles are always accounted
        # long before the first eviction, even under heavy host load
        agent = make_agent(fake, out, ENABLE_FLOWS_RINGBUF_FALLBACK="true",
                           CACHE_ACTIVE_TIMEOUT="2s")
        # two ringbuf singles for the same flow must be re-aggregated; queue
        # them BEFORE the agent starts so they land in one accounter window
        ev = make_events(1, nbytes=40)
        fake.inject_ringbuf(ev)
        fake.inject_ringbuf(ev)
        stop = threading.Event()
        t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 8
            merged = None
            while time.monotonic() < deadline:
                try:
                    batch = out.batches.get(timeout=0.5)
                except queue.Empty:
                    continue
                for r in batch:
                    if r.packets:
                        merged = r
                if merged and merged.bytes_ == 80:
                    break
            assert merged is not None
            assert merged.bytes_ == 80  # accumulated, not duplicated
            assert merged.packets == 4
        finally:
            stop.set()
            t.join(timeout=5)

    def test_final_eviction_on_shutdown(self):
        fake = FakeFetcher()
        out = CollectExporter()
        agent = make_agent(fake, out, CACHE_ACTIVE_TIMEOUT="30s")
        stop = threading.Event()
        t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
        t.start()
        time.sleep(0.2)
        # injected after start; ticker (30s) won't fire — shutdown must drain
        fake.inject_events(make_events(2))
        stop.set()
        t.join(timeout=5)
        batch = out.batches.get(timeout=1)
        assert len(batch) == 2


class TestStdoutExporter:
    def test_json_lines(self):
        from netobserv_tpu.model.record import records_from_events
        buf = io.StringIO()
        exp = StdoutJSONExporter(stream=buf)
        recs = records_from_events(make_events(2))
        exp.export_batch(recs)
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert len(lines) == 2
        assert lines[0]["SrcAddr"] == "10.0.0.1"
        assert lines[0]["DstPort"] == 443

    def test_flp_map_format(self):
        from netobserv_tpu.exporter.flp_map import record_to_map
        from netobserv_tpu.model.record import records_from_events
        recs = records_from_events(make_events(1))
        m = record_to_map(recs[0])
        assert m["SrcAddr"] == "10.0.0.1"
        assert m["Proto"] == 6
        assert m["SrcMac"] == "00:00:00:00:00:00"
        assert "TimeFlowStartMs" in m and "AgentIP" in m


class TestTpuSketchExporter:
    def test_reports_heavy_hitters(self):
        from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
        from netobserv_tpu.model.record import records_from_events
        from netobserv_tpu.sketch.state import SketchConfig

        reports = []
        exp = TpuSketchExporter(
            batch_size=64, window_s=3600,  # manual window close
            sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                    hll_precision=6, perdst_buckets=32,
                                    perdst_precision=4, topk=16,
                                    hist_buckets=64, ewma_buckets=32),
            mesh_shape="", sink=reports.append)
        # one elephant flow + background
        elephant = make_events(1, sport0=7777, nbytes=1_000_000)
        exp.export_batch(records_from_events(elephant))
        exp.export_batch(records_from_events(make_events(30, nbytes=10)))
        exp.flush()
        assert len(reports) == 1
        rep = reports[0]
        assert rep["Type"] == "sketch_window_report"
        assert rep["Records"] == 31
        top = rep["HeavyHitters"][0]
        assert top["SrcPort"] == 7777
        assert top["EstBytes"] >= 1_000_000
        assert rep["DistinctSrcEstimate"] > 0

    def test_columnar_fast_path(self):
        from netobserv_tpu.datapath.fetcher import EvictedFlows
        from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
        from netobserv_tpu.sketch.state import SketchConfig

        reports = []
        exp = TpuSketchExporter(
            batch_size=8192,  # larger than the injected evictions: the
            # window drain must still fold the partial batch
            window_s=3600,
            sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                    hll_precision=6, perdst_buckets=32,
                                    perdst_precision=4, topk=16,
                                    hist_buckets=64, ewma_buckets=32),
            sink=reports.append)
        assert exp.supports_columnar
        import numpy as np

        from netobserv_tpu.model import binfmt
        extra = np.zeros(3, dtype=binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = [5_000_000, 1_000_000, 9_000_000]
        exp.export_evicted(EvictedFlows(make_events(3), extra=extra))
        exp.export_evicted(EvictedFlows(make_events(2, sport0=9000)))
        exp.flush()
        assert len(reports) == 1
        rep = reports[0]
        assert rep["Records"] == 5
        # rtt feature column reached the histogram (values in ms range)
        assert rep["RttQuantilesUs"]["0.99"] > 1000

    def test_window_rolls_and_resets(self):
        from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
        from netobserv_tpu.model.record import records_from_events
        from netobserv_tpu.sketch.state import SketchConfig

        reports = []
        exp = TpuSketchExporter(
            batch_size=8, window_s=3600,
            sketch_cfg=SketchConfig(cm_depth=2, cm_width=256, hll_precision=6,
                                    perdst_buckets=32, perdst_precision=4,
                                    topk=8, hist_buckets=64, ewma_buckets=32),
            sink=reports.append)
        exp.export_batch(records_from_events(make_events(5)))
        exp.flush()
        exp.export_batch(records_from_events(make_events(7)))
        exp.flush()
        assert [r["Window"] for r in reports] == [0, 1]
        assert reports[0]["Records"] == 5
        assert reports[1]["Records"] == 7  # reset between windows


class TestDecayWindows:
    def test_decay_keeps_half_the_mass(self):
        from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
        from netobserv_tpu.model.record import records_from_events
        from netobserv_tpu.sketch.state import SketchConfig

        reports = []
        exp = TpuSketchExporter(
            batch_size=8, window_s=3600, decay_factor=0.5,
            sketch_cfg=SketchConfig(cm_depth=2, cm_width=256, hll_precision=6,
                                    perdst_buckets=32, perdst_precision=4,
                                    topk=8, hist_buckets=64, ewma_buckets=32),
            sink=reports.append)
        exp.export_batch(records_from_events(make_events(4, nbytes=1000)))
        exp.flush()
        exp.flush()  # no new traffic: the decayed mass remains visible
        assert reports[0]["Bytes"] == 4000
        assert reports[1]["Bytes"] == 2000  # decayed by 0.5, not reset to 0
        # heavy-hitter table survives decay AND its counts decay consistently
        assert len(reports[1]["HeavyHitters"]) > 0
        assert reports[1]["HeavyHitters"][0]["EstBytes"] == 500.0
        total_hh = sum(h["EstBytes"] for h in reports[1]["HeavyHitters"])
        assert total_hh <= reports[1]["Bytes"] + 1e-6


def test_port_scan_surfaces_in_exporter_window_report():
    """Agent-level scan detection: a scanning source fed through the FULL
    TpuSketchExporter pipeline (records -> batches -> device fold -> window
    roll -> JSON sink) must surface in PortScanSuspectBuckets."""
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig

    def rec(src, dst, dport):
        return Record(
            key=FlowKey.make(src, dst, 40000, dport, 6), bytes_=60,
            packets=1, eth_protocol=0x0800, tcp_flags=0x02, direction=1,
            src_mac=b"\x02" * 6, dst_mac=b"\x04" * 6, if_index=3,
            interface="eth0", dscp=0, sampling=0,
            agent_ip="192.0.2.1")

    reports = []
    exp = TpuSketchExporter(
        batch_size=128, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=32, persrc_buckets=64,
                                persrc_precision=6),
        mesh_shape="", sink=reports.append,
        scan_fanout_threshold=200)
    # the scanner: one source sweeping 1024 distinct (dst, port) pairs
    scan = [rec("10.9.9.9", f"10.0.{i % 250}.{i // 250 + 1}", 1 + i % 1024)
            for i in range(1024)]
    # normal client
    normal = [rec("10.1.1.1", "10.2.2.2", 443) for _ in range(32)]
    exp.export_batch(scan)
    exp.export_batch(normal)
    exp.flush()
    assert reports, "no window report emitted"
    suspects = reports[-1]["PortScanSuspectBuckets"]
    assert suspects, "scanner not reported through the exporter pipeline"
    assert suspects[0]["distinct_dst_port_pairs"] > 500
    exp.close()


def test_syn_flood_surfaces_in_exporter_window_report():
    """Agent-level SYN-flood detection: a spoofed flood (many half-open SYN
    records to one victim, few SYN-ACK responses) through the FULL
    TpuSketchExporter pipeline must surface in SynFloodSuspectBuckets;
    a busy-but-healthy service (every SYN answered) must not."""
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig

    def rec(src, dst, sport, dport, flags):
        return Record(
            key=FlowKey.make(src, dst, sport, dport, 6), bytes_=60,
            packets=1, eth_protocol=0x0800, tcp_flags=flags, direction=1,
            src_mac=b"\x02" * 6, dst_mac=b"\x04" * 6, if_index=3,
            interface="eth0", dscp=0, sampling=0, agent_ip="192.0.2.1")

    reports = []
    exp = TpuSketchExporter(
        batch_size=128, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=64),
        sink=reports.append, synflood_min=64, synflood_ratio=8.0)
    victim = "10.0.0.5"
    # the flood: 512 spoofed sources, SYN never ACKed (half-open), and the
    # victim manages only a handful of SYN-ACK responses
    flood = [rec(f"172.16.{i % 200}.{i % 250 + 1}", victim,
                 1024 + i, 80, 0x02) for i in range(512)]
    flood += [rec(victim, f"172.16.0.{i + 1}", 80, 2000 + i, 0x112)
              for i in range(4)]
    # a busy healthy service: 200 clients, every handshake completes (client
    # flows carry SYN|ACK, server responses carry SYN-ACK)
    healthy = [rec(f"10.7.0.{i % 250 + 1}", "10.0.0.9", 3000 + i, 443, 0x12)
               for i in range(200)]
    healthy += [rec("10.0.0.9", f"10.7.0.{i % 250 + 1}", 443, 3000 + i, 0x112)
                for i in range(200)]
    exp.export_batch(flood)
    exp.export_batch(healthy)
    exp.flush()  # close() below rolls one more (empty) window
    assert reports, "no window report emitted"
    suspects = reports[0]["SynFloodSuspectBuckets"]
    assert suspects, "flood not reported through the exporter pipeline"
    assert suspects[0]["syn"] >= 500
    assert suspects[0]["synack"] <= 8
    # exactly the victim's bucket: the healthy service bucket stays quiet
    assert len(suspects) == 1
    exp.close()


def test_drop_storm_surfaces_in_exporter_window_report():
    """Agent-level drop-anomaly detection over the COLUMNAR fast path: two
    calm windows seed the EWMA baseline, then a drop storm (kernel drops
    record array riding the eviction) must push the victim bucket's
    dropped-bytes z-score over the threshold and surface cause totals."""
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.sketch.state import SketchConfig

    reports = []
    exp = TpuSketchExporter(
        batch_size=64, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=64),
        sink=reports.append, drop_z_threshold=6.0)

    def evict(drop_bytes, cause=2):
        ev = make_events(64)
        drops = np.zeros(64, dtype=binfmt.DROPS_REC_DTYPE)
        if drop_bytes:
            drops["bytes"] = drop_bytes
            drops["packets"] = 3
            drops["latest_cause"] = cause
        return EvictedFlows(ev, drops=drops if drop_bytes else None)

    for _ in range(2):  # calm baseline windows (EWMA warmup)
        exp.export_evicted(evict(0))
        exp.flush()
    exp.export_evicted(evict(1400, cause=5))
    exp.flush()  # close() below rolls one more (empty) window
    storm = reports[2]
    assert storm["DropBytes"] == 1400.0 * 64
    assert storm["DropPackets"] == 3.0 * 64
    assert storm["DropCauses"] == {"5": 3.0 * 64}
    assert storm["DropAnomalyBuckets"], "drop storm not reported"
    calm = reports[1]
    assert calm["DropBytes"] == 0.0 and not calm["DropAnomalyBuckets"]
    exp.close()


def test_decay_preserves_signal_planes():
    """Decay-mode window rolls must treat the feature-lane planes
    consistently: linear histograms (drop causes, DSCP bytes) decay like
    the latency hists; the SYN-ACK window accumulator resets with its
    paired EWMA rate; totals decay."""
    import numpy as np

    from netobserv_tpu.sketch import state as sk

    cfg = sk.SketchConfig(cm_width=1 << 10, topk=16, ewma_buckets=32)
    n = 16
    arrays = {
        "keys": np.random.default_rng(0).integers(
            0, 2**32, (n, 10)).astype(np.uint32),
        "bytes": np.full(n, 100.0, np.float32),
        "packets": np.ones(n, np.int32),
        "rtt_us": np.zeros(n, np.int32),
        "dns_latency_us": np.zeros(n, np.int32),
        "sampling": np.zeros(n, np.int32),
        "valid": np.ones(n, np.bool_),
        "tcp_flags": np.full(n, 0x102, np.int32),  # SYN-ACK responses
        "dscp": np.full(n, 46, np.int32),
        "markers": np.full(n, 3, np.int32),        # quic + nat
        "drop_bytes": np.full(n, 10, np.int32),
        "drop_packets": np.ones(n, np.int32),
        "drop_cause": np.full(n, 4, np.int32),
    }
    s = sk.ingest(sk.init_state(cfg), arrays)
    assert float(s.synack.sum()) == n
    s2 = sk.decay_state(s, 0.5)
    assert float(s2.drop_causes.sum()) == n / 2        # linear: decays
    assert float(s2.dscp_bytes.sum()) == 100.0 * n / 2
    assert float(s2.total_drop_bytes) == 10 * n / 2
    assert float(s2.quic_records) == n / 2
    assert float(s2.nat_records) == n / 2
    assert float(s2.synack.sum()) == 0.0               # paired w/ EWMA rate


def test_window_analytics_gauges():
    """Window rolls publish last-window analytics to Prometheus (records,
    drop bytes, suspect counts per signal) so operators can alert off the
    metrics endpoint, not only the JSON stream."""
    from prometheus_client import CollectorRegistry

    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.metrics.registry import Metrics, MetricsSettings
    from netobserv_tpu.sketch.state import SketchConfig

    m = Metrics(MetricsSettings(), registry=CollectorRegistry())
    exp = TpuSketchExporter(
        batch_size=64, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=64),
        sink=lambda rep: None, metrics=m)
    ev = make_events(40)
    drops = np.zeros(40, dtype=binfmt.DROPS_REC_DTYPE)
    drops["bytes"] = 100
    drops["packets"] = 1
    exp.export_evicted(EvictedFlows(ev, drops=drops))
    exp.flush()  # close() rolls one more (empty) window afterwards
    assert m.sketch_window_records._value.get() == 40.0
    assert m.sketch_window_drop_bytes._value.get() == 100.0 * 40
    for sig in ("ddos", "port_scan", "syn_flood", "drop_storm"):
        assert m.sketch_window_suspects.labels(sig)._value.get() == 0.0
    exp.close()
    assert m.sketch_window_records._value.get() == 0.0  # last window wins


def test_ingest_never_retraces_across_windows():
    """CLAUDE.md invariant pinned: folding evictions of VARYING live counts
    (padding), rolling windows, and folding again must all hit ONE compiled
    ingest executable — a retrace would silently tank steady-state rate."""
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.sketch.state import SketchConfig

    exp = TpuSketchExporter(
        batch_size=64, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=32),
        sink=lambda rep: None)
    # warm: first fold compiles; a donated-state layout respecialization
    # may add ONE more executable on call 2 — steady state starts here
    for n in (64, 17):
        exp.export_evicted(EvictedFlows(make_events(n)))
        exp.flush()
    ingest_jit = exp._ring._ingest
    warm = ingest_jit._cache_size()
    assert warm <= 2, f"ingest compiled {warm} variants during warmup"
    for n in (64, 3, 64, 17, 5):
        exp.export_evicted(EvictedFlows(make_events(n)))
        exp.flush()  # windows roll between batches too
    assert ingest_jit._cache_size() == warm, "steady-state ingest retraced"
    fallback = getattr(exp._ring, "_ingest_fallback", None)
    if fallback is not None:
        assert fallback._cache_size() == 0, "dense fallback ran unexpectedly"
    exp.close()


def test_one_way_conversation_surfaces_in_exporter_window_report():
    """Conversation-asymmetry detection through the FULL exporter pipeline:
    a one-way elephant transfer (A->B only) must surface in
    AsymmetricConversationBuckets; a balanced conversation (both directions)
    must not — regardless of flow direction order."""
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig

    def rec(src, dst, sport, dport, nbytes):
        return Record(
            key=FlowKey.make(src, dst, sport, dport, 17), bytes_=nbytes,
            packets=max(1, nbytes // 1400), eth_protocol=0x0800, tcp_flags=0,
            direction=1, src_mac=b"\x02" * 6, dst_mac=b"\x04" * 6,
            if_index=3, interface="eth0", dscp=0, sampling=0,
            agent_ip="192.0.2.1")

    reports = []
    exp = TpuSketchExporter(
        batch_size=16, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=64),
        sink=reports.append, asym_min_bytes=1 << 20, asym_ratio=0.95)
    # one-way elephant: 4MB A->B, nothing back
    flows = [rec("10.5.0.1", "10.5.0.2", 5001, 5002, 1 << 20)
             for _ in range(4)]
    # balanced conversation, larger than the floor in BOTH directions
    flows += [rec("10.6.0.1", "10.6.0.2", 6001, 6002, 1 << 20),
              rec("10.6.0.2", "10.6.0.1", 6002, 6001, (1 << 20) - 4096)]
    exp.export_batch(flows)
    exp.flush()
    asym = reports[0]["AsymmetricConversationBuckets"]
    assert len(asym) == 1, f"expected exactly the one-way pair: {asym}"
    assert asym[0]["bytes"] == float(4 << 20)
    assert asym[0]["one_way_share"] == 1.0
    exp.close()


def test_hairpin_conversations_excluded_from_asymmetry():
    """src == dst traffic (hairpin NAT / loopback capture) has no
    meaningful direction — it must not fire a one-way alert."""
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.model.flow import FlowKey
    from netobserv_tpu.model.record import Record
    from netobserv_tpu.sketch.state import SketchConfig

    reports = []
    exp = TpuSketchExporter(
        batch_size=8, window_s=3600,
        sketch_cfg=SketchConfig(cm_depth=2, cm_width=1 << 10,
                                hll_precision=6, perdst_buckets=32,
                                perdst_precision=4, topk=16, hist_buckets=64,
                                ewma_buckets=64),
        sink=reports.append, asym_min_bytes=1 << 20)
    hair = [Record(key=FlowKey.make("10.9.9.9", "10.9.9.9", 4000 + d, 4001, 17),
                   bytes_=2 << 20, packets=9, eth_protocol=0x0800,
                   tcp_flags=0, direction=d % 2, src_mac=b"\x02" * 6,
                   dst_mac=b"\x04" * 6, if_index=3, interface="lo", dscp=0,
                   sampling=0, agent_ip="192.0.2.1") for d in range(4)]
    exp.export_batch(hair)
    exp.flush()
    assert reports[0]["AsymmetricConversationBuckets"] == []
    exp.close()


def test_feed_formats_agree_on_window_totals():
    """SKETCH_FEED=resident|compact|dense are three transports for the SAME
    math: identical evictions must produce identical window totals and
    heavy-hitter sets through the production exporter."""
    import numpy as np

    from netobserv_tpu.datapath.fetcher import EvictedFlows
    from netobserv_tpu.exporter.tpu_sketch import TpuSketchExporter
    from netobserv_tpu.model import binfmt
    from netobserv_tpu.sketch.state import SketchConfig

    cfg = SketchConfig(cm_depth=2, cm_width=1 << 10, hll_precision=6,
                       perdst_buckets=32, perdst_precision=4, topk=16,
                       hist_buckets=64, ewma_buckets=32)
    reports = {}
    for feed in ("resident", "compact", "dense"):
        out = []
        exp = TpuSketchExporter(batch_size=64, window_s=3600,
                                sketch_cfg=cfg, sink=out.append, feed=feed)
        extra = np.zeros(8, dtype=binfmt.EXTRA_REC_DTYPE)
        extra["rtt_ns"] = 2_000_000
        exp.export_evicted(EvictedFlows(make_events(8), extra=extra))
        exp.export_evicted(EvictedFlows(make_events(5, sport0=9000,
                                                    nbytes=50_000)))
        exp.flush()
        assert len(out) == 1, feed
        reports[feed] = out[0]
    base = reports["dense"]
    for feed in ("resident", "compact"):
        rep = reports[feed]
        assert rep["Records"] == base["Records"] == 13, feed
        assert rep["Bytes"] == base["Bytes"], feed
        hh = lambda r: {(h["SrcAddr"], h["SrcPort"], h["EstBytes"])
                        for h in r["HeavyHitters"]}
        assert hh(rep) == hh(base), feed
