"""PCA mode: packet pipeline e2e over fakes + in-process pbpacket collector
(reference analog: the PCA paths of `pkg/agent/packets_agent.go` tests)."""

import importlib.util
import queue
import struct
import threading
import time

import numpy as np
import pytest

#: the TLS legs mint a self-signed cert with `cryptography`, which this
#: image doesn't ship — they SKIP (visible in -rs) instead of erroring, so
#: tier-1 is genuinely green; the plaintext e2e tests below still run
needs_cryptography = pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="cryptography not installed (TLS cert minting)")

from netobserv_tpu.agent.packets_agent import FakePacketFetcher, PacketsAgent
from netobserv_tpu.config import load_config
from netobserv_tpu.exporter.grpc_packets import (
    GRPCPacketExporter, PacketClient, start_packet_collector,
)
from netobserv_tpu.model import binfmt
from netobserv_tpu.model.packet_record import PCAP_MAGIC


def make_packet_event(payload=b"\xaa" * 60, if_index=3):
    ev = np.zeros(1, dtype=binfmt.PACKET_EVENT_DTYPE)
    ev[0]["if_index"] = if_index
    ev[0]["pkt_len"] = len(payload)
    ev[0]["timestamp_ns"] = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
    ev[0]["payload"][:len(payload)] = np.frombuffer(payload, np.uint8)
    return ev.tobytes()


def test_packets_agent_end_to_end():
    server, port, out = start_packet_collector(0)
    try:
        cfg = load_config(environ={
            "EXPORT": "stdout", "ENABLE_PCA": "true",
            "TARGET_HOST": "127.0.0.1", "PCA_SERVER_PORT": str(port)})
        assert cfg.target_port == port  # deprecated-shim wiring
        fake = FakePacketFetcher()
        agent = PacketsAgent(
            cfg, fake, exporter=GRPCPacketExporter(
                "127.0.0.1", port, client=PacketClient("127.0.0.1", port)))
        stop = threading.Event()
        t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
        t.start()
        fake.inject(make_packet_event(b"\x01\x02\x03\x04" * 16))
        fake.inject(make_packet_event(b"\xff" * 80))
        # first message is the pcap file header
        header = out.get(timeout=5)
        magic = struct.unpack("<I", header[:4])[0]
        assert magic == PCAP_MAGIC
        pkt1 = out.get(timeout=5)
        # pcap per-packet header: ts_sec ts_usec incl orig
        _ts, _us, incl, orig = struct.unpack("<IIII", pkt1[:16])
        assert incl == orig == 64
        assert pkt1[16:20] == b"\x01\x02\x03\x04"
        stop.set()
        t.join(timeout=5)
    finally:
        server.stop(0)


def _self_signed(tmpdir, cn="localhost"):
    """One self-signed cert (CA == server cert, SAN localhost) — the same
    shape the reference e2e uses for its TLS legs."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(x509.SubjectAlternativeName(
                [x509.DNSName(cn)]), critical=False)
            .sign(key, hashes.SHA256()))
    cert_path = str(tmpdir / "tls.crt")
    key_path = str(tmpdir / "tls.key")
    with open(cert_path, "wb") as fh:
        fh.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as fh:
        fh.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return cert_path, key_path


@needs_cryptography
def test_pca_export_over_tls(tmp_path):
    """The packet client takes the same TLS options as the flow client
    (reference: pkg/grpc/packet/client.go) — a pcap stream over a secured
    channel must arrive intact."""
    cert, key = _self_signed(tmp_path)
    server, port, out = start_packet_collector(0, tls_cert=cert, tls_key=key)
    try:
        client = PacketClient("localhost", port, tls_ca=cert)
        exp = GRPCPacketExporter("localhost", port, client=client)
        from netobserv_tpu.model.packet_record import PacketRecord
        exp.export_packets([PacketRecord(
            if_index=1, timestamp_ns=123_000_000_000,
            payload=b"\xde\xad\xbe\xef" * 16)])
        header = out.get(timeout=10)
        assert struct.unpack("<I", header[:4])[0] == PCAP_MAGIC
        pkt = out.get(timeout=10)
        assert pkt[16:20] == b"\xde\xad\xbe\xef"
        exp.close()
    finally:
        server.stop(0)


@needs_cryptography
def test_pca_export_plaintext_rejected_by_tls_collector(tmp_path):
    """A plaintext client against the TLS collector must fail, proving the
    channel really is secured (not silently falling back)."""
    import grpc
    import pytest

    cert, key = _self_signed(tmp_path)
    server, port, out = start_packet_collector(0, tls_cert=cert, tls_key=key)
    try:
        plain = PacketClient("localhost", port)
        with pytest.raises(grpc.RpcError):
            plain.send_bytes(b"x", timeout_s=5)
        plain.close()
    finally:
        server.stop(0)


def test_perf_buffer_batches_by_timeout():
    from netobserv_tpu.flow.perf_buffer import PerfBuffer
    from netobserv_tpu.model.packet_record import PacketRecord
    inq, outq = queue.Queue(), queue.Queue()
    buf = PerfBuffer(inq, outq, max_batch=100, timeout_s=0.2)
    buf.start()
    try:
        inq.put(PacketRecord(1, 0, b"x"))
        inq.put(PacketRecord(1, 0, b"y"))
        batch = outq.get(timeout=2)
        assert [p.payload for p in batch] == [b"x", b"y"]
    finally:
        buf.stop()
