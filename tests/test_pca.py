"""PCA mode: packet pipeline e2e over fakes + in-process pbpacket collector
(reference analog: the PCA paths of `pkg/agent/packets_agent.go` tests)."""

import queue
import struct
import threading
import time

import numpy as np

from netobserv_tpu.agent.packets_agent import FakePacketFetcher, PacketsAgent
from netobserv_tpu.config import load_config
from netobserv_tpu.exporter.grpc_packets import (
    GRPCPacketExporter, PacketClient, start_packet_collector,
)
from netobserv_tpu.model import binfmt
from netobserv_tpu.model.packet_record import PCAP_MAGIC


def make_packet_event(payload=b"\xaa" * 60, if_index=3):
    ev = np.zeros(1, dtype=binfmt.PACKET_EVENT_DTYPE)
    ev[0]["if_index"] = if_index
    ev[0]["pkt_len"] = len(payload)
    ev[0]["timestamp_ns"] = time.clock_gettime_ns(time.CLOCK_MONOTONIC)
    ev[0]["payload"][:len(payload)] = np.frombuffer(payload, np.uint8)
    return ev.tobytes()


def test_packets_agent_end_to_end():
    server, port, out = start_packet_collector(0)
    try:
        cfg = load_config(environ={
            "EXPORT": "stdout", "ENABLE_PCA": "true",
            "TARGET_HOST": "127.0.0.1", "PCA_SERVER_PORT": str(port)})
        assert cfg.target_port == port  # deprecated-shim wiring
        fake = FakePacketFetcher()
        agent = PacketsAgent(
            cfg, fake, exporter=GRPCPacketExporter(
                "127.0.0.1", port, client=PacketClient("127.0.0.1", port)))
        stop = threading.Event()
        t = threading.Thread(target=agent.run, args=(stop,), daemon=True)
        t.start()
        fake.inject(make_packet_event(b"\x01\x02\x03\x04" * 16))
        fake.inject(make_packet_event(b"\xff" * 80))
        # first message is the pcap file header
        header = out.get(timeout=5)
        magic = struct.unpack("<I", header[:4])[0]
        assert magic == PCAP_MAGIC
        pkt1 = out.get(timeout=5)
        # pcap per-packet header: ts_sec ts_usec incl orig
        _ts, _us, incl, orig = struct.unpack("<IIII", pkt1[:16])
        assert incl == orig == 64
        assert pkt1[16:20] == b"\x01\x02\x03\x04"
        stop.set()
        t.join(timeout=5)
    finally:
        server.stop(0)


def test_perf_buffer_batches_by_timeout():
    from netobserv_tpu.flow.perf_buffer import PerfBuffer
    from netobserv_tpu.model.packet_record import PacketRecord
    inq, outq = queue.Queue(), queue.Queue()
    buf = PerfBuffer(inq, outq, max_batch=100, timeout_s=0.2)
    buf.start()
    try:
        inq.put(PacketRecord(1, 0, b"x"))
        inq.put(PacketRecord(1, 0, b"y"))
        batch = outq.get(timeout=2)
        assert [p.payload for p in batch] == [b"x", b"y"]
    finally:
        buf.stop()
