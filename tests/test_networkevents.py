from netobserv_tpu.utils.networkevents import decode_cookie, is_drop_event


def test_decode_v1_cookie():
    cookie = bytes([1, 1, 0, 0]) + (4242).to_bytes(4, "little")
    out = decode_cookie(cookie)
    assert out == {"Feature": "acl", "Action": "drop", "Type": "acl",
                   "Direction": "ingress", "Name": "4242"}
    assert is_drop_event(cookie)


def test_unknown_layout_surfaces_raw():
    out = decode_cookie(b"\x07\x01")
    assert out == {"raw": "0701"}
    assert not is_drop_event(b"\x07\x01")


def test_allow_egress():
    cookie = bytes([1, 0, 2, 1]) + (7).to_bytes(4, "little")
    out = decode_cookie(cookie)
    assert out["Action"] == "allow"
    assert out["Type"] == "lb"
    assert out["Direction"] == "egress"
    assert not is_drop_event(cookie)


# ---------------------------------------------------------------------------
# pluggable OVN sample decoders (utils/ovn_decoder.py)
# ---------------------------------------------------------------------------

import json
import os
import socket
import socketserver
import tempfile
import threading

from netobserv_tpu.utils import ovn_decoder


def make_cookie(action=1, actor=0, direction=1, obj_id=7):
    return bytes([1, action, actor, direction]) + obj_id.to_bytes(4, "little")


class _FakeOvsdb(socketserver.ThreadingUnixStreamServer):
    """Minimal OVSDB JSON-RPC fake: answers `transact` select on ACL."""

    daemon_threads = True  # handler blocks in recv; don't join it on close
    rows = {7: {"name": "allow-dns", "action": "drop", "direction": "egress",
                "external_ids": ["map", [["k8s.ovn.org/namespace", "prod"]]]}}

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            buf = b""
            dec = json.JSONDecoder()
            while True:
                try:
                    chunk = self.request.recv(65536)
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                try:
                    obj, end = dec.raw_decode(buf.decode())
                except ValueError:
                    continue
                buf = buf[end:]
                sel = obj["params"][1]
                obj_id = sel["where"][0][2]
                row = _FakeOvsdb.rows.get(obj_id)
                result = [{"rows": [row] if row else []}]
                self.request.sendall(json.dumps(
                    {"id": obj["id"], "result": result,
                     "error": None}).encode())


def test_ovsdb_decoder_enriches_from_socket():
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "ovnnb.sock")
    srv = _FakeOvsdb(path, _FakeOvsdb.Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        dec = ovn_decoder.OvsdbSampleDecoder(sock_path=path)
        out = dec.decode(make_cookie(obj_id=7))
        assert out["Name"] == "allow-dns"
        assert out["Action"] == "drop"
        assert out["Namespace"] == "prod"
        assert out["Feature"] == "acl"
        # unknown id: static fields survive untouched
        out2 = dec.decode(make_cookie(obj_id=99))
        assert out2["Name"] == "99"
        # cache: kill the server; the known id still resolves
        srv.shutdown()
        srv.server_close()
        out3 = dec.decode(make_cookie(obj_id=7))
        assert out3["Name"] == "allow-dns"
        dec.close()
    finally:
        try:
            srv.shutdown()
        except Exception:
            pass


def test_ovsdb_decoder_degrades_without_socket():
    dec = ovn_decoder.OvsdbSampleDecoder(sock_path="/nonexistent/ovn.sock")
    out = dec.decode(make_cookie(obj_id=3))
    assert out["Name"] == "3"  # static decode survived the socket failure
    assert out["Action"] == "drop"


def test_active_decoder_is_pluggable():
    class Custom:
        def decode(self, cookie):
            return {"Message": "custom"}

        def close(self):
            pass

    try:
        ovn_decoder.set_decoder(Custom())
        assert ovn_decoder.decode_event(b"\x01\x01")["Message"] == "custom"
    finally:
        ovn_decoder.set_decoder(None)
    assert "Message" not in ovn_decoder.decode_event(make_cookie())
