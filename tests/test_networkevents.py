from netobserv_tpu.utils.networkevents import decode_cookie, is_drop_event


def test_decode_v1_cookie():
    cookie = bytes([1, 1, 0, 0]) + (4242).to_bytes(4, "little")
    out = decode_cookie(cookie)
    assert out == {"Feature": "acl", "Action": "drop", "Type": "acl",
                   "Direction": "ingress", "Name": "4242"}
    assert is_drop_event(cookie)


def test_unknown_layout_surfaces_raw():
    out = decode_cookie(b"\x07\x01")
    assert out == {"raw": "0701"}
    assert not is_drop_event(b"\x07\x01")


def test_allow_egress():
    cookie = bytes([1, 0, 2, 1]) + (7).to_bytes(4, "little")
    out = decode_cookie(cookie)
    assert out["Action"] == "allow"
    assert out["Type"] == "lb"
    assert out["Direction"] == "egress"
    assert not is_drop_event(cookie)
