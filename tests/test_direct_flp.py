import io
import json

from netobserv_tpu.exporter.direct_flp import DirectFLPExporter
from tests.test_exporters import make_record

CFG = """
pipeline:
  - name: filter1
  - name: rename
    follows: filter1
  - name: out
    follows: rename
parameters:
  - name: filter1
    transform:
      type: filter
      filter:
        rules:
          - type: keep_entry_if_equal
            keepEntryField: Proto
            keepEntryValue: 6
          - type: remove_field
            removeField: SrcMac
  - name: rename
    transform:
      type: generic
      generic:
        policy: preserve
        rules:
          - input: SrcAddr
            output: SourceAddress
  - name: out
    write:
      type: stdout
"""


def _run(cfg, records):
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    exp.export_batch(records)
    return [json.loads(l) for l in buf.getvalue().splitlines()]


def test_pipeline_filters_renames_and_writes():
    tcp = make_record(proto=6)
    udp = make_record(proto=17)
    out = _run(CFG, [tcp, udp])
    assert len(out) == 1  # UDP filtered by keep_entry_if_equal Proto=6
    entry = out[0]
    assert "SrcMac" not in entry  # removed
    assert entry["SourceAddress"] == "10.1.1.1"  # renamed (preserve policy)
    assert entry["SrcAddr"] == "10.1.1.1"


def test_empty_config_passthrough():
    out = _run("", [make_record()])
    assert len(out) == 1
    assert out[0]["DstPort"] == 443


def test_replace_keys_policy():
    cfg = """
pipeline: [{name: t}, {name: w, follows: t}]
parameters:
  - name: t
    transform:
      type: generic
      generic:
        policy: replace_keys
        rules:
          - {input: Bytes, output: octets}
          - {input: Packets, output: packets}
  - name: w
    write: {type: stdout}
"""
    out = _run(cfg, [make_record(nbytes=777)])
    assert out[0] == {"octets": 777, "packets": 7}


# ---------------------------------------------------------------------------
# string-table parity vs the reference decode layer (parsed from its source)
# ---------------------------------------------------------------------------

import os
import re

import pytest

from netobserv_tpu.exporter import flp_tables

_REF_DECODE = "/root/reference/pkg/decode/decode_protobuf.go"
_REF_NEVENTS = "/root/reference/pkg/utils/networkevents/network_events.go"

needs_reference = pytest.mark.skipif(
    not os.path.exists(_REF_DECODE), reason="reference source unavailable")


def _parse_switch_cases(src: str, func: str) -> dict:
    """Extract {case-expression: string} from a Go switch-based mapper."""
    body = src.split(f"func {func}(")[1]
    body = body.split("\nfunc ")[0]
    return dict(re.findall(r'case ([^:]+):\s*\n\s*return "([^"]+)"', body))


@needs_reference
def test_tcp_state_table_matches_reference():
    src = open(_REF_DECODE).read()
    cases = _parse_switch_cases(src, "TCPStateToStr")
    expected = {int(k): v for k, v in cases.items()}
    assert flp_tables.TCP_STATES == expected
    assert flp_tables.tcp_state_to_str(99) == "TCP_INVALID_STATE"


@needs_reference
def test_dns_rcode_table_matches_reference():
    src = open(_REF_DECODE).read()
    cases = _parse_switch_cases(src, "DNSRcodeToStr")
    expected = {int(k): v for k, v in cases.items()}
    assert flp_tables.DNS_RCODES == expected
    assert flp_tables.dns_rcode_to_str(30) == "UnDefined"


@needs_reference
def test_drop_cause_table_matches_reference():
    src = open(_REF_DECODE).read()
    cases = _parse_switch_cases(src, "PktDropCauseToStr")
    expected = {}
    for expr, name in cases.items():
        base, _, off = expr.partition("+")
        base = base.strip()
        off = int(off.strip())
        if base == "skbDropReasonSubSysCore":
            expected[flp_tables.SKB_DROP_SUBSYS_CORE + off] = name
        elif base == "skbDropReasonSubSysOpenVSwitch":
            expected[flp_tables.SKB_DROP_SUBSYS_OVS + off] = name
        else:
            raise AssertionError(f"unknown subsystem {base}")
    assert flp_tables.DROP_CAUSES == expected
    for code, name in expected.items():
        assert flp_tables.pkt_drop_cause_to_str(code) == name
    assert flp_tables.pkt_drop_cause_to_str(12345678) == \
        "SKB_DROP_UNKNOWN_CAUSE"


@needs_reference
def test_ovn_event_causes_match_reference():
    src = open(_REF_NEVENTS).read()
    block = src.split("causes = []string{")[1].split("}")[0]
    expected = re.findall(r'"([^"]+)"', block)
    assert flp_tables.OVN_EVENT_CAUSES == expected
    shift = int(re.search(
        r"customDropReasonSubSysOVNEvents = \(1 << (\d+)\)", src).group(1))
    assert flp_tables.OVN_EVENTS_SUBSYS == 1 << shift
    # the injected names render with the NetworkEvent_ prefix
    assert flp_tables.pkt_drop_cause_to_str(
        flp_tables.OVN_EVENTS_SUBSYS + 4) == "NetworkEvent_NetworkPolicy"
