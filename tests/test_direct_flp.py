import io
import json

from netobserv_tpu.exporter.direct_flp import DirectFLPExporter
from tests.test_exporters import make_record

CFG = """
pipeline:
  - name: filter1
  - name: rename
    follows: filter1
  - name: out
    follows: rename
parameters:
  - name: filter1
    transform:
      type: filter
      filter:
        rules:
          - type: keep_entry_if_equal
            keepEntryField: Proto
            keepEntryValue: 6
          - type: remove_field
            removeField: SrcMac
  - name: rename
    transform:
      type: generic
      generic:
        policy: preserve
        rules:
          - input: SrcAddr
            output: SourceAddress
  - name: out
    write:
      type: stdout
"""


def _run(cfg, records):
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    exp.export_batch(records)
    return [json.loads(l) for l in buf.getvalue().splitlines()]


def test_pipeline_filters_renames_and_writes():
    tcp = make_record(proto=6)
    udp = make_record(proto=17)
    out = _run(CFG, [tcp, udp])
    assert len(out) == 1  # UDP filtered by keep_entry_if_equal Proto=6
    entry = out[0]
    assert "SrcMac" not in entry  # removed
    assert entry["SourceAddress"] == "10.1.1.1"  # renamed (preserve policy)
    assert entry["SrcAddr"] == "10.1.1.1"


def test_empty_config_passthrough():
    out = _run("", [make_record()])
    assert len(out) == 1
    assert out[0]["DstPort"] == 443


def test_replace_keys_policy():
    cfg = """
pipeline: [{name: t}, {name: w, follows: t}]
parameters:
  - name: t
    transform:
      type: generic
      generic:
        policy: replace_keys
        rules:
          - {input: Bytes, output: octets}
          - {input: Packets, output: packets}
  - name: w
    write: {type: stdout}
"""
    out = _run(cfg, [make_record(nbytes=777)])
    assert out[0] == {"octets": 777, "packets": 7}
