import io
import json

from netobserv_tpu.exporter.direct_flp import DirectFLPExporter
from tests.test_exporters import make_record

CFG = """
pipeline:
  - name: filter1
  - name: rename
    follows: filter1
  - name: out
    follows: rename
parameters:
  - name: filter1
    transform:
      type: filter
      filter:
        rules:
          - type: keep_entry_if_equal
            keepEntryField: Proto
            keepEntryValue: 6
          - type: remove_field
            removeField: SrcMac
  - name: rename
    transform:
      type: generic
      generic:
        policy: preserve
        rules:
          - input: SrcAddr
            output: SourceAddress
  - name: out
    write:
      type: stdout
"""


def _run(cfg, records):
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    exp.export_batch(records)
    return [json.loads(l) for l in buf.getvalue().splitlines()]


def test_pipeline_filters_renames_and_writes():
    tcp = make_record(proto=6)
    udp = make_record(proto=17)
    out = _run(CFG, [tcp, udp])
    assert len(out) == 1  # UDP filtered by keep_entry_if_equal Proto=6
    entry = out[0]
    assert "SrcMac" not in entry  # removed
    assert entry["SourceAddress"] == "10.1.1.1"  # renamed (preserve policy)
    assert entry["SrcAddr"] == "10.1.1.1"


def test_empty_config_passthrough():
    out = _run("", [make_record()])
    assert len(out) == 1
    assert out[0]["DstPort"] == 443


def test_replace_keys_policy():
    cfg = """
pipeline: [{name: t}, {name: w, follows: t}]
parameters:
  - name: t
    transform:
      type: generic
      generic:
        policy: replace_keys
        rules:
          - {input: Bytes, output: octets}
          - {input: Packets, output: packets}
  - name: w
    write: {type: stdout}
"""
    out = _run(cfg, [make_record(nbytes=777)])
    assert out[0] == {"octets": 777, "packets": 7}


def test_transform_network_rules():
    """FLP transform_network.go subset: subnet, service, subnet label, TCP
    flag decode, and reporter-viewpoint direction reinterpretation."""
    cfg = """
pipeline: [{name: n}, {name: w, follows: n}]
parameters:
  - name: n
    transform:
      type: network
      network:
        subnetLabels:
          - name: internal
            cidrs: ["10.0.0.0/8"]
        directionInfo:
          reporterIPField: AgentIP
          srcHostField: SrcHost
          dstHostField: DstHost
          flowDirectionField: FlowDirection
          ifDirectionField: IfDirections
        rules:
          - type: add_subnet
            add_subnet: {input: SrcAddr, output: SrcSubnet, parameters: /24}
          - type: add_service
            add_service: {input: DstPort, output: Service, protocol: Proto}
          - type: add_subnet_label
            add_subnet_label: {input: SrcAddr, output: SrcLabel}
          - type: decode_tcp_flags
            decode_tcp_flags: {input: Flags, output: Flags}
          - type: reinterpret_direction
  - name: w
    write: {type: stdout}
"""
    r = make_record(proto=6)     # 10.1.1.1 -> 10.2.2.2:443, flags 0x12
    recs = _run_with_extra(cfg, [r], extra={
        "SrcHost": "nodeA", "DstHost": "nodeB", "FlowDirection": 1})
    e = recs[0]
    assert e["SrcSubnet"] == "10.1.1.0/24"
    assert e["Service"] == "https"
    assert e["SrcLabel"] == "internal"
    assert set(e["Flags"]) == {"SYN", "ACK"}
    # reporter (AgentIP 192.0.2.1) is neither endpoint: direction unchanged,
    # but the interface-level copy was made first
    assert e["IfDirections"] == 1
    recs = _run_with_extra(cfg, [r], extra={
        "SrcHost": "192.0.2.1", "DstHost": "nodeB", "FlowDirection": 0})
    assert recs[0]["FlowDirection"] == 1     # reporter is src: egress
    recs = _run_with_extra(cfg, [r], extra={
        "SrcHost": "x", "DstHost": "x", "FlowDirection": 0})
    assert recs[0]["FlowDirection"] == 2     # same node both ends: inner


def _run_with_extra(cfg, records, extra):
    import unittest.mock as mock

    from netobserv_tpu.exporter import direct_flp as dfl
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    orig = dfl.record_to_map

    def patched(r):
        m = orig(r)
        m.update(extra)
        return m
    with mock.patch.object(dfl, "record_to_map", patched):
        exp.export_batch(records)
    return [json.loads(l) for l in buf.getvalue().splitlines()]


def test_encode_prom_metrics():
    """FLP encode_prom.go subset: counter/gauge/histogram with labels and
    filters, exposed on the exporter's registry; entries pass through."""
    cfg = """
pipeline: [{name: e}, {name: w, follows: e}]
parameters:
  - name: e
    encode:
      type: prom
      prom:
        prefix: flp_
        metrics:
          - name: flows_total
            type: counter
            labels: [Proto]
          - name: bytes_total
            type: counter
            valueKey: Bytes
            filters: [{type: equal, key: Proto, value: 6}]
          - name: last_bytes
            type: gauge
            valueKey: Bytes
          - name: bytes_hist
            type: histogram
            valueKey: Bytes
            buckets: [100, 10000]
  - name: w
    write: {type: stdout}
"""
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    exp.export_batch([make_record(proto=6, nbytes=4321),
                      make_record(proto=17, nbytes=10)])
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert len(out) == 2                       # encode passes entries through
    g = exp.prom_registry.get_sample_value
    assert g("flp_flows_total", {"Proto": "6"}) == 1
    assert g("flp_flows_total", {"Proto": "17"}) == 1
    assert g("flp_bytes_total") == 4321  # UDP filtered out
    assert g("flp_last_bytes") == 10           # latest entry wins
    assert g("flp_bytes_hist_bucket", {"le": "10000.0"}) == 2
    assert g("flp_bytes_hist_bucket", {"le": "100.0"}) == 1


def test_encode_prom_duplicate_metric_skipped():
    """A duplicate metric name (two pipeline entries sharing one, or an
    exporter rebuild against the same registry) must warn+skip like every
    other unsupported-config case, not abort agent startup."""
    import prometheus_client

    cfg = """
pipeline: [{name: e}, {name: w, follows: e}]
parameters:
  - name: e
    encode:
      type: prom
      prom:
        metrics:
          - {name: dup_total, type: counter}
          - {name: dup_total, type: counter}
          - {name: ok_total, type: counter}
  - name: w
    write: {type: stdout}
"""
    reg = prometheus_client.CollectorRegistry()
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf, prom_registry=reg)
    exp.export_batch([make_record()])
    # same-config duplicate: first definition wins, no double counting
    assert reg.get_sample_value("dup_total") == 1
    assert reg.get_sample_value("ok_total") == 1
    # a rebuild against the SAME registry (agent restart-in-place) adopts the
    # live collectors — the series keep moving instead of freezing
    exp2 = DirectFLPExporter(flp_config=cfg, stream=buf, prom_registry=reg)
    exp2.export_batch([make_record()])
    assert reg.get_sample_value("dup_total") == 2
    assert reg.get_sample_value("ok_total") == 2


def test_encode_prom_cross_stage_duplicate_not_double_counted():
    """Two prom ENCODE STAGES in one config sharing a metric name: the
    second stage must skip (not adopt) the collector, or every entry
    flowing through both stages would count twice."""
    import prometheus_client

    cfg = """
pipeline: [{name: e1}, {name: e2, follows: e1}, {name: w, follows: e2}]
parameters:
  - name: e1
    encode:
      type: prom
      prom:
        metrics: [{name: xs_total, type: counter}]
  - name: e2
    encode:
      type: prom
      prom:
        metrics: [{name: xs_total, type: counter}]
  - name: w
    write: {type: stdout}
"""
    reg = prometheus_client.CollectorRegistry()
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf, prom_registry=reg)
    exp.export_batch([make_record()])
    assert reg.get_sample_value("xs_total") == 1


def test_encode_prom_rebuild_with_changed_buckets_skips():
    """A restart-in-place that CHANGES a histogram's buckets must not adopt
    the stale collector (observations would misbin forever) — incompatible
    survivors degrade to warn+skip."""
    import prometheus_client

    def cfg(buckets):
        return f"""
pipeline: [{{name: e}}, {{name: w, follows: e}}]
parameters:
  - name: e
    encode:
      type: prom
      prom:
        metrics:
          - {{name: h_bytes, type: histogram, valueKey: Bytes,
              buckets: {buckets}}}
  - name: w
    write: {{type: stdout}}
"""
    reg = prometheus_client.CollectorRegistry()
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg([100, 10000]), stream=buf,
                            prom_registry=reg)
    exp.export_batch([make_record(nbytes=500)])
    assert reg.get_sample_value("h_bytes_bucket", {"le": "10000.0"}) == 1
    # same buckets -> adopted, keeps counting
    exp2 = DirectFLPExporter(flp_config=cfg([100, 10000]), stream=buf,
                             prom_registry=reg)
    exp2.export_batch([make_record(nbytes=500)])
    assert reg.get_sample_value("h_bytes_bucket", {"le": "10000.0"}) == 2
    # changed buckets -> skipped, stale series frozen rather than misbinned
    exp3 = DirectFLPExporter(flp_config=cfg([1, 2]), stream=buf,
                             prom_registry=reg)
    exp3.export_batch([make_record(nbytes=500)])
    assert reg.get_sample_value("h_bytes_bucket", {"le": "10000.0"}) == 2


CT_CFG = """
pipeline: [{name: ct}, {name: w, follows: ct}]
parameters:
  - name: ct
    extract:
      type: conntrack
      conntrack:
        keyDefinition:
          fieldGroups:
            - {name: src, fields: [SrcAddr, SrcPort]}
            - {name: dst, fields: [DstAddr, DstPort]}
            - {name: common, fields: [Proto]}
          hash:
            fieldGroupRefs: [common]
            fieldGroupARef: src
            fieldGroupBRef: dst
        outputRecordTypes: [newConnection, flowLog, endConnection]
        outputFields:
          - {name: Bytes, operation: sum, splitAB: true}
          - {name: Packets, operation: sum}
          - {name: numFlowLogs, operation: count}
        scheduling:
          - {endConnectionTimeout: 60s, terminatingTimeout: 100ms,
             heartbeatInterval: 300s}
        tcpFlags: {fieldName: Flags, detectEndConnection: true}
  - name: w
    write: {type: stdout}
"""


def test_extract_conntrack_bidirectional():
    """FLP extract/conntrack subset: A->B and B->A flow logs stitch into ONE
    connection (canonical bidirectional hash); aggregates split by
    direction; a FIN ends the connection after terminatingTimeout."""
    import time

    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=CT_CFG, stream=buf)
    ab = make_record(nbytes=1000)                     # 10.1.1.1 -> 10.2.2.2
    ba = make_record(src="10.2.2.2", dst="10.1.1.1", sport=443, dport=1111,
                     nbytes=300)
    ba.key = type(ba.key).make("10.2.2.2", "10.1.1.1", 443, 1111, 6)
    exp.export_batch([ab])
    exp.export_batch([ba])
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    types = [e["_RecordType"] for e in out]
    assert types == ["newConnection", "flowLog", "flowLog"], types
    new = out[0]
    assert new["SrcAddr"] == "10.1.1.1" and new["DstAddr"] == "10.2.2.2"
    hash_id = new["_HashId"]
    assert all(e["_HashId"] == hash_id for e in out), "split connection"
    # FIN from the B side ends the connection after terminatingTimeout
    fin = make_record(src="10.2.2.2", dst="10.1.1.1", sport=443, dport=1111,
                      nbytes=60)
    fin.key = type(fin.key).make("10.2.2.2", "10.1.1.1", 443, 1111, 6)
    fin.tcp_flags = 0x211                             # FIN|ACK|FIN_ACK
    exp.export_batch([fin])
    time.sleep(0.15)
    exp.export_batch([])                              # timer sweep
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert out[-1]["_RecordType"] == "endConnection", out[-1]
    end = out[-1]
    assert end["Bytes_AB"] == 1000 and end["Bytes_BA"] == 360
    assert end["Packets"] == 21                       # 3 logs x 7 packets
    assert end["numFlowLogs"] == 3
    assert end["_HashId"] == hash_id


def test_extract_conntrack_swap_ab():
    """swapAB: when the first observed flow log is the server's SYN_ACK, the
    connection is oriented from the client — including the record's field
    values, so Src/Dst and the _AB aggregates agree."""
    buf = io.StringIO()
    cfg = CT_CFG.replace("tcpFlags: {fieldName: Flags, detectEndConnection: true}",
                         "tcpFlags: {fieldName: Flags, swapAB: true}")
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    synack = make_record(src="10.2.2.2", dst="10.1.1.1", sport=443,
                         dport=1111, nbytes=60)
    synack.key = type(synack.key).make("10.2.2.2", "10.1.1.1", 443, 1111, 6)
    synack.tcp_flags = 0x112                          # SYN|ACK|SYN_ACK
    client = make_record(nbytes=500)                  # 10.1.1.1:1111 -> 443
    exp.export_batch([synack, client])
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    new = [e for e in out if e["_RecordType"] == "newConnection"][0]
    assert new["SrcAddr"] == "10.1.1.1" and new["SrcPort"] == 1111
    assert new["DstAddr"] == "10.2.2.2" and new["DstPort"] == 443
    exp.close()
    end = [json.loads(l) for l in buf.getvalue().splitlines()
           if json.loads(l)["_RecordType"] == "endConnection"][0]
    assert end["Bytes_AB"] == 500 and end["Bytes_BA"] == 60


def test_extract_conntrack_close_flushes():
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=CT_CFG, stream=buf)
    exp.export_batch([make_record()])
    exp.close()
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert out[-1]["_RecordType"] == "endConnection"
    assert out[-1]["numFlowLogs"] == 1


def test_extract_aggregates():
    """FLP extract/aggregates subset: group-by running totals with
    recent_* per-cycle values, replacing the flow-log stream."""
    cfg = """
pipeline: [{name: agg}, {name: w, follows: agg}]
parameters:
  - name: agg
    extract:
      type: aggregates
      aggregates:
        rules:
          - name: bytes_by_proto
            groupByKeys: [Proto]
            operationType: sum
            operationKey: Bytes
  - name: w
    write: {type: stdout}
"""
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    exp.export_batch([make_record(proto=6, nbytes=100),
                      make_record(proto=6, nbytes=50),
                      make_record(proto=17, nbytes=7)])
    exp.export_batch([make_record(proto=6, nbytes=25)])
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert all(e["name"] == "bytes_by_proto" for e in out)
    tcp1 = [e for e in out if e["Proto"] == "6"][0]
    assert tcp1["total_value"] == 150 and tcp1["total_count"] == 2
    assert tcp1["recent_op_value"] == 150
    tcp2 = [e for e in out if e["Proto"] == "6"][1]
    assert tcp2["total_value"] == 175 and tcp2["total_count"] == 3
    assert tcp2["recent_op_value"] == 25      # recent_* reset per cycle
    udp = [e for e in out if e["Proto"] == "17"][0]
    assert udp["total_value"] == 7 and udp["aggregate"] == "17"


def test_extract_timebased_topk():
    """FLP extract/timebased subset: sliding-window top-K by sum."""
    cfg = """
pipeline: [{name: tb}, {name: w, follows: tb}]
parameters:
  - name: tb
    extract:
      type: timebased
      timebased:
        rules:
          - name: top_senders
            indexKeys: [SrcAddr]
            operationType: sum
            operationKey: Bytes
            topK: 2
            timeInterval: 10s
  - name: w
    write: {type: stdout}
"""
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    recs = []
    for src, nbytes in (("10.0.0.1", 100), ("10.0.0.2", 900),
                        ("10.0.0.3", 500), ("10.0.0.2", 50)):
        r = make_record(src=src, nbytes=nbytes)
        r.key = type(r.key).make(src, "10.2.2.2", 1111, 443, 6)
        recs.append(r)
    exp.export_batch(recs)
    out = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert len(out) == 2                       # topK=2
    assert out[0]["SrcAddr"] == "10.0.0.2" and out[0]["Bytes"] == 950
    assert out[1]["SrcAddr"] == "10.0.0.3" and out[1]["Bytes"] == 500
    assert out[0]["name"] == "top_senders"
    assert out[0]["operation"] == "sum"


def test_write_loki():
    """FLP write_loki subset: entries stream to a live HTTP endpoint in the
    Loki push shape, grouped by label set, with tenant header — verified
    against an in-process HTTP server (the reference e2e asserts flows land
    in Loki; this is the in-image equivalent)."""
    import http.server
    import threading

    got = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            got["path"] = self.path
            got["tenant"] = self.headers.get("X-Scope-OrgID")
            got["body"] = json.loads(
                self.rfile.read(int(self.headers["Content-Length"])))
            self.send_response(204)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        cfg = f"""
pipeline: [{{name: w}}]
parameters:
  - name: w
    write:
      type: loki
      loki:
        url: http://127.0.0.1:{srv.server_port}
        tenantID: netobserv
        labels: [SrcAddr]
        staticLabels: {{app: netobserv}}
"""
        exp = DirectFLPExporter(flp_config=cfg)
        exp.export_batch([make_record(), make_record(src="10.9.9.9")])
        assert got["path"] == "/loki/api/v1/push"
        assert got["tenant"] == "netobserv"
        streams = got["body"]["streams"]
        assert len(streams) == 2               # one per SrcAddr label set
        by_src = {s["stream"]["SrcAddr"]: s for s in streams}
        assert by_src["10.1.1.1"]["stream"]["app"] == "netobserv"
        line = json.loads(by_src["10.9.9.9"]["values"][0][1])
        assert line["SrcAddr"] == "10.9.9.9"
        ts = int(by_src["10.1.1.1"]["values"][0][0])
        entry = json.loads(by_src["10.1.1.1"]["values"][0][1])
        # pinned to the entry's own TimeFlowEndMs at 1ms scale, not wall now
        assert ts == entry["TimeFlowEndMs"] * 10**6
    finally:
        srv.shutdown()
        srv.server_close()


def test_write_loki_unreachable_does_not_raise():
    cfg = """
pipeline: [{name: w}]
parameters:
  - name: w
    write:
      type: loki
      loki: {url: "http://127.0.0.1:1"}
"""
    exp = DirectFLPExporter(flp_config=cfg)
    exp.export_batch([make_record()])          # must not raise


def test_write_loki_backoff_after_consecutive_failures(monkeypatch):
    """An unreachable Loki must not throttle the export queue: after
    FAIL_THRESHOLD consecutive failures the writer skips pushes (no network
    attempt at all) until the backoff window elapses."""
    from netobserv_tpu.exporter import direct_flp as dflp

    w = dflp._LokiWriter({"url": "http://127.0.0.1:1"})
    attempts = {"n": 0}

    import urllib.request

    def counting_urlopen(req, timeout=None):
        attempts["n"] += 1
        assert timeout is not None and timeout <= 5, \
            "per-batch POST timeout must stay short"
        raise OSError("connection refused")

    monkeypatch.setattr(urllib.request, "urlopen", counting_urlopen)
    for _ in range(w.FAIL_THRESHOLD):
        w.push([{"SrcAddr": "10.0.0.1"}])
    assert attempts["n"] == w.FAIL_THRESHOLD
    # now inside the backoff window: pushes are dropped without dialing
    w.push([{"SrcAddr": "10.0.0.1"}])
    assert attempts["n"] == w.FAIL_THRESHOLD
    # window elapses -> the writer dials again
    w._backoff_until = 0.0
    w.push([{"SrcAddr": "10.0.0.1"}])
    assert attempts["n"] == w.FAIL_THRESHOLD + 1


# ---------------------------------------------------------------------------
# string-table parity vs the reference decode layer (parsed from its source)
# ---------------------------------------------------------------------------

import os
import re

import pytest

from netobserv_tpu.exporter import flp_tables

_REF_DECODE = "/root/reference/pkg/decode/decode_protobuf.go"
_REF_NEVENTS = "/root/reference/pkg/utils/networkevents/network_events.go"

needs_reference = pytest.mark.skipif(
    not os.path.exists(_REF_DECODE), reason="reference source unavailable")


def _parse_switch_cases(src: str, func: str) -> dict:
    """Extract {case-expression: string} from a Go switch-based mapper."""
    body = src.split(f"func {func}(")[1]
    body = body.split("\nfunc ")[0]
    return dict(re.findall(r'case ([^:]+):\s*\n\s*return "([^"]+)"', body))


@needs_reference
def test_tcp_state_table_matches_reference():
    src = open(_REF_DECODE).read()
    cases = _parse_switch_cases(src, "TCPStateToStr")
    expected = {int(k): v for k, v in cases.items()}
    assert flp_tables.TCP_STATES == expected
    assert flp_tables.tcp_state_to_str(99) == "TCP_INVALID_STATE"


@needs_reference
def test_dns_rcode_table_matches_reference():
    src = open(_REF_DECODE).read()
    cases = _parse_switch_cases(src, "DNSRcodeToStr")
    expected = {int(k): v for k, v in cases.items()}
    assert flp_tables.DNS_RCODES == expected
    assert flp_tables.dns_rcode_to_str(30) == "UnDefined"


@needs_reference
def test_drop_cause_table_matches_reference():
    src = open(_REF_DECODE).read()
    cases = _parse_switch_cases(src, "PktDropCauseToStr")
    expected = {}
    for expr, name in cases.items():
        base, _, off = expr.partition("+")
        base = base.strip()
        off = int(off.strip())
        if base == "skbDropReasonSubSysCore":
            expected[flp_tables.SKB_DROP_SUBSYS_CORE + off] = name
        elif base == "skbDropReasonSubSysOpenVSwitch":
            expected[flp_tables.SKB_DROP_SUBSYS_OVS + off] = name
        else:
            raise AssertionError(f"unknown subsystem {base}")
    assert flp_tables.DROP_CAUSES == expected
    for code, name in expected.items():
        assert flp_tables.pkt_drop_cause_to_str(code) == name
    assert flp_tables.pkt_drop_cause_to_str(12345678) == \
        "SKB_DROP_UNKNOWN_CAUSE"


@needs_reference
def test_ovn_event_causes_match_reference():
    src = open(_REF_NEVENTS).read()
    block = src.split("causes = []string{")[1].split("}")[0]
    expected = re.findall(r'"([^"]+)"', block)
    assert flp_tables.OVN_EVENT_CAUSES == expected
    shift = int(re.search(
        r"customDropReasonSubSysOVNEvents = \(1 << (\d+)\)", src).group(1))
    assert flp_tables.OVN_EVENTS_SUBSYS == 1 << shift
    # the injected names render with the NetworkEvent_ prefix
    assert flp_tables.pkt_drop_cause_to_str(
        flp_tables.OVN_EVENTS_SUBSYS + 4) == "NetworkEvent_NetworkPolicy"


def test_extract_aggregates_missing_key_does_not_skew():
    """Entries lacking the operation key contribute NOTHING — min must not
    lock to the 0.0 initializer and avg must not dilute toward 0."""
    cfg = """
pipeline: [{name: agg}, {name: w, follows: agg}]
parameters:
  - name: agg
    extract:
      type: aggregates
      aggregates:
        rules:
          - {name: min_rtt, groupByKeys: [Proto], operationType: min,
             operationKey: TimeFlowRttNs}
          - {name: avg_rtt, groupByKeys: [Proto], operationType: avg,
             operationKey: TimeFlowRttNs}
  - name: w
    write: {type: stdout}
"""
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=cfg, stream=buf)
    no_rtt = make_record(proto=6, with_features=False)   # no TimeFlowRttNs
    r10 = make_record(proto=6)
    r10.features.rtt_ns = 10
    r20 = make_record(proto=6)
    r20.features.rtt_ns = 20
    exp.export_batch([no_rtt, r10, r20])
    out = {e["name"]: e for e in
           (json.loads(l) for l in buf.getvalue().splitlines())}
    assert out["min_rtt"]["total_value"] == 10     # not 0.0
    assert out["avg_rtt"]["total_value"] == 15     # not diluted by no_rtt
    assert out["min_rtt"]["total_count"] == 2      # keyless entry uncounted


K8S_LOC_CFG = """
pipeline:
  - name: enrich
  - name: out
    follows: enrich
parameters:
  - name: enrich
    transform:
      type: network
      network:
        rules:
          - type: add_kubernetes
            kubernetes:
              ipField: SrcAddr
              output: SrcK8S
              add_zone: true
              labels_prefix: SrcK8S_labels
          - type: add_location
            add_location:
              input: DstAddr
              output: DstLoc
  - name: out
    write:
      type: stdout
"""


def test_add_kubernetes_with_pluggable_informer(tmp_path):
    """add_kubernetes enriches via the injected datasource with FLP's exact
    output-key naming (kubernetes/enrich.go:37-87); closes the
    warned-and-skipped gap against the reference's embedded FLP."""
    from netobserv_tpu.exporter.flp_enrich import StaticKubeDataSource

    ds = StaticKubeDataSource({
        "10.1.1.1": {"name": "web-1", "kind": "Pod", "namespace": "prod",
                     "owner_name": "web", "owner_kind": "Deployment",
                     "host_ip": "192.0.2.10", "host_name": "node-a",
                     "zone": "us-east-1a", "labels": {"app": "web"}},
    })
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=K8S_LOC_CFG, stream=buf,
                            kube_source=ds)
    exp.export_batch([make_record()])
    entry = json.loads(buf.getvalue().splitlines()[0])
    assert entry["SrcK8S_Namespace"] == "prod"
    assert entry["SrcK8S_Name"] == "web-1"
    assert entry["SrcK8S_Type"] == "Pod"
    assert entry["SrcK8S_OwnerName"] == "web"
    assert entry["SrcK8S_OwnerType"] == "Deployment"
    assert entry["SrcK8S_HostIP"] == "192.0.2.10"
    assert entry["SrcK8S_HostName"] == "node-a"
    assert entry["SrcK8S_Zone"] == "us-east-1a"
    assert entry["SrcK8S_labels_app"] == "web"


def test_add_kubernetes_json_file_and_unknown_ip(tmp_path):
    from netobserv_tpu.exporter.flp_enrich import StaticKubeDataSource

    p = tmp_path / "kube.json"
    p.write_text(json.dumps({
        "10.9.9.9": {"name": "other", "kind": "Service"}}))
    ds = StaticKubeDataSource(path=str(p))
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=K8S_LOC_CFG, stream=buf,
                            kube_source=ds)
    exp.export_batch([make_record()])  # SrcAddr 10.1.1.1 not in the map
    entry = json.loads(buf.getvalue().splitlines()[0])
    assert "SrcK8S_Name" not in entry  # unknown IP: untouched entry


def test_add_location_with_csv_db(tmp_path):
    """add_location resolves through the ip2location-layout range CSV with
    FLP's exact six output fields (transform_network.go:85-90)."""
    import ipaddress

    from netobserv_tpu.exporter.flp_enrich import CsvLocationDB

    dst = int(ipaddress.ip_address("10.2.2.2"))
    p = tmp_path / "loc.csv"
    p.write_text(
        f'"{dst - 10}","{dst + 10}","US","United States of America",'
        '"California","Mountain View","37.405","-122.078"\n'
        '"3232235520","3232301055","DE","Germany","Berlin","Berlin",'
        '"52.52","13.40"\n')
    buf = io.StringIO()
    exp = DirectFLPExporter(flp_config=K8S_LOC_CFG, stream=buf,
                            location_db=CsvLocationDB(str(p)))
    exp.export_batch([make_record()])
    entry = json.loads(buf.getvalue().splitlines()[0])
    assert entry["DstLoc_CountryName"] == "US"
    assert entry["DstLoc_CountryLongName"] == "United States of America"
    assert entry["DstLoc_RegionName"] == "California"
    assert entry["DstLoc_CityName"] == "Mountain View"
    assert entry["DstLoc_Latitude"] == "37.405"
    assert entry["DstLoc_Longitude"] == "-122.078"
    # no k8s source injected: the add_kubernetes rule warned and skipped
    assert "SrcK8S_Name" not in entry


def test_enrichment_backends_from_agent_config(tmp_path):
    """build_exporter wires FLP_KUBE_MAP / FLP_LOCATION_DB into the
    embedded pipeline."""
    from netobserv_tpu.config import load_config
    from netobserv_tpu.exporter import build_exporter

    kube = tmp_path / "kube.json"
    kube.write_text(json.dumps(
        {"10.1.1.1": {"name": "pod-x", "kind": "Pod", "namespace": "ns1"}}))
    cfg = load_config({
        "EXPORT": "direct-flp",
        "FLP_CONFIG": K8S_LOC_CFG,
        "FLP_KUBE_MAP": str(kube),
    })
    exp = build_exporter(cfg)
    exp._stream = buf = io.StringIO()
    exp.export_batch([make_record()])
    entry = json.loads(buf.getvalue().splitlines()[0])
    assert entry["SrcK8S_Name"] == "pod-x" and entry["SrcK8S_Namespace"] == "ns1"


def test_location_csv_ipv6_layout_mapped_v4(tmp_path):
    """ip2location IPv6-layout DBs carry IPv4 as ::ffff-mapped u128 ranges;
    those must land in the v4 table so plain v4 lookups resolve, and
    malformed rows must be skipped, never fatal."""
    from netobserv_tpu.exporter.flp_enrich import CsvLocationDB

    lo = 0xFFFF00000000 + int.from_bytes(bytes([10, 2, 2, 0]), "big")
    p = tmp_path / "loc6.csv"
    p.write_text(
        f'"{lo}","{lo + 255}","US","United States","CA","MV","1","2"\n'
        '"16777216","n/a","XX","malformed row tolerated","","","",""\n')
    db = CsvLocationDB(str(p))
    assert db.lookup("10.2.2.2")["CountryName"] == "US"
    assert db.lookup("::ffff:10.2.2.2")["CountryName"] == "US"
    assert db.lookup("10.3.0.1") is None


KAFKA_CFG = """
pipeline:
  - name: enc
  - name: out
    follows: enc
parameters:
  - name: enc
    encode:
      type: kafka
      kafka:
        address: 127.0.0.1:9092
        topic: network-flows
  - name: out
    write:
      type: stdout
"""


def test_encode_kafka_produces_and_passes_through():
    """FLP `encode kafka` (reference direct_flp.go embeds the full FLP, so
    any stage type in FLP_CONFIG works — encode_kafka.go): entries land on
    the topic as JSON AND continue to the terminal write stage."""
    import struct

    from netobserv_tpu.kafka.producer import KafkaProducer
    from tests.test_kafka_broker import FakeBroker

    broker = FakeBroker(topic="network-flows")
    broker.start()
    try:
        producer = KafkaProducer(
            brokers=[f"127.0.0.1:{broker.port}"], topic="network-flows")
        buf = io.StringIO()
        exp = DirectFLPExporter(flp_config=KAFKA_CFG, stream=buf,
                                kafka_producer=producer)
        exp.export_batch([make_record(proto=6), make_record(proto=17)])
        # pass-through to the terminal stage
        out = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert len(out) == 2
        # and produced to the topic: record count from the batch header,
        # JSON values visible in the (uncompressed) batch body
        assert broker.produced
        total = sum(struct.unpack(">i", b[57:61])[0]
                    for _p, b in broker.produced)
        assert total == 2
        blob = b"".join(b for _p, b in broker.produced)
        assert b'"Proto":6' in blob and b'"Proto":17' in blob
        producer.close()
    finally:
        broker.stop()


IPFIX_CFG_TMPL = """
pipeline:
  - name: out
parameters:
  - name: out
    write:
      type: ipfix
      ipfix:
        targetHost: 127.0.0.1
        targetPort: %d
        transport: udp
"""


def test_write_ipfix_emits_data_records():
    """FLP `write ipfix` (reference write_ipfix.go): the entry stream leaves
    as IPFIX messages through the wire exporter (v4/v6 templates)."""
    import socket
    import struct

    from netobserv_tpu.exporter.ipfix import IPFIX_VERSION, TEMPLATE_V4

    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(3)
    port = rx.getsockname()[1]
    exp = DirectFLPExporter(flp_config=IPFIX_CFG_TMPL % port)
    exp.export_batch([make_record(proto=6)])
    msg, _ = rx.recvfrom(65535)
    version = struct.unpack(">HH", msg[:4])[0]
    assert version == IPFIX_VERSION
    sid = struct.unpack(">HH", msg[16:20])[0]
    assert sid == 2  # template set leads the first message
    assert any(struct.unpack(">H", msg[o:o+2])[0] == TEMPLATE_V4
               for o in range(16, len(msg) - 1, 2))
    rx.close()


GRPC_CFG_TMPL = """
pipeline:
  - name: out
parameters:
  - name: out
    write:
      type: grpc
      grpc:
        targetHost: 127.0.0.1
        targetPort: %d
"""


def test_write_grpc_sends_pbflow_records():
    """FLP `write grpc` (reference write_grpc.go): entries leave as pbflow
    Records to a Collector (round-tripped through the in-repo server)."""
    from netobserv_tpu.grpc.flow import start_flow_collector

    server, port, out = start_flow_collector(0)
    try:
        exp = DirectFLPExporter(flp_config=GRPC_CFG_TMPL % port)
        exp.export_batch([make_record(proto=6), make_record(proto=17)])
        msg = out.get(timeout=10)
        assert len(msg.entries) == 2
        assert {e.transport.protocol for e in msg.entries} == {6, 17}
        exp.close()
    finally:
        server.stop(0)


def test_encode_s3_signed_put_roundtrip():
    """FLP `encode s3` (reference encode_s3.go): batched entries leave as
    JSON objects with the FLP store header under the reference's object
    layout — against a fake S3 endpoint that RE-DERIVES the AWS SigV4
    signature from the shared secret and rejects mismatches."""
    import hashlib
    import hmac as hmac_mod
    import http.server
    import re
    import threading

    access, secret = "testkey", "testsecret"
    puts = []

    class FakeS3(http.server.BaseHTTPRequestHandler):
        def do_PUT(self):
            body = self.rfile.read(int(self.headers["Content-Length"]))
            auth = self.headers["Authorization"]
            m = re.match(
                r"AWS4-HMAC-SHA256 Credential=([^/]+)/(\d+)/([^/]+)/s3/"
                r"aws4_request, SignedHeaders=([^,]+), Signature=(\w+)",
                auth)
            assert m, auth
            _key, datestamp, region, signed, got_sig = m.groups()
            headers = {k: self.headers[k]
                       for k in signed.split(";")}
            canonical = "\n".join([
                "PUT", self.path, "",
                "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
                signed, headers["x-amz-content-sha256"]])
            scope = f"{datestamp}/{region}/s3/aws4_request"
            to_sign = "\n".join([
                "AWS4-HMAC-SHA256", headers["x-amz-date"], scope,
                hashlib.sha256(canonical.encode()).hexdigest()])

            def hm(k, msg):
                return hmac_mod.new(k, msg.encode(), hashlib.sha256).digest()
            sig_key = hm(hm(hm(hm(("AWS4" + secret).encode(), datestamp),
                               region), "s3"), "aws4_request")
            want = hmac_mod.new(sig_key, to_sign.encode(),
                                hashlib.sha256).hexdigest()
            ok = (want == got_sig
                  and hashlib.sha256(body).hexdigest()
                  == headers["x-amz-content-sha256"])
            puts.append((self.path, body, ok))
            self.send_response(200 if ok else 403)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), FakeS3)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        cfg = f"""
pipeline: [{{name: e}}, {{name: w, follows: e}}]
parameters:
  - name: e
    encode:
      type: s3
      s3:
        endpoint: 127.0.0.1:{srv.server_port}
        bucket: flows
        account: tenant1
        accessKeyId: {access}
        secretAccessKey: {secret}
        batchSize: 2
        objectHeaderParameters: {{cluster: test}}
  - name: w
    write: {{type: stdout}}
"""
        buf = io.StringIO()
        exp = DirectFLPExporter(flp_config=cfg, stream=buf)
        exp.export_batch([make_record(proto=6), make_record(proto=17),
                          make_record(proto=6)])
        exp.close()  # remainder (1 entry) flushes as a final object
        # entries passed through to the terminal stage
        assert len(buf.getvalue().splitlines()) == 3
        assert len(puts) == 2
        for path, body, sig_ok in puts:
            assert sig_ok, "SigV4 signature mismatch"
            assert re.match(
                r"/flows/tenant1/year=\d{4}/month=\d{2}/day=\d{2}/"
                r"hour=\d{2}/stream-id=\w+/\d{8}", path), path
        o1 = json.loads(puts[0][1])
        assert o1["number_of_flow_logs"] == 2 and o1["cluster"] == "test"
        assert o1["version"] == "v0.1" and len(o1["flow_logs"]) == 2
        o2 = json.loads(puts[1][1])
        assert o2["number_of_flow_logs"] == 1
    finally:
        srv.shutdown()
