"""Fused signal-plane Pallas kernel equivalence (interpret mode on the CPU
mesh, like the sibling Count-Min/HLL kernel suites; the same kernel compiles
through Mosaic on TPU).

The kernel replaces the serialized per-table scatter chain with ONE batch
walk over all eight signal tables (ops/pallas/signal_kernel.py). Masses are
integer-valued f32 well under 2^24, so float sums are order-independent and
the equivalence pins are BIT-exact, not approximate."""

import numpy as np

import tests.conftest  # noqa: F401
import jax
import jax.numpy as jnp

from netobserv_tpu.ops.pallas import signal_kernel
from netobserv_tpu.sketch import state as sk

KW = 10
M = 256


def _planes(m: int = M, n_dscp: int = 64, n_causes: int = 128):
    return signal_kernel.SignalPlanes(
        ddos_rate=jnp.zeros((m,), jnp.float32),
        syn_rate=jnp.zeros((m,), jnp.float32),
        drops_rate=jnp.zeros((m,), jnp.float32),
        synack=jnp.zeros((m,), jnp.float32),
        conv_fwd=jnp.zeros((m,), jnp.float32),
        conv_rev=jnp.zeros((m,), jnp.float32),
        dscp_bytes=jnp.zeros((n_dscp,), jnp.float32),
        drop_causes=jnp.zeros((n_causes,), jnp.float32))


def _scatter_reference(planes, idx, vals):
    """The un-fused chain: one scatter-add per (family row, table)."""
    out = []
    fam = (0, 0, 0, 1, 2, 2)  # main rows -> index families dst/src/pair
    tables = list(planes[:6])
    for row, table in enumerate(tables):
        out.append(np.asarray(
            table.at[idx[fam[row]]].add(vals[row], mode="drop")))
    dscp = planes.dscp_bytes.at[idx[3]].add(vals[6], mode="drop")
    causes = planes.drop_causes.at[idx[4]].add(vals[7], mode="drop")
    return out + [np.asarray(dscp), np.asarray(causes)]


def _random_batch(b: int, m: int = M, seed: int = 1):
    rng = np.random.default_rng(seed)
    idx = np.stack([
        rng.integers(0, m, b), rng.integers(0, m, b), rng.integers(0, m, b),
        rng.integers(0, 64, b), rng.integers(0, 128, b),
    ]).astype(np.int32)
    # integer-valued f32 masses -> order-independent sums -> exact pins
    vals = rng.integers(0, 2000, (8, b)).astype(np.float32)
    vals *= rng.random((8, b)) < 0.8  # zero rows model masked records
    return jnp.asarray(idx), jnp.asarray(vals)


def test_signal_kernel_matches_scatter_chain_bit_exact():
    idx, vals = _random_batch(2048)
    planes = _planes()
    got = signal_kernel.update(planes, idx, vals, interpret=True)
    want = _scatter_reference(planes, idx, vals)
    for g, w, name in zip(got, want, signal_kernel.SignalPlanes._fields):
        np.testing.assert_array_equal(np.asarray(g), w, err_msg=name)


def test_signal_kernel_accumulates_and_pads_ragged():
    idx, vals = _random_batch(777, seed=4)  # not a CHUNK_B multiple
    planes = _planes()
    for _ in range(3):
        planes = signal_kernel.update(planes, idx, vals, interpret=True)
    want = _planes()
    for _ in range(3):
        want = signal_kernel.SignalPlanes(*(
            jnp.asarray(a) for a in _scatter_reference(want, idx, vals)))
    for g, w, name in zip(planes, want, signal_kernel.SignalPlanes._fields):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w),
                                      err_msg=name)


def test_eligibility_gate():
    assert signal_kernel.eligible(_planes(256))
    assert not signal_kernel.eligible(_planes(96))  # not lane-aligned
    bad = _planes()._replace(synack=jnp.zeros((128,), jnp.float32))
    assert not signal_kernel.eligible(bad)  # mismatched widths
    assert not signal_kernel.eligible(
        _planes(n_causes=signal_kernel.AUX_W + 1))


def _arrays(b: int, seed: int, features: bool = True):
    rng = np.random.default_rng(seed)
    out = {
        "keys": jnp.asarray(rng.integers(0, 2**32, (b, KW),
                                         dtype=np.uint32)),
        "bytes": jnp.asarray(rng.integers(1, 2000, b).astype(np.float32)),
        "packets": jnp.asarray(rng.integers(1, 8, b).astype(np.int32)),
        "rtt_us": jnp.asarray(rng.integers(0, 900, b).astype(np.int32)),
        "dns_latency_us": jnp.zeros(b, jnp.int32),
        "sampling": jnp.asarray(rng.integers(0, 4, b).astype(np.int32)),
        "valid": jnp.asarray(rng.random(b) < 0.9),
    }
    if features:
        out.update({
            "tcp_flags": jnp.asarray(
                rng.integers(0, 1 << 9, b).astype(np.int32)),
            "dscp": jnp.asarray(rng.integers(0, 64, b).astype(np.int32)),
            "markers": jnp.asarray(rng.integers(0, 4, b).astype(np.int32)),
            "drop_bytes": jnp.asarray(
                rng.integers(0, 200, b).astype(np.int32)),
            "drop_packets": jnp.asarray(
                rng.integers(0, 3, b).astype(np.int32)),
            "drop_cause": jnp.asarray(
                rng.integers(0, 300, b).astype(np.int32)),
        })
    return out


def test_full_ingest_signal_planes_bit_exact_vs_unfused():
    """The WHOLE ingest with use_pallas=True (signal kernel + CM + HLL
    kernels, all interpret mode on CPU) against the scatter path: every
    signal plane must match bit-for-bit, feature lanes included."""
    cfg = sk.SketchConfig(cm_width=1024, topk=16, hll_precision=10,
                          perdst_buckets=32, perdst_precision=4,
                          persrc_buckets=32, persrc_precision=4,
                          hist_buckets=64, ewma_buckets=M)
    for features in (True, False):
        arrays = _arrays(700, seed=2, features=features)
        ref = jax.jit(lambda s, a: sk.ingest(s, a, use_pallas=False))(
            sk.init_state(cfg), arrays)
        pal = jax.jit(lambda s, a: sk.ingest(s, a, use_pallas=True))(
            sk.init_state(cfg), arrays)
        for f in ("synack", "conv_fwd", "conv_rev", "dscp_bytes",
                  "drop_causes"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, f)), np.asarray(getattr(pal, f)),
                err_msg=f"{f} features={features}")
        for f in ("ddos", "syn", "drops_ewma"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, f).rate),
                np.asarray(getattr(pal, f).rate),
                err_msg=f"{f}.rate features={features}")
        assert float(ref.total_records) == float(pal.total_records)
        np.testing.assert_allclose(np.asarray(ref.cm_bytes.counts),
                                   np.asarray(pal.cm_bytes.counts),
                                   rtol=1e-6)


def test_full_ingest_signal_planes_asym_off():
    """enable_asym=False must leave conv planes untouched on BOTH paths."""
    cfg = sk.SketchConfig(cm_width=1024, topk=16, hll_precision=10,
                          perdst_buckets=32, perdst_precision=4,
                          persrc_buckets=32, persrc_precision=4,
                          hist_buckets=64, ewma_buckets=M)
    arrays = _arrays(512, seed=6)
    for pallas in (False, True):
        s = jax.jit(lambda st, a: sk.ingest(st, a, use_pallas=pallas,
                                            enable_asym=False))(
            sk.init_state(cfg), arrays)
        assert not np.asarray(s.conv_fwd).any()
        assert not np.asarray(s.conv_rev).any()
        assert np.asarray(s.synack).any()  # other signals still fold
