"""Pallas kernel equivalence vs the XLA scatter implementation (interpret mode
on the CPU mesh; the same kernel compiles through Mosaic on TPU)."""

import numpy as np

import tests.conftest  # noqa: F401
import jax.numpy as jnp

from netobserv_tpu.ops import countmin, hashing
from netobserv_tpu.ops.pallas import countmin_kernel

KW = 10


def test_pallas_countmin_matches_xla_scatter():
    rng = np.random.default_rng(11)
    b = 2048
    words = jnp.asarray(rng.integers(0, 2**32, (b, KW), dtype=np.uint32))
    vals = jnp.asarray(rng.integers(1, 1000, b).astype(np.float32))
    valid = jnp.asarray(rng.random(b) < 0.9)
    h1, h2 = hashing.base_hashes(words)

    ref = countmin.update(countmin.init(3, 1 << 11), h1, h2, vals, valid)
    got = countmin_kernel.update(countmin.init(3, 1 << 11), h1, h2, vals,
                                 valid, interpret=True)
    np.testing.assert_allclose(np.asarray(got.counts), np.asarray(ref.counts),
                               rtol=1e-6)


def test_pallas_countmin_accumulates_across_calls():
    rng = np.random.default_rng(12)
    words = jnp.asarray(rng.integers(0, 2**32, (1024, KW), dtype=np.uint32))
    vals = jnp.ones(1024, jnp.float32)
    valid = jnp.ones(1024, jnp.bool_)
    h1, h2 = hashing.base_hashes(words)
    cm = countmin.init(2, 1 << 10)
    for _ in range(3):
        cm = countmin_kernel.update(cm, h1, h2, vals, valid, interpret=True)
    est = countmin.query(cm, h1, h2)
    assert float(jnp.min(est)) >= 3.0


def test_pallas_hll_matches_xla_scatter():
    from netobserv_tpu.ops import hll
    from netobserv_tpu.ops.pallas import hll_kernel
    rng = np.random.default_rng(21)
    b = 3000  # ragged (not a CHUNK_B multiple)
    words = jnp.asarray(rng.integers(0, 2**32, (b, 4), dtype=np.uint32))
    valid = jnp.asarray(rng.random(b) < 0.9)
    h1, h2 = hashing.base_hashes(words)
    ref = hll.update(hll.init(12), h1, h2, valid)  # 4096 regs
    got = hll_kernel.update(hll.init(12), h1, h2, valid, interpret=True)
    np.testing.assert_array_equal(np.asarray(got.regs), np.asarray(ref.regs))


def test_full_ingest_pallas_matches_default():
    from netobserv_tpu.sketch import state as sk
    rng = np.random.default_rng(22)
    cfg = sk.SketchConfig(cm_width=1024, topk=16, hll_precision=10,
                          perdst_buckets=32, perdst_precision=4,
                          hist_buckets=64, ewma_buckets=32)
    arrays = {
        "keys": jnp.asarray(rng.integers(0, 2**32, (512, KW), dtype=np.uint32)),
        "bytes": jnp.asarray(rng.integers(1, 100, 512).astype(np.float32)),
        "packets": jnp.ones(512, jnp.int32),
        "rtt_us": jnp.zeros(512, jnp.int32),
        "dns_latency_us": jnp.zeros(512, jnp.int32),
        "sampling": jnp.zeros(512, jnp.int32),
        "valid": jnp.ones(512, jnp.bool_),
    }
    import jax
    s_ref = jax.jit(lambda s, a: __import__("netobserv_tpu.sketch.state",
                                            fromlist=["ingest"]).ingest(s, a))(
        sk.init_state(cfg), arrays)
    s_pal = sk.make_ingest_fn(donate=False, use_pallas=True)(
        sk.init_state(cfg), arrays)
    np.testing.assert_allclose(np.asarray(s_pal.cm_bytes.counts),
                               np.asarray(s_ref.cm_bytes.counts), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(s_pal.hll_src.regs),
                                  np.asarray(s_ref.hll_src.regs))
    assert float(s_pal.total_records) == float(s_ref.total_records)


def test_pallas_countmin_pads_ragged_batch():
    rng = np.random.default_rng(13)
    b = 777  # not a multiple of CHUNK_B
    words = jnp.asarray(rng.integers(0, 2**32, (b, KW), dtype=np.uint32))
    vals = jnp.asarray(rng.integers(1, 10, b).astype(np.float32))
    valid = jnp.ones(b, jnp.bool_)
    h1, h2 = hashing.base_hashes(words)
    ref = countmin.update(countmin.init(2, 1 << 10), h1, h2, vals, valid)
    got = countmin_kernel.update(countmin.init(2, 1 << 10), h1, h2, vals,
                                 valid, interpret=True)
    np.testing.assert_allclose(np.asarray(got.counts), np.asarray(ref.counts),
                               rtol=1e-6)


def test_use_pallas_auto_policy():
    """auto = TPU AND width >= the measured crossover; every bool spelling
    the old field accepted still forces its path (an operator's explicit
    SKETCH_USE_PALLAS=0 opt-out must never flip into Pallas-on)."""
    from netobserv_tpu.config import load_config
    from netobserv_tpu.sketch.state import SketchConfig

    for spelling, want in (("auto", None), ("", None),
                           ("0", False), ("off", False), ("no", False),
                           ("false", False),
                           ("1", True), ("on", True), ("true", True)):
        cfg = load_config({"SKETCH_USE_PALLAS": spelling})
        assert SketchConfig.from_agent_config(cfg).use_pallas is want, \
            spelling


def test_hll_grid_kernel_matches_scatter():
    """The flat-indexed grid fold (interpret mode on CPU) must equal the
    XLA scatter grid update bit-for-bit."""
    import numpy as np

    from netobserv_tpu.ops import hashing, hll
    from netobserv_tpu.ops.pallas import hll_kernel

    rng = np.random.default_rng(5)
    n = 512
    dsts = jnp.asarray(rng.integers(0, 2**32, (n, 4), dtype=np.uint32))
    srcs = jnp.asarray(rng.integers(0, 2**32, (n, 4), dtype=np.uint32))
    valid = jnp.asarray(rng.random(n) < 0.9)
    dh, _ = hashing.base_hashes(dsts, seed=1)
    sh1, sh2 = hashing.base_hashes(srcs)
    s0 = hll.init_per_dst(dst_buckets=32, precision=4)  # 32*16=512 lanes
    ref = hll.update_per_dst(s0, dh, sh1, sh2, valid)
    got = hll_kernel.update_per_dst(s0, dh, sh1, sh2, valid, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref.regs), np.asarray(got.regs))
