"""Pallas kernel equivalence vs the XLA scatter implementation (interpret mode
on the CPU mesh; the same kernel compiles through Mosaic on TPU)."""

import numpy as np

import tests.conftest  # noqa: F401
import jax.numpy as jnp

from netobserv_tpu.ops import countmin, hashing
from netobserv_tpu.ops.pallas import countmin_kernel

KW = 10


def test_pallas_countmin_matches_xla_scatter():
    rng = np.random.default_rng(11)
    b = 2048
    words = jnp.asarray(rng.integers(0, 2**32, (b, KW), dtype=np.uint32))
    vals = jnp.asarray(rng.integers(1, 1000, b).astype(np.float32))
    valid = jnp.asarray(rng.random(b) < 0.9)
    h1, h2 = hashing.base_hashes(words)

    ref = countmin.update(countmin.init(3, 1 << 11), h1, h2, vals, valid)
    got = countmin_kernel.update(countmin.init(3, 1 << 11), h1, h2, vals,
                                 valid, interpret=True)
    np.testing.assert_allclose(np.asarray(got.counts), np.asarray(ref.counts),
                               rtol=1e-6)


def test_pallas_countmin_accumulates_across_calls():
    rng = np.random.default_rng(12)
    words = jnp.asarray(rng.integers(0, 2**32, (1024, KW), dtype=np.uint32))
    vals = jnp.ones(1024, jnp.float32)
    valid = jnp.ones(1024, jnp.bool_)
    h1, h2 = hashing.base_hashes(words)
    cm = countmin.init(2, 1 << 10)
    for _ in range(3):
        cm = countmin_kernel.update(cm, h1, h2, vals, valid, interpret=True)
    est = countmin.query(cm, h1, h2)
    assert float(jnp.min(est)) >= 3.0


def test_pallas_countmin_pads_ragged_batch():
    rng = np.random.default_rng(13)
    b = 777  # not a multiple of CHUNK_B
    words = jnp.asarray(rng.integers(0, 2**32, (b, KW), dtype=np.uint32))
    vals = jnp.asarray(rng.integers(1, 10, b).astype(np.float32))
    valid = jnp.ones(b, jnp.bool_)
    h1, h2 = hashing.base_hashes(words)
    ref = countmin.update(countmin.init(2, 1 << 10), h1, h2, vals, valid)
    got = countmin_kernel.update(countmin.init(2, 1 << 10), h1, h2, vals,
                                 valid, interpret=True)
    np.testing.assert_allclose(np.asarray(got.counts), np.asarray(ref.counts),
                               rtol=1e-6)
